"""TPC-DS benchmark — BASELINE.md ladder rung 5 (q17 / q25 / q64).

Generates the deterministic table subset (hyperspace_tpu/tpcds), creates
the covering indexes, and times each query three ways, warm best-of-N:
  - rules ON   (index-accelerated framework execution)
  - rules OFF  (framework execution without indexes)
  - pandas     (vectorized CPU oracle — the commodity baseline)
Result equality across all three is asserted before timing is reported
(the reference's E2E guarantee, `E2EHyperspaceRulesTests.scala:330-346`).

Methodology note: both lanes run warm and in-memory — the framework
serves repeat reads from its stamped decoded-read cache (`io/parquet.py`,
invalidated on any file change) and the pandas lane keeps its DataFrames
resident (tables are read once, outside the timer). Set
HYPERSPACE_READ_CACHE_BYTES=0 to time the framework with cold reads.

Prints exactly ONE JSON line:
  {"metric": "tpcds_q17_q25_q64_wall_s", "value": <rules-on total>,
   "vs_baseline": <pandas total / rules-on total>, "queries": {...}}

BENCH_TPCDS_SCALE scales the fact tables (1.0 ~ 300k store_sales rows).
BENCH_TPCDS_QUERIES selects a comma-separated subset. The metric key is
"tpcds_q17_q25_q64_wall_s" only for exactly that trio (the BASELINE.md
headline set; artifact continuity with earlier rounds); any other
selection — including the ALL-99 default — reports
"tpcds_<N>q_wall_s", an intentional break because it measures a
different workload.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

SCALE = float(os.environ.get("BENCH_TPCDS_SCALE", 1.0))
WARM_RUNS = int(os.environ.get("BENCH_WARM_RUNS", 3))
# Comma-separated subset (e.g. "q17,q25,q64"); empty = all 12.
QUERY_FILTER = [q for q in os.environ.get(
    "BENCH_TPCDS_QUERIES", "").split(",") if q]


from bench_common import link_probe, log, timed_runs  # noqa: E402
from hyperspace_tpu import telemetry  # noqa: E402


def best_of(fn, runs=WARM_RUNS, label=""):
    """(best_s, median_s, out) over warm runs (medians ride along in the
    artifact so a lucky run can't carry a headline — round-4 review)."""
    best, median, out = timed_runs(fn, runs, label)
    return best, median, out


def norm(df):
    out = df.sort_values(list(df.columns)).reset_index(drop=True)
    return out.astype({c: "float64" for c in out.columns
                       if out[c].dtype.kind in "fi"})


def main():
    import pandas as pd
    import pyarrow.parquet as pq

    from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
    from hyperspace_tpu.tpcds import QUERIES, generate
    from hyperspace_tpu.tpcds.queries import create_indexes

    work = tempfile.mkdtemp(prefix="hs_tpcds_")
    try:
        t0 = time.perf_counter()
        paths = generate(os.path.join(work, "data"), scale=SCALE)
        log(f"generate (scale={SCALE}): {time.perf_counter() - t0:.1f}s")

        conf = {"hyperspace.warehouse.dir": os.path.join(work, "wh"),
                "spark.hyperspace.index.num.buckets": "32"}
        # Dev-loop overrides, e.g. forcing the device lane at small scale:
        # BENCH_TPCDS_CONF='{"spark.hyperspace.execution.min.device.rows":"0"}'
        extra = os.environ.get("BENCH_TPCDS_CONF")
        if extra:
            conf.update(json.loads(extra))
        sess = HyperspaceSession(HyperspaceConf(conf))
        hs = Hyperspace(sess)
        dfs = {n: sess.read_parquet(p) for n, p in paths.items()}
        selected = {n: q for n, q in QUERIES.items()
                    if not QUERY_FILTER or n in QUERY_FILTER}
        t0 = time.perf_counter()
        create_indexes(hs, dfs, queries=list(selected))
        index_build_s = time.perf_counter() - t0
        log(f"index build: {index_build_s:.1f}s")

        # In-memory to in-memory: the pandas lane holds its DataFrames
        # resident (read once, outside the timer), mirroring the
        # framework's decoded-read cache serving the timed runs.
        pdfs = {n: pq.read_table(os.path.join(p, "part-0.parquet"))
                .to_pandas() for n, p in paths.items()}

        probe = link_probe()
        queries = {}
        tot_on = tot_off = tot_cpu = 0.0
        # Fallback-freedom: with strings born-sharded there is ONE
        # execution architecture — any `spmd.fallbacks` increment during
        # the query set means a bucketed SMJ with an active mesh dropped
        # off the SPMD lane. Asserted here and gated absolutely by
        # `bench_regress.py`.
        fallbacks0 = telemetry.get_registry().counters_dict().get(
            "spmd.fallbacks", 0)
        for name, (build, oracle) in selected.items():
            cpu_s, cpu_med, expected = best_of(lambda: oracle(pdfs),
                                               label=f"{name} pandas")
            sess.enable_hyperspace()
            build(dfs).collect()  # warm (compiles, file listings)
            on_s, on_med, got_on = best_of(
                lambda: build(dfs).collect().to_pandas(),
                label=f"{name} rules-on")
            # Per-operator telemetry for the artifact: the recorder of
            # the LAST timed rules-on run (collect always records) —
            # operator self-times, fusion lanes, rule decisions, and
            # index usage ride next to the wall-clock numbers so later
            # rounds see operator-level trajectories, not just totals.
            qmetrics = sess.last_query_metrics()
            sess.disable_hyperspace()
            off_s, off_med, got_off = best_of(
                lambda: build(dfs).collect().to_pandas(),
                label=f"{name} rules-off")
            for got, tag in ((got_on, "rules-on"), (got_off, "rules-off")):
                pd.testing.assert_frame_equal(
                    norm(got), norm(expected), check_dtype=False,
                    check_exact=False, rtol=1e-6)
            log(f"{name}: on {on_s:.3f}s off {off_s:.3f}s cpu {cpu_s:.3f}s "
                f"(vs cpu x{cpu_s / on_s:.2f}, vs no-index x{off_s / on_s:.2f})")
            queries[name] = {"rules_on_s": round(on_s, 4),
                             "rules_off_s": round(off_s, 4),
                             "pandas_s": round(cpu_s, 4),
                             "rules_on_median_s": round(on_med, 4),
                             "rules_off_median_s": round(off_med, 4),
                             "pandas_median_s": round(cpu_med, 4),
                             "vs_baseline": round(cpu_s / on_s, 3),
                             "vs_no_index": round(off_s / on_s, 3),
                             "rows": int(len(expected)),
                             # summary digest + full operator tree —
                             # the node-level shape telemetry.diff
                             # aligns round-over-round.
                             **telemetry.artifact.query_metrics_block(
                                 qmetrics)}
            tot_on += on_s
            tot_off += off_s
            tot_cpu += cpu_s

        spmd_fallbacks = telemetry.get_registry().counters_dict().get(
            "spmd.fallbacks", 0) - fallbacks0
        assert spmd_fallbacks == 0, (
            f"{spmd_fallbacks} SPMD-lane fallbacks during the TPC-DS "
            "set — the one-architecture contract is broken")
        # Canonical, versioned artifact (telemetry/artifact.py): the
        # ONE emitter both bench drivers share, so TPC-DS rounds and
        # micro-ladder rounds diff with the same tooling
        # (scripts/bench_diff.py) and gate with the same script
        # (scripts/bench_regress.py).
        print(json.dumps(telemetry.artifact.make_artifact(
            driver="bench_tpcds.py",
            metric=("tpcds_q17_q25_q64_wall_s"
                    if set(selected) == {"q17", "q25", "q64"}
                    else f"tpcds_{len(selected)}q_wall_s"),
            value=round(tot_on, 3),
            unit="s",
            vs_baseline=round(tot_cpu / tot_on, 3),
            queries=queries,
            extra={"scale": SCALE,
                   "index_build_s": round(index_build_s, 2),
                   "link_probe": probe,
                   "spmd": {"fallbacks": float(spmd_fallbacks)}})))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
