#!/usr/bin/env python
"""Advisor rung: a synthetic recurring filter+join workload, advisor
OFF vs advisor ON.

Phase 1 (advisor off) runs K repetitions of a selective point-filter
query and a co-keyed equi-join over an un-indexed source, measuring
scanned bytes and wall per repetition. Phase 2 runs one
`IndexAdvisor.run_once()` cycle — the miner reads exactly the flight
ring phase 1 filled, the what-if scorer replays the recorded plans,
and the executor auto-builds the winners through the lease path — then
re-runs the identical workload and measures again. The rung's claim:

- the advisor recommended AND built at least one index,
- the repeat workload is served by it (rule-usage telemetry), and
- it reads STRICTLY fewer bytes, with bit-identical results.

Prints exactly ONE JSON line (canonical schema via
`telemetry.artifact.make_artifact`; `scripts/bench_regress.py
--advisor` gates built-count and the byte reduction from it).

Env knobs: BENCH_ADVISOR_ROWS (40000), BENCH_ADVISOR_REPEATS (4).
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("BENCH_ADVISOR_ROWS", 40_000))
REPEATS = int(os.environ.get("BENCH_ADVISOR_REPEATS", 4))


def _write(path: str, table) -> str:
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    pq.write_table(table, os.path.join(path, "part-0.parquet"))
    return path


def _canonical(table):
    """Row order is not part of the result contract (an index-served
    SMJ legitimately orders by join key); bit-identity compares the
    sorted table, same as the serving/chaos suites."""
    return table.sort_by([(n, "ascending") for n in table.schema.names])


def _scan_bytes(metrics) -> int:
    return sum(op.detail.get("bytes_scanned", 0)
               for op in metrics.operators if op.name == "Scan")


def _run_workload(session, queries):
    """One pass over the workload: total wall, total scanned bytes,
    result tables (the bit-identity oracle), and whether any index rule
    applied."""
    wall = 0.0
    nbytes = 0
    applied = 0
    tables = []
    for q in queries:
        t0 = time.perf_counter()
        table = q.collect()
        wall += time.perf_counter() - t0
        m = session.last_query_metrics()
        nbytes += _scan_bytes(m)
        applied += sum(1 for e in m.events
                       if e.get("category") == "rule"
                       and e.get("action") == "applied")
        tables.append(table)
    return wall, nbytes, applied, tables


def main():
    import pyarrow as pa

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace
    from hyperspace_tpu.plan import expr as E

    work = tempfile.mkdtemp(prefix="bench_advisor_")
    try:
        rng = np.random.default_rng(7)
        facts = pa.table({
            "k": rng.integers(0, ROWS // 8, ROWS).astype(np.int64),
            "v": rng.random(ROWS),
            "tag": rng.integers(0, 50, ROWS).astype(np.int32),
        })
        dims = pa.table({
            "k": np.arange(ROWS // 8, dtype=np.int64),
            "label": rng.integers(0, 9, ROWS // 8).astype(np.int64),
        })
        facts_dir = _write(os.path.join(work, "facts"), facts)
        dims_dir = _write(os.path.join(work, "dims"), dims)

        conf = HyperspaceConf({
            "spark.hyperspace.warehouse.dir": os.path.join(work, "wh"),
            "spark.hyperspace.index.num.buckets": 8,
            # One cycle may build the filter index, the skipping
            # sketch, AND the join pair (default 2 spreads them over
            # runs — fine in production, noisy in a bench).
            "spark.hyperspace.advisor.max.builds": 6,
        })
        session = HyperspaceSession(conf).enable_hyperspace()
        hs = Hyperspace(session)
        f = session.read_parquet(facts_dir)
        d = session.read_parquet(dims_dir)
        queries = [
            f.filter(E.col("tag") == 7).select("k", "v", "tag"),
            f.join(d, on="k").select("k", "v", "label"),
        ]

        before_wall = before_bytes = 0
        tables_before = None
        for _ in range(REPEATS):
            w, b, _a, tables_before = _run_workload(session, queries)
            before_wall += w
            before_bytes += b

        advisor = hs.advisor()
        t0 = time.perf_counter()
        summary = advisor.run_once()
        advise_s = time.perf_counter() - t0
        built = [d for d in summary["decisions"]
                 if d.get("action") == "built"]

        after_wall = after_bytes = after_applied = 0
        tables_after = None
        for _ in range(REPEATS):
            w, b, a, tables_after = _run_workload(session, queries)
            after_wall += w
            after_bytes += b
            after_applied += a

        bit_identical = all(_canonical(x).equals(_canonical(y))
                            for x, y in
                            zip(tables_before, tables_after))
        advisor_block = {
            "repeats": REPEATS,
            "rows": ROWS,
            "signatures": len(summary["signatures"]),
            "recommended": len(summary["recommendations"]),
            "built": sum(len(d.get("indexes", ())) for d in built),
            "advise_s": round(advise_s, 4),
            "bytes_scanned_before": before_bytes,
            "bytes_scanned_after": after_bytes,
            "bytes_reduction": round(1.0 - after_bytes
                                     / max(before_bytes, 1), 4),
            "wall_before_s": round(before_wall, 4),
            "wall_after_s": round(after_wall, 4),
            "rule_applied_after": after_applied,
            "bit_identical": bit_identical,
            "decisions": summary["decisions"],
        }
        print(f"# advisor: {advisor_block['built']} built, bytes "
              f"{before_bytes} -> {after_bytes} "
              f"({advisor_block['bytes_reduction']:.1%} less), "
              f"applied {after_applied}, bit_identical {bit_identical}",
              file=sys.stderr)

        result = telemetry.artifact.make_artifact(
            driver="bench_advisor.py",
            metric="advisor_bytes_reduction",
            value=advisor_block["bytes_reduction"],
            unit="fraction",
            vs_baseline=round(before_bytes / max(after_bytes, 1), 3),
            extra={"advisor": advisor_block})
        print(json.dumps(result))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
