"""Continuous-ingest benchmark — the staleness-vs-p99 frontier.

The first rung where the full write path meets the full serve path:
micro-batch appends land and incremental refresh runs (bucketed delta
for the covering index, sketch-append for the skipping index) WHILE an
8-client closed loop serves — lease-coordinated, pressure-gated, every
concurrent answer bit-checked against its serial oracle.

The workload is append-invariant by construction: queries filter the
LOW key range (g 0..7 on facts; e < 6000 on events) and every appended
file carries only HIGH-range rows (g >= 16; e >= 100000), so the
correct answer never changes while the index version flips under the
readers — any drift is a real snapshot-isolation bug, not churn. A
separate freshness count over the appended range (fresh reader each
time) proves appends actually become visible. Clients build their
DataFrame fresh per query so the scan re-lists the growing source:
hybrid scan serves the unindexed remainder between refreshes, with the
skipping index's delta sketches thinning it.

Phases, one artifact:

1. **quiet lap** — closed-loop p99 with no ingest: the baseline.
2. **append-rate sweep** — a bench-owned ticker thread drives
   `IngestCoordinator.run_once` at each rate (one appended file per
   source per tick, then incremental refresh of both indexes) while
   the clients serve. Per rate: p99, staleness gauge max/mean,
   refreshes/conflicts/deferred, segment-cache warm hit rate +
   `cache.segments.rekeyed` delta. The committed operating point is
   the HIGHEST swept rate that still holds the warm-hit-rate floor —
   rates past the knee stay in the sweep as the frontier's far edge
   but are not what the regression gates defend.
3. **chaos** — crash injection at refresh phase boundaries for BOTH
   incremental actions plus transient storage faults, under full
   client load with the maintenance lease shrunk so the next tick's
   lease recovery heals the op log. Green = zero mismatches, zero
   stuck clients, zero non-ACTIVE op-log leftovers, staleness drains
   to 0 after quiesce.

Prints exactly ONE JSON line (canonical schema via
`telemetry.artifact.make_artifact`; gated by
`scripts/bench_regress.py --ingest`).

Env knobs: BENCH_INGEST_CLIENTS (8), BENCH_INGEST_ROWS (16000 initial
facts rows), BENCH_INGEST_LAP_SECONDS (6 per lap),
BENCH_INGEST_RATES (appends/s per source, "0.5,1.0,2.0"),
BENCH_INGEST_APPEND_ROWS (400 rows per appended file),
BENCH_INGEST_CHAOS_SECONDS (8).
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

CLIENTS = int(os.environ.get("BENCH_INGEST_CLIENTS", 8))
ROWS = int(os.environ.get("BENCH_INGEST_ROWS", 16_000))
LAP_SECONDS = float(os.environ.get("BENCH_INGEST_LAP_SECONDS", 6))
RATES = [float(r) for r in os.environ.get(
    "BENCH_INGEST_RATES", "0.5,1.0,2.0").split(",")]
APPEND_ROWS = int(os.environ.get("BENCH_INGEST_APPEND_ROWS", 400))
CHAOS_SECONDS = float(os.environ.get("BENCH_INGEST_CHAOS_SECONDS", 8))

from bench_common import link_probe, log  # noqa: E402
from hyperspace_tpu import telemetry  # noqa: E402


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _counter(name: str) -> float:
    return telemetry.get_registry().counters_dict().get(name, 0)


def canonical(table):
    return table.sort_by([(n, "ascending") for n in table.column_names])


def generate(data_dir: str):
    """facts: 8 files, g in 0..15 (low range). events: 6 files, e in
    disjoint low blocks. Appends later use g >= 16 / e >= 100000."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    facts = os.path.join(data_dir, "facts")
    events = os.path.join(data_dir, "events")
    os.makedirs(facts)
    os.makedirs(events)
    per = max(1, ROWS // 8)
    for i in range(8):
        k = np.arange(i * per, (i + 1) * per, dtype=np.int64)
        pq.write_table(pa.table({
            "k": k, "g": k % 16,
            "v": rng.random(per).astype(np.float64)}),
            os.path.join(facts, f"f{i:03d}.parquet"))
    for i in range(6):
        e = np.arange(i * 1000, (i + 1) * 1000, dtype=np.int64)
        pq.write_table(pa.table({
            "e": e, "w": rng.random(1000).astype(np.float64)}),
            os.path.join(events, f"e{i:03d}.parquet"))
    return facts, events


class Appender:
    """Atomic micro-batch producer: each call writes one HIGH-range
    file into facts and events (tmp + rename so a concurrent listing
    never sees a partial file) and returns the new paths. Each facts
    file carries ONE g value, so a refresh touches at most one bucket
    and the warm-set story is measurable."""

    def __init__(self, facts: str, events: str):
        self.facts = facts
        self.events = events
        self.n = 0
        self.rows_appended = 0
        self.rng = np.random.default_rng(23)

    def _write(self, table, directory: str, name: str) -> str:
        import pyarrow.parquet as pq
        tmp = os.path.join(directory, f".tmp.{name}")
        out = os.path.join(directory, name)
        pq.write_table(table, tmp)
        os.replace(tmp, out)
        return out

    def __call__(self):
        import pyarrow as pa
        i = self.n
        self.n += 1
        g = np.int64(16 + (i % 8))
        k = np.arange(ROWS + i * APPEND_ROWS,
                      ROWS + (i + 1) * APPEND_ROWS, dtype=np.int64)
        f1 = self._write(pa.table({
            "k": k, "g": np.full(APPEND_ROWS, g, dtype=np.int64),
            "v": self.rng.random(APPEND_ROWS).astype(np.float64)}),
            self.facts, f"a{i:05d}.parquet")
        e = np.arange(100_000 + i * APPEND_ROWS,
                      100_000 + (i + 1) * APPEND_ROWS, dtype=np.int64)
        f2 = self._write(pa.table({
            "e": e, "w": self.rng.random(APPEND_ROWS).astype(np.float64)}),
            self.events, f"a{i:05d}.parquet")
        self.rows_appended += 2 * APPEND_ROWS
        return [f1, f2]


def build_queries(session, facts: str, events: str):
    """(name, build_fn) pairs; build_fn returns a FRESH DataFrame so
    the scan re-lists the growing source every execution."""
    from hyperspace_tpu.plan.expr import col, lit

    queries = []
    for g in range(8):
        def q(g=g):
            return (session.read_parquet(facts)
                    .filter(col("g") == lit(g)).select("k", "g", "v"))
        queries.append((f"point_g{g}", q))
    for lo, hi in ((0, 1000), (2500, 3500), (4000, 6000)):
        def q(lo=lo, hi=hi):
            return (session.read_parquet(events)
                    .filter(col("e") >= lit(lo))
                    .filter(col("e") < lit(hi)).select("e", "w"))
        queries.append((f"range_e{lo}", q))
    return queries


def serve_lap(session, queries, oracles, seconds: float, clients: int):
    """Closed loop: each client builds + runs queries round-robin until
    the deadline, checking every answer against the serial oracle.
    Returns (latencies sorted, ok, mismatches, errors, stuck)."""
    lock = threading.Lock()
    latencies, errors = [], []
    counts = {"ok": 0, "mismatch": 0}
    deadline = time.time() + seconds

    def client(cid: int):
        i = cid
        while time.time() < deadline:
            name, build = queries[i % len(queries)]
            i += clients
            t0 = time.perf_counter()
            try:
                out = build().collect()
            except Exception as exc:
                with lock:
                    errors.append(f"{name}: {exc!r}")
                continue
            dt = time.perf_counter() - t0
            good = canonical(out).equals(oracles[name])
            with lock:
                latencies.append(dt)
                counts["ok" if good else "mismatch"] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    for t in threads:
        t.start()
    stuck = 0
    for t in threads:
        t.join(timeout=seconds + 60)
        if t.is_alive():
            stuck += 1
    return (sorted(latencies), counts["ok"], counts["mismatch"],
            errors, stuck)


class Ticker:
    """Bench-owned coordinator driver (the coordinator itself is
    caller-threaded by design): ticks `run_once` at `interval_s`,
    sampling the staleness gauge after each tick. Injected crashes are
    caught HERE — the ticker models the supervised process that dies
    and restarts; the next tick's lease recovery heals the log."""

    def __init__(self, coord, interval_s: float):
        self.coord = coord
        self.interval_s = interval_s
        self.staleness_samples = []
        self.crashes = 0
        self.tick_errors = []
        self._stop = threading.Event()
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            t0 = time.time()
            try:
                self.coord.run_once()
            except BaseException as exc:  # noqa: BLE001 - injected crash
                self.crashes += 1
                self.tick_errors.append(repr(exc))
            self.staleness_samples.append(self.coord.staleness_s())
            elapsed = time.time() - t0
            self._stop.wait(max(0.01, self.interval_s - elapsed))

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bench-ingest-ticker")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60)


def drain(coord, timeout_s: float = 30.0) -> float:
    """Tick until staleness reaches 0 (all appends indexed)."""
    end = time.time() + timeout_s
    while time.time() < end:
        try:
            coord.run_once()
        except BaseException:
            pass
        if coord.staleness_s() <= 0.0:
            return 0.0
    return coord.staleness_s()


def stranded_entries(session) -> int:
    """Non-ACTIVE latest op-log entries after recovery = stranded."""
    from hyperspace_tpu.facade import Hyperspace
    manager = Hyperspace.get_context(session).index_collection_manager
    if hasattr(manager, "clear_cache"):
        manager.clear_cache()
    bad = 0
    for entry in manager.get_indexes():
        if entry.state != "ACTIVE":
            bad += 1
    return bad


def main():
    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace
    from hyperspace_tpu.index.index_config import (DataSkippingIndexConfig,
                                                   IndexConfig)
    from hyperspace_tpu.plan.expr import col, lit
    from hyperspace_tpu.telemetry.artifact import make_artifact
    from hyperspace_tpu.utils import faults

    tmp = tempfile.mkdtemp(prefix="hs_bench_ingest_")
    try:
        data_dir = os.path.join(tmp, "data")
        os.makedirs(data_dir)
        facts, events = generate(data_dir)
        session = HyperspaceSession(HyperspaceConf({
            "hyperspace.warehouse.dir": os.path.join(tmp, "wh"),
            "spark.hyperspace.index.num.buckets": "8",
            "spark.hyperspace.index.lineage.enabled": "true",
            "spark.hyperspace.index.hybridscan.enabled": "true",
            "spark.hyperspace.execution.min.device.rows": "0",
            "spark.hyperspace.distribution.enabled": "false",
            "spark.hyperspace.serve.queue.depth": "64",
            # Small lease so chaos-phase crash recovery lands on the
            # next tick, not 10 minutes later. The coordinator is the
            # only writer outside chaos, so no live writer can be
            # mistaken for a stale one.
            "spark.hyperspace.maintenance.lease.seconds": "2",
            "spark.hyperspace.io.retry.base.ms": "5",
            "spark.hyperspace.io.retry.max.ms": "40",
        }))
        hs = Hyperspace(session)
        log("bench_ingest: building indexes")
        hs.create_index(session.read_parquet(facts),
                        IndexConfig("cov", ["g"], ["k", "v"]))
        hs.create_index(session.read_parquet(events),
                        DataSkippingIndexConfig("sk", ["e"]))

        queries = build_queries(session, facts, events)
        oracles = {}
        for name, build in queries:
            oracles[name] = canonical(build().collect())
        session.enable_hyperspace()
        # Warm lap: settle jit/segment caches before timing anything.
        for name, build in queries:
            out = canonical(build().collect())
            assert out.equals(oracles[name]), f"warm mismatch: {name}"

        log(f"bench_ingest: quiet lap ({CLIENTS} clients, "
            f"{LAP_SECONDS:.0f}s)")
        lat, ok, mism, errs, stuck = serve_lap(
            session, queries, oracles, LAP_SECONDS, CLIENTS)
        quiet = {"p50_s": _percentile(lat, 0.50),
                 "p99_s": _percentile(lat, 0.99),
                 "qps": round(len(lat) / LAP_SECONDS, 2),
                 "queries": len(lat), "mismatches": mism,
                 "errors": len(errs), "stuck_threads": stuck}
        assert mism == 0 and stuck == 0, (mism, stuck, errs[:3])

        appender = Appender(facts, events)
        coord = hs.ingest(producer=appender, indexes=["cov", "sk"])
        sweep = []
        for rate in RATES:
            interval = 1.0 / max(rate, 1e-6)
            c0 = telemetry.get_registry().counters_dict()
            ticker = Ticker(coord, interval)
            log(f"bench_ingest: sweep rate={rate}/s "
                f"(tick every {interval:.2f}s)")
            ticker.start()
            lat, ok, mism, errs, stuck = serve_lap(
                session, queries, oracles, LAP_SECONDS, CLIENTS)
            ticker.stop()
            c1 = telemetry.get_registry().counters_dict()

            def delta(name):
                return c1.get(name, 0) - c0.get(name, 0)

            hits, misses = delta("cache.segments.hits"), delta(
                "cache.segments.misses")
            samples = ticker.staleness_samples or [0.0]
            sweep.append({
                "rate_files_per_s": rate,
                "p50_s": _percentile(lat, 0.50),
                "p99_s": _percentile(lat, 0.99),
                "qps": round(len(lat) / LAP_SECONDS, 2),
                "queries": len(lat),
                "mismatches": mism, "errors": len(errs),
                "stuck_threads": stuck,
                "staleness_max_s": round(max(samples), 3),
                "staleness_mean_s": round(sum(samples) / len(samples), 3),
                "appends": delta("ingest.appends"),
                "refreshes": delta("ingest.refreshes"),
                "conflicts": delta("ingest.conflicts"),
                "deferred": delta("ingest.deferred"),
                "failures": delta("ingest.failures"),
                "segcache": {
                    "hits": hits, "misses": misses,
                    "warm_hit_rate": round(hits / (hits + misses), 4)
                    if hits + misses else None,
                    "rekeyed": delta("cache.segments.rekeyed"),
                },
            })
            assert mism == 0 and stuck == 0, (rate, mism, stuck, errs[:3])
        # Operating point: highest rate that holds the warm-hit floor.
        # Past-the-knee rates stay in the sweep as the frontier's far
        # edge; gates defend the rate we'd actually run at.
        sustainable = [s for s in sweep
                       if (s["segcache"]["warm_hit_rate"] or 0.0) >= 0.5]
        committed = (sustainable[-1] if sustainable else sweep[-1])

        # -- chaos: crash + transient mid-refresh under full load ------
        log("bench_ingest: chaos lap (crash + transient mid-refresh)")
        recoveries0 = _counter("resilience.recoveries")
        injector = faults.FaultInjector([
            faults.FaultRule("action.RefreshIncrementalAction.op",
                             kind="crash", nth=2, times=1),
            faults.FaultRule("action.RefreshSkippingAppendAction.op",
                             kind="crash", nth=3, times=1),
            faults.FaultRule("action.RefreshIncrementalAction.end",
                             kind="crash", nth=6, times=1),
            faults.FaultRule("file.write", kind="transient", times=2,
                             path="*indexes*"),
        ], seed=7)
        faults.install(injector)
        chaos_ticker = Ticker(coord, 0.6)
        chaos_ticker.start()
        try:
            lat, ok, mism, errs, stuck = serve_lap(
                session, queries, oracles, CHAOS_SECONDS, CLIENTS)
        finally:
            chaos_ticker.stop()
            faults.uninstall()
        injected = injector.fired("*")
        # Quiesce: drain the backlog, then the log must be fully healed.
        final_staleness = drain(coord)
        stranded = stranded_entries(session)
        chaos = {
            "seconds": CHAOS_SECONDS,
            "queries": len(lat),
            "mismatches": mism,
            "errors": len(errs),
            "stuck_threads": stuck,
            "deadlock": stuck > 0,
            "crashes_caught": chaos_ticker.crashes,
            "injections_fired": injected,
            "recoveries": _counter("resilience.recoveries") - recoveries0,
            "stranded_entries": stranded,
            "final_staleness_s": final_staleness,
            "p99_s": _percentile(lat, 0.99),
        }

        # -- freshness: every appended row is indexed + visible --------
        fresh = session.read_parquet(facts).filter(col("g") >= lit(16))
        visible = fresh.collect().num_rows
        expected = appender.n * APPEND_ROWS
        freshness = {"appended_files": appender.n * 2,
                     "appended_rows_facts": expected,
                     "visible_rows_facts": visible,
                     "final_staleness_s": final_staleness}

        p99_degradation = (committed["p99_s"] / quiet["p99_s"]
                           if quiet["p99_s"] else None)
        doc = make_artifact(
            driver="bench_ingest.py",
            metric="ingest_p99_s",
            value=committed["p99_s"],
            unit="s",
            vs_baseline=round(p99_degradation, 4)
            if p99_degradation else None,
            extra={"ingest": {
                "clients": CLIENTS,
                "rows_initial": ROWS,
                "append_rows_per_file": APPEND_ROWS,
                "lap_seconds": LAP_SECONDS,
                "quiet": quiet,
                "sweep": sweep,
                "committed_rate": committed,
                "p99_degradation_x": round(p99_degradation, 4)
                if p99_degradation else None,
                "segcache": committed["segcache"],
                "chaos": chaos,
                "freshness": freshness,
            }},
        )
        doc["link_probe"] = link_probe()
        print(json.dumps(doc))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
