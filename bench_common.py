"""Shared bench utilities: link-health probe + timing helpers.

The tunneled device link this rig benches over is SHARED and wobbles ~2x
by time of day (round-4 committed artifact hit a degraded window; its
own pandas lane swung 40-60% same-day). Every artifact therefore
carries a `link_probe` — raw device_put bandwidth + scalar-fetch sync
latency, median of N — so a regression in a committed number can be
attributed to code vs link after the fact, and per-phase timings report
median alongside best.
"""

import statistics
import sys
import time

import numpy as np

PROBE_RUNS = 5
# H2D probed at several buffer sizes: a single mid-size probe conflates
# per-transfer latency with stream bandwidth (the r05 artifact's
# "17 MB/s" was a small-buffer latency artifact — ~2s of per-put
# overhead dwarfing a 32 MB payload, not a 17 MB/s wire). Per-size
# MB/s + the sync-latency floor reported separately let a reader
# decompose the two. Fewer runs at the big sizes keep the probe's
# wall bounded on a slow link.
PROBE_SIZES_BYTES = (1 * 1024 * 1024, 16 * 1024 * 1024, 128 * 1024 * 1024)
PROBE_RUNS_BY_SIZE = (5, 3, 2)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _size_label(nbytes: int) -> str:
    return f"{nbytes // (1 << 20)}MiB"


def link_probe(runs: int = PROBE_RUNS) -> dict:
    """Raw-link health: host->device bandwidth probed at EACH size in
    `PROBE_SIZES_BYTES` (median of a few synced raw `device_put`s per
    size — deliberately bypassing the transfer engine: this measures
    the wire, not the pipeline) plus the sync round-trip latency floor
    (fetch of an already-computed device scalar, median of `runs`).
    Runs against whatever backend jax resolves (the real chip under the
    driver; CPU locally) — the artifact records which. The headline
    `h2d_mb_s` is the LARGEST-buffer bandwidth, where per-put latency
    amortizes away."""
    import jax

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)

    bump = jax.jit(lambda x: x + 1.0)
    small = jax.device_put(np.float32(1.0), dev)
    float(bump(small))  # warm compile
    jax.device_put(rng.random(1024).astype(np.float32),
                   dev).block_until_ready()  # warm the put path

    sync_s = []
    for _ in range(runs):
        # One jitted dispatch + device->host scalar fetch: the cost every
        # output-sizing sync in query execution pays.
        t0 = time.perf_counter()
        small = bump(small)
        float(small)
        sync_s.append(time.perf_counter() - t0)

    by_size = {}
    h2d_s_by_size = {}
    for nbytes, n_runs in zip(PROBE_SIZES_BYTES, PROBE_RUNS_BY_SIZE):
        # DISTINCT payloads per trial: a repeated put of the same host
        # array can hit client-side caching and under-report.
        payloads = [rng.random(nbytes // 4).astype(np.float32)
                    for _ in range(n_runs)]
        times = []
        for payload in payloads:
            t0 = time.perf_counter()
            jax.device_put(payload, dev).block_until_ready()
            times.append(time.perf_counter() - t0)
        label = _size_label(nbytes)
        by_size[label] = round(nbytes / (1 << 20)
                               / statistics.median(times), 1)
        h2d_s_by_size[label] = [round(x, 4) for x in times]

    largest = _size_label(PROBE_SIZES_BYTES[-1])
    probe = {
        "platform": dev.platform,
        "h2d_mb_s": by_size[largest],
        "h2d_mb_s_by_size": by_size,
        "sync_latency_s": round(statistics.median(sync_s), 4),
        "h2d_s_by_size": h2d_s_by_size,
        "sync_s_all": [round(x, 4) for x in sync_s],
    }
    per_size = ", ".join(f"{k} {v} MB/s" for k, v in by_size.items())
    log(f"link probe: h2d [{per_size}], "
        f"{probe['sync_latency_s'] * 1e3:.1f} ms sync floor "
        f"({dev.platform})")
    return probe


def transfer_summary() -> dict:
    """Ladder-lifetime digest of the pipelined transfer engine's link
    counters (process registry) — embedded by both bench drivers so the
    overlap the engine claims is a committed number, not an assumption.
    The schema authority is `telemetry.artifact.transfer_digest`; this
    is the bench-side alias (kept for stderr logging before the final
    artifact assembly)."""
    from hyperspace_tpu.telemetry import artifact

    return artifact.transfer_digest()


def timed_runs(fn, runs: int, label: str = ""):
    """Run `fn` `runs` times; returns (best_s, median_s, last_output).
    Medians ride next to best in every artifact so a lucky single run
    can't carry a headline."""
    times = []
    out = None
    for i in range(runs):
        t0 = time.perf_counter()
        out = fn()
        elapsed = time.perf_counter() - t0
        log(f"  {label} run {i}: {elapsed:.3f}s")
        times.append(elapsed)
    return min(times), statistics.median(times), out
