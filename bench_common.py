"""Shared bench utilities: link-health probe + timing helpers.

The tunneled device link this rig benches over is SHARED and wobbles ~2x
by time of day (round-4 committed artifact hit a degraded window; its
own pandas lane swung 40-60% same-day). Every artifact therefore
carries a `link_probe` — raw device_put bandwidth + scalar-fetch sync
latency, median of N — so a regression in a committed number can be
attributed to code vs link after the fact, and per-phase timings report
median alongside best.
"""

import statistics
import sys
import time

import numpy as np

PROBE_RUNS = 5
PROBE_BYTES = 32 * 1024 * 1024


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def link_probe(runs: int = PROBE_RUNS) -> dict:
    """Median raw-link health over `runs` trials: host->device bandwidth
    (one `device_put` of 32 MB float32, synced) and sync round-trip
    latency (fetch of an already-computed device scalar). Runs against
    whatever backend jax resolves (the real chip under the driver; CPU
    locally) — the artifact records which."""
    import jax

    dev = jax.devices()[0]
    # DISTINCT payloads per trial: a repeated put of the same host array
    # can hit client-side caching and under-report.
    rng = np.random.default_rng(0)
    payloads = [rng.random(PROBE_BYTES // 4).astype(np.float32)
                for _ in range(runs)]
    jax.device_put(payloads[0], dev).block_until_ready()  # warm the path

    bump = jax.jit(lambda x: x + 1.0)
    small = jax.device_put(np.float32(1.0), dev)
    float(bump(small))  # warm compile
    h2d_s, sync_s = [], []
    for i in range(runs):
        t0 = time.perf_counter()
        jax.device_put(payloads[i], dev).block_until_ready()
        h2d_s.append(time.perf_counter() - t0)
        # One jitted dispatch + device->host scalar fetch: the cost every
        # output-sizing sync in query execution pays.
        t0 = time.perf_counter()
        small = bump(small)
        float(small)
        sync_s.append(time.perf_counter() - t0)
    probe = {
        "platform": dev.platform,
        "h2d_mb_s": round(PROBE_BYTES / (1 << 20) / statistics.median(h2d_s),
                          1),
        "sync_latency_s": round(statistics.median(sync_s), 4),
        "h2d_s_all": [round(x, 4) for x in h2d_s],
        "sync_s_all": [round(x, 4) for x in sync_s],
    }
    log(f"link probe: {probe['h2d_mb_s']} MB/s h2d, "
        f"{probe['sync_latency_s'] * 1e3:.1f} ms sync "
        f"({dev.platform})")
    return probe


def timed_runs(fn, runs: int, label: str = ""):
    """Run `fn` `runs` times; returns (best_s, median_s, last_output).
    Medians ride next to best in every artifact so a lucky single run
    can't carry a headline."""
    times = []
    out = None
    for i in range(runs):
        t0 = time.perf_counter()
        out = fn()
        elapsed = time.perf_counter() - t0
        log(f"  {label} run {i}: {elapsed:.3f}s")
        times.append(elapsed)
    return min(times), statistics.median(times), out
