"""Benchmark ladder (BASELINE.md configs 1-4).

Rungs measured warm, best-of-N, each against the fastest commodity
single-node CPU comparator available here (vectorized numpy/pyarrow/pandas
— the reference publishes no numbers, BASELINE.md):

  1. covering-index build (hash-partition + bucket sort + bucketed parquet)
  2. multi-column filter query served by FilterIndexRule (incl. included cols)
  3. two-table equi-join served by JoinIndexRule's bucketed SMJ
  4. hybrid scan: index + appended source files (no refresh)

Prints exactly ONE JSON line on stdout — the north-star metric
(covering_index_build_rows_per_sec_chip) with per-rung detail nested under
"rungs". Diagnostics go to stderr.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# 4M rows: a realistic lake-partition scale where every rung's ratio is
# stable (device sort and pruned reads scale better than the host
# comparators — at 4M all four rungs beat the baseline on a v5e chip).
N_ROWS = int(os.environ.get("BENCH_ROWS", 4_000_000))
N_RIGHT = int(os.environ.get("BENCH_RIGHT_ROWS", max(N_ROWS // 10, 1)))
NUM_BUCKETS = int(os.environ.get("BENCH_BUCKETS", 64))
WARM_RUNS = int(os.environ.get("BENCH_WARM_RUNS", 5))


from bench_common import link_probe, log  # noqa: E402

# label -> median seconds over the warm runs; rides in the artifact next
# to the best-of numbers so a lucky run can't carry a headline.
MEDIANS = {}


def best_of(fn, runs=WARM_RUNS, label=""):
    from bench_common import timed_runs
    best, median, out = timed_runs(fn, runs, label)
    del out
    if label:
        MEDIANS[label] = round(median, 4)
    return best


def fmix32(h):
    h = h ^ (h >> np.uint32(16))
    h = (h * np.uint32(0x85EBCA6B))
    h = h ^ (h >> np.uint32(13))
    h = (h * np.uint32(0xC2B2AE35))
    return h ^ (h >> np.uint32(16))


def cpu_bucket_ids(key, num_buckets):
    hi = (key >> 32).astype(np.uint32)
    lo = (key & 0xFFFFFFFF).astype(np.uint32)
    h1, h2 = fmix32(hi), fmix32(lo)
    h = h1 ^ (h2 + np.uint32(0x9E3779B9) + (h1 << np.uint32(6))
              + (h1 >> np.uint32(2)))
    return (h % np.uint32(num_buckets)).astype(np.int32)


def make_tables():
    import pyarrow as pa
    rng = np.random.default_rng(42)
    left = pa.table({
        "key": rng.integers(0, N_ROWS // 4, N_ROWS).astype(np.int64),
        "k2": rng.integers(0, 100, N_ROWS).astype(np.int64),
        "id": np.arange(N_ROWS, dtype=np.int64),
        "score": rng.random(N_ROWS).astype(np.float64),
    })
    right = pa.table({
        "key": rng.integers(0, N_ROWS // 4, N_RIGHT).astype(np.int64),
        "val": rng.random(N_RIGHT).astype(np.float64),
    })
    return left, right


# ---------------------------------------------------------------------------
# Rung 1 — covering-index build
# ---------------------------------------------------------------------------


def cpu_build(table, out_dir):
    """Same pipeline, vectorized numpy + pyarrow on host."""
    import pyarrow.parquet as pq

    key = table.column("key").to_numpy()
    bucket = cpu_bucket_ids(key, NUM_BUCKETS)
    order = np.lexsort((key, bucket))
    sorted_table = table.take(order)
    sorted_bucket = bucket[order]
    starts = np.searchsorted(sorted_bucket, np.arange(NUM_BUCKETS), "left")
    ends = np.searchsorted(sorted_bucket, np.arange(NUM_BUCKETS), "right")
    os.makedirs(out_dir, exist_ok=True)
    for b in range(NUM_BUCKETS):
        if ends[b] > starts[b]:
            pq.write_table(sorted_table.slice(int(starts[b]),
                                              int(ends[b] - starts[b])),
                           os.path.join(out_dir, f"part-{b:05d}.parquet"))


def rung1_build(table, work):
    """PRODUCT build path. Builds route by data residency
    (`io/builder._host_lane_preferred`): a host-resident source sorts in
    the native C++ radix lane — zero link traffic, link-independent cost —
    while device/mesh-resident batches keep the on-chip XLA sort. Both
    lanes are phase-timed here: the product lane's sort and write phases,
    AND the device path's key-staging (H2D), on-chip compute, and
    permutation D2H, so the artifact shows what the link would have cost
    and which part moved when the headline moves (round-3/4 reviews)."""
    import jax

    from hyperspace_tpu.io.builder import (_host_build_permutation,
                                           _stage_key_tree,
                                           write_bucketed_table)
    from hyperspace_tpu.ops.build import permutation_from_tree

    counter = [0]

    def dev():
        out = os.path.join(work, f"tpu{counter[0]}")
        counter[0] += 1
        write_bucketed_table(table, ["key"], NUM_BUCKETS, out)
        shutil.rmtree(out, ignore_errors=True)

    def cpu():
        out = os.path.join(work, f"cpu{counter[0]}")
        counter[0] += 1
        cpu_build(table, out)
        shutil.rmtree(out, ignore_errors=True)

    t0 = time.perf_counter()
    dev()
    log(f"rung1 cold build (incl. compile): {time.perf_counter() - t0:.2f}s")
    dev_s = best_of(dev, label="rung1 product")
    # Same N runs for both sides: best-of over unequal sample counts
    # favors whichever side drew more (round-3 review).
    cpu_s = best_of(cpu, label="rung1 cpu")

    # Product-lane phase: the host sort (hash + permutation). The lane
    # label IS the routing predicate's answer (`io/builder.build_lane`),
    # so the artifact can't drift from the product's actual path.
    from hyperspace_tpu.io.builder import build_lane
    lane = build_lane(table.num_rows)
    sort_s = best_of(lambda: _host_build_permutation(table, ["key"],
                                                     NUM_BUCKETS),
                     label="rung1 host-sort") if lane != "device" else None

    # Device-path phases (measured regardless of the chosen lane — this
    # is what a device-resident build pays). Key staging = H2D over the
    # link (fresh each run); compute = the bucket+sort permutation on
    # ALREADY-staged keys, synced; d2h = the permutation's trip back.
    def stage():
        tree = _stage_key_tree(table, ["key"])
        jax.block_until_ready(jax.tree_util.tree_leaves(tree))
        return tree

    stage()  # warm any lazy init
    stage_s = best_of(stage, label="rung1 key-stage(link)")
    tree = stage()

    def compute():
        chunks, starts, ends = permutation_from_tree(
            tree, ["key"], table.num_rows, NUM_BUCKETS)
        jax.block_until_ready([*chunks, starts, ends])
        return chunks

    compute()  # warm compile for this call pattern
    compute_s = best_of(compute, label="rung1 device-compute")

    def compute_and_fetch():
        # Fresh dispatch each run: jax caches an array's host copy, so
        # re-fetching the SAME chunks would time a no-op after run 0.
        # Mirror the product fetch (`_write_sorted_runs`): every
        # chunk's async D2H is issued before the first blocking
        # asarray, so the streams overlap exactly like the build's
        # permutation fetch does.
        chunks = compute()
        for c in chunks:
            if hasattr(c, "copy_to_host_async"):
                c.copy_to_host_async()
        for c in chunks:
            np.asarray(c)

    fetch_s = best_of(compute_and_fetch, label="rung1 compute+perm-d2h")
    d2h_s = max(fetch_s - compute_s, 0.0)
    return dev_s, cpu_s, stage_s, compute_s, d2h_s, sort_s, lane


def rung1_partition_kernel(table):
    """Fused Pallas partition kernel vs the two-pass jnp path, ON the
    device this bench runs against — the round-4 review asked for the
    kernel's on-chip win as a committed number, not just the
    interpret-mode bit-for-bit pin. Returns (kernel_s, jnp_s) or None
    when the backend has no Mosaic lowering (CPU runs)."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.hash_partition import bucket_ids
    from hyperspace_tpu.ops.pallas.partition_kernel import (batch_partition,
                                                            kernel_supported)

    if not kernel_supported(NUM_BUCKETS):
        log("rung1-partition: Pallas kernel unsupported on this backend; "
            "skipping")
        return None
    batch = columnar.from_arrow(table.select(["key"]))

    def kernel():
        ids, lengths = batch_partition(batch, ["key"], NUM_BUCKETS)
        jax.block_until_ready([ids, lengths])

    def two_pass():
        ids = bucket_ids(batch, ["key"], NUM_BUCKETS)
        lengths = jax.ops.segment_sum(
            jnp.ones(batch.num_rows, dtype=jnp.int32), ids,
            num_segments=NUM_BUCKETS)
        jax.block_until_ready([ids, lengths])

    kernel()  # compile
    two_pass()
    kernel_s = best_of(kernel, label="rung1 partition-kernel")
    jnp_s = best_of(two_pass, label="rung1 partition-jnp")
    log(f"rung1-partition: kernel {kernel_s:.4f}s vs jnp two-pass "
        f"{jnp_s:.4f}s (x{jnp_s / kernel_s:.2f})")
    return kernel_s, jnp_s


# ---------------------------------------------------------------------------
# Session fixture for the query rungs
# ---------------------------------------------------------------------------


def make_session(work):
    from hyperspace_tpu import HyperspaceConf, HyperspaceSession
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": os.path.join(work, "wh"),
        "spark.hyperspace.index.num.buckets": str(NUM_BUCKETS),
    })
    return HyperspaceSession(conf)


# ---------------------------------------------------------------------------
# Rung 2 — multi-column filter query via FilterIndexRule
# ---------------------------------------------------------------------------


def rung2_filter(sess, hs, ldf, left, work):
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.plan.expr import col, lit
    import pyarrow.parquet as pq

    # Bucket by `key` only: the k2 range term can then still be served
    # (included column) while the key equality prunes the read to one
    # bucket. Bucketing by both would defeat pruning for range predicates.
    hs.create_index(ldf, IndexConfig("bench_filter_idx", ["key"],
                                     ["k2", "id", "score"]))
    key_hit = int(left.column("key")[0].as_py())

    def q():
        return (ldf.filter((col("key") == lit(key_hit)) & (col("k2") < lit(50)))
                .select("id", "score").collect())

    sess.enable_hyperspace()
    plan = (ldf.filter((col("key") == lit(key_hit)) & (col("k2") < lit(50)))
            .select("id", "score"))._optimized_plan()
    roots = [p for s in plan.collect_leaves() for p in s.root_paths]
    assert any("v__=" in p for p in roots), f"rung2 not index-served: {roots}"
    q()  # warm compile
    dev_s = best_of(q, label="rung2 device")
    # Operator-level telemetry of the last timed run rides in the
    # artifact (collect always records onto the session); the full
    # QueryMetrics goes back to main so the artifact can embed BOTH
    # the summary digest and the diff-alignable operator tree.
    qm = sess.last_query_metrics()
    sess.disable_hyperspace()

    src_files = sorted(
        os.path.join(work, "left", f) for f in os.listdir(
            os.path.join(work, "left")))

    def cpu():
        t = pq.read_table(src_files, columns=["key", "k2", "id", "score"])
        key = t.column("key").to_numpy()
        k2 = t.column("k2").to_numpy()
        mask = (key == key_hit) & (k2 < 50)
        return t.select(["id", "score"]).take(np.nonzero(mask)[0])

    cpu_s = best_of(cpu, label="rung2 cpu")
    return dev_s, cpu_s, qm


# ---------------------------------------------------------------------------
# Rung 3 — two-table bucketed SMJ via JoinIndexRule
# ---------------------------------------------------------------------------


def rung3_join(sess, hs, ldf, rdf, work):
    from hyperspace_tpu import IndexConfig
    import pyarrow.parquet as pq

    hs.create_index(ldf, IndexConfig("bench_join_l", ["key"], ["id"]))
    hs.create_index(rdf, IndexConfig("bench_join_r", ["key"], ["val"]))

    def q():
        return (ldf.select("key", "id").join(rdf.select("key", "val"),
                                             on="key")
                .select("id", "val").collect())

    sess.enable_hyperspace()
    plan = (ldf.select("key", "id").join(rdf.select("key", "val"), on="key")
            .select("id", "val"))._optimized_plan()
    scans = plan.collect_leaves()
    assert all(s.bucket_spec is not None for s in scans), "rung3 not bucketed"
    q()
    dev_s = best_of(q, label="rung3 device")
    qm = sess.last_query_metrics()
    sess.disable_hyperspace()

    lfiles = [os.path.join(work, "left", f)
              for f in os.listdir(os.path.join(work, "left"))]
    rfiles = [os.path.join(work, "right", f)
              for f in os.listdir(os.path.join(work, "right"))]

    def cpu():
        import pandas as pd
        lt = pq.read_table(lfiles, columns=["key", "id"]).to_pandas()
        rt = pq.read_table(rfiles, columns=["key", "val"]).to_pandas()
        return lt.merge(rt, on="key")[["id", "val"]]

    cpu_s = best_of(cpu, label="rung3 cpu")
    return dev_s, cpu_s, qm


# ---------------------------------------------------------------------------
# Rung 4 — hybrid scan (index + appended files)
# ---------------------------------------------------------------------------


def rung4_hybrid(sess, hs, left, work):
    import pyarrow as pa
    import pyarrow.parquet as pq
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.plan.expr import col, lit

    # Fresh source dir so the appended files don't disturb rungs 2/3.
    hdir = os.path.join(work, "hybrid")
    os.makedirs(hdir)
    pq.write_table(left, os.path.join(hdir, "part-0.parquet"))
    hdf = sess.read_parquet(hdir)
    hs.create_index(hdf, IndexConfig("bench_hybrid_idx", ["key"],
                                     ["id", "score"]))
    # Append ~5% new rows AFTER the index build.
    rng = np.random.default_rng(7)
    n_app = max(N_ROWS // 20, 1)
    appended = pa.table({
        "key": rng.integers(0, N_ROWS // 4, n_app).astype(np.int64),
        "k2": rng.integers(0, 100, n_app).astype(np.int64),
        "id": np.arange(N_ROWS, N_ROWS + n_app, dtype=np.int64),
        "score": rng.random(n_app).astype(np.float64),
    })
    pq.write_table(appended, os.path.join(hdir, "part-1.parquet"))
    sess.conf.set("hyperspace.index.hybridscan.enabled", "true")

    key_hit = int(left.column("key")[0].as_py())
    hdf = sess.read_parquet(hdir)  # re-list: new files

    def q():
        return (hdf.filter(col("key") == lit(key_hit))
                .select("id", "score").collect())

    sess.enable_hyperspace()
    plan = (hdf.filter(col("key") == lit(key_hit))
            .select("id", "score"))._optimized_plan()
    from hyperspace_tpu.plan.nodes import Union as UnionNode
    found_union = [False]

    def _see(node):
        if isinstance(node, UnionNode):
            found_union[0] = True
        return node

    plan.transform_up(_see)
    assert found_union[0], "rung4 not hybrid-served (no Union in plan)"
    q()
    dev_s = best_of(q, label="rung4 device")
    qm = sess.last_query_metrics()
    sess.disable_hyperspace()

    files = sorted(os.path.join(hdir, f) for f in os.listdir(hdir))

    def cpu():
        t = pq.read_table(files, columns=["key", "id", "score"])
        key = t.column("key").to_numpy()
        mask = key == key_hit
        return t.select(["id", "score"]).take(np.nonzero(mask)[0])

    cpu_s = best_of(cpu, label="rung4 cpu")
    return dev_s, cpu_s, qm


# ---------------------------------------------------------------------------
# Rung 4b — hybrid JOIN: left side served from index UNION appended files
# ---------------------------------------------------------------------------


def rung4b_hybrid_join(sess, hs, rdf, work):
    import pyarrow.parquet as pq
    from hyperspace_tpu.plan.expr import col

    # The hybrid dir (rung 4) already has: an index built over part-0 and
    # an appended part-1. Join it against the rung-3 right index.
    hdir = os.path.join(work, "hybrid")
    hdf = sess.read_parquet(hdir)
    q_df = (hdf.select("key", "id")
            .join(rdf.select("key", "val"),
                  on=col("key") == col("key")).select("id", "val"))

    sess.enable_hyperspace()
    plan = q_df._optimized_plan()
    from hyperspace_tpu.plan.nodes import Union as UnionNode
    found_union = [False]

    def _see(node):
        if isinstance(node, UnionNode):
            found_union[0] = True
        return node

    plan.transform_up(_see)
    assert found_union[0], "rung4b left side not hybrid-served"

    def q():
        return q_df.collect()

    q()
    dev_s = best_of(q, label="rung4b device")
    qm = sess.last_query_metrics()
    sess.disable_hyperspace()

    lfiles = sorted(os.path.join(hdir, f) for f in os.listdir(hdir))
    rfiles = [os.path.join(work, "right", f)
              for f in os.listdir(os.path.join(work, "right"))]

    def cpu():
        lt = pq.read_table(lfiles, columns=["key", "id"]).to_pandas()
        rt = pq.read_table(rfiles, columns=["key", "val"]).to_pandas()
        return lt.merge(rt, on="key")[["id", "val"]]

    cpu_s = best_of(cpu, label="rung4b cpu")
    return dev_s, cpu_s, qm


# ---------------------------------------------------------------------------
# Steady-state repeat-query phase — the segment-cache acceptance bar
# ---------------------------------------------------------------------------


def warm_repeat_phase(sess, left, ldf, rdf, work):
    """Re-run rungs 2/3/4's queries cold (full cache clear first — the
    fill cost) and then steady-state warm, with the DEVICE lane forced
    (`min.device.rows=0`): this is the serving scenario the segment
    cache exists for — index segments resident in HBM. The warm runs
    must be LINK-FREE: every scanned segment hits the segment cache
    (`io/segcache.py`), so `link.h2d.chunks` must not move — the
    binary acceptance bar this phase commits per round, and what
    `bench_regress.py`'s warm-rung gate enforces. (The rung 2/3/4
    best-of numbers above keep the default adaptive lane and stay
    comparable to earlier rounds.)"""
    from hyperspace_tpu import telemetry
    from hyperspace_tpu.io.parquet import clear_read_cache
    from hyperspace_tpu.plan.expr import col, lit

    key_hit = int(left.column("key")[0].as_py())
    saved_min_rows = sess.conf.get(
        "spark.hyperspace.execution.min.device.rows")
    sess.conf.set("spark.hyperspace.execution.min.device.rows", "0")
    hdf = sess.read_parquet(os.path.join(work, "hybrid"))
    queries = {
        "2_filter_query": lambda: (
            ldf.filter((col("key") == lit(key_hit)) & (col("k2") < lit(50)))
            .select("id", "score").collect()),
        "3_bucketed_smj": lambda: (
            ldf.select("key", "id").join(rdf.select("key", "val"),
                                         on="key")
            .select("id", "val").collect()),
        "4_hybrid_scan": lambda: (
            hdf.filter(col("key") == lit(key_hit))
            .select("id", "score").collect()),
    }
    sess.enable_hyperspace()
    reg = telemetry.get_registry()
    out = {}
    try:
        for name, q in queries.items():
            clear_read_cache()  # cold start: decode + stage from scratch
            c0 = reg.counter("link.h2d.chunks").value
            t0 = time.perf_counter()
            q()
            cold_s = time.perf_counter() - t0
            cold_chunks = int(reg.counter("link.h2d.chunks").value - c0)
            q()  # settle jit/fusion caches so the measured run is steady
            h0 = reg.counter("link.h2d.chunks").value
            hits0 = reg.counter("cache.segments.hits").value
            t0 = time.perf_counter()
            q()
            warm_s = time.perf_counter() - t0
            out[name] = {
                "cold_s": round(cold_s, 4),
                "warm_s": round(warm_s, 4),
                "cold_h2d_chunks": cold_chunks,
                "h2d_chunks": int(reg.counter("link.h2d.chunks").value
                                  - h0),
                "segment_hits": int(
                    reg.counter("cache.segments.hits").value - hits0),
            }
            log(f"warm {name}: cold {cold_s:.3f}s ({cold_chunks} h2d "
                f"chunks) -> warm {warm_s:.3f}s "
                f"({out[name]['h2d_chunks']} h2d chunks, "
                f"{out[name]['segment_hits']} segment hits)")
    finally:
        sess.disable_hyperspace()
        if saved_min_rows is None:
            sess.conf.unset("spark.hyperspace.execution.min.device.rows")
        else:
            sess.conf.set("spark.hyperspace.execution.min.device.rows",
                          saved_min_rows)
    return out


# ---------------------------------------------------------------------------
# Rung 5 — Optimize merge-compaction vs full refresh
# ---------------------------------------------------------------------------


def rung5_compaction(sess, hs, work):
    """Index maintenance after appends: incremental refresh (delta-only
    build) + Optimize merge-compaction, against a full refresh of the
    grown source. Every timed run starts COLD-CACHE (maintenance reads
    fresh files in production), and each timed optimize compacts a
    genuinely multi-run version (an untimed append+incremental precedes
    it)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.io.parquet import clear_read_cache

    cdir = os.path.join(work, "compact_src")
    os.makedirs(cdir)
    rng = np.random.default_rng(13)
    n = max(N_ROWS // 2, 1000)
    pq.write_table(pa.table({
        "key": rng.integers(0, n // 4, n).astype(np.int64),
        "score": rng.random(n).astype(np.float64),
    }), os.path.join(cdir, "part-0.parquet"))
    cdf = sess.read_parquet(cdir)
    hs.create_index(cdf, IndexConfig("bench_opt", ["key"], ["score"]))
    slice_no = [0]

    def append_slice():
        i = slice_no[0]
        slice_no[0] += 1
        pq.write_table(pa.table({
            "key": rng.integers(0, n // 4, n // 20).astype(np.int64),
            "score": rng.random(n // 20).astype(np.float64),
        }), os.path.join(cdir, f"part-extra{i}.parquet"))

    inc_s = float("inf")
    opt_s = float("inf")
    for i in range(3):
        append_slice()
        clear_read_cache()
        t0 = time.perf_counter()
        hs.refresh_index("bench_opt", mode="incremental")
        dt = time.perf_counter() - t0
        log(f"  rung5 incremental refresh run {i}: {dt:.3f}s")
        inc_s = min(inc_s, dt)
        clear_read_cache()
        t0 = time.perf_counter()
        hs.optimize_index("bench_opt")
        dt = time.perf_counter() - t0
        log(f"  rung5 optimize run {i}: {dt:.3f}s")
        opt_s = min(opt_s, dt)

    full_s = float("inf")
    for i in range(2):
        clear_read_cache()
        t0 = time.perf_counter()
        hs.refresh_index("bench_opt", mode="full")
        dt = time.perf_counter() - t0
        log(f"  rung5 full refresh run {i}: {dt:.3f}s")
        full_s = min(full_s, dt)
    return inc_s, opt_s, full_s


# ---------------------------------------------------------------------------
# Rung 5b — data-skipping index: pruned vs unpruned selective scans
# ---------------------------------------------------------------------------


def rung_skipping(sess, hs, work):
    """Data-skipping pruning at three selectivities (point / ~1% range /
    ~25% range) over a 16-file key-clustered source: the SAME query with
    sketches consulted (hyperspace on) vs the raw multi-file scan
    (hyperspace off), results asserted bit-identical. Reports walls,
    files/bytes pruned (from the query's own skipping counters), and
    the admission-side footprint credit the pruned plan earns."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from hyperspace_tpu import telemetry
    from hyperspace_tpu.index.index_config import DataSkippingIndexConfig
    from hyperspace_tpu.io.parquet import clear_read_cache
    from hyperspace_tpu.plan.expr import col, lit

    sdir = os.path.join(work, "skip_src")
    os.makedirs(sdir)
    rng = np.random.default_rng(21)
    n_files = 16
    per = max(N_ROWS // n_files, 1)
    for i in range(n_files):
        # Key-clustered files: zones are tight, so range predicates
        # refute whole files (the layout a date/id-partitioned lake
        # naturally has).
        keys = np.arange(i * per, (i + 1) * per, dtype=np.int64)
        pq.write_table(pa.table({
            "key": keys,
            "k2": rng.integers(0, 100, per).astype(np.int64),
            "score": rng.random(per).astype(np.float64),
        }), os.path.join(sdir, f"part-x{i:02d}.parquet"))
    total_rows = per * n_files
    sdf = sess.read_parquet(sdir)
    t0 = time.perf_counter()
    hs.create_index(sdf, DataSkippingIndexConfig("bench_skip", ["key"]))
    build_s = time.perf_counter() - t0

    point = total_rows // 2
    preds = {
        "point": col("key") == lit(point),
        "narrow_1pct": (col("key") >= lit(point))
        & (col("key") < lit(point + total_rows // 100)),
        "broad_25pct": (col("key") >= lit(point))
        & (col("key") < lit(point + total_rows // 4)),
    }
    reg = telemetry.get_registry()
    out = {}
    files_pruned_point = 0
    bytes_pruned_point = 0
    for name, pred in preds.items():
        q_df = sdf.filter(pred).select("key", "score")
        sess.enable_hyperspace()
        clear_read_cache()
        credit0 = reg.counter("serve.footprint_credit_bytes").value
        t_pruned, m = q_df.collect(with_metrics=True)
        credit = int(reg.counter("serve.footprint_credit_bytes").value
                     - credit0)

        # COLD-cache timing on both sides: data skipping's win is the
        # first-touch read (files never decoded, bytes never staged);
        # warm repeats are the segment/host caches' story, measured by
        # the warm phase below.
        def cold(run):
            clear_read_cache()
            return run()

        pruned_s = best_of(lambda: cold(q_df.collect),
                           label=f"skip {name} pruned")
        files_pruned = int(m.counters.get("skipping.files_pruned", 0))
        bytes_pruned = int(m.counters.get("skipping.bytes_pruned", 0))
        sess.disable_hyperspace()
        t_plain = q_df.collect()
        plain_s = best_of(lambda: cold(q_df.collect),
                          label=f"skip {name} unpruned")
        order = [("key", "ascending"), ("score", "ascending")]
        assert t_pruned.sort_by(order).equals(t_plain.sort_by(order)), \
            f"rung5b {name}: pruned result differs from unpruned"
        if name == "point":
            files_pruned_point = files_pruned
            bytes_pruned_point = bytes_pruned
        out[name] = {
            "pruned_s": round(pruned_s, 4),
            "unpruned_s": round(plain_s, 4),
            "speedup": round(plain_s / pruned_s, 3),
            "files_pruned": files_pruned,
            "files_total": n_files,
            "bytes_pruned": bytes_pruned,
            "footprint_credit_bytes": credit,
            "rows_out": t_pruned.num_rows,
        }
        log(f"rung5b {name}: pruned {pruned_s:.3f}s vs unpruned "
            f"{plain_s:.3f}s (x{plain_s / pruned_s:.2f}; "
            f"{files_pruned}/{n_files} files pruned, credit "
            f"{credit / 1e6:.1f} MB)")
    return build_s, out, files_pruned_point, bytes_pruned_point


def main():
    work = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        import jax
        log(f"devices: {jax.devices()}")
        import pyarrow.parquet as pq
        from hyperspace_tpu import telemetry
        # Span tracing across the whole ladder: queries, operators,
        # fusion stages, maintenance actions, and link transfers on
        # their real threads. Exported when BENCH_TRACE_OUT names a
        # path; the bounded ring costs nothing measurable either way.
        telemetry.enable_tracing()
        probe = link_probe()
        left, right = make_tables()
        os.makedirs(os.path.join(work, "left"))
        os.makedirs(os.path.join(work, "right"))
        pq.write_table(left, os.path.join(work, "left", "part-0.parquet"))
        pq.write_table(right, os.path.join(work, "right", "part-0.parquet"))

        dev1, cpu1, stage1, compute1, d2h1, sort1, lane1 = \
            rung1_build(left, work)
        part = rung1_partition_kernel(left)
        rate1 = N_ROWS / dev1
        # Product-lane write phase (gather + parquet encode) = end-to-end
        # minus the sort phase; on the native lane nothing touches the
        # link, so this split is exact rather than a residual.
        write1 = max(dev1 - sort1, 0.0) if sort1 is not None else None
        log(f"rung1 [{lane1}]: build {dev1:.3f}s"
            + (f" (sort {sort1:.3f}s, write {write1:.3f}s)"
               if sort1 is not None else "")
            + f" vs cpu {cpu1:.3f}s ({rate1:,.0f} rows/s, "
              f"x{cpu1 / dev1:.2f}); device path would pay: key-stage "
              f"{stage1:.3f}s + compute {compute1:.3f}s + perm-d2h "
              f"{d2h1:.3f}s")

        sess = make_session(work)
        from hyperspace_tpu import Hyperspace
        hs = Hyperspace(sess)
        ldf = sess.read_parquet(os.path.join(work, "left"))
        rdf = sess.read_parquet(os.path.join(work, "right"))

        dev2, cpu2, met2 = rung2_filter(sess, hs, ldf, left, work)
        log(f"rung2: device {dev2:.3f}s vs cpu {cpu2:.3f}s (x{cpu2 / dev2:.2f})")
        dev3, cpu3, met3 = rung3_join(sess, hs, ldf, rdf, work)
        log(f"rung3: device {dev3:.3f}s vs cpu {cpu3:.3f}s (x{cpu3 / dev3:.2f})")
        dev4, cpu4, met4 = rung4_hybrid(sess, hs, left, work)
        log(f"rung4: device {dev4:.3f}s vs cpu {cpu4:.3f}s (x{cpu4 / dev4:.2f})")
        dev4b, cpu4b, met4b = rung4b_hybrid_join(sess, hs, rdf, work)
        log(f"rung4b: device {dev4b:.3f}s vs cpu {cpu4b:.3f}s "
            f"(x{cpu4b / dev4b:.2f})")
        inc5, opt5, full5 = rung5_compaction(sess, hs, work)
        log(f"rung5: incremental {inc5:.3f}s, optimize {opt5:.3f}s vs "
            f"full refresh {full5:.3f}s (optimize x{full5 / opt5:.2f}, "
            f"incremental x{full5 / inc5:.2f})")
        skip_build, skip_sel, skip_files, skip_bytes = \
            rung_skipping(sess, hs, work)
        warm = warm_repeat_phase(sess, left, ldf, rdf, work)

        rungs = {
                "1_build": {"build_s": round(dev1, 3),
                            "lane": lane1,
                            "sort_s": (round(sort1, 3)
                                       if sort1 is not None else None),
                            "write_s": (round(write1, 3)
                                        if write1 is not None else None),
                            "device_path": {
                                "key_stage_link_s": round(stage1, 3),
                                "device_compute_s": round(compute1, 3),
                                "perm_d2h_link_s": round(d2h1, 3),
                                "device_compute_rows_per_sec": round(
                                    N_ROWS / compute1, 1)},
                            "cpu_s": round(cpu1, 3),
                            "partition_kernel_s": (round(part[0], 4)
                                                   if part else None),
                            "partition_jnp_s": (round(part[1], 4)
                                                if part else None),
                            "vs_baseline": round(cpu1 / dev1, 3)},
                "2_filter_query": {"device_s": round(dev2, 3),
                                   "cpu_s": round(cpu2, 3),
                                   "vs_baseline": round(cpu2 / dev2, 3),
                                   **telemetry.artifact
                                   .query_metrics_block(met2)},
                "3_bucketed_smj": {"device_s": round(dev3, 3),
                                   "cpu_s": round(cpu3, 3),
                                   "vs_baseline": round(cpu3 / dev3, 3),
                                   **telemetry.artifact
                                   .query_metrics_block(met3)},
                "4_hybrid_scan": {"device_s": round(dev4, 3),
                                  "cpu_s": round(cpu4, 3),
                                  "vs_baseline": round(cpu4 / dev4, 3),
                                  **telemetry.artifact
                                  .query_metrics_block(met4)},
                "4b_hybrid_join": {"device_s": round(dev4b, 3),
                                   "cpu_s": round(cpu4b, 3),
                                   "vs_baseline": round(cpu4b / dev4b, 3),
                                   **telemetry.artifact
                                   .query_metrics_block(met4b)},
                "5_compaction": {"incremental_refresh_s": round(inc5, 3),
                                 "optimize_s": round(opt5, 3),
                                 "full_refresh_s": round(full5, 3),
                                 "vs_baseline": round(full5 / opt5, 3),
                                 "incremental_vs_full": round(
                                     full5 / inc5, 3)},
                # Selective predicates with ONLY a skipping index
                # available: pruned-vs-unpruned wall + bytes at three
                # selectivities; vs_baseline is the point query's
                # speedup. bench_regress.py additionally gates
                # files_pruned > 0 absolutely (the acceptance bar: a
                # selective query must read strictly fewer files).
                "5_data_skipping": {
                    "build_s": round(skip_build, 3),
                    "selectivities": skip_sel,
                    "files_pruned": skip_files,
                    "bytes_pruned": skip_bytes,
                    "vs_baseline": skip_sel["point"]["speedup"]},
        }
        # Canonical, versioned artifact (telemetry/artifact.py): the
        # emitter attaches the transfer digest, the process-lifetime
        # counter aggregates, and the memory/cache/compile section —
        # no committed round can miss the telemetry the regression
        # differ attributes from. bench_regress.py gates rung ratios,
        # peak HBM, and the rung-1 link share from this shape.
        result = telemetry.artifact.make_artifact(
            driver="bench.py",
            metric="covering_index_build_rows_per_sec_chip",
            value=round(rate1, 1),
            unit="rows/s",
            vs_baseline=round(cpu1 / dev1, 3),
            rungs=rungs,
            extra={"link_probe": probe,
                   "phase_medians_s": dict(MEDIANS),
                   "segments": {**telemetry.artifact.segments_digest(),
                                "warm": warm}})
        xfer = result["transfer"]
        log(f"transfer: h2d {xfer['h2d_bytes'] / 1e6:.1f} MB in "
            f"{xfer['h2d_chunks']} chunks / {xfer['h2d_transfers']} "
            f"transfers, d2h {xfer['d2h_bytes'] / 1e6:.1f} MB in "
            f"{xfer['d2h_chunks']} chunks, overlap saved "
            f"{xfer['overlap_saved_seconds']:.2f}s")
        trace_out = os.environ.get("BENCH_TRACE_OUT")
        if trace_out:
            result["trace"] = telemetry.export_trace(trace_out)
            log(f"trace: {result['trace']['events']} events -> "
                f"{trace_out}")
        print(json.dumps(result))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
