"""Benchmark: CoveringIndex build rows/sec/chip (BASELINE.md north star).

Measures the warm end-to-end index build — source batch on device ->
hash-partition -> single bucket+key sort -> host transfer -> bucketed
parquet write — and compares against an equivalent vectorized CPU pipeline
(numpy hash + lexsort + pyarrow bucketed write), the fastest commodity
single-node baseline available here (the reference publishes no numbers,
BASELINE.md).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Diagnostics go to stderr.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 2_000_000))
NUM_BUCKETS = int(os.environ.get("BENCH_BUCKETS", 64))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_table():
    import pyarrow as pa
    rng = np.random.default_rng(42)
    return pa.table({
        "key": rng.integers(0, N_ROWS // 4, N_ROWS).astype(np.int64),
        "id": np.arange(N_ROWS, dtype=np.int64),
        "score": rng.random(N_ROWS).astype(np.float64),
    })


def cpu_baseline(table, out_dir):
    """Same pipeline, vectorized numpy + pyarrow on host."""
    import pyarrow.parquet as pq

    t0 = time.perf_counter()
    key = table.column("key").to_numpy()
    # murmur-style mix on 32-bit halves (same work as the device kernel)
    def fmix32(h):
        h = h ^ (h >> np.uint32(16))
        h = (h * np.uint32(0x85EBCA6B))
        h = h ^ (h >> np.uint32(13))
        h = (h * np.uint32(0xC2B2AE35))
        return h ^ (h >> np.uint32(16))
    hi = (key >> 32).astype(np.uint32)
    lo = (key & 0xFFFFFFFF).astype(np.uint32)
    h1, h2 = fmix32(hi), fmix32(lo)
    h = h1 ^ (h2 + np.uint32(0x9E3779B9) + (h1 << np.uint32(6))
              + (h1 >> np.uint32(2)))
    bucket = (h % np.uint32(NUM_BUCKETS)).astype(np.int32)
    order = np.lexsort((key, bucket))
    sorted_table = table.take(order)
    sorted_bucket = bucket[order]
    starts = np.searchsorted(sorted_bucket, np.arange(NUM_BUCKETS), "left")
    ends = np.searchsorted(sorted_bucket, np.arange(NUM_BUCKETS), "right")
    os.makedirs(out_dir, exist_ok=True)
    for b in range(NUM_BUCKETS):
        if ends[b] > starts[b]:
            pq.write_table(sorted_table.slice(int(starts[b]),
                                              int(ends[b] - starts[b])),
                           os.path.join(out_dir, f"part-{b:05d}.parquet"))
    return time.perf_counter() - t0


def device_build(table, out_dir_base):
    """The PRODUCT build path (`io/builder.write_bucketed_table` with no
    pre-staged device state): per build, the key column is staged to the
    device (narrow 32-bit lane transport when the range allows), the
    device computes the bucket+sort permutation, and the host streams
    bucket files while permutation chunks are still in flight. The
    payload never crosses the link."""
    from hyperspace_tpu.io.builder import write_bucketed_table

    import jax
    log(f"devices: {jax.devices()}")
    # Warm-up: compile the fused permutation program for this shape.
    t0 = time.perf_counter()
    write_bucketed_table(table, ["key"], NUM_BUCKETS, out_dir_base + "_warm")
    log(f"cold build (incl. compile): {time.perf_counter() - t0:.2f}s")
    shutil.rmtree(out_dir_base + "_warm", ignore_errors=True)

    best = float("inf")
    for i in range(5):
        out = f"{out_dir_base}_{i}"
        t0 = time.perf_counter()
        write_bucketed_table(table, ["key"], NUM_BUCKETS, out)
        elapsed = time.perf_counter() - t0
        log(f"warm build {i}: {elapsed:.3f}s ({N_ROWS/elapsed:,.0f} rows/s)")
        best = min(best, elapsed)
        shutil.rmtree(out, ignore_errors=True)
    return best


def main():
    work = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        table = make_table()
        cpu_s = min(cpu_baseline(table, os.path.join(work, f"cpu{i}"))
                    for i in range(2))
        cpu_rate = N_ROWS / cpu_s
        log(f"cpu baseline (best of 2): {cpu_s:.3f}s ({cpu_rate:,.0f} rows/s)")

        tpu_s = device_build(table, os.path.join(work, "tpu"))
        tpu_rate = N_ROWS / tpu_s

        print(json.dumps({
            "metric": "covering_index_build_rows_per_sec_chip",
            "value": round(tpu_rate, 1),
            "unit": "rows/s",
            "vs_baseline": round(tpu_rate / cpu_rate, 3),
        }))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
