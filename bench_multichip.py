"""Multi-chip SPMD scaling rung — the MULTICHIP artifact, grown from an
8-device smoke check into a real scaling ladder.

An SF100-*shaped* workload (store_sales / store_returns / catalog_sales
schemas and TPC-DS q17/q25/q64-shaped join+aggregate queries, row counts
scaled to the bench budget) runs the BORN-SHARDED pipeline end to end at
1 / 4 / 8 (virtual) devices:

  build     distributed all_to_all build -> per-device parquet shards
            (contiguous bucket ranges, `io/builder.write_bucket_ordered`)
  read      per-device bucket-range segment-cache fills
            (`parallel/spmd.read_sharded`) — the WARM repeat must be
            link-free per device (`link.h2d.chunks` delta == 0)
  q17       SMJ ss|><|sr + group-by aggregate, two SPMD stages with a
            device-resident intermediate
  q25       three-way: (ss|><|sr) -> ICI repartition -> |><| cs ->
            aggregate (the second join's side arrives with a DIFFERENT
            bucket count, exercising the in-program repartition)
  q64       SMJ over MISMATCHED bucket counts (64 vs 32) direct
  qstr      STRING-keyed SMJ (born-sharded per-range dictionaries,
            in-program rank remaps — PR 13) + a string-predicate
            sharded filter; reported as `string_smj_wall_s` /
            `string_smj_speedup`, gated like the numeric headline

Reported per device count: build wall, per-query cold/warm walls, the
SMJ-stage wall (the distributed claim), the warm H2D chunk delta, and
the inter-stage D2H chunk delta (must be 0 — device-resident stage flow).
Bit-identity: every query's aggregate output and exact int64 join
checksums must MATCH the 1-device run.

`vs_baseline` is the 8-device speedup of the SHUFFLE-FREE co-bucketed
SMJ stages (q17/q25) over 1 device — the paper's claim. q64's
mismatched-bucket rung is reported separately
(`repartition_smj_wall_s`): its in-program all_to_all is correctness
coverage; on virtual single-core devices the collective is emulated
serially, so its wall is not a scaling claim. NOTE the platform field:
on the container's CPU backend the devices are virtual (one core), so
the honest multi-chip claim is the RATIO discipline — per-shard sorts
of T/8 beating one sort of T and zero link traffic — not absolute
seconds (docs/round6-notes.md precedent).

Prints exactly ONE JSON line (canonical schema via
`telemetry.artifact.make_artifact`; `scripts/bench_regress.py
--multichip` gates speedup, warm link-freedom, and bit-identity).

NEW (PR 14): the SCALE-OUT grid — devices x slices x concurrent
clients. A serving-shaped workload (Zipf-skewed point joins + semi
membership, the traffic shape a hot-keyed serving plane actually sees)
runs at topologies 1x8 / 2x4 / 4x2 with 1 and 8 concurrent clients:
the flat mesh serializes every query over all 8 devices, while the
replicated topologies route each query to a replica slice
(`parallel/replica.py` least-loaded routing — the routed counts feed
the balance gate) holding the full bucket-range map at slice
granularity. Reported as `multislice`: per-cell QPS, the headline
`qps_ratio` (2x4 replicated @ 8 clients over 1x8 flat @ 8 clients —
scale-out must WIN concurrency), `replica_max_share`,
`dcn_byte_share` of the 2-axis in-program repartition, warm-fill
link-freedom, `spmd.fallbacks` delta, and cross-topology
bit-identity. Replication smooths the padded [S*C] layout too: 8
narrow ranges each pad to the hot bucket's rows where 4 merged ranges
absorb it — the skewed-traffic case is where scale-out wins even on
emulated devices.

Env knobs: MULTICHIP_ROWS (fact rows, default 1200000),
MULTICHIP_BUCKETS (default 64), MULTICHIP_DEVICES (default "1,4,8"),
MULTICHIP_GRID_CLIENTS (default "1,8").
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = int(os.environ.get("MULTICHIP_ROWS", 1_200_000))
BUCKETS = int(os.environ.get("MULTICHIP_BUCKETS", 64))
DEVICES = [int(x) for x in
           os.environ.get("MULTICHIP_DEVICES", "1,4,8").split(",")]

from hyperspace_tpu.parallel.virtual import ensure_devices  # noqa: E402

ensure_devices(max(DEVICES))

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

from bench_common import log  # noqa: E402
from hyperspace_tpu import telemetry  # noqa: E402
from hyperspace_tpu.io import columnar  # noqa: E402


def _counters(*names):
    c = telemetry.get_registry().counters_dict()
    return {n: float(c.get(n, 0)) for n in names}


def generate():
    """SF100-shaped tables (schema + key structure; row counts scaled).
    High key cardinality keeps the join sort-dominated — the regime the
    bucketed layout exists for (few matches per key, no expansion
    blow-up)."""
    rng = np.random.default_rng(17)
    n_items = max(ROWS, 1)
    ss = columnar.from_arrow(pa.table({
        "ss_item_sk": rng.integers(0, n_items, ROWS).astype(np.int64),
        "ss_ticket": np.arange(ROWS, dtype=np.int64),
        "ss_qty": rng.integers(1, 10, ROWS).astype(np.int64),
        "ss_price": rng.random(ROWS).astype(np.float64),
    }))
    m = ROWS // 2
    sr = columnar.from_arrow(pa.table({
        "sr_item_sk": rng.integers(0, n_items, m).astype(np.int64),
        "sr_qty": rng.integers(1, 5, m).astype(np.int64),
    }))
    k = ROWS // 2
    cs = columnar.from_arrow(pa.table({
        "cs_item_sk": rng.integers(0, n_items, k).astype(np.int64),
        "cs_qty": rng.integers(1, 8, k).astype(np.int64),
    }))
    # String-keyed pair (TPC-DS joins ride i_item_id-style business
    # keys): high-cardinality dictionaries so the remap tables are a
    # real workload, not a toy.
    n_ids = max(ROWS // 8, 64)
    sk = ROWS // 2
    ssk = columnar.from_arrow(pa.table({
        "ss_item_id": pa.array(
            [f"AAAA{int(x):08d}"
             for x in rng.integers(0, n_ids, sk)]),
        "ssk_qty": rng.integers(1, 10, sk).astype(np.int64),
        "ssk_price": rng.random(sk).astype(np.float64),
    }))
    im = ROWS // 4
    itm = columnar.from_arrow(pa.table({
        "i_item_id": pa.array(
            [f"AAAA{int(x):08d}"
             for x in rng.integers(0, n_ids, im)]),
        "i_qty": rng.integers(1, 6, im).astype(np.int64),
    }))
    return ss, sr, cs, ssk, itm


def agg_schema(group_col, specs, schema):
    from hyperspace_tpu.plan.nodes import Aggregate, Scan
    return Aggregate([group_col], specs, Scan(["/nx"], schema)).schema


def join_checksum(sh, li, key):
    import jax.numpy as jnp
    return int(jnp.sum(jnp.take(
        jnp.asarray(sh.batch.column(key).data), li).astype(jnp.int64)))


def agg_frame(batch):
    df = columnar.to_arrow(batch).to_pandas()
    return df.sort_values(list(df.columns)[:1]).reset_index(drop=True)


def run_rung(n, data_dirs, lengths_map):
    """One device count: read through the per-device segment cache, run
    the three query shapes twice (cold, warm), return measurements."""
    import jax

    from hyperspace_tpu.io import parquet, segcache
    from hyperspace_tpu.io.segcache import SegmentRef
    from hyperspace_tpu.ops.bucketed_join import assemble_join_output
    from hyperspace_tpu.parallel import spmd
    from hyperspace_tpu.parallel.mesh import bucket_ranges, make_mesh
    from hyperspace_tpu.plan.nodes import AggSpec

    mesh = make_mesh(n)

    def read(tag):
        root, num_buckets = data_dirs[tag]
        per_bucket = parquet.bucket_files(root)
        ranges = bucket_ranges(num_buckets, n)
        per_shard = [[f for b in range(lo, hi)
                      for f in per_bucket.get(b, [])]
                     for lo, hi in ranges]
        cols = [f.name for f in lengths_map[tag]["schema"].fields]
        ref = SegmentRef(index_name=f"mc_{tag}", index_root=root,
                         version=0, bucket="mc")
        return spmd.read_sharded(per_shard, lengths_map[tag]["lengths"],
                                 cols, lengths_map[tag]["schema"], mesh,
                                 base_ref=ref)

    def q17(ss, sr):
        t0 = time.perf_counter()
        li, ri = spmd.sharded_join_indices(ss, sr, ["ss_item_sk"],
                                           ["sr_item_sk"])
        jax.block_until_ready((li, ri))
        smj_s = time.perf_counter() - t0
        joined = assemble_join_output(ss.batch, sr.batch, li, ri,
                                      how="inner")
        stage2 = spmd.repartition_sharded(joined, ["ss_qty"], BUCKETS,
                                          mesh)
        specs = [AggSpec("count", "*", "cnt"),
                 AggSpec("avg", "ss_price", "avg_price"),
                 AggSpec("sum", "sr_qty", "ret_qty")]
        out = spmd.sharded_group_aggregate(
            stage2, ["ss_qty"], specs,
            agg_schema("ss_qty", specs, joined.schema))
        return {"agg": agg_frame(out), "pairs": len(np.asarray(li)),
                "checksum": join_checksum(ss, li, "ss_item_sk"),
                "smj_s": smj_s}

    def q25(ss, sr, cs):
        t0 = time.perf_counter()
        li, ri = spmd.sharded_join_indices(ss, sr, ["ss_item_sk"],
                                           ["sr_item_sk"])
        jax.block_until_ready((li, ri))
        smj_s = time.perf_counter() - t0
        joined = assemble_join_output(
            ss.batch, sr.batch, li, ri, how="inner",
            columns=["ss_item_sk", "ss_qty", "sr_qty"])
        stage2 = spmd.repartition_sharded(joined, ["ss_item_sk"],
                                          BUCKETS, mesh)
        # cs carries HALF the bucket count: the second join's right side
        # re-buckets over ICI inside the program.
        li2, ri2 = spmd.sharded_join_indices(stage2, cs, ["ss_item_sk"],
                                             ["cs_item_sk"])
        joined2 = assemble_join_output(
            stage2.batch, cs.batch, li2, ri2, how="inner",
            columns=["ss_qty", "cs_qty"])
        stage3 = spmd.repartition_sharded(joined2, ["ss_qty"], BUCKETS,
                                          mesh)
        specs = [AggSpec("count", "*", "cnt"),
                 AggSpec("sum", "cs_qty", "cs_qty_sum")]
        out = spmd.sharded_group_aggregate(
            stage3, ["ss_qty"], specs,
            agg_schema("ss_qty", specs, joined2.schema))
        return {"agg": agg_frame(out),
                "pairs": len(np.asarray(li2)),
                "checksum": join_checksum(stage2, li2, "ss_item_sk"),
                "smj_s": smj_s}

    def qstr(ssk, itm):
        # String-predicate sharded filter (code-space range test on the
        # global dictionary), then the string-keyed SMJ: rank-remap
        # tables unify the two per-version dictionaries in-program.
        from hyperspace_tpu.plan.expr import col, lit
        cutoff = "AAAA%08d" % (ROWS // 16)
        filt = spmd.sharded_filter(ssk, col("ss_item_id") < lit(cutoff))
        # Timer hygiene: the filter's compaction gather is async — let
        # it land before the SMJ timer starts, or its wall (which the
        # retrace of the per-call filter program dominates) books
        # against the join stage.
        jax.block_until_ready([c.data for c in filt.columns.values()])
        t0 = time.perf_counter()
        li, ri = spmd.sharded_join_indices(ssk, itm, ["ss_item_id"],
                                           ["i_item_id"])
        jax.block_until_ready((li, ri))
        smj_s = time.perf_counter() - t0
        joined = assemble_join_output(
            ssk.batch, itm.batch, li, ri, how="inner",
            columns=["ssk_qty", "ssk_price", "i_qty"])
        stage2 = spmd.repartition_sharded(joined, ["ssk_qty"], BUCKETS,
                                          mesh)
        specs = [AggSpec("count", "*", "cnt"),
                 AggSpec("avg", "ssk_price", "avg_price"),
                 AggSpec("sum", "i_qty", "i_qty_sum")]
        out = spmd.sharded_group_aggregate(
            stage2, ["ssk_qty"], specs,
            agg_schema("ssk_qty", specs, joined.schema))
        return {"agg": agg_frame(out), "pairs": len(np.asarray(li)),
                "checksum": (join_checksum(ssk, li, "ssk_qty")
                             + filt.num_rows),
                "smj_s": smj_s}

    def q64(ss, cs):
        t0 = time.perf_counter()
        li, ri = spmd.sharded_join_indices(ss, cs, ["ss_item_sk"],
                                           ["cs_item_sk"])
        jax.block_until_ready((li, ri))
        smj_s = time.perf_counter() - t0
        joined = assemble_join_output(
            ss.batch, cs.batch, li, ri, how="inner",
            columns=["ss_qty", "cs_qty", "ss_price"])
        stage2 = spmd.repartition_sharded(joined, ["ss_qty"], BUCKETS,
                                          mesh)
        specs = [AggSpec("count", "*", "cnt"),
                 AggSpec("avg", "ss_price", "avg_price")]
        out = spmd.sharded_group_aggregate(
            stage2, ["ss_qty"], specs,
            agg_schema("ss_qty", specs, joined.schema))
        return {"agg": agg_frame(out), "pairs": len(np.asarray(li)),
                "checksum": join_checksum(ss, li, "ss_item_sk"),
                "smj_s": smj_s}

    segcache.clear()
    out = {"n_devices": n, "queries": {}}

    # Cold read (fills, counted) then warm read (must be link-free).
    t0 = time.perf_counter()
    ss = read("ss")
    sr = read("sr")
    cs = read("cs")
    ssk = read("ssk")
    itm = read("itm")
    out["read_cold_s"] = round(time.perf_counter() - t0, 3)
    before = _counters("link.h2d.chunks")
    t0 = time.perf_counter()
    ss = read("ss")
    sr = read("sr")
    cs = read("cs")
    ssk = read("ssk")
    itm = read("itm")
    out["read_warm_s"] = round(time.perf_counter() - t0, 3)
    after = _counters("link.h2d.chunks")
    out["warm_h2d_chunks"] = after["link.h2d.chunks"] \
        - before["link.h2d.chunks"]

    runners = {"q17": lambda: q17(ss, sr),
               "q25": lambda: q25(ss, sr, cs),
               "q64": lambda: q64(ss, cs),
               "qstr": lambda: qstr(ssk, itm)}
    for name, fn in runners.items():
        t0 = time.perf_counter()
        cold = fn()
        cold_s = time.perf_counter() - t0
        d2h0 = _counters("link.d2h.chunks")["link.d2h.chunks"]
        t0 = time.perf_counter()
        warm = fn()
        warm_s = time.perf_counter() - t0
        inter_d2h = _counters("link.d2h.chunks")["link.d2h.chunks"] - d2h0
        # SMJ stage wall = BEST of three warm laps: the ratio claim
        # rides this number, and on the shared container a single lap
        # is hostage to background load (the r06->r07 comparator-side
        # variance, docs/round10-notes.md).
        for _ in range(2):
            lap = fn()
            if lap["smj_s"] < warm["smj_s"]:
                warm["smj_s"] = lap["smj_s"]
        out["queries"][name] = {
            "cold_s": round(cold_s, 3),
            "warm_s": round(warm_s, 3),
            "smj_s": round(warm["smj_s"], 4),
            "pairs": warm["pairs"],
            "checksum": warm["checksum"],
            "inter_stage_d2h_chunks": inter_d2h,
            "agg": warm["agg"],
        }
        log(f"  n={n} {name}: cold {cold_s:.2f}s warm {warm_s:.2f}s "
            f"(smj {warm['smj_s']:.3f}s, {warm['pairs']} pairs, "
            f"d2h {inter_d2h:+.0f})")
    return out


GRID_CLIENTS = [int(x) for x in
                os.environ.get("MULTICHIP_GRID_CLIENTS", "1,8").split(",")]


def run_multislice_grid(work: str):
    """The devices x slices x concurrent-clients serving grid (module
    docstring). Returns the `multislice` artifact section."""
    import threading

    import pandas as pd  # noqa: F401  (env parity with main)

    from hyperspace_tpu.config import HyperspaceConf
    from hyperspace_tpu.engine.scheduler import QueryScheduler
    from hyperspace_tpu.io import builder, parquet
    from hyperspace_tpu.io.segcache import SegmentRef
    from hyperspace_tpu.parallel import replica as replica_mod
    from hyperspace_tpu.parallel import spmd
    from hyperspace_tpu.parallel.build import distributed_build
    from hyperspace_tpu.parallel.mesh import (bucket_ranges, make_mesh,
                                              slice_submesh, total_shards)

    rng = np.random.default_rng(23)
    N, M, B = 4000, 1500, 64
    # Zipf-shaped point-join traffic: one dominant key (~half the left
    # rows — the hot-product / default-value shape) over a long tail.
    hot_l = np.where(rng.random(N) < 0.52, 7,
                     rng.integers(0, 4000, N))
    hot_r = np.where(rng.random(M) < 0.05, 7,
                     rng.integers(0, 4000, M))
    left = columnar.from_arrow(pa.table({
        "g_key": hot_l.astype(np.int64), "g_val": rng.random(N)}))
    right = columnar.from_arrow(pa.table({
        "g_key": hot_r.astype(np.int64), "g_val": rng.random(M)}))

    widest = make_mesh(8)
    roots = {}
    for tag, batch in (("gl", left), ("gr", right)):
        built, lengths = distributed_build(batch, ["g_key"], B, widest)
        root = os.path.join(work, tag)
        builder.write_bucket_ordered(built, lengths, B, root, mesh=widest)
        roots[tag] = (root, lengths, built.schema)

    def read_pair(mesh):
        out = []
        for tag in ("gl", "gr"):
            root, lengths, schema = roots[tag]
            per_bucket = parquet.bucket_files(root)
            S = total_shards(mesh)
            per_shard = [[f for b in range(lo, hi)
                          for f in per_bucket.get(b, [])]
                         for lo, hi in bucket_ranges(B, S)]
            ref = SegmentRef(index_name=f"grid_{tag}", index_root=root,
                             version=0, bucket="grid")
            out.append(spmd.read_sharded(
                per_shard, lengths, [f.name for f in schema.fields],
                schema, mesh, base_ref=ref))
        return tuple(out)

    def query(pair, q):
        """One serving query: point join (even q) / semi membership
        (odd q); returns the topology-invariant identity
        (result rows, int64 key checksum)."""
        import jax.numpy as jnp
        lsh, rsh = pair
        with spmd.dispatch_guard(lsh.mesh):
            if q % 2:
                li = spmd.sharded_semi_anti_indices(lsh, rsh,
                                                    ["g_key"], ["g_key"])
            else:
                li, _ri = spmd.sharded_join_indices(lsh, rsh,
                                                    ["g_key"], ["g_key"])
            keys = jnp.take(jnp.asarray(lsh.batch.column("g_key").data),
                            li)
            return len(np.asarray(li)), int(jnp.sum(keys))

    topologies = {"1x8": 1, "2x4": 2, "4x2": 4}
    cells = {}
    identities = {}
    warm_h2d = 0.0
    replica_routed = {}
    reg = telemetry.get_registry()
    fallbacks0 = reg.counters_dict().get("spmd.fallbacks", 0)
    for topo, n_slices in topologies.items():
        conf = HyperspaceConf({
            "hyperspace.distribution.enabled": "true",
            "hyperspace.distribution.slices": n_slices})
        replica_mod.reset_router()
        router = replica_mod.get_router()
        sched = QueryScheduler()
        if n_slices == 1:
            pairs = [read_pair(make_mesh(8))]
        else:
            mesh = make_mesh(8, dcn_size=n_slices)
            pairs = [read_pair(slice_submesh(mesh, i))
                     for i in range(n_slices)]
        # Warm every replica, then assert the timed phase is fill-free.
        for pair in pairs:
            for q in range(2):
                query(pair, q)
        h2d0 = _counters("link.h2d.chunks")["link.h2d.chunks"]
        # Cross-topology bit-identity: one deterministic lap.
        identities[topo] = [query(pairs[0], q) for q in range(4)]
        cells[topo] = {}
        for K in GRID_CLIENTS:
            Q = 6
            done = []

            def client(i):
                for q in range(Q):
                    rep = router.route(None, conf, sched)
                    pair = pairs[rep if rep is not None else 0]
                    query(pair, q)
                done.append(Q)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(K)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            qps = sum(done) / wall
            cells[topo][str(K)] = {"qps": round(qps, 2),
                                   "wall_s": round(wall, 3),
                                   "queries": sum(done)}
            log(f"  grid {topo} K={K}: {qps:.1f} QPS")
        if n_slices == 2:
            replica_routed = {str(k): v for k, v
                              in router.routed_counts().items()}
        warm_h2d += _counters("link.h2d.chunks")["link.h2d.chunks"] - h2d0

    # Cross-slice repartition attribution: one mismatched-bucket join
    # over the FULL 2-axis mesh (key lanes cross slices over DCN,
    # re-bucket within over ICI) — the dcn_byte_share gate's evidence.
    mesh2 = make_mesh(8, dcn_size=2)
    rb2, rl2 = distributed_build(right, ["g_key"], B // 2, mesh2)
    lb2, ll2 = distributed_build(left, ["g_key"], B, mesh2)
    lsh2 = spmd.shard_bucket_ordered(lb2, ll2, mesh2)
    rsh2 = spmd.shard_bucket_ordered(rb2, rl2, mesh2)
    c0 = _counters("spmd.repartition.ici.bytes",
                   "spmd.repartition.dcn.bytes")
    li2, _ri2 = spmd.sharded_join_indices(lsh2, rsh2, ["g_key"],
                                          ["g_key"])
    repart_pairs = len(np.asarray(li2))
    c1 = _counters("spmd.repartition.ici.bytes",
                   "spmd.repartition.dcn.bytes")
    ici = c1["spmd.repartition.ici.bytes"] - c0["spmd.repartition.ici.bytes"]
    dcn = c1["spmd.repartition.dcn.bytes"] - c0["spmd.repartition.dcn.bytes"]
    dcn_share = round(dcn / (ici + dcn), 4) if (ici + dcn) else None

    base = identities["1x8"]
    bit_identical = all(identities[t] == base for t in topologies)
    fallbacks = reg.counters_dict().get("spmd.fallbacks", 0) - fallbacks0
    flat = cells["1x8"][str(max(GRID_CLIENTS))]["qps"]
    repl = cells["2x4"][str(max(GRID_CLIENTS))]["qps"]
    routed_total = sum(replica_routed.values()) or 1
    return {
        "workload": {"left_rows": N, "right_rows": M, "buckets": B,
                     "hot_fraction": 0.52,
                     "clients": GRID_CLIENTS},
        "cells": cells,
        "qps_ratio": round(repl / flat, 3) if flat else None,
        "replica_routed": replica_routed,
        "replica_max_share": round(
            max(replica_routed.values()) / routed_total, 3)
        if replica_routed else None,
        "dcn_byte_share": dcn_share,
        "repartition_pairs": repart_pairs,
        "warm_h2d_chunks": warm_h2d,
        "spmd_fallbacks": fallbacks,
        "bit_identical": bit_identical,
    }


def main():
    import pandas as pd

    from hyperspace_tpu.io import builder
    from hyperspace_tpu.parallel.build import distributed_build
    from hyperspace_tpu.parallel.mesh import make_mesh

    work = tempfile.mkdtemp(prefix="hs_multichip_")
    try:
        ss, sr, cs, ssk, itm = generate()
        log(f"generated SF100-shaped tables: ss={ss.num_rows} "
            f"sr={sr.num_rows} cs={cs.num_rows} "
            f"ssk={ssk.num_rows} itm={itm.num_rows} rows, "
            f"B={BUCKETS} buckets")

        # Build rung per device count (the all_to_all exchange), then
        # persist ONE born-sharded copy (global bucket order is mesh-
        # independent; the per-device shard suffixes come from the
        # widest mesh).
        build_walls = {}
        built = {}
        for n in DEVICES:
            mesh = make_mesh(n)
            t0 = time.perf_counter()
            built["ss"] = distributed_build(ss, ["ss_item_sk"], BUCKETS,
                                            mesh)
            built["sr"] = distributed_build(sr, ["sr_item_sk"], BUCKETS,
                                            mesh)
            built["cs"] = distributed_build(cs, ["cs_item_sk"],
                                            BUCKETS // 2, mesh)
            built["ssk"] = distributed_build(ssk, ["ss_item_id"],
                                             BUCKETS, mesh)
            built["itm"] = distributed_build(itm, ["i_item_id"],
                                             BUCKETS, mesh)
            build_walls[str(n)] = round(time.perf_counter() - t0, 3)
            log(f"build n={n}: {build_walls[str(n)]}s")

        data_dirs = {}
        lengths_map = {}
        widest = make_mesh(max(DEVICES))
        for tag, num_buckets in (("ss", BUCKETS), ("sr", BUCKETS),
                                 ("cs", BUCKETS // 2),
                                 ("ssk", BUCKETS), ("itm", BUCKETS)):
            batch, lengths = built[tag]
            root = os.path.join(work, tag)
            builder.write_bucket_ordered(batch, lengths, num_buckets,
                                         root, mesh=widest)
            data_dirs[tag] = (root, num_buckets)
            lengths_map[tag] = {"lengths": lengths,
                                "schema": batch.schema}

        rungs = {}
        for n in DEVICES:
            rungs[str(n)] = run_rung(n, data_dirs, lengths_map)

        log("multislice serving grid (devices x slices x clients)...")
        multislice = run_multislice_grid(work)
        log(f"grid: qps_ratio {multislice['qps_ratio']} "
            f"(2x4 replicated vs 1x8 flat at "
            f"{max(GRID_CLIENTS)} clients), replica shares "
            f"{multislice['replica_routed']}, dcn byte share "
            f"{multislice['dcn_byte_share']}, bit_identical="
            f"{multislice['bit_identical']}")

        # Bit-identity vs the 1-device run: aggregate frames equal,
        # join pair counts + int64 key checksums equal.
        base = rungs[str(DEVICES[0])]
        bit_identical = True
        for n in DEVICES[1:]:
            for q, res in rungs[str(n)]["queries"].items():
                ref = base["queries"][q]
                try:
                    pd.testing.assert_frame_equal(
                        res["agg"], ref["agg"], check_dtype=False)
                except AssertionError:
                    bit_identical = False
                    log(f"MISMATCH: {q} aggregate differs at n={n}")
                if (res["pairs"], res["checksum"]) != (ref["pairs"],
                                                       ref["checksum"]):
                    bit_identical = False
                    log(f"MISMATCH: {q} join identity differs at n={n}")
        for r in rungs.values():
            for q in r["queries"].values():
                q.pop("agg")  # frames checked; not serialized

        n_hi = str(max(DEVICES))
        n_lo = str(min(DEVICES))
        # The headline is the SHUFFLE-FREE co-bucketed SMJ (q17/q25) —
        # the paper's claim the bucketed layout exists for. q64's
        # mismatched-bucket rung exercises the in-program ICI
        # repartition for CORRECTNESS and is reported separately: on
        # virtual single-core devices the all_to_all is emulated
        # serially, so its wall measures emulation overhead, not the
        # collective a real mesh would run (ratio discipline,
        # docs/round6-notes.md).
        cobucketed = ("q17", "q25")
        smj = {k: sum(r["queries"][q]["smj_s"] for q in cobucketed)
               for k, r in rungs.items()}
        repart = {k: r["queries"]["q64"]["smj_s"]
                  for k, r in rungs.items()}
        # The string-keyed SMJ rung: same co-bucketed shuffle-free shape
        # as the headline, with in-program rank remaps doing the
        # dictionary unification — gated like the numeric speedup.
        string_smj = {k: r["queries"]["qstr"]["smj_s"]
                      for k, r in rungs.items()}
        string_speedup = (round(string_smj[n_lo] / string_smj[n_hi], 3)
                          if string_smj[n_hi] else None)
        wall = {k: sum(q["warm_s"] for q in r["queries"].values())
                for k, r in rungs.items()}
        speedup = round(smj[n_lo] / smj[n_hi], 3) if smj[n_hi] else None
        efficiency = {k: round(smj[n_lo] / (int(k) * smj[k]), 3)
                      for k in smj if smj[k]}
        multichip = {
            "rows": ROWS,
            "buckets": BUCKETS,
            "devices": rungs,
            "build_walls_s": build_walls,
            "smj_wall_s": {k: round(v, 3) for k, v in smj.items()},
            "repartition_smj_wall_s": {k: round(v, 4)
                                       for k, v in repart.items()},
            "string_smj_wall_s": {k: round(v, 4)
                                  for k, v in string_smj.items()},
            "string_smj_speedup": string_speedup,
            "query_wall_s": {k: round(v, 3) for k, v in wall.items()},
            "smj_speedup": speedup,
            "efficiency": efficiency,
            "bit_identical": bit_identical,
            "warm_h2d_chunks": {k: r["warm_h2d_chunks"]
                                for k, r in rungs.items()},
            "multislice": multislice,
        }
        log(f"co-bucketed SMJ walls {multichip['smj_wall_s']} -> "
            f"speedup {speedup} at {n_hi} devices; efficiency "
            f"{efficiency}; repartition rung "
            f"{multichip['repartition_smj_wall_s']}; string SMJ "
            f"{multichip['string_smj_wall_s']} -> {string_speedup}x; "
            f"bit_identical={bit_identical}")

        result = telemetry.artifact.make_artifact(
            driver="bench_multichip.py",
            metric="multichip_cobucketed_smj_8dev_speedup",
            value=speedup,
            unit="x vs 1 device",
            vs_baseline=speedup,
            extra={"multichip": multichip})
        print(json.dumps(result))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
