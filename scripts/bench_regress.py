#!/usr/bin/env python
"""Pre-merge perf gate: diff the newest bench artifact against the
previous one and exit nonzero on a regression — arriving WITH its own
diagnosis: any gate failure auto-runs the regression differ
(`telemetry/diff.py`) on the same pair and prints the ranked
attribution tree, so the reviewer sees *why*, not just *that*.

Two artifact families, one gate:

  python scripts/bench_regress.py                 # newest two BENCH_r*.json
  python scripts/bench_regress.py --tpcds         # newest two BENCH_TPCDS_r*.json
  python scripts/bench_regress.py OLD.json NEW.json
  python scripts/bench_regress.py --threshold 0.10 --glob 'BENCH_r*.json'

  python scripts/bench_regress.py --serve         # newest two BENCH_SERVE_r*.json

Rung artifacts (bench.py) gate per-rung `vs_baseline`, peak HBM growth,
the rung-1 link share, AND the warm-rung segment-cache bar: the
steady-state repeat run of each query rung must show ZERO
`link.h2d.chunks` (absolute gate — the healthy value is 0), and the
segment-cache hit rate must not drop >threshold. Query artifacts (bench_tpcds.py /
bench_tpch.py) gate the aggregate `vs_baseline` AND every per-query
`vs_baseline` — the r03->r04 TPC-DS regression (aggregate 3.14x ->
0.81x, q64 at 0.45x) is exactly the failure this mode exists to stop
at the door. The mode is detected from artifact content (`queries` vs
`rungs` vs `serve`), so explicit paths need no flag.

Serving artifacts (bench_serve.py, `--serve`) gate the closed-loop
scaling ratio (`vs_baseline` = K-client QPS / 1-client QPS), p99 and
p50 latency GROWTH, and the reject/timeout RATES — rates gate on
absolute movement (> 2 points), because a 0 -> 0.3 reject-rate jump is
exactly the regression a ratio gate on zero cannot see.

Artifacts must be in the canonical schema (`telemetry/artifact.py`,
`schema_version` + `process_metrics`); a legacy-schema artifact is
REFUSED with exit 2 — gating shapes that cannot be compared
mechanically is how the r04 regression went unnoticed for two rounds.
Migrate committed legacy rounds with
`python -m hyperspace_tpu.telemetry.artifact migrate FILE`.

Entries present in only one artifact are reported but never gate (a
new rung/query has no baseline; a removed one is a review question,
not a perf fact). The 15% default threshold leaves headroom for the
shared tunneled link's ~2x time-of-day wobble on sub-ratios near 1
(see `link_probe` in bench_common.py) while still catching real
cliffs; artifacts carry the probe so a borderline failure can be
attributed to link vs code before overriding the gate.
"""

import argparse
import glob
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def load_artifact(path: str) -> dict:
    """Canonical-schema load; legacy artifacts are refused LOUDLY
    (exit 2) — the gate must never silently pass what it cannot
    mechanically compare."""
    from hyperspace_tpu.telemetry import artifact

    try:
        return artifact.load(path)
    except artifact.LegacyArtifactError as exc:
        print(f"bench_regress: REFUSING to gate a legacy-schema "
              f"artifact:\n  {exc}", file=sys.stderr)
        raise SystemExit(2)
    except (OSError, ValueError) as exc:
        raise SystemExit(f"{path}: not a bench artifact object ({exc})")


def _round_key(path: str):
    """Numeric round ordering: `_r9` sorts before `_r10` (a plain
    lexicographic sort would interleave them); non-round files sort
    last, then by name, so the newest ROUND is always picked."""
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return (m is None, int(m.group(1)) if m else 0, path)


def pick_latest_two(pattern: str):
    paths = sorted(glob.glob(os.path.join(REPO_ROOT, pattern)),
                   key=_round_key)
    if len(paths) < 2:
        raise SystemExit(
            f"need at least two artifacts matching {pattern!r}; "
            f"found {len(paths)}")
    return paths[-2], paths[-1]


def _rung1_link_share(doc: dict):
    """(key_stage_link_s + perm_d2h_link_s) / build_s of rung 1 — the
    fraction of the build the device path spends on the link. The
    pipelined transfer engine exists to drive this DOWN; a >threshold
    rebound means the link seam regressed even if wall times still
    pass. None when the artifact predates the device-path phases."""
    r1 = (doc.get("rungs") or {}).get("1_build") or {}
    phases = r1.get("device_path") or {}
    stage = phases.get("key_stage_link_s")
    d2h = phases.get("perm_d2h_link_s")
    build = r1.get("build_s")
    if not all(isinstance(v, (int, float)) for v in (stage, d2h, build)) \
            or not build:
        return None
    return (stage + d2h) / build


# Reject/timeout RATES gate on absolute movement, not ratio: the
# healthy value is 0, and nothing ratio-gates against zero.
RATE_SLACK = 0.02

# Latency-anatomy absolutes (PR 17). The critical-path decomposition
# is sum-exact BY CONSTRUCTION (the host_python residual absorbs the
# unattributed remainder), so the only honest tolerance is rounding:
# segments round to 1 µs, nine of them. The profiler bound is the
# tentpole promise: "continuous" means cheap enough to leave on.
CRITPATH_EPSILON_S = 1e-4
PROFILER_OVERHEAD_MAX = 0.02


def _segment_rows(old: dict, new: dict, threshold: float):
    """Warm-rung gate rows from the `segments` block bench.py embeds:

    - `warm_h2d.<rung>` — the steady-state repeat run of each query
      rung must cross the link ZERO times (`link.h2d.chunks` delta).
      This gates on the NEW artifact alone and absolutely: the healthy
      value is 0, and nothing ratio-gates against zero (same logic as
      the serve rates).
    - `segment_hit_rate` — hits/(hits+misses) of the HBM segment cache
      over the whole ladder; a >threshold drop means repeat queries
      started re-paying decode+H2D even if walls still pass.
    """
    rows = []
    oseg = old.get("segments") or {}
    nseg = new.get("segments") or {}
    for rung, w in sorted((nseg.get("warm") or {}).items()):
        chunks = w.get("h2d_chunks")
        if not isinstance(chunks, (int, float)):
            continue
        ow = ((oseg.get("warm") or {}).get(rung) or {}).get("h2d_chunks")
        rows.append((f"warm_h2d.{rung}",
                     float(ow) if isinstance(ow, (int, float)) else 0.0,
                     float(chunks), float(chunks), chunks > 0))

    def rate(seg):
        hits, misses = seg.get("hits"), seg.get("misses")
        if not (isinstance(hits, (int, float))
                and isinstance(misses, (int, float))) \
                or hits + misses <= 0:
            return None
        return hits / (hits + misses)

    old_rate, new_rate = rate(oseg), rate(nseg)
    if old_rate and new_rate is not None:
        change = new_rate / old_rate - 1.0
        rows.append(("segment_hit_rate", old_rate, new_rate, change,
                     change < -threshold))
    return rows


def _skipping_rows(old: dict, new: dict):
    """Data-skipping gate row: the `5_data_skipping` rung's
    `files_pruned` must be > 0 in the NEW artifact (absolute gate, like
    the warm-H2D rows — the healthy value is never zero: a selective
    predicate over the clustered bench source must read strictly fewer
    files than the unindexed plan). Artifacts predating the rung are
    not gated."""
    r = (new.get("rungs") or {}).get("5_data_skipping") or {}
    fp = r.get("files_pruned")
    if not isinstance(fp, (int, float)):
        return []
    old_fp = ((old.get("rungs") or {}).get("5_data_skipping")
              or {}).get("files_pruned")
    return [("skipping_files_pruned",
             float(old_fp) if isinstance(old_fp, (int, float)) else 0.0,
             float(fp), float(fp), fp <= 0)]


def _spmd_rows(old: dict, new: dict):
    """One-architecture gate row: a TPC-DS artifact carrying the
    `spmd.fallbacks` counter must report ZERO (absolute — the healthy
    value is 0 and nothing ratio-gates against zero). A fallback means
    a bucketed SMJ with an active mesh dropped off the single-program
    SPMD lane, i.e. a second execution architecture crept back."""
    fb = (new.get("spmd") or {}).get("fallbacks")
    if not isinstance(fb, (int, float)):
        return []
    old_fb = (old.get("spmd") or {}).get("fallbacks")
    return [("spmd_fallbacks",
             float(old_fb) if isinstance(old_fb, (int, float)) else 0.0,
             float(fb), float(fb), fb > 0)]


def compare_multichip(old: dict, new: dict, threshold: float):
    """Multi-chip artifact gate rows (same row shape as `compare`):

    - `smj_speedup_8dev` — the 8-vs-1-device SMJ speedup must not drop
      >threshold between rounds (the scaling claim itself);
    - `warm_h2d.<n>dev` — the warm per-device read of each rung must
      cross the link ZERO times (absolute gate on the NEW artifact —
      the healthy value is 0 and nothing ratio-gates against zero);
    - `inter_stage_d2h.<q>@<n>dev` — a warm multi-stage query must
      record zero D2H link crossings between stages (absolute);
    - `bit_identical` — sharded results must equal the 1-device run
      (absolute: False fails regardless of history).

    Legacy MULTICHIP rounds (the migrated `{n_devices, rc, ok, tail}`
    smoke blobs) carry no `multichip` section: their rows report as
    not-gated, the new artifact's absolute rows still gate."""
    o = old.get("multichip") or {}
    n = new.get("multichip") or {}
    rows = []

    def ratio(name, old_v, new_v):
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            rows.append((name, old_v, new_v, None, False))
            return
        change = new_v / old_v - 1.0
        rows.append((name, old_v, new_v, change, change < -threshold))

    ratio("smj_speedup_8dev", o.get("smj_speedup"), n.get("smj_speedup"))
    if isinstance(n.get("smj_speedup"), (int, float)):
        # Absolute floor: the whole point of the rung — the widest mesh
        # must beat one device, this round, regardless of history.
        rows.append(("smj_speedup_floor", 1.0, n["smj_speedup"],
                     n["smj_speedup"] - 1.0, n["smj_speedup"] <= 1.0))
    # String-keyed SMJ (strings born-sharded, PR 13): gated exactly like
    # the numeric co-bucketed headline — ratio vs the previous round
    # when it carried the rung, plus the absolute >1x floor.
    ratio("string_smj_speedup", o.get("string_smj_speedup"),
          n.get("string_smj_speedup"))
    if isinstance(n.get("string_smj_speedup"), (int, float)):
        v = n["string_smj_speedup"]
        rows.append(("string_smj_speedup_floor", 1.0, v, v - 1.0,
                     v <= 1.0))
    for ndev, chunks in sorted((n.get("warm_h2d_chunks") or {}).items()):
        if isinstance(chunks, (int, float)):
            old_c = (o.get("warm_h2d_chunks") or {}).get(ndev)
            rows.append((f"warm_h2d.{ndev}dev",
                         float(old_c) if isinstance(old_c, (int, float))
                         else 0.0, float(chunks), float(chunks),
                         chunks > 0))
    for ndev, rung in sorted((n.get("devices") or {}).items()):
        for q, res in sorted((rung.get("queries") or {}).items()):
            d2h = res.get("inter_stage_d2h_chunks")
            if isinstance(d2h, (int, float)):
                rows.append((f"inter_stage_d2h.{q}@{ndev}dev", 0.0,
                             float(d2h), float(d2h), d2h > 0))
    bi = n.get("bit_identical")
    if bi is not None:
        rows.append(("bit_identical", 1.0, 1.0 if bi else 0.0,
                     0.0 if bi else -1.0, not bi))
    rows.extend(_multislice_rows(o, n, threshold))
    return rows


# Replica routing balance bar: at steady state no replica may take
# more than this share of routed queries (least-loaded routing that
# degenerates to one slice is replication paying HBM for nothing).
REPLICA_MAX_SHARE = 0.70
# Cross-slice byte-share bar: under the two-hop hierarchy each routed
# row crosses DCN at most once and ICI at most once, so the DCN share
# of a full re-bucket sits near 1/2 by construction (slab rounding adds
# a little). A share past this bar means the heavy fan-out inverted
# onto the slow axis — stage order or capacity sizing regressed.
DCN_BYTE_SHARE_MAX = 0.60


def _multislice_rows(o: dict, n: dict, threshold: float):
    """Multi-slice + replication gate rows (the scale-OUT section of
    the MULTICHIP artifact):

    - `multislice_qps_ratio` — concurrent-client aggregate QPS of the
      replicated multi-slice topology over the flat whole-mesh
      topology; absolute floor 1.0 (replication that loses to the flat
      mesh is the regression) plus the usual ratio-vs-previous-round;
    - `replica_max_share` — no replica may take > REPLICA_MAX_SHARE of
      routed queries at steady state (absolute);
    - `dcn_byte_share` — cross-slice DCN bytes /
      (ICI + DCN) of the in-program repartitions must stay under
      DCN_BYTE_SHARE_MAX (absolute — the hierarchy's point is that the
      heavy hop rides ICI);
    - `multislice_warm_h2d` / `multislice_spmd_fallbacks` /
      `multislice_bit_identical` — the flat-lane absolutes, re-asserted
      on the replicated grid. Rounds predating the section gate
      nothing."""
    om = o.get("multislice") or {}
    nm = n.get("multislice") or {}
    rows = []
    if not nm:
        return rows
    ratio = nm.get("qps_ratio")
    if isinstance(ratio, (int, float)):
        rows.append(("multislice_qps_floor", 1.0, ratio, ratio - 1.0,
                     ratio < 1.0))
        old_r = om.get("qps_ratio")
        if isinstance(old_r, (int, float)) and old_r > 0:
            change = ratio / old_r - 1.0
            rows.append(("multislice_qps_ratio", old_r, ratio, change,
                         change < -threshold))
    share = nm.get("replica_max_share")
    if isinstance(share, (int, float)):
        rows.append(("replica_max_share", REPLICA_MAX_SHARE, share,
                     share - REPLICA_MAX_SHARE,
                     share > REPLICA_MAX_SHARE))
    dcn = nm.get("dcn_byte_share")
    if isinstance(dcn, (int, float)):
        rows.append(("dcn_byte_share", DCN_BYTE_SHARE_MAX, dcn,
                     dcn - DCN_BYTE_SHARE_MAX, dcn > DCN_BYTE_SHARE_MAX))
    wh = nm.get("warm_h2d_chunks")
    if isinstance(wh, (int, float)):
        rows.append(("multislice_warm_h2d", 0.0, float(wh), float(wh),
                     wh > 0))
    fb = nm.get("spmd_fallbacks")
    if isinstance(fb, (int, float)):
        rows.append(("multislice_spmd_fallbacks", 0.0, float(fb),
                     float(fb), fb > 0))
    bi = nm.get("bit_identical")
    if bi is not None:
        rows.append(("multislice_bit_identical", 1.0,
                     1.0 if bi else 0.0, 0.0 if bi else -1.0, not bi))
    return rows


def compare_advisor(old: dict, new: dict, threshold: float):
    """Advisor-rung gate rows (same row shape as `compare`):

    - `advisor_built` — the cycle must have auto-built at least one
      index (absolute: a run that recommends but never builds has not
      closed the loop);
    - `advisor_bytes_reduction` — the recommended index must REDUCE
      scanned bytes on the repeat workload (absolute > 0), and must not
      drop >threshold vs the previous round;
    - `advisor_bit_identical` — index-served results must equal the
      unindexed run (absolute: False fails regardless of history);
    - `advisor_rule_applied` — the rebuilt workload must actually be
      SERVED by an index (rule-usage telemetry > 0, absolute)."""
    o = old.get("advisor") or {}
    n = new.get("advisor") or {}
    rows = []
    built = n.get("built")
    if isinstance(built, (int, float)):
        ob = o.get("built")
        rows.append(("advisor_built",
                     float(ob) if isinstance(ob, (int, float)) else 0.0,
                     float(built), float(built), built < 1))
    red = n.get("bytes_reduction")
    if isinstance(red, (int, float)):
        rows.append(("advisor_bytes_reduction_floor", 0.0, float(red),
                     float(red), red <= 0))
        ored = o.get("bytes_reduction")
        if isinstance(ored, (int, float)) and ored > 0:
            change = red / ored - 1.0
            rows.append(("advisor_bytes_reduction", float(ored),
                         float(red), change, change < -threshold))
    applied = n.get("rule_applied_after")
    if isinstance(applied, (int, float)):
        rows.append(("advisor_rule_applied", 0.0, float(applied),
                     float(applied), applied < 1))
    bi = n.get("bit_identical")
    if bi is not None:
        rows.append(("advisor_bit_identical", 1.0, 1.0 if bi else 0.0,
                     0.0 if bi else -1.0, not bi))
    return rows


# --ingest gate bounds. Staleness at the committed append rate must
# stay under the alert rule's firing threshold (an artifact that ships
# already-alerting staleness is a regression by definition), and the
# ingest lap's p99 may cost at most this multiple of the quiet lap.
# The degradation cap is a coarse backstop, not a target: in a
# single-process GIL-bound engine the refresh's sketch/bucket work
# inevitably stalls concurrent clients (measured ~25-35x at the
# committed rate), so the cap only catches runaway regressions —
# the old-vs-new p99_degradation_x ratio row is the tight gate.
INGEST_STALENESS_MAX_S = 30.0
INGEST_P99_DEGRADATION_MAX = 60.0
INGEST_WARM_HIT_RATE_FLOOR = 0.5


def compare_ingest(old: dict, new: dict, threshold: float):
    """Continuous-ingest gate rows (PR 19): the staleness-vs-p99
    frontier must not regress, and the chaos/warm-set ABSOLUTE wins the
    plane exists for stay won:

    - `p99_degradation_x` — ingest-lap p99 over quiet-lap p99, ratio
      vs the previous artifact plus an absolute ceiling;
    - `staleness_max_s` — worst staleness at the committed append
      rate, ratio when history is nonzero plus the absolute alert
      bound (nothing ratio-gates against zero);
    - `chaos_{mismatches,stuck,stranded}` — crash + transient
      injection mid-refresh under load: zero wrong answers, zero stuck
      clients, zero non-ACTIVE op-log leftovers after recovery;
    - `warm_hit_rate` / `segments_rekeyed` — sustained append must not
      collapse the segment cache: hit rate holds the floor and version
      rekeying actually ran (rekeyed == 0 means every flip dumped the
      warm set)."""
    o = old.get("ingest") or {}
    n = new.get("ingest") or {}
    rows = []

    def add(name, old_v, new_v, lower_is_better=False):
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            return
        change = new_v / old_v - 1.0
        gated = (change > threshold if lower_is_better
                 else change < -threshold)
        rows.append((name, old_v, new_v, change, gated))

    add("p99_degradation_x", o.get("p99_degradation_x"),
        n.get("p99_degradation_x"), lower_is_better=True)
    add("quiet_p99_s", (o.get("quiet") or {}).get("p99_s"),
        (n.get("quiet") or {}).get("p99_s"), lower_is_better=True)
    add("staleness_max_s",
        (o.get("committed_rate") or {}).get("staleness_max_s"),
        (n.get("committed_rate") or {}).get("staleness_max_s"),
        lower_is_better=True)

    chaos = n.get("chaos") or {}
    for key, label in (("mismatches", "chaos_mismatches"),
                       ("stuck_threads", "chaos_stuck"),
                       ("stranded_entries", "chaos_stranded")):
        v = chaos.get(key)
        if isinstance(v, (int, float)):
            rows.append((label, 0.0, float(v), float(v), v > 0))

    seg = n.get("segcache") or {}
    hit_rate = seg.get("warm_hit_rate")
    if isinstance(hit_rate, (int, float)):
        rows.append(("warm_hit_rate", INGEST_WARM_HIT_RATE_FLOOR,
                     float(hit_rate),
                     float(hit_rate) - INGEST_WARM_HIT_RATE_FLOOR,
                     hit_rate < INGEST_WARM_HIT_RATE_FLOOR))
    rekeyed = seg.get("rekeyed")
    if isinstance(rekeyed, (int, float)):
        rows.append(("segments_rekeyed", 1.0, float(rekeyed),
                     float(rekeyed), rekeyed <= 0))

    staleness = (n.get("committed_rate") or {}).get("staleness_max_s")
    if isinstance(staleness, (int, float)):
        rows.append(("staleness_abs_s", INGEST_STALENESS_MAX_S,
                     float(staleness), float(staleness),
                     staleness > INGEST_STALENESS_MAX_S))
    degradation = n.get("p99_degradation_x")
    if isinstance(degradation, (int, float)):
        rows.append(("p99_degradation_abs", INGEST_P99_DEGRADATION_MAX,
                     float(degradation), float(degradation),
                     degradation > INGEST_P99_DEGRADATION_MAX))
    return rows


def compare_serve(old: dict, new: dict, threshold: float):
    """Serving-artifact gate rows (same row shape as `compare`):
    scaling ratio + QPS drop >threshold, p50/p99 growth >threshold,
    reject/timeout rate growth > RATE_SLACK absolute — plus, for
    artifacts carrying the batched-execution sections (PR 12), the
    ABSOLUTE wins the lane exists for:

    - `scaling_floor` — the 8-client closed loop must BEAT serial
      (`vs_baseline >= 1.0`; concurrency that loses is the regression,
      whatever history said);
    - `batch_occupancy` — `serve.batch.members / serve.batch.
      invocations` on the concurrent rung must exceed 1 (an occupancy
      of exactly 1 means the lane ran but never coalesced anything);
    - `aot_warm_traces` — the AOT-warmed replica phase must record
      ZERO new `compile.traces` (absolute, like the warm-H2D rows:
      the healthy value is 0 and nothing ratio-gates against zero);
    - `window_p99_agreement` / `slo_burn` — operations-plane rounds
      (PR 15): the sampler's sliding-window p99 must agree with the
      closed-loop percentile within the log2-bucket + population
      slack, and the steady-state SLO burn rate must not exceed 1.0;
    - `tenant_victim_p99_x` / `tenant_mismatches` / `tenant_deadlock`
      / `tenant_chargeback_exact` — multi-tenant rounds (PR 16): the
      victim tenant's co-located p99 stays <= 2x solo, chaos costs no
      correctness or liveness, and per-tenant chargeback sums equal
      the global counters exactly;
    - `critpath_sum_exact` / `profiler_overhead` — latency-anatomy
      rounds (PR 17): every sweep rate's stamped p99 decomposition
      sums to its measured wall within CRITPATH_EPSILON_S, and the
      sampling profiler costs <= PROFILER_OVERHEAD_MAX of closed-loop
      QPS;
    - `clean_run_incidents` — incident-plane rounds (PR 18): zero
      alert incidents fired during the timed closed loop (the
      false-positive gate on the default rule set).

    Absolute rows gate on the NEW artifact alone; rounds predating the
    sections are not gated on them."""
    o = old.get("serve") or {}
    n = new.get("serve") or {}
    rows = []

    def add(name, old_v, new_v, lower_is_better=False):
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            return
        change = new_v / old_v - 1.0
        gated = (change > threshold if lower_is_better
                 else change < -threshold)
        rows.append((name, old_v, new_v, change, gated))

    add("scaling_ratio", old.get("vs_baseline"), new.get("vs_baseline"))
    add("qps", o.get("qps"), n.get("qps"))
    add("p50_s", o.get("p50_s"), n.get("p50_s"), lower_is_better=True)
    add("p99_s", o.get("p99_s"), n.get("p99_s"), lower_is_better=True)
    for rate in ("reject_rate", "timeout_rate"):
        ov, nv = o.get(rate), n.get(rate)
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)):
            delta = nv - ov
            rows.append((rate, ov, nv, delta, delta > RATE_SLACK))

    vb = new.get("vs_baseline")
    if isinstance(vb, (int, float)) and ("batch" in n or "aot" in n):
        rows.append(("scaling_floor", 1.0, vb, vb - 1.0, vb < 1.0))
    b = n.get("batch") or {}
    inv, mem = b.get("invocations"), b.get("members")
    if isinstance(inv, (int, float)) and isinstance(mem, (int, float)):
        occ = (mem / inv) if inv > 0 else 0.0
        rows.append(("batch_occupancy", 1.0, occ, occ - 1.0, occ <= 1.0))
    a = n.get("aot") or {}
    wt = a.get("warm_traces")
    if isinstance(wt, (int, float)):
        rows.append(("aot_warm_traces", 0.0, float(wt), float(wt),
                     wt > 0))
    # Operations-plane gates (rounds predating the sections skip):
    # - `window_p99_agreement` — the timeseries sampler's sliding-
    #   window p99 over the timed closed loop must agree with the
    #   client-measured percentile. The window value is a log2-bucket
    #   UPPER bound (within 2x above the truth by construction), and
    #   the two populations differ slightly (server walls vs client
    #   walls), so the gate allows 4x each way: outside that, the
    #   window math or the sampling itself broke.
    # - `slo_burn` — the closed loop ran with the SLO window reset at
    #   the timed-loop start, so a burn rate above 1.0 means the
    #   steady-state serving round violated its own p99 objective
    #   (absolute — the healthy value is ~0 and nothing ratio-gates
    #   against zero).
    wp, cp = n.get("window_p99_s"), n.get("p99_s")
    if isinstance(wp, (int, float)) and isinstance(cp, (int, float)) \
            and wp > 0 and cp > 0:
        ratio = wp / cp
        rows.append(("window_p99_agreement", cp, wp, ratio - 1.0,
                     not (0.25 <= ratio <= 4.0)))
    burn = (n.get("slo") or {}).get("burn_rate")
    if isinstance(burn, (int, float)):
        rows.append(("slo_burn", 1.0, float(burn), float(burn) - 1.0,
                     burn > 1.0))
    # Multi-tenant gates (PR 16; rounds predating `--tenants` skip the
    # section rows, but chargeback exactness gates on ANY new artifact
    # that carries the `tenant_cost` digest):
    # - `tenant_victim_p99_x` — the victim tenant's p99 co-located
    #   with the greedy + doomed tenants must stay <= 2x its solo p99
    #   (absolute: the isolation promise the weighted-fair queue and
    #   per-tenant quotas exist for);
    # - `tenant_mismatches` / `tenant_deadlock` — chaos must not cost
    #   correctness or liveness (healthy values 0/false);
    # - `tenant_chargeback_exact` — per-tenant chargeback sums must
    #   equal the global device/link/cache counters exactly.
    tn = n.get("tenants") or {}
    solo = tn.get("victim_solo_p99_s")
    coloc = tn.get("victim_coloc_p99_s")
    if isinstance(solo, (int, float)) and solo > 0 \
            and isinstance(coloc, (int, float)):
        x = coloc / solo
        rows.append(("tenant_victim_p99_x", 2.0, round(x, 3),
                     x - 2.0, x > 2.0))
    mm = tn.get("mismatches")
    if isinstance(mm, (int, float)):
        rows.append(("tenant_mismatches", 0.0, float(mm), float(mm),
                     mm > 0))
    dl = tn.get("deadlock")
    if isinstance(dl, bool):
        rows.append(("tenant_deadlock", 0.0, float(dl), float(dl), dl))
    cb = tn.get("chargeback") or new.get("tenant_cost") or {}
    exact = cb.get("exact")
    if isinstance(exact, bool):
        rows.append(("tenant_chargeback_exact", 1.0, float(exact),
                     float(exact) - 1.0, not exact))
    ol = n.get("open_loop") or {}
    slo_qps = ol.get("qps_at_p99_slo")
    oslo = (old.get("serve") or {}).get("open_loop") or {}
    if isinstance(slo_qps, (int, float)):
        add("qps_at_p99_slo", oslo.get("qps_at_p99_slo"), slo_qps)
        if not isinstance(oslo.get("qps_at_p99_slo"), (int, float)):
            rows.append(("qps_at_p99_slo_floor", 0.0, float(slo_qps),
                         float(slo_qps), slo_qps <= 0))
    # Latency-anatomy gates (PR 17; rounds predating the sections skip):
    # - `critpath_sum_exact` — every sweep rate's stamped p99 query
    #   must satisfy the sum-exactness contract (segments sum to the
    #   measured wall within CRITPATH_EPSILON_S — absolute: the
    #   decomposition's one invariant, and a nonzero error means a
    #   segment was double-counted or dropped);
    # - `profiler_overhead` — the closed-loop QPS with the sampling
    #   profiler ON must stay within PROFILER_OVERHEAD_MAX of
    #   profiler-off (absolute: the price of always-on visibility is
    #   part of the contract, not a footnote).
    errs = [e["critical_path"]["p99_sum_error_s"]
            for e in (ol.get("sweep") or [])
            if isinstance((e.get("critical_path") or {})
                          .get("p99_sum_error_s"), (int, float))]
    if errs:
        worst = max(errs)
        rows.append(("critpath_sum_exact", CRITPATH_EPSILON_S, worst,
                     worst - CRITPATH_EPSILON_S,
                     worst > CRITPATH_EPSILON_S))
    ovh = (n.get("profiler") or {}).get("overhead_fraction")
    if isinstance(ovh, (int, float)):
        rows.append(("profiler_overhead", PROFILER_OVERHEAD_MAX,
                     float(ovh), ovh - PROFILER_OVERHEAD_MAX,
                     ovh > PROFILER_OVERHEAD_MAX))
    # Incident-plane gate (PR 18; rounds predating the `alerts` digest
    # skip): `clean_run_incidents` — the timed closed loop is a clean,
    # correctly-sized lap, so ANY incident fired during it is a false
    # positive of the alert rules (absolute: the healthy value is 0 and
    # nothing ratio-gates against zero). The open-loop sweep past the
    # knee may legitimately fire; those land in the digest but do not
    # gate.
    cf = (n.get("alerts") or {}).get("clean_run_fired")
    if isinstance(cf, (int, float)):
        rows.append(("clean_run_incidents", 0.0, float(cf), float(cf),
                     cf > 0))
    return rows


def compare(old: dict, new: dict, threshold: float):
    """[(name, old_ratio, new_ratio, change, gated)] for every
    comparable vs_baseline (higher is better), headline first — rungs
    for rung artifacts, per-query rows for query artifacts — plus the
    peak-HBM row and the rung-1 link share (both lower is better —
    they gate on GROWTH)."""
    rows = []

    def add(name, old_v, new_v, lower_is_better=False):
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            return
        change = new_v / old_v - 1.0
        gated = (change > threshold if lower_is_better
                 else change < -threshold)
        rows.append((name, old_v, new_v, change, gated))

    add("headline", old.get("vs_baseline"), new.get("vs_baseline"))
    for section, prefix in (("rungs", ""), ("queries", "")):
        old_entries = old.get(section) or {}
        new_entries = new.get(section) or {}
        for entry in sorted(set(old_entries) | set(new_entries)):
            o, n = old_entries.get(entry), new_entries.get(entry)
            if o is None or n is None:
                rows.append((prefix + entry,
                             (o or {}).get("vs_baseline"),
                             (n or {}).get("vs_baseline"), None, False))
                continue
            add(prefix + entry, o.get("vs_baseline"),
                n.get("vs_baseline"))
    add("peak_hbm_bytes",
        (old.get("memory") or {}).get("peak_hbm_bytes"),
        (new.get("memory") or {}).get("peak_hbm_bytes"),
        lower_is_better=True)
    add("rung1_link_share", _rung1_link_share(old),
        _rung1_link_share(new), lower_is_better=True)
    rows.extend(_segment_rows(old, new, threshold))
    rows.extend(_skipping_rows(old, new))
    rows.extend(_spmd_rows(old, new))
    return rows


def print_attribution(old: dict, new: dict, old_path: str,
                      new_path: str) -> None:
    """The failed gate's own diagnosis: run the differ on the gated
    pair and print the ranked attribution tree."""
    from hyperspace_tpu.telemetry import diff

    d = diff.diff_artifacts(old, new,
                            old_name=os.path.basename(old_path),
                            new_name=os.path.basename(new_path))
    print()
    print(d.format_tree())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="*",
                    help="explicit OLD NEW artifact paths")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="max tolerated vs_baseline drop (default 0.15)")
    ap.add_argument("--glob", default=None,
                    help="artifact family when paths are not given "
                         "(default BENCH_r*.json)")
    ap.add_argument("--tpcds", action="store_true",
                    help="gate the TPC-DS macro-bench family "
                         "(BENCH_TPCDS_r*.json) instead of the "
                         "micro-rung ladder")
    ap.add_argument("--serve", action="store_true",
                    help="gate the serving-bench family "
                         "(BENCH_SERVE_r*.json): scaling ratio, QPS, "
                         "p50/p99 latency growth, reject/timeout "
                         "rates")
    ap.add_argument("--advisor", action="store_true",
                    help="gate the index-advisor family "
                         "(BENCH_ADVISOR_r*.json): at least one "
                         "auto-built index, scanned-bytes reduction, "
                         "index-served repeats, bit-identity")
    ap.add_argument("--ingest", action="store_true",
                    help="gate the continuous-ingest family "
                         "(BENCH_INGEST_r*.json): staleness-vs-p99 "
                         "frontier, chaos zeros, warm hit-rate floor, "
                         "p99 degradation vs the quiet lap")
    ap.add_argument("--multichip", action="store_true",
                    help="gate the multi-chip scaling family "
                         "(MULTICHIP_r*.json): 8-device SMJ speedup, "
                         "per-device warm link-freedom, inter-stage "
                         "D2H, bit-identity vs 1 device")
    ap.add_argument("--no-diff", action="store_true",
                    help="skip the attribution tree on gate failure")
    args = ap.parse_args()

    if len(args.artifacts) == 2:
        old_path, new_path = args.artifacts
    elif not args.artifacts:
        pattern = args.glob or ("MULTICHIP_r*.json" if args.multichip
                                else "BENCH_ADVISOR_r*.json"
                                if args.advisor
                                else "BENCH_INGEST_r*.json"
                                if args.ingest
                                else "BENCH_SERVE_r*.json" if args.serve
                                else "BENCH_TPCDS_r*.json" if args.tpcds
                                else "BENCH_r*.json")
        old_path, new_path = pick_latest_two(pattern)
    else:
        ap.error("pass exactly two artifact paths, or none for auto")

    old = load_artifact(old_path)
    new = load_artifact(new_path)
    # Serving / multichip artifacts are content-detected like the other
    # families, so explicit paths gate correctly without the flag.
    serve_mode = args.serve or ("serve" in old and "serve" in new)
    multichip_mode = args.multichip or "multichip" in new
    advisor_mode = args.advisor or "advisor" in new
    ingest_mode = args.ingest or "ingest" in new
    rows = (compare_multichip(old, new, args.threshold) if multichip_mode
            else compare_advisor(old, new, args.threshold)
            if advisor_mode
            else compare_ingest(old, new, args.threshold)
            if ingest_mode
            else compare_serve(old, new, args.threshold) if serve_mode
            else compare(old, new, args.threshold))

    print(f"bench_regress: {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} "
          f"(gate: vs_baseline drop > {args.threshold:.0%})")
    regressions = []
    for name, old_v, new_v, change, gated in rows:
        if change is None:
            print(f"  {name:18s} {old_v!s:>9} -> {new_v!s:>9}   "
                  "(not in both artifacts; not gated)")
            continue
        flag = "REGRESSION" if gated else "ok"
        print(f"  {name:18s} {old_v:9.3f} -> {new_v:9.3f}   "
              f"{change:+7.1%}  {flag}")
        if gated:
            regressions.append(name)
    if regressions:
        if not args.no_diff:
            print_attribution(old, new, old_path, new_path)
        print(f"bench_regress: FAILED — {len(regressions)} gate(s) "
              f"regressed >{args.threshold:.0%}: "
              + ", ".join(regressions), file=sys.stderr)
        return 1
    print("bench_regress: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
