#!/usr/bin/env python
"""Pre-merge perf gate: diff the newest BENCH_*.json artifact against
the previous one and exit nonzero on a >15% regression in any rung's
`vs_baseline` ratio (or the headline ratio) — or a >15% GROWTH in
peak HBM bytes (`memory.peak_hbm_bytes`, the per-device-peak total
the memory accountant embeds): a query ladder that suddenly holds
more device memory is a pre-OOM regression even when its wall times
still pass. Artifacts predating the memory section simply don't gate.

  python scripts/bench_regress.py                 # newest two BENCH_r*.json
  python scripts/bench_regress.py OLD.json NEW.json
  python scripts/bench_regress.py --threshold 0.10 --glob 'BENCH_r*.json'

Artifacts are the driver-wrapped form ({"parsed": {...}}) or the raw
bench.py output ({"rungs": {...}}); both load. Rungs present in only
one artifact are reported but never gate (a new rung has no baseline;
a removed rung is a review question, not a perf fact). The 15%
default leaves headroom for the shared tunneled link's ~2x
time-of-day wobble on sub-ratios that sit near 1 (see `link_probe` in
bench_common.py) while still catching real order-of-magnitude cliffs;
artifacts carry the probe so a borderline failure can be attributed
to link vs code before overriding the gate.
"""

import argparse
import glob
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_artifact(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise SystemExit(f"{path}: not a bench artifact object")
    return doc


def _round_key(path: str):
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return (m is None, int(m.group(1)) if m else 0, path)


def pick_latest_two(pattern: str):
    paths = sorted(glob.glob(os.path.join(REPO_ROOT, pattern)),
                   key=_round_key)
    if len(paths) < 2:
        raise SystemExit(
            f"need at least two artifacts matching {pattern!r}; "
            f"found {len(paths)}")
    return paths[-2], paths[-1]


def _rung1_link_share(doc: dict):
    """(key_stage_link_s + perm_d2h_link_s) / build_s of rung 1 — the
    fraction of the build the device path spends on the link. The
    pipelined transfer engine exists to drive this DOWN; a >threshold
    rebound means the link seam regressed even if wall times still
    pass. None when the artifact predates the device-path phases."""
    r1 = (doc.get("rungs") or {}).get("1_build") or {}
    phases = r1.get("device_path") or {}
    stage = phases.get("key_stage_link_s")
    d2h = phases.get("perm_d2h_link_s")
    build = r1.get("build_s")
    if not all(isinstance(v, (int, float)) for v in (stage, d2h, build)) \
            or not build:
        return None
    return (stage + d2h) / build


def compare(old: dict, new: dict, threshold: float):
    """[(name, old_ratio, new_ratio, change, gated)] for every
    comparable vs_baseline (higher is better), headline first, plus
    the peak-HBM row and the rung-1 link share (both lower is better —
    they gate on GROWTH)."""
    rows = []

    def add(name, old_v, new_v, lower_is_better=False):
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            return
        change = new_v / old_v - 1.0
        gated = (change > threshold if lower_is_better
                 else change < -threshold)
        rows.append((name, old_v, new_v, change, gated))

    add("headline", old.get("vs_baseline"), new.get("vs_baseline"))
    old_rungs = old.get("rungs") or {}
    new_rungs = new.get("rungs") or {}
    for rung in sorted(set(old_rungs) | set(new_rungs)):
        o, n = old_rungs.get(rung), new_rungs.get(rung)
        if o is None or n is None:
            rows.append((rung, (o or {}).get("vs_baseline"),
                         (n or {}).get("vs_baseline"), None, False))
            continue
        add(rung, o.get("vs_baseline"), n.get("vs_baseline"))
    add("peak_hbm_bytes",
        (old.get("memory") or {}).get("peak_hbm_bytes"),
        (new.get("memory") or {}).get("peak_hbm_bytes"),
        lower_is_better=True)
    add("rung1_link_share", _rung1_link_share(old),
        _rung1_link_share(new), lower_is_better=True)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="*",
                    help="explicit OLD NEW artifact paths")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="max tolerated vs_baseline drop (default 0.15)")
    ap.add_argument("--glob", default="BENCH_r*.json",
                    help="artifact family when paths are not given")
    args = ap.parse_args()

    if len(args.artifacts) == 2:
        old_path, new_path = args.artifacts
    elif not args.artifacts:
        old_path, new_path = pick_latest_two(args.glob)
    else:
        ap.error("pass exactly two artifact paths, or none for auto")

    old = load_artifact(old_path)
    new = load_artifact(new_path)
    rows = compare(old, new, args.threshold)

    print(f"bench_regress: {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} "
          f"(gate: vs_baseline drop > {args.threshold:.0%})")
    regressions = []
    for name, old_v, new_v, change, gated in rows:
        if change is None:
            print(f"  {name:18s} {old_v!s:>9} -> {new_v!s:>9}   "
                  "(not in both artifacts; not gated)")
            continue
        flag = "REGRESSION" if gated else "ok"
        print(f"  {name:18s} {old_v:9.3f} -> {new_v:9.3f}   "
              f"{change:+7.1%}  {flag}")
        if gated:
            regressions.append(name)
    if regressions:
        print(f"bench_regress: FAILED — {len(regressions)} rung(s) "
              f"regressed >{args.threshold:.0%}: "
              + ", ".join(regressions), file=sys.stderr)
        return 1
    print("bench_regress: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
