"""Operator-level wall-clock profile of a TPC-DS query at scale.

Wraps every PhysicalNode.execute/execute_bucketed with timers (inclusive
time per operator instance) and prints the per-node breakdown of ONE
warm run against a persistent generated dataset + warehouse, so engine
hot spots at scale are measured instead of guessed.

    python scripts/profile_tpcds.py --query q25 --data /root/tpcds100 \
        --scale 100 [--rules-off]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--query", default="q25")
    ap.add_argument("--data", default="/root/tpcds100")
    ap.add_argument("--scale", type=float, default=100.0)
    ap.add_argument("--rules-off", action="store_true")
    ap.add_argument("--runs", type=int, default=2)
    args = ap.parse_args()

    from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
    from hyperspace_tpu.engine import physical
    from hyperspace_tpu.tpcds import QUERIES, generate
    from hyperspace_tpu.tpcds.queries import create_indexes

    paths = generate(os.path.join(args.data, "data"), scale=args.scale)
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": os.path.join(args.data, "wh"),
        "spark.hyperspace.index.num.buckets": "32"}))
    hs = Hyperspace(sess)
    dfs = {n: sess.read_parquet(p) for n, p in paths.items()}
    existing = set()
    try:
        cat = hs.indexes()
        if len(cat):
            existing = set(cat["name"])
    except Exception:
        pass
    t0 = time.perf_counter()
    create_indexes(hs, dfs, queries=[args.query], skip=existing)
    if time.perf_counter() - t0 > 1:
        print(f"index build: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)

    build, _oracle = QUERIES[args.query]
    if args.rules_off:
        sess.disable_hyperspace()
    else:
        sess.enable_hyperspace()

    # -- instrument ------------------------------------------------------
    records = []

    def wrap(cls, method):
        orig = getattr(cls, method)

        def timed(self, *a, **kw):
            t0 = time.perf_counter()
            out = orig(self, *a, **kw)
            records.append((time.perf_counter() - t0,
                            self.simple_string()[:110]))
            return out

        setattr(cls, method, timed)

    for name in dir(physical):
        cls = getattr(physical, name)
        if (isinstance(cls, type) and name.endswith("Exec")
                and hasattr(cls, "execute")):
            wrap(cls, "execute")
            if "execute_bucketed" in cls.__dict__:
                wrap(cls, "execute_bucketed")

    for i in range(args.runs):
        records.clear()
        t0 = time.perf_counter()
        out = build(dfs).collect()
        total = time.perf_counter() - t0
        print(f"run {i}: {total:.2f}s total, {out.num_rows} rows",
              file=sys.stderr)
    # Last run's breakdown, slowest first (times are INCLUSIVE of
    # children — read top-down).
    for dt, label in sorted(records, reverse=True)[:25]:
        print(f"{dt:9.3f}s  {label}")


if __name__ == "__main__":
    main()
