"""Unified TPC-DS profiling against a PERSISTENT workspace (data +
indexes reused across runs) — consolidates the former prof_tpcds.py /
profile_tpcds.py pair into one script driven by the engine's own
telemetry records instead of ad-hoc monkeypatching.

  python scripts/profile_tpcds.py q64 [--scale 10] [--runs 3]
      [--work /tmp/hs_prof] [--no-fuse] [--rules-off]
      [--mode class|node] [--trace-out trace.json] [--trace-dir DIR]
      [--registry]

Modes (both read the LAST timed run's `QueryMetrics`):
  class  per-PhysicalNode-class SELF seconds + call counts (the q64
         perf dev loop view; default)
  node   the 25 slowest operator INSTANCES, inclusive wall (read
         top-down — times include children)

Plus fusion-stage STATS (dispatch/sync seconds; the registry-backed
`engine.fusion.STATS` view), an optional process trace export in
Chrome trace-event format (`--trace-out`, loads in chrome://tracing /
ui.perfetto.dev), an optional XLA profiler capture for the last run
(`--trace-dir`), and an optional Prometheus registry dump
(`--registry`).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("query", nargs="?", default=None)
    ap.add_argument("--query", dest="query_opt", default="q64",
                    help="query name (compat alias for the positional)")
    ap.add_argument("--scale", type=float, default=10.0)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--work", default="/tmp/hs_prof")
    ap.add_argument("--no-fuse", action="store_true")
    ap.add_argument("--rules-off", action="store_true")
    ap.add_argument("--mode", choices=("class", "node"), default="class")
    ap.add_argument("--trace-out", default=None,
                    help="export engine spans as Chrome trace-event "
                         "JSON to this path")
    ap.add_argument("--trace-dir", default=None,
                    help="XLA profiler capture dir for the last run")
    ap.add_argument("--registry", action="store_true",
                    help="print the Prometheus registry dump at exit")
    args = ap.parse_args()
    query = args.query or args.query_opt

    from hyperspace_tpu import (Hyperspace, HyperspaceConf,
                                HyperspaceSession, telemetry)
    from hyperspace_tpu.engine import fusion
    from hyperspace_tpu.tpcds import QUERIES, generate
    from hyperspace_tpu.tpcds.queries import create_indexes

    if args.trace_out:
        telemetry.enable_tracing()

    work = os.path.join(args.work, f"s{args.scale:g}")
    data_dir = os.path.join(work, "data")
    wh = os.path.join(work, "wh")
    t0 = time.perf_counter()
    paths = generate(data_dir, scale=args.scale)  # reuses existing files
    print(f"generate/reuse: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    conf_map = {"hyperspace.warehouse.dir": wh,
                "spark.hyperspace.index.num.buckets": "32"}
    extra = os.environ.get("BENCH_TPCDS_CONF")
    if extra:
        conf_map.update(json.loads(extra))
    if args.no_fuse:
        conf_map["spark.hyperspace.execution.fusion.enabled"] = "false"
    sess = HyperspaceSession(HyperspaceConf(conf_map))
    hs = Hyperspace(sess)
    dfs = {n: sess.read_parquet(p) for n, p in paths.items()}
    idx_df = hs.indexes()
    existing = set(idx_df["name"]) if len(idx_df) else set()
    t0 = time.perf_counter()
    create_indexes(hs, dfs, queries=[query], skip=existing)
    print(f"index build (skip {len(existing)} existing): "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    if args.rules_off:
        sess.disable_hyperspace()
    else:
        sess.enable_hyperspace()
    build, _oracle = QUERIES[query]

    build(dfs).collect()  # warm: compiles, file listings, caches
    for k in fusion.STATS:
        fusion.STATS[k] = 0 if isinstance(fusion.STATS[k], int) else 0.0
    walls = []
    metrics = None
    for r in range(args.runs):
        if args.trace_dir and r == args.runs - 1:
            sess.conf.set("spark.hyperspace.trace.dir", args.trace_dir)
        t0 = time.perf_counter()
        out, metrics = build(dfs).collect(with_metrics=True)
        walls.append(time.perf_counter() - t0)
    print(f"rows={out.num_rows} walls={[round(w, 3) for w in walls]}")
    total = sum(walls)

    if args.mode == "class":
        # SELF seconds per operator class over the LAST run, from the
        # recorder's parent/child linkage (the same subtraction
        # `QueryMetrics.summary` ships in bench artifacts).
        per_op = metrics.summary()["operators"]
        print(f"\nper-class SELF seconds, last run "
              f"(of {walls[-1]:.3f}s):")
        for name, ent in sorted(per_op.items(),
                                key=lambda kv: -kv[1]["self_s"]):
            print(f"  {name:26s} calls={ent['count']:4d}  "
                  f"self={ent['self_s']:8.3f}s "
                  f"({100 * ent['self_s'] / walls[-1]:4.1f}%)")
    else:
        # Slowest operator INSTANCES, inclusive wall — read top-down.
        records = sorted(metrics.operators, key=lambda op: -op.wall_s)
        print("\nslowest operator instances, last run (INCLUSIVE of "
              "children — read top-down):")
        for op in records[:25]:
            rows = f" rows={op.rows_out}" if op.rows_out is not None else ""
            print(f"{op.wall_s:9.3f}s  {op.label[:110]}{rows}")

    print(f"\nfusion STATS over {args.runs} timed runs "
          f"(total {total:.3f}s): {dict(fusion.STATS)}")
    if args.trace_out:
        info = telemetry.export_trace(args.trace_out)
        print(f"trace: {info['events']} events -> {info['path']} "
              f"(load in chrome://tracing or ui.perfetto.dev)",
              file=sys.stderr)
    if args.registry:
        print("\n" + telemetry.get_registry().to_text())


if __name__ == "__main__":
    main()
