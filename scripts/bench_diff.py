#!/usr/bin/env python
"""Regression attribution CLI: diff two bench artifacts and print the
ranked attribution tree (`telemetry/diff.py`).

  python scripts/bench_diff.py OLD.json NEW.json
  python scripts/bench_diff.py BENCH_TPCDS_r03.json BENCH_TPCDS_r04.json
  python scripts/bench_diff.py OLD.json NEW.json --json   # machine form
  python scripts/bench_diff.py OLD.json NEW.json --query q64

Artifacts are expected in the canonical schema
(`telemetry/artifact.py`); legacy rounds are migrated IN MEMORY with a
visible note (the attribution is then per-lane only — migrate the
committed file with `python -m hyperspace_tpu.telemetry.artifact
migrate FILE` to make the note part of the record). Driver command
envelopes (`{parsed: ...}`) unwrap automatically.

Exit code: 0 — this tool diagnoses; `scripts/bench_regress.py` gates
(and auto-runs this differ when a gate fails).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Attribute the wall-clock delta between two bench "
                    "artifacts to telemetry buckets.")
    ap.add_argument("old", help="previous-round artifact path")
    ap.add_argument("new", help="current-round artifact path")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable diff (to_json)")
    ap.add_argument("--query", default=None,
                    help="restrict the report to one query/rung name")
    args = ap.parse_args()

    from hyperspace_tpu.telemetry import artifact, diff

    docs = []
    for path in (args.old, args.new):
        try:
            docs.append(artifact.load(path))
        except artifact.LegacyArtifactError:
            docs.append(artifact.load(path, migrate_legacy=True))
            print(f"bench_diff: note: {os.path.basename(path)} is a "
                  "legacy-schema artifact, migrated in memory",
                  file=sys.stderr)
    old_doc, new_doc = docs

    d = diff.diff_artifacts(old_doc, new_doc,
                            old_name=os.path.basename(args.old),
                            new_name=os.path.basename(args.new))
    if args.query:
        d.queries = [q for q in d.queries if q.name == args.query]
        if not d.queries:
            print(f"bench_diff: no query/rung named {args.query!r} "
                  "in both artifacts", file=sys.stderr)
            return 2
    print(d.to_json() if args.json else d.format_tree())
    return 0


if __name__ == "__main__":
    sys.exit(main())
