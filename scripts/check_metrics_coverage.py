#!/usr/bin/env python
"""Lint: every PhysicalNode subclass must emit operator metrics
records, and every Action subclass must emit an action report.

`PhysicalNode.__init_subclass__` (engine/physical.py) wraps each
subclass's `execute` / `execute_bucketed` with the telemetry operator
hook and stamps the wrapper with `__telemetry_instrumented__`;
`Action.__init_subclass__` (actions/base.py) does the same for `run`
with `__action_report_instrumented__`. This check imports EVERY module
under `hyperspace_tpu`, walks both live subclass trees, and fails if
any subclass resolves an entry point to an unstamped callable — i.e.
an operator that could execute without a metrics record, or an index
maintenance action that could run without emitting its structured
report (assigned after class creation, shadowed by a plain function,
or otherwise routed around the instrumentation).

Compile coverage rides the same check: every `jax.jit` entry point
must route through `telemetry.compilation.instrumented_jit` (the
compile-span stamp — trace counters, retrace-cause events, Perfetto
compile track). A direct `jax.jit(...)` / `partial(jax.jit, ...)`
call anywhere in the package besides telemetry/compilation.py is a
jit entry point that can trace without being seen, and fails the
lint; so does a registered wrapper missing its
`__compile_span_instrumented__` stamp.

Runs in the tier-1 flow via `tests/test_telemetry.py`; also runnable
standalone:  python scripts/check_metrics_coverage.py
"""

import ast
import importlib
import os
import pkgutil
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _all_subclasses(cls):
    for sub in cls.__subclasses__():
        yield sub
        yield from _all_subclasses(sub)


# Direct jit construction — the only sanctioned caller is the
# instrumented_jit wrapper itself. Doc mentions of the NAME don't
# match (the pattern requires a call/partial form).
_RAW_JIT_RE = re.compile(r"jax\.jit\s*\(|partial\(\s*jax\.jit\b")
_JIT_ALLOWED = os.path.join("telemetry", "compilation.py")


def check_jit_entry_points(package_dir: str):
    """Source lint: no direct `jax.jit` outside the sanctioned wrapper
    module, and every registered wrapper carries the compile-span
    stamp."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _JIT_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_JIT_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: jit entry "
                            "point lacks the compile-span stamp — route "
                            "it through telemetry.instrumented_jit")
    from hyperspace_tpu.telemetry import compilation
    for name, wrapper in sorted(compilation.REGISTRY.items()):
        if not getattr(wrapper, "__compile_span_instrumented__", False):
            failures.append(
                f"instrumented jit {name!r} lost its compile-span stamp")
    return failures


# The ONE sanctioned link seam: every host->device placement routes
# through the pipelined transfer engine (chunked staging, in-flight
# byte window, fault injection, link.{h2d,d2h}.* counters). A raw
# `jax.device_put` anywhere else in the package is a link crossing the
# engine cannot pipeline, observe, or fault-inject. Tests and bench
# drivers live outside the package tree and stay exempt (the raw-link
# probe in bench_common.py MUST bypass the engine by design).
_RAW_PUT_RE = re.compile(r"jax\.device_put\s*\(|partial\(\s*jax\.device_put\b")
_PUT_ALLOWED = os.path.join("io", "transfer.py")


def check_device_put_seam(package_dir: str):
    """Source lint: no direct `jax.device_put` outside io/transfer.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _PUT_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_PUT_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: raw "
                            "jax.device_put bypasses the transfer "
                            "engine — route it through io/transfer.py")
    return failures


# The ONE sanctioned device-residency seam: HBM-resident batches live
# in the segment cache (io/segcache.py — version-keyed, byte-budgeted,
# single-flight fills, index-FSM invalidation). The legacy device-batch
# LRU's entry points are BANNED outside that module: a raw
# `_device_cache` map or `read_device_batch(...)` call anywhere else is
# device residency the cache cannot budget, invalidate, or coalesce.
_RAW_DEVCACHE_RE = re.compile(r"\b_device_cache\b|\bread_device_batch\b")
_DEVCACHE_ALLOWED = os.path.join("io", "segcache.py")


def check_segment_cache_seam(package_dir: str):
    """Source lint: no direct `_device_cache`/`read_device_batch`
    access outside io/segcache.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _DEVCACHE_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_DEVCACHE_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: direct "
                            "device-batch cache access bypasses the "
                            "HBM segment cache — route it through "
                            "io/segcache.py")
    return failures


# The ONE sanctioned artifact emitter: every bench driver's committed
# JSON routes through telemetry.artifact.make_artifact, which stamps
# `schema_version` and unconditionally attaches `process_metrics`,
# `memory`, and `transfer`. A driver assembling its own top-level
# artifact can silently drop the telemetry the regression differ
# attributes from — exactly how the r03/r04 TPC-DS rounds became
# mechanically incomparable.
_BENCH_EXEMPT = ("bench_common.py",)  # helpers; prints no artifact


def check_bench_artifact_seam(repo_root: str):
    """Source lint: every `bench*.py` driver at the repo root must
    route its artifact through `telemetry.artifact.make_artifact`."""
    import glob as _glob

    failures = []
    for path in sorted(_glob.glob(os.path.join(repo_root, "bench*.py"))):
        fname = os.path.basename(path)
        if fname in _BENCH_EXEMPT:
            continue
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if "make_artifact(" not in src:
            failures.append(
                f"{fname}: bench driver emits an artifact without "
                "routing through telemetry.artifact.make_artifact — "
                "schema_version/process_metrics can silently go "
                "missing from a committed round")
    return failures


# The ONE sanctioned serving-concurrency point: engine/ code runs on
# the caller's thread or on the sanctioned pools
# (`telemetry.propagating`-wrapped executors); a raw threading.Thread
# in the engine is concurrency the scheduler cannot admit, cancel,
# budget, or drain at shutdown. Only the scheduler module itself may
# own threads (it currently owns none — waiting happens on caller
# threads — but it is the one place that legitimately could).
_RAW_THREAD_RE = re.compile(r"threading\.Thread\s*\(")
_THREAD_ALLOWED = os.path.join("engine", "scheduler.py")


def check_engine_thread_seam(package_dir: str):
    """Source lint: no raw `threading.Thread(...)` under engine/
    outside scheduler.py."""
    failures = []
    engine_dir = os.path.join(package_dir, "engine")
    for root, _dirs, files in os.walk(engine_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _THREAD_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_THREAD_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: raw "
                            "threading.Thread in engine/ — concurrency "
                            "the query scheduler cannot admit, cancel, "
                            "or drain; route it through "
                            "engine/scheduler.py or a propagating-"
                            "wrapped executor")
    return failures


def check_serving_error_counters():
    """Every typed serving error must have a registry counter behind
    it: each `QueryServingError` subclass declares `counter`, and
    `scheduler.SERVING_ERROR_COUNTERS` (the table the scheduler's
    raise-path bookkeeping reads) must list exactly that counter — a
    new serving failure mode cannot ship without a scrape-able
    series."""
    from hyperspace_tpu.engine import scheduler
    from hyperspace_tpu.exceptions import QueryServingError

    failures = []
    seen = set()
    for cls in sorted(set(_all_subclasses(QueryServingError)),
                      key=lambda c: c.__name__):
        counter = getattr(cls, "counter", "")
        if not counter:
            failures.append(
                f"{cls.__module__}.{cls.__name__}: typed serving error "
                "lacks a registry counter (declare `counter = "
                "'serve.<name>'`)")
            continue
        mapped = scheduler.SERVING_ERROR_COUNTERS.get(cls.__name__)
        if mapped != counter:
            failures.append(
                f"{cls.__module__}.{cls.__name__}: counter "
                f"{counter!r} not registered in "
                "scheduler.SERVING_ERROR_COUNTERS "
                f"(found {mapped!r}) — the scheduler cannot count what "
                "it does not know about")
        seen.add(cls.__name__)
    for name in scheduler.SERVING_ERROR_COUNTERS:
        if name not in seen:
            failures.append(
                f"scheduler.SERVING_ERROR_COUNTERS lists {name!r} but "
                "no such QueryServingError subclass exists")
    return failures


# Index-kind serde registry: every derived-dataset index kind must be
# registered in `log_entry.DERIVED_DATASET_KINDS` (so IndexLogEntry
# serde can dispatch it through the log FSM) and must round-trip
# `from_dict(x.to_dict()) == x` on its declared `_serde_sample()`. A
# new index-kind class that ships without registration would serialize
# through `begin()` and then be UNREADABLE by every later action and
# rule — this lint makes that a build failure, not a corrupt catalog.
def check_index_kind_serde():
    from hyperspace_tpu.index import log_entry

    failures = []
    registry = log_entry.DERIVED_DATASET_KINDS
    registered = {cls for cls in registry.values()}
    for name, obj in sorted(vars(log_entry).items()):
        if not isinstance(obj, type):
            continue
        kind = getattr(obj, "kind", None)
        if not isinstance(kind, str) or not kind.endswith("Index"):
            continue
        if obj not in registered:
            failures.append(
                f"index.log_entry.{name}: index-kind class (kind="
                f"{kind!r}) missing from DERIVED_DATASET_KINDS — "
                "IndexLogEntry serde cannot dispatch it")
            continue
        if registry.get(kind) is not obj:
            failures.append(
                f"index.log_entry.{name}: registered under a kind "
                f"string that is not its own ({kind!r})")
    for kind, cls in sorted(registry.items()):
        sample_fn = getattr(cls, "_serde_sample", None)
        if sample_fn is None:
            failures.append(
                f"{cls.__name__}: registered index kind lacks "
                "_serde_sample() — the serde round-trip cannot be "
                "proven")
            continue
        try:
            sample = sample_fn()
            d = sample.to_dict()
            back = log_entry.derived_dataset_from_dict(d)
            if back.to_dict() != d:
                failures.append(
                    f"{cls.__name__}: serde round-trip is lossy "
                    "(from_dict(to_dict(x)).to_dict() != to_dict(x))")
        except Exception as exc:
            failures.append(
                f"{cls.__name__}: serde round-trip raised {exc!r}")
    return failures


# The ONE sanctioned sketch-consultation point: data-skipping pruning
# decisions live in the rules module (`plan/rules/skipping.py` calls
# into the blob loader `index/sketch.py`). A `load_sketches(...)` or
# `prune_files(...)` call anywhere else is a pruning decision the
# optimizer cannot see, the telemetry cannot attribute, and the
# no-false-negative property test does not cover.
_RAW_SKETCH_RE = re.compile(r"\bload_sketches\s*\(|\bprune_files\s*\(")
_SKETCH_ALLOWED = (os.path.join("index", "sketch.py"),)
_SKETCH_ALLOWED_DIR = os.path.join("plan", "rules")


def check_sketch_seam(package_dir: str):
    """Source lint: no sketch-consulting calls outside plan/rules/ and
    the blob-IO module."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel in _SKETCH_ALLOWED \
                    or rel.startswith(_SKETCH_ALLOWED_DIR + os.sep):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_SKETCH_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: sketch-"
                            "consulting call outside the rules module — "
                            "pruning decisions belong in "
                            "plan/rules/skipping.py")
    return failures


# The ONE sanctioned layout-spec seam: every NamedSharding /
# PartitionSpec / shard_map the package constructs comes from
# parallel/mesh.py (row_spec, shard_rows, replicated, compat_shard_map,
# bucket_ranges) — the born-sharded on-disk layout, the per-device cache
# residency, and the SPMD collectives all derive from that ONE map, and a
# raw construction elsewhere is a layout that can silently drift from it.
# SLICE TOPOLOGY rides the same seam: constructing a `jax.sharding.Mesh`
# (flat or hierarchical), reshaping a device grid, or spelling the DCN
# axis name as a literal anywhere else is a (slice, device) topology the
# bucket-range hierarchy (`slice_bucket_ranges`), the replica router,
# and the two-hop repartition cannot see — topology construction stays
# inside parallel/mesh.py (`make_mesh` / `slice_submesh`).
_RAW_SHARDING_RE = re.compile(
    r"NamedSharding\s*\(|PartitionSpec\s*\(|(?<!compat_)shard_map\s*\(|"
    r"from\s+jax\.sharding\s+import|from\s+jax\.experimental\s+import\s+"
    r"shard_map|from\s+jax\.experimental\.shard_map\s+import|"
    r"(?<![\w.])Mesh\s*\(|jax\.sharding\.Mesh|create_device_mesh\s*\(|"
    r"[\"']dcn[\"']")
_SHARDING_ALLOWED = os.path.join("parallel", "mesh.py")


def check_sharding_seam(package_dir: str):
    """Source lint: no raw NamedSharding/PartitionSpec/shard_map/Mesh/
    device-grid/slice-topology construction outside parallel/mesh.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _SHARDING_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_SHARDING_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: raw "
                            "sharding/layout construction outside "
                            "parallel/mesh.py — derive the spec from "
                            "the canonical helpers (row_spec/"
                            "shard_rows/replicated/compat_shard_map/"
                            "bucket_ranges)")
    return failures


# The ONE sanctioned advisor build point: every index the advisor
# creates goes through its executor module, which routes through the
# collection manager's lease-gated Create path (stale-writer recovery,
# OCC one-winner, action reports). Constructing an Action — or even
# importing the actions package — anywhere else under advisor/ is a
# build that could bypass the lease and corrupt an index a concurrent
# maintenance verb owns.
_RAW_ADVISOR_BUILD_RE = re.compile(
    r"\b[A-Z]\w*Action\s*\(|from\s+hyperspace_tpu\.actions\b|"
    r"import\s+hyperspace_tpu\.actions\b")
_ADVISOR_BUILD_ALLOWED = os.path.join("advisor", "executor.py")


def check_advisor_build_seam(package_dir: str):
    """Source lint: no Action construction / actions import inside
    advisor/ outside executor.py."""
    failures = []
    advisor_dir = os.path.join(package_dir, "advisor")
    for root, _dirs, files in os.walk(advisor_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _ADVISOR_BUILD_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_ADVISOR_BUILD_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: Action "
                            "construction inside advisor/ outside the "
                            "executor — advisor builds must go through "
                            "advisor/executor.py's lease path")
    return failures


def check_ingest_build_seam(package_dir: str):
    """Source lint: no Action construction / actions import anywhere
    under engine/ — in particular the ingest coordinator
    (engine/ingest.py) must drive every refresh through the collection
    manager's lease-gated path (stale-writer recovery, OCC one-winner),
    never by constructing a maintenance verb directly. There is NO
    allowed file: the engine executes queries; the actions package owns
    writes."""
    failures = []
    engine_dir = os.path.join(package_dir, "engine")
    for root, _dirs, files in os.walk(engine_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_ADVISOR_BUILD_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: Action "
                            "construction inside engine/ — refresh and "
                            "every other maintenance verb must go "
                            "through the collection manager's "
                            "lease-gated path (see engine/ingest.py)")
    return failures


# The ONE sanctioned batched-execution point: the stacked-predicate
# program (`parallel/spmd.batched_predicate_masks`, the serve.batch jit
# entry) may only be invoked by the batching lane in engine/batcher.py.
# Any other caller is a K-query execution the scheduler never grouped:
# its members would have no cohort accounting, no per-member deadline
# settlement, and no fallback contract — exactly the properties
# tests/test_batcher.py pins on the sanctioned lane.
_RAW_BATCH_RE = re.compile(r"\bbatched_predicate_masks\s*\(")
_BATCH_DEF = os.path.join("parallel", "spmd.py")
_BATCH_ALLOWED = os.path.join("engine", "batcher.py")


def check_batch_seam(package_dir: str):
    """Source lint: no `batched_predicate_masks(...)` calls outside the
    defining module and engine/batcher.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel in (_BATCH_DEF, _BATCH_ALLOWED):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_BATCH_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: batched-"
                            "program invocation outside the batching "
                            "lane — route it through engine/batcher.py "
                            "so cohort accounting, per-member deadlines,"
                            " and the fallback contract apply")
    return failures


# The legacy per-query-placement mesh join (`parallel/join.py`) is
# DELETED — the born-sharded SPMD lane (`parallel/spmd.py`) is the one
# distributed execution architecture. Any import or call of its entry
# points is a resurrection of the second architecture the deletion
# exists to prevent.
_LEGACY_JOIN_RE = re.compile(
    r"hyperspace_tpu\.parallel\.join\b|"
    r"from\s+hyperspace_tpu\.parallel\s+import\s+(?:[\w,\s]*\b)?join\b|"
    r"\bdistributed_bucketed_join_indices\s*\(|"
    r"\bdistributed_semi_anti_indices\s*\(")


def check_legacy_mesh_path(repo_root: str):
    """Source lint: no references to the deleted legacy mesh-join entry
    points anywhere in the repo (package, tests, benches, scripts)."""
    failures = []
    for root, dirs, files in os.walk(repo_root):
        dirs[:] = [d for d in dirs
                   if d not in ("__pycache__", ".git", "node_modules")]
        for fname in files:
            if not fname.endswith(".py") or fname == os.path.basename(
                    __file__):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, repo_root)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _LEGACY_JOIN_RE.search(line):
                        failures.append(
                            f"{rel}:{lineno}: reference to the deleted "
                            "legacy mesh join (parallel/join.py) — the "
                            "born-sharded SPMD lane (parallel/spmd.py) "
                            "is the one distributed join architecture")
    return failures


# The ONE sanctioned dictionary-remap constructor: cross-side string
# unification on the SPMD lane goes through
# `parallel/spmd.string_remap_tables` (content-keyed segment-cache
# residency, `spmd.strings.*` accounting, in-program application). A
# remap built elsewhere would re-pay the merge per query and ship
# uncached tables over the link.
_RAW_REMAP_RE = re.compile(r"\bstring_remap_tables\s*\(")
_REMAP_ALLOWED = os.path.join("parallel", "spmd.py")


def check_string_remap_seam(package_dir: str):
    """Source lint: no `string_remap_tables(...)` construction outside
    parallel/spmd.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _REMAP_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_REMAP_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: dictionary-"
                            "remap construction outside parallel/spmd.py"
                            " — remap tables must come from the cached "
                            "seam (string_remap_tables) so warm queries "
                            "never rebuild or reship them")
    return failures


# Doc drift: every counter/gauge/histogram NAME LITERAL registered in
# the package must have a row in docs/telemetry.md. Dynamic names
# (f-strings — per-index, per-entry-point series) are exempt by
# construction: the regex requires a plain string literal as the first
# argument. A metric that ships without its doc row is a series an
# operator cannot interpret from the scrape alone.
_METRIC_NAME_RE = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*\n?\s*"([^"]+)"')


def _expand_braces(token: str):
    """`a.{x,y}.b` -> `a.x.b`, `a.y.b` (multiple groups expand
    cross-product) — the doc table's compact spelling for metric
    families."""
    m = re.search(r"\{([^{}]*)\}", token)
    if m is None:
        yield token
        return
    for alt in m.group(1).split(","):
        yield from _expand_braces(token[:m.start()] + alt
                                  + token[m.end():])


def check_metric_doc_rows(package_dir: str, repo_root: str):
    """Source lint: every literal metric name must appear in
    docs/telemetry.md (plainly, or inside a backticked
    `family.{a,b}`-style brace pattern)."""
    doc_path = os.path.join(repo_root, "docs", "telemetry.md")
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc = f.read()
    except OSError:
        return [f"{doc_path}: missing — the metrics reference lives "
                "there"]
    documented = set()
    for token in re.findall(r"`([^`\s]+)`", doc):
        if "{" in token:
            documented.update(_expand_braces(token))
    names = {}
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for m in _METRIC_NAME_RE.finditer(src):
                names.setdefault(m.group(1), f"hyperspace_tpu/{rel}")
    failures = []
    for name in sorted(names):
        if name not in doc and name not in documented:
            failures.append(
                f"{names[name]}: metric {name!r} has no row in "
                "docs/telemetry.md — document the series before "
                "shipping it")
    return failures


# The ONE sanctioned tenant-attribution seam: the serving tenant rides
# a telemetry contextvar (`telemetry._tenant`) that the chargeback
# mirror (`charge_tenant`) and the flight/SLO attribution all read.
# The ONLY writers are the declared seam: `telemetry.tenant_scope`
# (the contextvar owner), `HyperspaceSession.tenant` (the sticky
# session default), and the scheduler's collect() (which resolves the
# effective tenant and opens the scope around execution). A raw
# `_tenant.set(...)` — or even a `tenant_scope(...)` entered anywhere
# else in the package — is a query whose device/link/cache charges
# land on a tenant the admission plane never admitted, silently
# breaking the chargeback exactness contract
# (`bench_regress.py --serve` gates per-tenant sums == globals).
_RAW_TENANT_RE = re.compile(r"\b_tenant\s*\.\s*set\s*\(|"
                            r"\btenant_scope\s*\(")
_TENANT_ALLOWED = (os.path.join("telemetry", "__init__.py"),
                   os.path.join("engine", "scheduler.py"),
                   os.path.join("engine", "session.py"))


def check_tenant_seam(package_dir: str):
    """Source lint: no tenant contextvar writes (`_tenant.set` /
    `tenant_scope`) outside the telemetry owner, the session setter,
    and the scheduler's collect seam."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel in _TENANT_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_TENANT_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: tenant "
                            "contextvar write outside the sanctioned "
                            "seam — set the tenant via "
                            "session.tenant()/collect(tenant=...) so "
                            "admission and chargeback see the same "
                            "identity")
    return failures


# The ONE sanctioned HTTP surface: the operations endpoint
# (`telemetry/ops_server.py` — localhost-bound by default, counted,
# error-guarded). A raw `http.server` anywhere else is a listening
# socket the ops-plane knobs don't govern and the security note
# doesn't cover.
_RAW_HTTP_RE = re.compile(
    r"http\.server|ThreadingHTTPServer|BaseHTTPRequestHandler")
_HTTP_ALLOWED = os.path.join("telemetry", "ops_server.py")


def check_http_server_seam(package_dir: str):
    """Source lint: no `http.server` use outside telemetry/ops_server.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _HTTP_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_HTTP_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: raw "
                            "http.server use outside the ops endpoint "
                            "— serve it through telemetry/ops_server.py "
                            "(bind policy, counters, error guards)")
    return failures


# The ONE sanctioned backoff point: every storage retry routes through
# the policy in utils/retry.py (typed classification, conf-driven
# backoff, io.retries/io.giveups counters, fault-injection coverage).
_RETRY_ALLOWED = os.path.join("utils", "retry.py")


def check_retry_seams(package_dir: str):
    """AST lint: a `sleep` call lexically inside an `except` handler is
    an ad-hoc retry loop — invisible to the retry conf, uncounted by the
    io.* counters, unreachable by the fault-injection tests. Only
    utils/retry.py may back off."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _RETRY_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # surfaced by the import walk instead

            class Visitor(ast.NodeVisitor):
                def __init__(self):
                    self.except_depth = 0

                def visit_ExceptHandler(self, node):
                    self.except_depth += 1
                    self.generic_visit(node)
                    self.except_depth -= 1

                def visit_Call(self, node):
                    func = node.func
                    name = (func.attr if isinstance(func, ast.Attribute)
                            else func.id if isinstance(func, ast.Name)
                            else None)
                    if name == "sleep" and self.except_depth:
                        failures.append(
                            f"hyperspace_tpu/{rel}:{node.lineno}: ad-hoc "
                            "retry loop (sleep inside an except block) — "
                            "route the backoff through utils/retry.py")
                    self.generic_visit(node)

            Visitor().visit(tree)
    return failures


# The ONE sanctioned profiling seam: `telemetry/profiler.py` owns both
# instruments — the host stack sampler and the `jax.profiler` device
# capture (jax allows one active trace session per process; the seam's
# lock serializes them, and triggered captures inherit its rate limit
# and keep-N pruning). A raw `jax.profiler` / `cProfile` /
# `sys.setprofile` anywhere else is profiling the overhead gate does
# not measure and the capture policy does not govern.
_RAW_PROFILER_RE = re.compile(
    r"jax\s*\.\s*profiler|\bcProfile\b|sys\s*\.\s*setprofile")
_PROFILER_ALLOWED = os.path.join("telemetry", "profiler.py")


def check_profiler_seam(package_dir: str):
    """Source lint: no jax.profiler / cProfile / sys.setprofile use
    outside telemetry/profiler.py."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == _PROFILER_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_PROFILER_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: raw "
                            "profiler use outside the profiling seam — "
                            "route it through telemetry/profiler.py "
                            "(device_trace / the sampling profiler)")
    return failures


def check_critpath_doc_rows(repo_root: str):
    """Doc-drift lint for the critical-path family: the per-segment
    counters are emitted with an f-string
    (`critpath.<segment>.seconds`), so the generic literal-name lint
    cannot see them — require a docs/telemetry.md row for every
    segment in the closed set explicitly."""
    from hyperspace_tpu.telemetry import critical_path
    doc_path = os.path.join(repo_root, "docs", "telemetry.md")
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc = f.read()
    except OSError:
        return [f"{doc_path}: missing — the metrics reference lives "
                "there"]
    documented = set(re.findall(r"`([^`\s]+)`", doc))
    for token in list(documented):
        if "{" in token:
            documented.update(_expand_braces(token))
    failures = []
    for segment in critical_path.SEGMENTS:
        name = f"critpath.{segment}.seconds"
        if name not in doc and name not in documented:
            failures.append(
                f"hyperspace_tpu/telemetry/critical_path.py: segment "
                f"counter {name!r} has no row in docs/telemetry.md — "
                "every segment of the closed set must be documented")
    return failures


def check_alert_rule_doc_rows(repo_root: str):
    """Doc-drift lint for the default alert rules: every series a
    shipped rule reads must have a docs/telemetry.md row (the
    `hit_ratio` kind reads the `<series>.{hits,misses}` counter family;
    warm gates read their counter too). An alert an operator cannot
    trace to a documented series is an incident nobody can interpret —
    and each rule's NAME must appear in the default-rule table so its
    conf override knobs are discoverable."""
    from hyperspace_tpu.telemetry import alerts
    doc_path = os.path.join(repo_root, "docs", "telemetry.md")
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc = f.read()
    except OSError:
        return [f"{doc_path}: missing — the metrics reference lives "
                "there"]
    documented = set(re.findall(r"`([^`\s]+)`", doc))
    for token in list(documented):
        if "{" in token:
            documented.update(_expand_braces(token))
    failures = []
    for rule in alerts.DEFAULT_RULES:
        series = ([f"{rule.series}.hits", f"{rule.series}.misses"]
                  if rule.kind == "hit_ratio" else
                  [rule.series] if rule.series else [])
        if rule.warm_counter:
            series.append(rule.warm_counter)
        for name in series:
            if name not in doc and name not in documented:
                failures.append(
                    f"hyperspace_tpu/telemetry/alerts.py: default rule "
                    f"{rule.name!r} reads series {name!r} which has no "
                    "row in docs/telemetry.md — an undocumented series "
                    "cannot anchor an alert")
        if rule.name not in doc:
            failures.append(
                f"hyperspace_tpu/telemetry/alerts.py: default rule "
                f"{rule.name!r} missing from the docs/telemetry.md "
                "rule table — its conf override knobs are "
                "undiscoverable")
    return failures


# The ONE sanctioned telemetry-history writer: durable segments under
# `<warehouse>/.hyperspace_telemetry/` are written only by
# telemetry/history.py (atomic publish, schema version, age/byte
# pruning, torn-segment skipping on read). The directory-name literal
# is defined once in constants.py (TELEMETRY_HISTORY_DIRNAME); spelling
# it anywhere else in the package is a history file the reader's merge
# and the pruner's budget do not govern.
_RAW_HISTORY_RE = re.compile(r"\.hyperspace_telemetry")
_HISTORY_ALLOWED = ("constants.py",
                    os.path.join("telemetry", "history.py"))


def check_history_write_seam(package_dir: str):
    """Source lint: the telemetry-history directory literal appears
    only in constants.py (the definition) and telemetry/history.py
    (the writer)."""
    failures = []
    for root, _dirs, files in os.walk(package_dir):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel in _HISTORY_ALLOWED:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _RAW_HISTORY_RE.search(line):
                        failures.append(
                            f"hyperspace_tpu/{rel}:{lineno}: telemetry-"
                            "history directory literal outside the "
                            "sanctioned writer — history segments are "
                            "written only by telemetry/history.py "
                            "(reference constants."
                            "TELEMETRY_HISTORY_DIRNAME)")
    return failures


def main() -> int:
    import hyperspace_tpu

    import_errors = []
    for mod in pkgutil.walk_packages(hyperspace_tpu.__path__,
                                     prefix="hyperspace_tpu."):
        if "libhyperspace_host" in mod.name:
            continue  # the ctypes-loaded .so, not an importable module
        try:
            importlib.import_module(mod.name)
        except Exception as exc:
            import_errors.append(f"{mod.name}: {exc!r}")

    from hyperspace_tpu.engine.physical import PhysicalNode

    base_execute = PhysicalNode.__dict__["execute"]
    base_bucketed = PhysicalNode.__dict__["execute_bucketed"]
    failures = []
    checked = 0
    for cls in sorted(set(_all_subclasses(PhysicalNode)),
                      key=lambda c: (c.__module__, c.__name__)):
        checked += 1
        for attr, base in (("execute", base_execute),
                           ("execute_bucketed", base_bucketed)):
            fn = getattr(cls, attr, None)
            if fn is None or getattr(fn, "__func__", fn) is base:
                continue  # inherited abstract stub: never executes rows
            if not getattr(fn, "__telemetry_instrumented__", False):
                failures.append(
                    f"{cls.__module__}.{cls.__name__}.{attr} executes "
                    "without emitting a telemetry operator record")

    # Mirror check for index-maintenance actions: run() must resolve to
    # the report-instrumented wrapper on every subclass.
    from hyperspace_tpu.actions.base import Action

    checked_actions = 0
    for cls in sorted(set(_all_subclasses(Action)),
                      key=lambda c: (c.__module__, c.__name__)):
        checked_actions += 1
        fn = getattr(cls, "run", None)
        if fn is None or not getattr(fn, "__action_report_instrumented__",
                                     False):
            failures.append(
                f"{cls.__module__}.{cls.__name__}.run can execute "
                "without emitting an action report")

    failures.extend(check_jit_entry_points(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_device_put_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_segment_cache_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_engine_thread_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_serving_error_counters())
    failures.extend(check_index_kind_serde())
    failures.extend(check_sketch_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_sharding_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_advisor_build_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_ingest_build_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_batch_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_retry_seams(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_legacy_mesh_path(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    failures.extend(check_string_remap_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_bench_artifact_seam(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    failures.extend(check_http_server_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_tenant_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_metric_doc_rows(
        os.path.dirname(hyperspace_tpu.__file__),
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    failures.extend(check_profiler_seam(
        os.path.dirname(hyperspace_tpu.__file__)))
    failures.extend(check_critpath_doc_rows(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    failures.extend(check_alert_rule_doc_rows(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    failures.extend(check_history_write_seam(
        os.path.dirname(hyperspace_tpu.__file__)))

    if import_errors:
        print("check_metrics_coverage: module import failures "
              "(coverage cannot be proven):", file=sys.stderr)
        for line in import_errors:
            print(f"  {line}", file=sys.stderr)
    if failures:
        print("check_metrics_coverage: FAILED", file=sys.stderr)
        for line in failures:
            print(f"  {line}", file=sys.stderr)
    if failures or import_errors:
        return 1
    from hyperspace_tpu.telemetry import compilation
    print(f"check_metrics_coverage: OK "
          f"({checked} PhysicalNode subclasses, {checked_actions} "
          f"Action subclasses, and {len(compilation.REGISTRY)} jit "
          f"entry points instrumented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
