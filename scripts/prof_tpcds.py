"""Per-operator-class profiling of a TPC-DS query against a PERSISTENT
workspace (data + indexes reused across runs) — the q64 perf dev loop.

Usage:
  python scripts/prof_tpcds.py q64 [--scale 10] [--runs 3] [--work DIR]

Prints per-PhysicalNode-class cumulative wall seconds and execute-call
counts for one warm run, plus fusion-stage STATS (dispatch/sync seconds)
and total wall per run.
"""

import argparse
import collections
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("query")
    ap.add_argument("--scale", type=float, default=10.0)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--work", default="/tmp/hs_prof")
    ap.add_argument("--no-fuse", action="store_true")
    ap.add_argument("--trace-dir", default=None,
                    help="XLA profiler capture dir for the last run")
    args = ap.parse_args()

    from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
    from hyperspace_tpu.tpcds import QUERIES, generate
    from hyperspace_tpu.tpcds.queries import create_indexes
    from hyperspace_tpu.engine import physical, fusion

    work = os.path.join(args.work, f"s{args.scale:g}")
    data_dir = os.path.join(work, "data")
    wh = os.path.join(work, "wh")
    t0 = time.perf_counter()
    paths = generate(data_dir, scale=args.scale)  # reuses existing files
    print(f"generate/reuse: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    conf_map = {"hyperspace.warehouse.dir": wh,
                "spark.hyperspace.index.num.buckets": "32"}
    extra = os.environ.get("BENCH_TPCDS_CONF")
    if extra:
        conf_map.update(json.loads(extra))
    if args.no_fuse:
        conf_map["spark.hyperspace.execution.fusion.enabled"] = "false"
    sess = HyperspaceSession(HyperspaceConf(conf_map))
    hs = Hyperspace(sess)
    dfs = {n: sess.read_parquet(p) for n, p in paths.items()}
    idx_df = hs.indexes()
    existing = set(idx_df["name"]) if len(idx_df) else set()
    t0 = time.perf_counter()
    create_indexes(hs, dfs, queries=[args.query], skip=existing)
    print(f"index build (skip {len(existing)} existing): "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    sess.enable_hyperspace()
    build, _oracle = QUERIES[args.query]

    # -- per-class execute() timing hooks: SELF time via a call stack ----
    stats = collections.defaultdict(lambda: [0, 0.0])  # cls -> [calls, secs]
    stack = []

    def wrap(cls):
        orig = cls.execute

        def timed(self, bucket=None, _orig=orig, _name=cls.__name__):
            t0 = time.perf_counter()
            stack.append([_name, 0.0])
            try:
                return _orig(self, bucket)
            finally:
                dt = time.perf_counter() - t0
                _me = stack.pop()
                child_s = _me[1]
                if stack:
                    stack[-1][1] += dt
                st = stats[_name]
                st[0] += 1
                st[1] += dt - child_s  # SELF time
        cls.execute = timed
        return orig

    classes = [getattr(physical, n) for n in dir(physical)
               if isinstance(getattr(physical, n), type)
               and issubclass(getattr(physical, n), physical.PhysicalNode)
               and getattr(physical, n) is not physical.PhysicalNode]
    classes.append(fusion.FusedStageExec)
    classes.append(fusion._SourceExec)
    origs = [(c, wrap(c)) for c in classes]

    try:
        build(dfs).collect()  # warm: compiles, file listings, caches
        for st in stats.values():
            st[0] = 0
            st[1] = 0.0
        for k in fusion.STATS:
            fusion.STATS[k] = 0 if isinstance(fusion.STATS[k], int) else 0.0
        walls = []
        for r in range(args.runs):
            if args.trace_dir and r == args.runs - 1:
                sess.conf.set("spark.hyperspace.trace.dir", args.trace_dir)
            t0 = time.perf_counter()
            out = build(dfs).collect().to_pandas()
            walls.append(time.perf_counter() - t0)
        print(f"rows={len(out)} walls={[round(w, 3) for w in walls]}")
        total = sum(walls)
        print(f"\nper-class SELF seconds over {args.runs} warm runs:")
        for name, (calls, secs) in sorted(stats.items(),
                                          key=lambda kv: -kv[1][1]):
            if calls:
                print(f"  {name:26s} calls={calls:4d}  self={secs:8.3f}s "
                      f"({100 * secs / total:4.1f}%)")
        print(f"\nfusion STATS: {dict(fusion.STATS)}")
    finally:
        for c, o in origs:
            c.execute = o


if __name__ == "__main__":
    main()
