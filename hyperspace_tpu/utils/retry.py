"""THE storage-IO retry seam: one policy point for every backoff in the
package.

The paper's design premise is that all index data AND metadata live on
the lake with no catalog service (PAPER.md; cf. Delta Lake's lake-resident
log protocol) — every correctness guarantee rides on storage calls that
can fail transiently. Before this module, retry logic existed as ad-hoc
inline loops (the log manager's torn-read loop, the S3 409 conflict loop)
that no test exercised; now every retry routes through `call()` under one
configurable `RetryPolicy`, and `scripts/check_metrics_coverage.py` fails
the build if a `time.sleep` inside an `except` block appears anywhere
else in the package.

Policy: exponential backoff (`base_ms * 2**retry`, capped at `max_ms`)
with DETERMINISTIC jitter — a hash of (operation, attempt) spreads
concurrent writers without nondeterminism, so a seeded fault-injection
run replays byte-identically. Conf knobs (session-scoped):
`spark.hyperspace.io.retry.{attempts,base.ms,max.ms}`.

Classification is TYPED, transient-vs-permanent:

- transient (retried): ConnectionError/TimeoutError/InterruptedError
  families, OSErrors whose errno says "try again" (EAGAIN/EBUSY/EIO/...),
  exceptions carrying an HTTP status of 408/409/429/5xx (fsspec
  object-store backends flatten server errors into such shapes), and any
  caller-supplied `retryable` types/predicate (e.g. the log reader's
  torn-read JSONDecodeError);
- permanent (raised immediately): everything else — not-found,
  permission, 4xx, programming errors. Misclassifying permanent as
  transient turns a clean failure into attempts× the latency, so the
  default answer is "permanent".

Observability: every retry increments the process registry counter
`io.retries` and emits a `resilience: retry` decision event on the
active `QueryMetrics`; exhausting the policy increments `io.giveups`
and emits `resilience: giveup` before re-raising the last error.
"""

from __future__ import annotations

import errno
import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Tuple, Union

from hyperspace_tpu import constants

# errno values that mean "the operation may succeed if simply re-issued".
_TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.EBUSY, errno.EINTR, errno.EIO, errno.ETIMEDOUT,
    errno.ECONNRESET, errno.ECONNABORTED, errno.ECONNREFUSED,
    errno.ENETUNREACH, errno.ENETRESET, errno.EHOSTUNREACH,
    errno.EPIPE, errno.ESTALE,
})

# Typed families that are transient by construction. NOTE: FileNotFoundError,
# PermissionError, FileExistsError etc. are OSError subclasses but carry
# errnos outside _TRANSIENT_ERRNOS, so they classify permanent below.
_TRANSIENT_TYPES = (ConnectionError, TimeoutError, InterruptedError)

_TRANSIENT_HTTP = frozenset({408, 409, 429, 500, 502, 503, 504})


def _http_status(exc: Exception) -> Optional[int]:
    """HTTP status carried by `exc`, across the attr spellings fsspec
    backends use (same shapes `storage._is_precondition_failure` reads)."""
    for attr in ("code", "status", "status_code"):
        value = getattr(exc, attr, None)
        if isinstance(value, int):
            return value
    response = getattr(exc, "response", None)  # botocore ClientError shape
    if isinstance(response, dict):
        meta = response.get("ResponseMetadata") or {}
        status = meta.get("HTTPStatusCode")
        if isinstance(status, int):
            return status
    return None


def is_transient(exc: Exception) -> bool:
    """Typed transient-vs-permanent classification (module docstring)."""
    if isinstance(exc, _TRANSIENT_TYPES):
        return True
    if isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS:
        return True
    status = _http_status(exc)
    return status in _TRANSIENT_HTTP


def _jitter(operation: str, attempt: int) -> float:
    """[0, 1) jitter, deterministic in (operation, attempt) — replayable
    under seeded fault injection, yet decorrelated across operations."""
    digest = hashlib.blake2b(f"{operation}#{attempt}".encode(),
                             digest_size=4).digest()
    return int.from_bytes(digest, "big") / 2 ** 32


@dataclass(frozen=True)
class RetryPolicy:
    """attempts = TOTAL tries (>=1); delays double from base_ms, capped at
    max_ms, scaled by 0.5 + 0.5*jitter. `clock`/`sleep` are injectable so
    tests assert backoff schedules without wall-clock waits."""

    attempts: int = constants.IO_RETRY_ATTEMPTS_DEFAULT
    base_ms: float = constants.IO_RETRY_BASE_MS_DEFAULT
    max_ms: float = constants.IO_RETRY_MAX_MS_DEFAULT
    clock: Callable[[], float] = field(default=time.monotonic, repr=False)
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def delay_s(self, operation: str, attempt: int) -> float:
        """Backoff before try `attempt+1` (attempt is the 1-based try that
        just failed)."""
        raw = min(self.base_ms * (2 ** (attempt - 1)), self.max_ms)
        return raw * (0.5 + 0.5 * _jitter(operation, attempt)) / 1000.0


DEFAULT_POLICY = RetryPolicy()


def policy_for(conf=None) -> RetryPolicy:
    """RetryPolicy from a HyperspaceConf (None -> package defaults)."""
    if conf is None:
        return DEFAULT_POLICY
    try:
        return RetryPolicy(attempts=conf.io_retry_attempts,
                           base_ms=conf.io_retry_base_ms,
                           max_ms=conf.io_retry_max_ms)
    except Exception:
        # A conf-shaped object without the retry properties (test fakes):
        # defaults, not a crash on the IO path.
        return DEFAULT_POLICY


Retryable = Union[Sequence[type], Tuple[type, ...],
                  Callable[[Exception], bool], None]


def _should_retry(exc: Exception, retryable: Retryable) -> bool:
    if retryable is not None:
        if callable(retryable) and not isinstance(retryable, type):
            if retryable(exc):
                return True
        elif isinstance(exc, tuple(retryable)):
            return True
    return is_transient(exc)


def call(fn: Callable, *, operation: str,
         policy: Optional[RetryPolicy] = None, conf=None,
         retryable: Retryable = None):
    """Run `fn()` under the retry policy. `operation` names the IO for
    counters, decision events, and the deterministic jitter stream.
    `retryable` extends the typed transient classification with extra
    exception types or a predicate (it can only ADD retries, never
    suppress one). Exceptions that classify permanent — and BaseExceptions
    like an injected crash — propagate on the first failure."""
    pol = policy if policy is not None else policy_for(conf)
    attempts = max(1, int(pol.attempts))
    last: Optional[Exception] = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as exc:
            last = exc
            if attempt >= attempts or not _should_retry(exc, retryable):
                if attempt > 1:
                    _record_giveup(operation, attempt, exc)
                raise
            delay = pol.delay_s(operation, attempt)
            _record_retry(operation, attempt, delay, exc)
            pol.sleep(delay)
    raise last  # unreachable; keeps the type checker honest


def _record_retry(operation: str, attempt: int, delay_s: float,
                  exc: Exception) -> None:
    try:
        from hyperspace_tpu import telemetry
        telemetry.get_registry().counter("io.retries").inc()
        telemetry.event("resilience", "retry", operation=operation,
                        attempt=attempt, delay_ms=round(delay_s * 1000, 3),
                        error=repr(exc))
    except Exception:
        pass  # observability must never fail the IO it observes


def _record_giveup(operation: str, attempts: int, exc: Exception) -> None:
    try:
        from hyperspace_tpu import telemetry
        telemetry.get_registry().counter("io.giveups").inc()
        telemetry.event("resilience", "giveup", operation=operation,
                        attempts=attempts, error=repr(exc))
    except Exception:
        pass
