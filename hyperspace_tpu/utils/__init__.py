from hyperspace_tpu.utils.hashing import md5_hex
from hyperspace_tpu.utils.name_utils import normalize_index_name

__all__ = ["md5_hex", "normalize_index_name"]
