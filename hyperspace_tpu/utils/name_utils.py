"""Index name normalization.

Parity: reference `util/IndexNameUtils.scala:31` (trim, spaces -> `_`).
"""


def normalize_index_name(name: str) -> str:
    return name.strip().replace(" ", "_")
