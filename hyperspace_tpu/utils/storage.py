"""Storage seam: one place that says whether a path is a URL and hands out
its fsspec filesystem.

The reference rides the Hadoop `FileSystem` API so HDFS/ABFS work for free
(`util/FileUtils.scala:37-116`); here plain paths keep the fast os/posix
implementations and anything with a `scheme://` routes through fsspec
(`memory://` in tests; object stores in deployment). Only THIS module
imports fsspec.

OCC without rename (SURVEY hard part #5): the op log's write-if-absent
maps to fsspec exclusive create (mode "xb"). Local and memory filesystems
enforce it atomically; object-store backends are atomic exactly when the
backend implements a create precondition (GCS `ifGenerationMatch`,
S3 `If-None-Match`) — backends without one degrade to check-then-create,
which is safe for single-writer deployments only.
"""

from __future__ import annotations

import posixpath
from typing import List, Tuple


def is_url(path: str) -> bool:
    return "://" in path


def get_fs(path: str) -> Tuple[object, str]:
    """(fsspec filesystem, path stripped of its protocol)."""
    import fsspec
    return fsspec.core.url_to_fs(path)


def protocol_of(path: str) -> str:
    return path.split("://", 1)[0] + "://"


def join(base: str, *parts: str) -> str:
    """Path join that never mangles a URL's double slash."""
    import os
    if is_url(base):
        proto = protocol_of(base)
        rest = base[len(proto):]
        return proto + posixpath.join(rest, *parts)
    return os.path.join(base, *parts)


def canonical(path: str) -> str:
    """Absolute/normalized form for plain paths; URLs pass through (their
    identity is the string — os normalization would corrupt `://`)."""
    import os
    if is_url(path):
        return path
    return os.path.abspath(path)


def listdir_names(path: str) -> List[str]:
    """Base names of the direct children of a directory ([] if absent)."""
    import os
    if not is_url(path):
        if not os.path.isdir(path):
            return []
        return os.listdir(path)
    fs, real = get_fs(path)
    if not fs.isdir(real):
        return []
    return [posixpath.basename(p.rstrip("/")) for p in fs.ls(real,
                                                             detail=False)]
