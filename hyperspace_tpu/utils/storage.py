"""Storage seam: one place that says whether a path is a URL and hands out
its fsspec filesystem.

The reference rides the Hadoop `FileSystem` API so HDFS/ABFS work for free
(`util/FileUtils.scala:37-116`); here plain paths keep the fast os/posix
implementations and anything with a `scheme://` routes through fsspec
(`memory://` in tests; object stores in deployment). Only THIS module
imports fsspec.

OCC without rename (SURVEY hard part #5): the op log's write-if-absent
routes through `exclusive_create`, which dispatches per backend to a REAL
create precondition — GCS `if_generation_match=0`, S3 conditional put
(`If-None-Match: *`), exclusive-create mode for local/memory filesystems
(atomic there). Backends with no enforceable precondition RAISE
`PreconditionUnsupported` instead of silently degrading; callers may
degrade to check-then-create only under an explicit
`spark.hyperspace.single.writer=true` conf (`file_utils.py`).
"""

from __future__ import annotations

import posixpath
from typing import List, Tuple


class PreconditionUnsupported(Exception):
    """The backend cannot enforce an atomic create-if-absent."""


def is_url(path: str) -> bool:
    return "://" in path


def get_fs(path: str) -> Tuple[object, str]:
    """(fsspec filesystem, path stripped of its protocol)."""
    import fsspec
    return fsspec.core.url_to_fs(path)


def protocol_of(path: str) -> str:
    return path.split("://", 1)[0] + "://"


def join(base: str, *parts: str) -> str:
    """Path join that never mangles a URL's double slash."""
    import os
    if is_url(base):
        proto = protocol_of(base)
        rest = base[len(proto):]
        return proto + posixpath.join(rest, *parts)
    return os.path.join(base, *parts)


def canonical(path: str) -> str:
    """Absolute/normalized form for plain paths; URLs pass through (their
    identity is the string — os normalization would corrupt `://`)."""
    import os
    if is_url(path):
        return path
    return os.path.abspath(path)


# Protocols whose fsspec "x" (exclusive-create) mode is genuinely atomic:
# local files use O_CREAT|O_EXCL; the in-process memory fs is serialized
# by the interpreter. Object stores are NOT in this set — their "x" mode
# is check-then-create (two racy calls), so they need a server-side
# precondition instead.
_ATOMIC_X_PROTOCOLS = {"file", "local", "memory"}

# Serializes the memory-fs exclusive-create fallback (fsspec versions
# without mode "x" support on MemoryFileSystem).
import threading as _threading

_memory_x_lock = _threading.Lock()


def _protocols(fs) -> set:
    proto = getattr(fs, "protocol", ())
    return {proto} if isinstance(proto, str) else set(proto)


def _is_precondition_failure(exc: Exception) -> bool:
    """TYPED lost-the-race signatures across backends: GCS/S3 surface
    HTTP 412 (PreconditionFailed); some wrappers raise FileExistsError
    directly. Deliberately no message-text matching here — an unrelated
    backend error whose text merely echoes the string must not silently
    become "another writer won" (a dropped OCC commit); the text path is
    `_lost_race`, which verifies the other writer's object exists."""
    if isinstance(exc, FileExistsError):
        return True
    for attr in ("code", "status", "status_code"):
        if getattr(exc, attr, None) == 412:
            return True
    response = getattr(exc, "response", None)  # botocore ClientError shape
    if isinstance(response, dict):
        meta = response.get("ResponseMetadata") or {}
        error = response.get("Error") or {}
        if (meta.get("HTTPStatusCode") == 412
                or error.get("Code") in ("PreconditionFailed", "412")):
            return True
    return False


def _lost_race(fs, real: str, exc: Exception) -> bool:
    """True iff `exc` means a concurrent writer beat this one. Typed 412
    signatures are trusted as-is; a message that merely *reads* like a
    precondition failure (wrapper exceptions that flatten the status into
    text) is only believed after verifying the winner's object actually
    exists — with the listing cache dropped first, since fsspec serves
    exists() from a dircache that predates the race."""
    if _is_precondition_failure(exc):
        return True
    compact = f"{type(exc).__name__}{exc}".replace(" ", "").lower()
    if "preconditionfailed" not in compact:
        return False
    try:
        fs.invalidate_cache(posixpath.dirname(real))
    except Exception:
        pass
    try:
        return bool(fs.exists(real))
    except Exception:
        return False


def _is_conflict(exc: Exception) -> bool:
    """S3 409 ConflictError from a concurrent conditional put."""
    for attr in ("code", "status", "status_code"):
        if getattr(exc, attr, None) == 409:
            return True
    response = getattr(exc, "response", None)
    if isinstance(response, dict):
        meta = response.get("ResponseMetadata") or {}
        error = response.get("Error") or {}
        if (meta.get("HTTPStatusCode") == 409
                or error.get("Code") in ("ConflictError", "409")):
            return True
    return "conflicterror" in f"{type(exc).__name__}{exc}".lower()


def exclusive_create(path: str, data: bytes) -> bool:
    """Create `path` with `data` only if it does not exist, using a true
    backend precondition. Returns True iff this caller created it; False
    when a concurrent (or earlier) writer won. Raises
    `PreconditionUnsupported` when the backend offers no atomic create —
    silent check-then-create here would corrupt the op log's OCC
    (reference `IndexLogManager.scala:139-156`)."""
    import os

    from hyperspace_tpu.utils import faults

    faults.fire("storage.exclusive_create", path)
    fs, real = get_fs(path)
    fs.makedirs(posixpath.dirname(real) or os.path.dirname(real),
                exist_ok=True)
    protos = _protocols(fs)
    if protos & {"gs", "gcs"}:
        # GCS: generation 0 precondition = object must not exist.
        try:
            fs.pipe_file(real, data, if_generation_match=0)
            return True
        except TypeError as exc:
            raise PreconditionUnsupported(
                f"gcsfs on this system does not accept "
                f"if_generation_match: {exc}")
        except Exception as exc:
            if _lost_race(fs, real, exc):
                return False
            raise
    if protos & {"s3", "s3a"}:
        # S3 conditional put (If-None-Match: *), supported by AWS S3
        # since 2024 and by MinIO. Concurrent conditional puts against
        # the same key may return 409 ConflictError while another upload
        # is in flight (AWS documents retry); retry through the package
        # retry seam, then treat a persistent conflict as the other
        # writer winning.
        from hyperspace_tpu.utils import retry

        def conditional_put():
            try:
                fs.pipe_file(real, data, IfNoneMatch="*")
                return True
            except TypeError as exc:
                raise PreconditionUnsupported(
                    f"s3fs on this system does not accept IfNoneMatch: "
                    f"{exc}")
            except Exception as exc:
                if _lost_race(fs, real, exc):
                    return False
                raise

        try:
            return retry.call(conditional_put,
                              operation=f"s3.exclusive_create:{real}",
                              retryable=_is_conflict)
        except PreconditionUnsupported:
            raise
        except Exception as exc:
            if not _is_conflict(exc):
                raise
            # Persistent 409: "another writer won" is only true if their
            # object actually landed — a crashed/aborted upload also
            # 409s, and silently reporting a loss then would corrupt the
            # OCC log (the caller would trust a log entry that never
            # exists). Drop any cached listing first: s3fs serves
            # exists() from its dircache, which predates the race.
            try:
                fs.invalidate_cache(posixpath.dirname(real))
            except Exception:
                pass
            if fs.exists(real):
                return False
            raise
    if protos & _ATOMIC_X_PROTOCOLS:
        try:
            with fs.open(real, "xb") as f:
                f.write(data)
            return True
        except FileExistsError:
            return False
        except ValueError:
            # fsspec versions whose MemoryFileSystem rejects mode "x":
            # the memory fs is in-process only, so a process-wide lock
            # around check-then-write IS exclusive-create for it.
            if "memory" not in protos:
                raise
            with _memory_x_lock:
                if fs.exists(real):
                    return False
                with fs.open(real, "wb") as f:
                    f.write(data)
                return True
    raise PreconditionUnsupported(
        f"Backend {sorted(protos)} has no atomic create-if-absent; "
        "concurrent index operations could corrupt the operation log. "
        "Set spark.hyperspace.single.writer=true to accept "
        "check-then-create semantics for single-writer deployments.")


def listdir_names(path: str) -> List[str]:
    """Base names of the direct children of a directory ([] if absent)."""
    import os
    if not is_url(path):
        if not os.path.isdir(path):
            return []
        return os.listdir(path)
    fs, real = get_fs(path)
    if not fs.isdir(real):
        return []
    return [posixpath.basename(p.rstrip("/")) for p in fs.ls(real,
                                                             detail=False)]
