"""JSON (de)serialization helpers.

Parity: reference `util/JsonUtils.scala:34-44` (Jackson mapper with Scala
module). Here serializable metadata objects implement `to_dict`/`from_dict`;
these helpers pin the wire format.
"""

from __future__ import annotations

import json
from typing import Any


def to_json(obj: Any, indent: int | None = None) -> str:
    if hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    return json.dumps(obj, indent=indent, sort_keys=False)


def from_json(text: str) -> Any:
    return json.loads(text)


def json_to_map(text: str) -> dict:
    return json.loads(text)
