"""Filesystem utilities: local/posix fast paths + fsspec URLs.

Parity: reference `util/FileUtils.scala:37-116` (createFile, readContents,
getDirectorySize, createDirectory, delete, save/loadByteArray) — the
reference goes through the Hadoop FileSystem API, which is what lets it
run on HDFS/ABFS unchanged; here plain paths use os/posix directly and
`scheme://` paths route through fsspec (`utils/storage.py`). Atomicity
helpers used by the op log's optimistic concurrency live here too.
"""

from __future__ import annotations

import os
import shutil
import uuid

from hyperspace_tpu.utils import faults, storage


def create_file(path: str, contents: str) -> None:
    directive = faults.fire("file.create", path)
    data = contents.encode("utf-8")
    if directive == faults.TORN:
        # Writer "dies" mid-write: a prefix of the payload lands.
        data = data[:max(1, len(data) // 2)]
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        fs.makedirs(os.path.dirname(real), exist_ok=True)
        with fs.open(real, "wb") as f:
            f.write(data)
    else:
        create_directory(os.path.dirname(path))
        with open(path, "wb") as f:
            f.write(data)
    if directive == faults.TORN:
        raise faults.TornWriteError(f"injected torn write at {path}")


def read_contents(path: str) -> str:
    faults.fire("file.read", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        with fs.open(real, "rb") as f:
            return f.read().decode("utf-8")
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def get_directory_size(path: str) -> int:
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        if not fs.exists(real):
            return 0
        return sum(info.get("size", 0) or 0
                   for info in fs.find(real, detail=True).values())
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            total += os.path.getsize(os.path.join(root, name))
    return total


def create_directory(path: str) -> None:
    if not path:
        return
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        fs.makedirs(real, exist_ok=True)
        return
    os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        return fs.exists(real)
    return os.path.exists(path)


def is_dir(path: str) -> bool:
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        return fs.isdir(real)
    return os.path.isdir(path)


def is_file(path: str) -> bool:
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        return fs.isfile(real)
    return os.path.isfile(path)


def delete(path: str) -> None:
    faults.fire("file.delete", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        if fs.exists(real):
            fs.rm(real, recursive=True)
        return
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    elif os.path.exists(path):
        os.remove(path)


def remove_file(path: str) -> None:
    faults.fire("file.delete", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        fs.rm_file(real)
        return
    os.remove(path)


def save_byte_array(path: str, data: bytes) -> None:
    faults.fire("file.write", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        fs.makedirs(os.path.dirname(real), exist_ok=True)
        with fs.open(real, "wb") as f:
            f.write(data)
        return
    create_directory(os.path.dirname(path))
    with open(path, "wb") as f:
        f.write(data)


def load_byte_array(path: str) -> bytes:
    faults.fire("file.read", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        with fs.open(real, "rb") as f:
            return f.read()
    with open(path, "rb") as f:
        return f.read()


def atomic_publish(path: str, contents: str) -> None:
    """Publish `contents` at `path` so that a concurrent reader observes
    either the previous contents or the new ones IN FULL — never a torn
    mix. Local filesystems write a temp file (fsynced) and `os.replace`
    it over the target (atomic on POSIX, overwrite allowed — unlike the
    OCC primitive above, which must FAIL on an existing target). URL
    paths publish with a single object put: object stores materialize an
    object only when its upload completes, and the in-process memory fs
    swaps the buffer under the GIL, so a plain streamed open/write (which
    CAN tear on some backends) is avoided.

    Used for `latestStable`: it is a rewritten-in-place convenience copy,
    the one log file whose readers do not tolerate torn contents via the
    OCC torn-read retry (a half-written id file is retried until its
    writer finishes; a half-written latestStable used to parse as
    corruption)."""
    data = contents.encode("utf-8")
    directive = faults.fire("file.publish", path)
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        fs.makedirs(os.path.dirname(real), exist_ok=True)
        if directive == faults.TORN:
            # The torn upload never completes: no object materializes,
            # the previous one (if any) stays intact.
            raise faults.TornWriteError(f"injected torn publish at {path}")
        fs.pipe_file(real, data)
        return
    create_directory(os.path.dirname(path))
    tmp = path + ".tmp" + uuid.uuid4().hex
    try:
        with open(tmp, "wb") as f:
            if directive == faults.TORN:
                f.write(data[:max(1, len(data) // 2)])
                f.flush()
                os.fsync(f.fileno())
                raise faults.TornWriteError(
                    f"injected torn publish at {path}")
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass


def atomic_write_if_absent(path: str, contents: str,
                           single_writer: bool = False) -> bool:
    """Write `contents` to `path` only if `path` does not already exist.

    This is the op log's optimistic-concurrency primitive: the reference
    writes a `temp<UUID>` file and atomically renames it, treating rename
    failure as "a concurrent writer won" (`index/IndexLogManager.scala:139-156`).
    POSIX rename overwrites, so the atomic publish here is `os.link` (hard
    link creation fails with EEXIST if the target exists) with an
    O_CREAT|O_EXCL fallback for filesystems without hard links. URL paths
    go through `storage.exclusive_create`, which uses each backend's REAL
    create precondition (GCS generation match, S3 conditional put) and
    RAISES on backends that have none — unless `single_writer` (the
    `spark.hyperspace.single.writer` conf) explicitly accepts
    check-then-create semantics.
    Returns True iff this caller won the write.
    """
    faults.fire("file.write_if_absent", path)
    if storage.is_url(path):
        from hyperspace_tpu.exceptions import HyperspaceException
        try:
            return storage.exclusive_create(path, contents.encode("utf-8"))
        except storage.PreconditionUnsupported as exc:
            if not single_writer:
                raise HyperspaceException(str(exc)) from exc
            fs, real = storage.get_fs(path)
            fs.makedirs(os.path.dirname(real), exist_ok=True)
            if fs.exists(real):
                return False
            with fs.open(real, "wb") as f:
                f.write(contents.encode("utf-8"))
            return True
    create_directory(os.path.dirname(path))
    tmp = path + ".temp" + uuid.uuid4().hex
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(contents)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
        return True
    except FileExistsError:
        return False
    except OSError:
        # Filesystem without hard-link support: fall back to exclusive
        # create. This publishes the filename before its contents are
        # visible, so readers must tolerate a torn read (see
        # IndexLogManagerImpl.get_log's retry); contents are fsynced before
        # the winner returns.
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(contents)
            f.flush()
            os.fsync(f.fileno())
        return True
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
