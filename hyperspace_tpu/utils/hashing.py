"""Hashing utilities.

Parity: reference `util/HashingUtils.scala:32` (`md5Hex`). A fast 64-bit
mixing hash is also provided for device-side bucket assignment seeds.
"""

import hashlib


def md5_hex(value: str) -> str:
    return hashlib.md5(value.encode("utf-8")).hexdigest()


def fingerprint64(value: bytes) -> int:
    """Stable 64-bit fingerprint of a byte string (first 8 bytes of md5)."""
    return int.from_bytes(hashlib.md5(value).digest()[:8], "little")
