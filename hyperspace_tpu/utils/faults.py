"""Seedable, plan-driven fault injection for the storage seam and the
action FSM.

The resilience layer (`utils/retry.py`, crash recovery, graceful query
degradation) is only as good as the failure paths a test can actually
reach — so the injector is wired into the SAME seams production traffic
crosses: every `file_utils` primitive, `storage.exclusive_create`, the
parquet read/write entry points, each Action phase boundary
(`action.<Class>.<phase>` fires just before validate/begin/op/end runs —
a "crash" there is an abort BETWEEN phases, exactly the stranded-writer
scenario CancelAction/lease recovery must unwind), and the execution
plane's serving seams: `transfer.put` (every host->device link
crossing, `io/transfer.py`), `fusion.stage` (fused-stage entry,
`engine/fusion.py`), and the scheduler boundaries `scheduler.admit` /
`scheduler.run` (`engine/scheduler.py`) the chaos harness
(`tests/chaos.py`) drives concurrent query traffic against.

A `FaultPlan` is just a list of `FaultRule`s: fail the `nth` call whose
operation matches an fnmatch pattern (optionally path-filtered), `times`
consecutive matches (-1 = forever), with a `kind`:

- `transient` -> raises `InjectedTransientError` (a ConnectionError, so
  the retry seam classifies and retries it);
- `permanent` -> raises `InjectedPermanentError` (never retried);
- `torn`      -> the call site that supports tearing writes a PREFIX of
  the payload then raises `TornWriteError` (partial bytes LAND, like a
  writer dying mid-write); sites without torn support treat it as
  transient;
- `crash`     -> raises `InjectedCrash`, a BaseException — no
  `except Exception` guard in the stack can swallow it, simulating
  process death at that instant.

Probabilistic rules (`probability=`) draw from a `random.Random(seed)`
owned by the injector, so a chaos run replays exactly. When no injector
is installed, `fire()` is one global read + None check — the always-off
cost at every seam.

Tests arm it through the `fault_injector` conftest fixture, which
guarantees uninstall on teardown.
"""

from __future__ import annotations

import fnmatch
import random
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class InjectedTransientError(ConnectionError):
    """A retryable injected failure (classified transient by retry.py)."""


class TornWriteError(InjectedTransientError):
    """A write that left partial bytes behind; a fresh attempt rewrites
    the payload in full, so the retry seam treats it as transient."""


class InjectedPermanentError(RuntimeError):
    """A non-retryable injected failure."""


class InjectedCrash(BaseException):
    """Simulated process death — deliberately NOT an Exception so no
    best-effort `except Exception` guard can absorb it."""


TORN = "torn"
_KINDS = ("transient", "permanent", "torn", "crash")


@dataclass
class FaultRule:
    """Fail calls whose operation (and optional path) match. Counting is
    per rule: the `nth` matching call (1-based) starts firing, `times`
    consecutive matches fire (-1 = forever). With `probability` set, each
    matching call past warm-up fires with that chance instead (seeded by
    the injector), still bounded by `times`."""

    operation: str
    kind: str = "transient"
    nth: int = 1
    times: int = 1
    path: Optional[str] = None
    probability: Optional[float] = None
    # runtime counters (owned by the installing injector's lock)
    calls: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"Unknown fault kind: {self.kind!r} "
                             f"(use one of {_KINDS})")


class FaultInjector:
    """Holds a fault plan plus the audit log of everything it fired."""

    def __init__(self, rules: Sequence[FaultRule] = (), seed: int = 0):
        self.rules: List[FaultRule] = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.log: List[Tuple[str, Optional[str], str]] = []

    def add(self, rule: FaultRule) -> "FaultInjector":
        with self._lock:
            self.rules.append(rule)
        return self

    def fired(self, operation_pattern: str = "*") -> int:
        """How many injections matching `operation_pattern` have fired."""
        with self._lock:
            return sum(1 for op, _p, _k in self.log
                       if fnmatch.fnmatchcase(op, operation_pattern))

    def check(self, operation: str, path: Optional[str] = None):
        """Evaluate the plan for one seam crossing: raises the injected
        error, returns `TORN` for a cooperative torn write, or returns
        None (no fault)."""
        directive = None
        with self._lock:
            for rule in self.rules:
                if not fnmatch.fnmatchcase(operation, rule.operation):
                    continue
                if rule.path is not None and (
                        path is None
                        or not fnmatch.fnmatchcase(path, rule.path)):
                    continue
                rule.calls += 1
                if rule.times >= 0 and rule.fired >= rule.times:
                    continue
                if rule.calls < rule.nth:
                    continue
                if rule.probability is not None \
                        and self._rng.random() >= rule.probability:
                    continue
                rule.fired += 1
                self.log.append((operation, path, rule.kind))
                directive = rule.kind
                break
        if directive is None:
            return None
        self._count_injection()
        message = f"injected {directive} fault at {operation}" \
                  + (f" ({path})" if path else "")
        if directive == "transient":
            raise InjectedTransientError(message)
        if directive == "permanent":
            raise InjectedPermanentError(message)
        if directive == "crash":
            raise InjectedCrash(message)
        return TORN

    @staticmethod
    def _count_injection() -> None:
        try:
            from hyperspace_tpu import telemetry
            telemetry.get_registry().counter("faults.injected").inc()
        except Exception:
            pass


_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _active
    _active = injector
    return injector


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[FaultInjector]:
    return _active


def fire(operation: str, path: Optional[str] = None):
    """The seam hook: no-op unless an injector is installed. Returns
    `TORN` when the call site should tear its write; raises the injected
    error otherwise."""
    injector = _active
    if injector is None:
        return None
    return injector.check(operation, path)
