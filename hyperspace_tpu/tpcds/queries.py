"""Forty-two TPC-DS queries on the framework DataFrame API, with pandas
oracles: q1, q3, q6, q7, q13, q15, q17, q19, q20, q25, q26, q27, q28,
q29, q32, q34, q36, q41, q42, q43, q46, q48, q50, q52, q53, q55, q61,
q63, q64, q65, q67, q68, q70, q73, q79, q81, q88, q89, q93, q96, q97,
q98 (the round-4 additions live in `queries_ext.py`).

Each query is expressed as a join tree the rewrite rules can accelerate:
the innermost join is a linear scan pair (JoinIndexRule's applicability,
reference `JoinIndexRule.scala:210-211`), dimension filters run before
their joins (FilterIndexRule + bucket pruning serve them), and dimension
key columns are projected away immediately after each join so repeatedly
joined dimensions never collide on output names.

The pandas oracle for each query doubles as the CPU baseline and the
correctness check: `bench_tpcds.py` and `tests/test_tpcds.py` assert
sorted-result equality between rules-on, rules-off, and the oracle —
the reference's own E2E guarantee
(`E2EHyperspaceRulesTests.scala:330-346`).

The round-3 queries run in UN-REDUCED shape: full official column
lists, SUM/AVG over expression inputs, ORDER BY aggregate aliases
descending, SUBSTR (incl. the q19 zip-prefix column-to-column
inequality), and the q68 current-city <> bought-city string comparison.
The six late-round-3 additions cover the remaining official idioms:
OR-of-band disjuncts applied above the star joins (q13, q48 — the
official text embeds the identical equi-join in every disjunct;
extracting it is standard planner normalization), SUBSTR-IN zip probes
(q15), the catalog twin of q7 (q26), and SUM(CASE WHEN ...) pivots
(q43 weekday columns, q50 return-lag buckets over the ss-sr ticket
identity join).
q64 runs at FULL official width since round 4 (the 13-way cross_sales
join with both customer addresses, demographics/income-band pairs, and
all three year columns); q19 probes 1999 instead of the official 1998 because the
deterministic generator concentrates sales in 1999-2001; q79 appends
ss_ticket_number as a final sort key on both lanes because the official
ORDER BY does not totally order rows and the 3-way equality check needs
a deterministic top-100.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from hyperspace_tpu.plan.expr import col, lit


# ---------------------------------------------------------------------------
# q17 — quarterly store/catalog behaviour of returned items
# ---------------------------------------------------------------------------


def q17(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_quantity")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_return_quantity")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
        "cs_quantity")
    d1 = (dfs["date_dim"].filter(col("d_quarter_name") == lit("2000Q1"))
          .select("d_date_sk"))
    d23q = col("d_quarter_name").isin("2000Q1", "2000Q2", "2000Q3")
    d2 = dfs["date_dim"].filter(d23q).select("d_date_sk")
    d3 = dfs["date_dim"].filter(d23q).select("d_date_sk")
    store = dfs["store"].select("s_store_sk", "s_state")
    item = dfs["item"].select("i_item_sk", "i_item_id", "i_item_desc")

    j = ss.join(sr, on=(col("ss_customer_sk") == col("sr_customer_sk"))
                & (col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(cs, on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_returned_date_sk",
        "sr_return_quantity", "cs_sold_date_sk", "cs_quantity")
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_return_quantity",
        "cs_sold_date_sk", "cs_quantity")
    j = j.join(d3, on=col("cs_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_return_quantity",
        "cs_quantity")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    out = (j.group_by("i_item_id", "i_item_desc", "s_state").agg(
        ("count", "ss_quantity", "store_sales_quantitycount"),
        ("avg", "ss_quantity", "store_sales_quantityave"),
        ("stddev", "ss_quantity", "store_sales_quantitystdev"),
        ("count", "sr_return_quantity", "store_returns_quantitycount"),
        ("avg", "sr_return_quantity", "store_returns_quantityave"),
        ("stddev", "sr_return_quantity", "store_returns_quantitystdev"),
        ("count", "cs_quantity", "catalog_sales_quantitycount"),
        ("avg", "cs_quantity", "catalog_sales_quantityave"),
        ("stddev", "cs_quantity", "catalog_sales_quantitystdev"))
        .sort("i_item_id", "i_item_desc", "s_state").limit(100))
    return out


def q17_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    d1 = d[d.d_quarter_name == "2000Q1"][["d_date_sk"]]
    d23 = d[d.d_quarter_name.isin(["2000Q1", "2000Q2", "2000Q3"])][["d_date_sk"]]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = j.merge(t["catalog_sales"],
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_state"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id", "i_item_desc"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_state"]).agg(
        store_sales_quantitycount=("ss_quantity", "count"),
        store_sales_quantityave=("ss_quantity", "mean"),
        store_sales_quantitystdev=("ss_quantity", "std"),
        store_returns_quantitycount=("sr_return_quantity", "count"),
        store_returns_quantityave=("sr_return_quantity", "mean"),
        store_returns_quantitystdev=("sr_return_quantity", "std"),
        catalog_sales_quantitycount=("cs_quantity", "count"),
        catalog_sales_quantityave=("cs_quantity", "mean"),
        catalog_sales_quantitystdev=("cs_quantity", "std"),
    ).reset_index()
    return (g.sort_values(["i_item_id", "i_item_desc", "s_state"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q25 — net profit flow of returned items, April..October
# ---------------------------------------------------------------------------


def q25(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_net_profit")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_net_loss")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
        "cs_net_profit")
    d1 = (dfs["date_dim"]
          .filter((col("d_moy") == lit(4)) & (col("d_year") == lit(2000)))
          .select("d_date_sk"))
    d23f = ((col("d_moy") >= lit(4)) & (col("d_moy") <= lit(10))
            & (col("d_year") == lit(2000)))
    d2 = dfs["date_dim"].filter(d23f).select("d_date_sk")
    d3 = dfs["date_dim"].filter(d23f).select("d_date_sk")
    store = dfs["store"].select("s_store_sk", "s_store_id", "s_store_name")
    item = dfs["item"].select("i_item_sk", "i_item_id", "i_item_desc")

    j = ss.join(sr, on=(col("ss_customer_sk") == col("sr_customer_sk"))
                & (col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(cs, on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_returned_date_sk",
        "sr_net_loss", "cs_sold_date_sk", "cs_net_profit")
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_net_loss",
        "cs_sold_date_sk", "cs_net_profit")
    j = j.join(d3, on=col("cs_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_net_loss",
        "cs_net_profit")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    out = (j.group_by("i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name").agg(
        ("sum", "ss_net_profit", "store_sales_profit"),
        ("sum", "sr_net_loss", "store_returns_loss"),
        ("sum", "cs_net_profit", "catalog_sales_profit"))
        .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
        .limit(100))
    return out


def q25_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    d1 = d[(d.d_moy == 4) & (d.d_year == 2000)][["d_date_sk"]]
    d23 = d[(d.d_moy >= 4) & (d.d_moy <= 10) & (d.d_year == 2000)][["d_date_sk"]]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = j.merge(t["catalog_sales"],
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_id", "s_store_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id", "i_item_desc"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"]).agg(
        store_sales_profit=("ss_net_profit", "sum"),
        store_returns_loss=("sr_net_loss", "sum"),
        catalog_sales_profit=("cs_net_profit", "sum")).reset_index()
    return (g.sort_values(["i_item_id", "i_item_desc", "s_store_id",
                           "s_store_name"]).head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q64 — year-over-year cross-channel sales of returned items (reduced width)
# ---------------------------------------------------------------------------

_Q64_COLORS = ("plum", "puff", "misty")


def _q64_cs_ui(dfs):
    """Catalog sales whose list-price total exceeds 2x the refund total —
    the HAVING subquery of q64 (filter over an aggregate)."""
    cs = dfs["catalog_sales"].select("cs_item_sk", "cs_order_number",
                                     "cs_ext_list_price")
    cr = dfs["catalog_returns"].select(
        "cr_item_sk", "cr_order_number", "cr_refunded_cash",
        "cr_reversed_charge", "cr_store_credit")
    j = cs.join(cr, on=(col("cs_item_sk") == col("cr_item_sk"))
                & (col("cs_order_number") == col("cr_order_number")))
    agg = j.group_by("cs_item_sk").agg(
        ("sum", "cs_ext_list_price", "sale"),
        ("sum", "cr_refunded_cash", "refund_cash"),
        ("sum", "cr_reversed_charge", "refund_charge"),
        ("sum", "cr_store_credit", "refund_credit"))
    having = (col("sale") > ((col("refund_cash") + col("refund_charge")
                              + col("refund_credit")) * lit(2.0)))
    return agg.filter(having).select("cs_item_sk")


def _q64_cross_sales(dfs):
    """FULL-WIDTH cross_sales, built ONCE over both probe years (the
    official WITH-view shape): the 13-way join — ss x sr x cs_ui x
    d1/d2/d3 x store x customer x cd1/cd2 x promotion x hd1/hd2 (with
    income bands) x ad1/ad2 x item — grouped by the official column list
    (syear distinguishes the years; the final query self-joins filtered
    slices, so the heavy chain executes once via common-subplan reuse).
    """
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_cdemo_sk", "ss_hdemo_sk", "ss_addr_sk", "ss_promo_sk",
        "ss_ticket_number", "ss_wholesale_cost", "ss_list_price",
        "ss_coupon_amt")
    sr = dfs["store_returns"].select("sr_item_sk", "sr_ticket_number")
    dy = (dfs["date_dim"].filter(col("d_year").isin(2000, 2001))
          .select("d_date_sk", col("d_year").alias("syear")))
    store = dfs["store"].select("s_store_sk", "s_store_name", "s_zip")
    item = (dfs["item"]
            .filter(col("i_color").isin(*_Q64_COLORS)
                    & (col("i_current_price") >= lit(25.0))
                    & (col("i_current_price") <= lit(60.0)))
            .select("i_item_sk", "i_product_name"))
    customer = dfs["customer"].select(
        "c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
        "c_current_addr_sk", "c_first_sales_date_sk",
        "c_first_shipto_date_sk")
    cd = dfs["customer_demographics"].select("cd_demo_sk",
                                             "cd_marital_status")
    hd = dfs["household_demographics"].select("hd_demo_sk",
                                              "hd_income_band_sk")
    ib = dfs["income_band"].select("ib_income_band_sk")
    ad = dfs["customer_address"].select(
        "ca_address_sk", "ca_street_number", "ca_street_name", "ca_city",
        "ca_zip")
    promo = dfs["promotion"].select("p_promo_sk")

    j = ss.join(sr, on=(col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(_q64_cs_ui(dfs), on=col("ss_item_sk") == col("cs_item_sk"))
    j = j.join(dy, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_customer_sk", "ss_store_sk", "ss_cdemo_sk",
        "ss_hdemo_sk", "ss_addr_sk", "ss_promo_sk", "ss_wholesale_cost",
        "ss_list_price", "ss_coupon_amt", "syear")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(customer, on=col("ss_customer_sk") == col("c_customer_sk"))
    # cd1 (sale-time) and cd2 (current) with differing marital status.
    j = j.join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    j = j.filter(col("cd_marital_status") != col("cd_marital_status_r"))
    j = j.join(promo, on=col("ss_promo_sk") == col("p_promo_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(ib, on=col("hd_income_band_sk") == col("ib_income_band_sk"))
    j = j.join(hd, on=col("c_current_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(ib, on=col("hd_income_band_sk_r")
               == col("ib_income_band_sk"))
    # first-sales / first-shipto years (d2 / d3).
    d2 = dfs["date_dim"].select("d_date_sk",
                                col("d_year").alias("fsyear"))
    d3 = dfs["date_dim"].select("d_date_sk",
                                col("d_year").alias("s2year"))
    j = j.join(d2, on=col("c_first_sales_date_sk") == col("d_date_sk"))
    j = j.join(d3, on=col("c_first_shipto_date_sk") == col("d_date_sk"))
    # bought-at (ad1 -> b_*) and current (ad2 -> c_*) addresses.
    j = j.join(ad, on=col("ss_addr_sk") == col("ca_address_sk"))
    j = j.join(ad, on=col("c_current_addr_sk") == col("ca_address_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.select(
        "i_product_name", col("ss_item_sk").alias("item_sk"),
        "s_store_name", "s_zip",
        col("ca_street_number").alias("b_street_number"),
        col("ca_street_name").alias("b_street_name"),
        col("ca_city").alias("b_city"), col("ca_zip").alias("b_zip"),
        col("ca_street_number_r").alias("c_street_number"),
        col("ca_street_name_r").alias("c_street_name"),
        col("ca_city_r").alias("c_city"), col("ca_zip_r").alias("c_zip"),
        "syear", "fsyear", "s2year", "ss_wholesale_cost", "ss_list_price",
        "ss_coupon_amt")
    keys = ["i_product_name", "item_sk", "s_store_name", "s_zip",
            "b_street_number", "b_street_name", "b_city", "b_zip",
            "c_street_number", "c_street_name", "c_city", "c_zip",
            "syear", "fsyear", "s2year"]
    return j.group_by(*keys).agg(
        ("count", "*", "cnt"),
        ("sum", "ss_wholesale_cost", "s1"),
        ("sum", "ss_list_price", "s2"),
        ("sum", "ss_coupon_amt", "s3"))


def q64(dfs: Dict[str, "object"]):
    cross_sales = _q64_cross_sales(dfs)
    cs1 = cross_sales.filter(col("syear") == lit(2000))
    cs2 = cross_sales.filter(col("syear") == lit(2001)).select(
        col("item_sk").alias("item_sk2"),
        col("s_store_name").alias("store_name2"),
        col("s_zip").alias("store_zip2"), col("syear").alias("syear2"),
        col("cnt").alias("cnt2"), col("s1").alias("s1_2"),
        col("s2").alias("s2_2"), col("s3").alias("s3_2"))
    j = cs1.join(cs2, on=(col("item_sk") == col("item_sk2"))
                 & (col("s_store_name") == col("store_name2"))
                 & (col("s_zip") == col("store_zip2")))
    j = j.filter(col("cnt2") <= col("cnt"))
    return (j.select(
        "i_product_name", "item_sk", "s_store_name", "s_zip",
        "b_street_number", "b_street_name", "b_city", "b_zip",
        "c_street_number", "c_street_name", "c_city", "c_zip",
        "syear", "cnt", "s1", "s2", "s3",
        "syear2", "cnt2", "s1_2", "s2_2", "s3_2")
        .sort("i_product_name", "s_store_name", "cnt2", "item_sk",
              "s_zip", "b_street_number", "b_street_name", "b_city",
              "b_zip", "c_street_number", "c_street_name", "c_city",
              "c_zip", "s1", "s2", "s3", "s1_2", "s2_2",
              "s3_2").limit(100))


def _q64_cs_ui_pandas(t):
    j = t["catalog_sales"].merge(
        t["catalog_returns"], left_on=["cs_item_sk", "cs_order_number"],
        right_on=["cr_item_sk", "cr_order_number"])
    g = j.groupby("cs_item_sk").agg(
        sale=("cs_ext_list_price", "sum"),
        refund_cash=("cr_refunded_cash", "sum"),
        refund_charge=("cr_reversed_charge", "sum"),
        refund_credit=("cr_store_credit", "sum")).reset_index()
    keep = g[g.sale > 2.0 * (g.refund_cash + g.refund_charge
                             + g.refund_credit)]
    return keep[["cs_item_sk"]]


def _q64_cross_sales_pandas(t):
    d = t["date_dim"]
    dy = d[d.d_year.isin([2000, 2001])][["d_date_sk", "d_year"]].rename(
        columns={"d_year": "syear"})
    it = t["item"]
    it = it[it.i_color.isin(list(_Q64_COLORS))
            & (it.i_current_price >= 25.0) & (it.i_current_price <= 60.0)]
    j = t["store_sales"].merge(
        t["store_returns"][["sr_item_sk", "sr_ticket_number"]],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    j = j.merge(_q64_cs_ui_pandas(t), left_on="ss_item_sk",
                right_on="cs_item_sk")
    j = j.merge(dy, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_name", "s_zip"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    cd = t["customer_demographics"][["cd_demo_sk", "cd_marital_status"]]
    j = j.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk",
                suffixes=("", "_r"))
    j = j[j.cd_marital_status != j.cd_marital_status_r]
    j = j.merge(t["promotion"][["p_promo_sk"]], left_on="ss_promo_sk",
                right_on="p_promo_sk")
    hd = t["household_demographics"][["hd_demo_sk", "hd_income_band_sk"]]
    ib = t["income_band"][["ib_income_band_sk"]]
    j = j.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(ib, left_on="hd_income_band_sk",
                right_on="ib_income_band_sk")
    j = j.merge(hd, left_on="c_current_hdemo_sk", right_on="hd_demo_sk",
                suffixes=("", "_r"))
    j = j.merge(ib, left_on="hd_income_band_sk_r",
                right_on="ib_income_band_sk", suffixes=("", "_r"))
    dd = t["date_dim"][["d_date_sk", "d_year"]]
    j = j.merge(dd.rename(columns={"d_year": "fsyear"}),
                left_on="c_first_sales_date_sk", right_on="d_date_sk")
    j = j.merge(dd.rename(columns={"d_year": "s2year"}),
                left_on="c_first_shipto_date_sk", right_on="d_date_sk")
    ad = t["customer_address"][["ca_address_sk", "ca_street_number",
                                "ca_street_name", "ca_city", "ca_zip"]]
    j = j.merge(ad, left_on="ss_addr_sk", right_on="ca_address_sk")
    j = j.merge(ad, left_on="c_current_addr_sk", right_on="ca_address_sk",
                suffixes=("", "_r"))
    j = j.merge(it[["i_item_sk", "i_product_name"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    j = j.rename(columns={
        "ss_item_sk": "item_sk",
        "ca_street_number": "b_street_number",
        "ca_street_name": "b_street_name", "ca_city": "b_city",
        "ca_zip": "b_zip", "ca_street_number_r": "c_street_number",
        "ca_street_name_r": "c_street_name", "ca_city_r": "c_city",
        "ca_zip_r": "c_zip"})
    keys = ["i_product_name", "item_sk", "s_store_name", "s_zip",
            "b_street_number", "b_street_name", "b_city", "b_zip",
            "c_street_number", "c_street_name", "c_city", "c_zip",
            "syear", "fsyear", "s2year"]
    return j.groupby(keys, as_index=False).agg(
        cnt=("item_sk", "size"),
        s1=("ss_wholesale_cost", "sum"),
        s2=("ss_list_price", "sum"),
        s3=("ss_coupon_amt", "sum"))


def q64_pandas(t: Dict[str, "object"]):
    cross_sales = _q64_cross_sales_pandas(t)
    cs1 = cross_sales[cross_sales.syear == 2000]
    cs2 = cross_sales[cross_sales.syear == 2001]
    cs2 = cs2[["item_sk", "s_store_name", "s_zip", "syear", "cnt", "s1",
               "s2", "s3"]].rename(columns={
        "item_sk": "item_sk2", "s_store_name": "store_name2",
        "s_zip": "store_zip2", "syear": "syear2", "cnt": "cnt2",
        "s1": "s1_2", "s2": "s2_2", "s3": "s3_2"})
    j = cs1.merge(cs2, left_on=["item_sk", "s_store_name", "s_zip"],
                  right_on=["item_sk2", "store_name2", "store_zip2"])
    j = j[j.cnt2 <= j.cnt]
    out = j[["i_product_name", "item_sk", "s_store_name", "s_zip",
             "b_street_number", "b_street_name", "b_city", "b_zip",
             "c_street_number", "c_street_name", "c_city", "c_zip",
             "syear", "cnt", "s1", "s2", "s3",
             "syear2", "cnt2", "s1_2", "s2_2", "s3_2"]]
    return (out.sort_values(["i_product_name", "s_store_name", "cnt2",
                             "item_sk", "s_zip", "b_street_number",
                             "b_street_name", "b_city", "b_zip",
                             "c_street_number", "c_street_name", "c_city",
                             "c_zip", "s1", "s2", "s3", "s1_2", "s2_2",
                             "s3_2"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# Index set + registry
# ---------------------------------------------------------------------------


_STAR_FAMILY = ("q3", "q7", "q13", "q19", "q42", "q43", "q48", "q52",
                "q53", "q55", "q63", "q65", "q67", "q68", "q79", "q89",
                "q98")

# index name -> (table, IndexConfig args, queries that can use it)
_INDEX_DEFS = (
    ("idx_ss_ret", "store_sales",
     (["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
      ["ss_sold_date_sk", "ss_store_sk", "ss_quantity", "ss_net_profit"]),
     ("q17", "q25", "q29", "q50")),
    ("idx_sr_ret", "store_returns",
     (["sr_customer_sk", "sr_item_sk", "sr_ticket_number"],
      ["sr_returned_date_sk", "sr_return_quantity", "sr_net_loss"]),
     ("q17", "q25", "q29", "q50")),
    ("idx_ss_ticket", "store_sales",
     (["ss_item_sk", "ss_ticket_number"],
      ["ss_sold_date_sk", "ss_customer_sk", "ss_store_sk",
       "ss_wholesale_cost", "ss_list_price"]),
     ("q64",)),
    ("idx_sr_ticket", "store_returns",
     (["sr_item_sk", "sr_ticket_number"], []), ("q64",)),
    ("idx_cs_order", "catalog_sales",
     (["cs_item_sk", "cs_order_number"], ["cs_ext_list_price"]), ("q64",)),
    ("idx_cr_order", "catalog_returns",
     (["cr_item_sk", "cr_order_number"],
      ["cr_refunded_cash", "cr_reversed_charge", "cr_store_credit"]),
     ("q64",)),
    ("idx_dd_quarter", "date_dim",
     (["d_quarter_name"], ["d_date_sk"]), ("q17",)),
    # The star family all joins store_sales to a filtered date_dim
    # innermost; one covering pair serves the whole family.
    ("idx_ss_date", "store_sales",
     (["ss_sold_date_sk"],
      ["ss_item_sk", "ss_customer_sk", "ss_store_sk", "ss_hdemo_sk",
       "ss_cdemo_sk", "ss_addr_sk", "ss_promo_sk", "ss_ticket_number",
       "ss_quantity", "ss_list_price", "ss_sales_price", "ss_coupon_amt",
       "ss_ext_sales_price", "ss_ext_list_price", "ss_ext_tax",
       "ss_ext_wholesale_cost", "ss_net_profit"]),
     _STAR_FAMILY + ("q61", "q6", "q27", "q34", "q36", "q46", "q70", "q73")),
    ("idx_dd_datesk", "date_dim",
     (["d_date_sk"],
      ["d_year", "d_moy", "d_dom", "d_dow", "d_qoy", "d_day_name"]),
     _STAR_FAMILY + ("q15", "q26", "q61", "q1", "q6", "q20", "q27", "q29", "q32", "q34", "q36", "q46", "q70", "q73", "q81", "q97")),
    # q15 / q26 join catalog_sales to a filtered date_dim innermost.
    ("idx_cs_date", "catalog_sales",
     (["cs_sold_date_sk"],
      ["cs_bill_customer_sk", "cs_bill_cdemo_sk", "cs_item_sk",
       "cs_promo_sk", "cs_quantity", "cs_list_price", "cs_sales_price",
       "cs_coupon_amt", "cs_ext_sales_price", "cs_ext_discount_amt"]),
     ("q15", "q26", "q20", "q32", "q97")),
    # q96 / q88 join store_sales to household_demographics innermost.
    ("idx_ss_hdemo", "store_sales",
     (["ss_hdemo_sk"], ["ss_sold_time_sk", "ss_store_sk"]), ("q96", "q88")),
    ("idx_hd_demo", "household_demographics",
     (["hd_demo_sk"], ["hd_dep_count", "hd_vehicle_count"]), ("q96", "q88")),
    # q28's six band filters all probe ss_quantity first.
    ("idx_ss_qty", "store_sales",
     (["ss_quantity"],
      ["ss_list_price", "ss_coupon_amt", "ss_wholesale_cost"]), ("q28",)),
)


def create_indexes(hs, dfs, queries=None, skip=()) -> None:
    """Build the covering indexes the given queries (default: all) can
    use — each query family's innermost-join pair plus the dimension
    filter indexes for FilterIndexRule + bucket pruning. `skip` names
    indexes that already exist (persistent-warehouse callers)."""
    from hyperspace_tpu import IndexConfig

    wanted = None if queries is None else set(queries)
    for name, table, (indexed, included), used_by in _INDEX_DEFS:
        if wanted is not None and not (wanted & set(used_by)):
            continue
        if name in skip:
            continue
        hs.create_index(dfs[table], IndexConfig(name, indexed, included))


# ---------------------------------------------------------------------------
# q3 / q42 / q52 / q55 — the brand/category star family (un-reduced shape:
# computed SUM over ss_ext_sales_price, ORDER BY the aggregate descending)
# ---------------------------------------------------------------------------


def q3(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_ext_sales_price")
    dt = (dfs["date_dim"].filter(col("d_moy") == lit(11))
          .select("d_date_sk", "d_year"))
    it = (dfs["item"].filter(col("i_manufact_id") == lit(128))
          .select("i_item_sk", "i_brand_id", "i_brand"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg(("sum", "ss_ext_sales_price", "sum_agg"))
            .sort("d_year", "-sum_agg", "i_brand_id").limit(100))


def q3_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[d.d_moy == 11][["d_date_sk", "d_year"]]
    i = t["item"]
    it = i[i.i_manufact_id == 128][["i_item_sk", "i_brand_id", "i_brand"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["d_year", "i_brand_id", "i_brand"]).agg(
        sum_agg=("ss_ext_sales_price", "sum")).reset_index()
    return (g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                          ascending=[True, False, True])
            .head(100).reset_index(drop=True))


def q42(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_ext_sales_price")
    dt = (dfs["date_dim"]
          .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
          .select("d_date_sk", "d_year"))
    it = (dfs["item"].filter(col("i_manager_id") == lit(1))
          .select("i_item_sk", "i_category_id", "i_category"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("d_year", "i_category_id", "i_category")
            .agg(("sum", "ss_ext_sales_price", "sum_sales"))
            .sort("-sum_sales", "d_year", "i_category_id", "i_category")
            .limit(100))


def q42_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_moy == 11) & (d.d_year == 2000)][["d_date_sk", "d_year"]]
    i = t["item"]
    it = i[i.i_manager_id == 1][["i_item_sk", "i_category_id", "i_category"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["d_year", "i_category_id", "i_category"]).agg(
        sum_sales=("ss_ext_sales_price", "sum")).reset_index()
    return (g.sort_values(["sum_sales", "d_year", "i_category_id",
                           "i_category"],
                          ascending=[False, True, True, True])
            [["d_year", "i_category_id", "i_category", "sum_sales"]]
            .head(100).reset_index(drop=True))


def q52(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_ext_sales_price")
    dt = (dfs["date_dim"]
          .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
          .select("d_date_sk", "d_year"))
    it = (dfs["item"].filter(col("i_manager_id") == lit(1))
          .select("i_item_sk", "i_brand_id", "i_brand"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg(("sum", "ss_ext_sales_price", "ext_price"))
            .sort("d_year", "-ext_price", "i_brand_id").limit(100))


def q52_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_moy == 11) & (d.d_year == 2000)][["d_date_sk", "d_year"]]
    i = t["item"]
    it = i[i.i_manager_id == 1][["i_item_sk", "i_brand_id", "i_brand"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["d_year", "i_brand_id", "i_brand"]).agg(
        ext_price=("ss_ext_sales_price", "sum")).reset_index()
    return (g.sort_values(["d_year", "ext_price", "i_brand_id"],
                          ascending=[True, False, True])
            .head(100).reset_index(drop=True))


def q55(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_ext_sales_price")
    dt = (dfs["date_dim"]
          .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(1999)))
          .select("d_date_sk"))
    it = (dfs["item"].filter(col("i_manager_id") == lit(28))
          .select("i_item_sk", "i_brand_id", "i_brand"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("i_brand_id", "i_brand")
            .agg(("sum", "ss_ext_sales_price", "ext_price"))
            .sort("-ext_price", "i_brand_id").limit(100))


def q55_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_moy == 11) & (d.d_year == 1999)][["d_date_sk"]]
    i = t["item"]
    it = i[i.i_manager_id == 28][["i_item_sk", "i_brand_id", "i_brand"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_brand_id", "i_brand"]).agg(
        ext_price=("ss_ext_sales_price", "sum")).reset_index()
    return (g.sort_values(["ext_price", "i_brand_id"],
                          ascending=[False, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q7 — demographic/promotion star with four AVG aggregates
# ---------------------------------------------------------------------------


def q7(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    cd = (dfs["customer_demographics"]
          .filter((col("cd_gender") == lit("M"))
                  & (col("cd_marital_status") == lit("S"))
                  & (col("cd_education_status") == lit("College")))
          .select("cd_demo_sk"))
    promo = (dfs["promotion"]
             .filter((col("p_channel_email") == lit("N"))
                     | (col("p_channel_event") == lit("N")))
             .select("p_promo_sk"))
    it = dfs["item"].select("i_item_sk", "i_item_id")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(promo, on=col("ss_promo_sk") == col("p_promo_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("i_item_id")
            .agg(("avg", "ss_quantity", "agg1"),
                 ("avg", "ss_list_price", "agg2"),
                 ("avg", "ss_coupon_amt", "agg3"),
                 ("avg", "ss_sales_price", "agg4"))
            .sort("i_item_id").limit(100))


def q7_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    c = t["customer_demographics"]
    cd = c[(c.cd_gender == "M") & (c.cd_marital_status == "S")
           & (c.cd_education_status == "College")][["cd_demo_sk"]]
    p = t["promotion"]
    promo = p[(p.p_channel_email == "N")
              | (p.p_channel_event == "N")][["p_promo_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(promo, left_on="ss_promo_sk", right_on="p_promo_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby("i_item_id").agg(
        agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
        agg3=("ss_coupon_amt", "mean"),
        agg4=("ss_sales_price", "mean")).reset_index()
    return g.sort_values("i_item_id").head(100).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q19 — brand star with the SUBSTR(zip) <> SUBSTR(zip) cross-column test
# ---------------------------------------------------------------------------


def q19(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ext_sales_price")
    dt = (dfs["date_dim"]
          .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(1999)))
          .select("d_date_sk"))
    it = (dfs["item"].filter(col("i_manager_id") == lit(8))
          .select("i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
                  "i_manufact"))
    cust = dfs["customer"].select("c_customer_sk", "c_current_addr_sk")
    ca = dfs["customer_address"].select("ca_address_sk", "ca_zip")
    st = dfs["store"].select("s_store_sk", "s_zip")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(cust, on=col("ss_customer_sk") == col("c_customer_sk"))
    j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.filter(col("ca_zip").substr(1, 5) != col("s_zip").substr(1, 5))
    return (j.group_by("i_brand_id", "i_brand", "i_manufact_id",
                       "i_manufact")
            .agg(("sum", "ss_ext_sales_price", "ext_price"))
            .sort("-ext_price", "i_brand", "i_brand_id", "i_manufact_id",
                  "i_manufact")
            .limit(100))


def q19_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_moy == 11) & (d.d_year == 1999)][["d_date_sk"]]
    i = t["item"]
    it = i[i.i_manager_id == 8][["i_item_sk", "i_brand_id", "i_brand",
                                 "i_manufact_id", "i_manufact"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["customer"][["c_customer_sk", "c_current_addr_sk"]],
                left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_zip"]],
                left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(t["store"][["s_store_sk", "s_zip"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.ca_zip.str[:5] != j.s_zip.str[:5]]
    g = j.groupby(["i_brand_id", "i_brand", "i_manufact_id",
                   "i_manufact"]).agg(
        ext_price=("ss_ext_sales_price", "sum")).reset_index()
    return (g.sort_values(["ext_price", "i_brand", "i_brand_id",
                           "i_manufact_id", "i_manufact"],
                          ascending=[False, True, True, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q68 — per-ticket aggregate subquery joined back to customer, with the
# current-city <> bought-city string column comparison
# ---------------------------------------------------------------------------


def q68(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ss_hdemo_sk",
        "ss_sold_date_sk", "ss_store_sk", "ss_ext_sales_price",
        "ss_ext_list_price", "ss_ext_tax")
    dt = (dfs["date_dim"]
          .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                  & col("d_year").isin(1999, 2000, 2001))
          .select("d_date_sk"))
    st = (dfs["store"].filter(col("s_city").isin("Midway", "Fairview"))
          .select("s_store_sk"))
    hd = (dfs["household_demographics"]
          .filter((col("hd_dep_count") == lit(4))
                  | (col("hd_vehicle_count") == lit(3)))
          .select("hd_demo_sk"))
    ca = dfs["customer_address"].select("ca_address_sk", "ca_city")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(ca, on=col("ss_addr_sk") == col("ca_address_sk"))
    dn = (j.group_by("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "ca_city")
          .agg(("sum", "ss_ext_sales_price", "extended_price"),
               ("sum", "ss_ext_list_price", "list_price"),
               ("sum", "ss_ext_tax", "extended_tax"))
          .select("ss_ticket_number", "ss_customer_sk",
                  col("ca_city").alias("bought_city"), "extended_price",
                  "list_price", "extended_tax"))
    cust = dfs["customer"].select("c_customer_sk", "c_current_addr_sk",
                                  "c_first_name", "c_last_name")
    ca2 = dfs["customer_address"].select("ca_address_sk", "ca_city")
    out = dn.join(cust, on=col("ss_customer_sk") == col("c_customer_sk"))
    out = out.join(ca2, on=col("c_current_addr_sk") == col("ca_address_sk"))
    out = out.filter(col("ca_city") != col("bought_city"))
    return (out.select("c_last_name", "c_first_name", "ca_city",
                       "bought_city", "ss_ticket_number", "extended_price",
                       "extended_tax", "list_price")
            .sort("c_last_name", "ss_ticket_number").limit(100))


def q68_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_dom >= 1) & (d.d_dom <= 2)
           & d.d_year.isin([1999, 2000, 2001])][["d_date_sk"]]
    s = t["store"]
    st = s[s.s_city.isin(["Midway", "Fairview"])][["s_store_sk"]]
    h = t["household_demographics"]
    hd = h[(h.hd_dep_count == 4) | (h.hd_vehicle_count == 3)][["hd_demo_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_city"]],
                left_on="ss_addr_sk", right_on="ca_address_sk")
    dn = j.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                    "ca_city"]).agg(
        extended_price=("ss_ext_sales_price", "sum"),
        list_price=("ss_ext_list_price", "sum"),
        extended_tax=("ss_ext_tax", "sum")).reset_index()
    dn = dn.rename(columns={"ca_city": "bought_city"})
    out = dn.merge(t["customer"][["c_customer_sk", "c_current_addr_sk",
                                  "c_first_name", "c_last_name"]],
                   left_on="ss_customer_sk", right_on="c_customer_sk")
    out = out.merge(t["customer_address"][["ca_address_sk", "ca_city"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
    out = out[out.ca_city != out.bought_city]
    out = out[["c_last_name", "c_first_name", "ca_city", "bought_city",
               "ss_ticket_number", "extended_price", "extended_tax",
               "list_price"]]
    return (out.sort_values(["c_last_name", "ss_ticket_number"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q79 — per-ticket coupon/profit aggregate with SUBSTR in the output.
# ss_ticket_number is appended as a final sort key on both lanes: the
# official ORDER BY (last_name, first_name, substr(city), profit) does not
# totally order rows, and the 3-way equality check needs a deterministic
# top-100.
# ---------------------------------------------------------------------------


def q79(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_ticket_number", "ss_customer_sk", "ss_hdemo_sk", "ss_addr_sk",
        "ss_sold_date_sk", "ss_store_sk", "ss_coupon_amt", "ss_net_profit")
    dt = (dfs["date_dim"]
          .filter((col("d_dow") == lit(1))
                  & col("d_year").isin(1999, 2000, 2001))
          .select("d_date_sk"))
    st = (dfs["store"]
          .filter(col("s_number_employees").between(200, 295))
          .select("s_store_sk", "s_city"))
    hd = (dfs["household_demographics"]
          .filter((col("hd_dep_count") == lit(6))
                  | (col("hd_vehicle_count") > lit(2)))
          .select("hd_demo_sk"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    ms = (j.group_by("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "s_city")
          .agg(("sum", "ss_coupon_amt", "amt"),
               ("sum", "ss_net_profit", "profit")))
    cust = dfs["customer"].select("c_customer_sk", "c_last_name",
                                  "c_first_name")
    out = ms.join(cust, on=col("ss_customer_sk") == col("c_customer_sk"))
    out = out.select("c_last_name", "c_first_name",
                     col("s_city").substr(1, 30).alias("city"),
                     "ss_ticket_number", "amt", "profit")
    return (out.sort("c_last_name", "c_first_name", "city", "profit",
                     "ss_ticket_number").limit(100))


def q79_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_dow == 1) & d.d_year.isin([1999, 2000, 2001])][["d_date_sk"]]
    s = t["store"]
    st = s[(s.s_number_employees >= 200)
           & (s.s_number_employees <= 295)][["s_store_sk", "s_city"]]
    h = t["household_demographics"]
    hd = h[(h.hd_dep_count == 6) | (h.hd_vehicle_count > 2)][["hd_demo_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    ms = j.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                    "s_city"]).agg(
        amt=("ss_coupon_amt", "sum"),
        profit=("ss_net_profit", "sum")).reset_index()
    out = ms.merge(t["customer"][["c_customer_sk", "c_last_name",
                                  "c_first_name"]],
                   left_on="ss_customer_sk", right_on="c_customer_sk")
    out = out.assign(city=out.s_city.str[:30])
    out = out[["c_last_name", "c_first_name", "city", "ss_ticket_number",
               "amt", "profit"]]
    return (out.sort_values(["c_last_name", "c_first_name", "city",
                             "profit", "ss_ticket_number"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q96 — COUNT(*) over the time/demographic/store probe
# ---------------------------------------------------------------------------


def q96(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_time_sk", "ss_hdemo_sk",
                                   "ss_store_sk")
    hd = (dfs["household_demographics"]
          .filter(col("hd_dep_count") == lit(7)).select("hd_demo_sk"))
    td = (dfs["time_dim"]
          .filter((col("t_hour") == lit(20)) & (col("t_minute") >= lit(30)))
          .select("t_time_sk"))
    st = (dfs["store"].filter(col("s_store_name") == lit("ese"))
          .select("s_store_sk"))
    j = ss.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    return j.group_by().agg(("count", "*", "cnt"))


def q96_pandas(t: Dict[str, "object"]):
    import pandas as pd
    h = t["household_demographics"]
    hd = h[h.hd_dep_count == 7][["hd_demo_sk"]]
    tm = t["time_dim"]
    td = tm[(tm.t_hour == 20) & (tm.t_minute >= 30)][["t_time_sk"]]
    s = t["store"]
    st = s[s.s_store_name == "ese"][["s_store_sk"]]
    j = t["store_sales"].merge(hd, left_on="ss_hdemo_sk",
                               right_on="hd_demo_sk")
    j = j.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    return pd.DataFrame({"cnt": [len(j)]})


# ---------------------------------------------------------------------------
# q13 / q48 — the OR-of-bands family: demographic and address disjuncts over
# value ranges, applied AFTER the star joins (the official shape embeds the
# same equi-join in every disjunct; extracting it is the standard planner
# normalization and what Spark itself executes)
# ---------------------------------------------------------------------------


def q13(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_hdemo_sk",
        "ss_addr_sk", "ss_quantity", "ss_sales_price", "ss_ext_sales_price",
        "ss_ext_wholesale_cost", "ss_net_profit")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2001))
          .select("d_date_sk"))
    st = dfs["store"].select("s_store_sk")
    cd = dfs["customer_demographics"].select(
        "cd_demo_sk", "cd_marital_status", "cd_education_status")
    hd = dfs["household_demographics"].select("hd_demo_sk", "hd_dep_count")
    ca = (dfs["customer_address"]
          .filter(col("ca_country") == lit("United States"))
          .select("ca_address_sk", "ca_state"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(ca, on=col("ss_addr_sk") == col("ca_address_sk"))
    demo = (((col("cd_marital_status") == lit("M"))
             & (col("cd_education_status") == lit("Advanced Degree"))
             & col("ss_sales_price").between(lit(100.0), lit(150.0))
             & (col("hd_dep_count") == lit(3)))
            | ((col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))
               & col("ss_sales_price").between(lit(50.0), lit(100.0))
               & (col("hd_dep_count") == lit(1)))
            | ((col("cd_marital_status") == lit("W"))
               & (col("cd_education_status") == lit("2 yr Degree"))
               & col("ss_sales_price").between(lit(150.0), lit(200.0))
               & (col("hd_dep_count") == lit(1))))
    addr = ((col("ca_state").isin("TX", "OH")
             & col("ss_net_profit").between(lit(100), lit(200)))
            | (col("ca_state").isin("OR", "NM", "KY")
               & col("ss_net_profit").between(lit(150), lit(300)))
            | (col("ca_state").isin("VA", "TX", "MS")
               & col("ss_net_profit").between(lit(50), lit(250))))
    return (j.filter(demo & addr)
            .agg(("avg", "ss_quantity", "avg_qty"),
                 ("avg", "ss_ext_sales_price", "avg_esp"),
                 ("avg", "ss_ext_wholesale_cost", "avg_ewc"),
                 ("sum", "ss_ext_wholesale_cost", "sum_ewc")))


def q13_pandas(t: Dict[str, "object"]):
    import pandas as pd

    d = t["date_dim"]
    dt = d[d.d_year == 2001][["d_date_sk"]]
    ca = t["customer_address"]
    ca = ca[ca.ca_country == "United States"][["ca_address_sk", "ca_state"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk"]], left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(t["customer_demographics"][
        ["cd_demo_sk", "cd_marital_status", "cd_education_status"]],
        left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(t["household_demographics"][["hd_demo_sk", "hd_dep_count"]],
                left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "Advanced Degree")
             & j.ss_sales_price.between(100.0, 150.0)
             & (j.hd_dep_count == 3))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(50.0, 100.0)
               & (j.hd_dep_count == 1))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "2 yr Degree")
               & j.ss_sales_price.between(150.0, 200.0)
               & (j.hd_dep_count == 1)))
    addr = ((j.ca_state.isin(["TX", "OH"])
             & j.ss_net_profit.between(100, 200))
            | (j.ca_state.isin(["OR", "NM", "KY"])
               & j.ss_net_profit.between(150, 300))
            | (j.ca_state.isin(["VA", "TX", "MS"])
               & j.ss_net_profit.between(50, 250)))
    j = j[demo & addr]
    return pd.DataFrame({
        "avg_qty": [j.ss_quantity.mean()],
        "avg_esp": [j.ss_ext_sales_price.mean()],
        "avg_ewc": [j.ss_ext_wholesale_cost.mean()],
        # min_count=1: SUM over zero rows is SQL NULL, not 0.0.
        "sum_ewc": [j.ss_ext_wholesale_cost.sum(min_count=1)]})


def q48(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_addr_sk",
        "ss_quantity", "ss_sales_price", "ss_net_profit")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    st = dfs["store"].select("s_store_sk")
    cd = dfs["customer_demographics"].select(
        "cd_demo_sk", "cd_marital_status", "cd_education_status")
    ca = (dfs["customer_address"]
          .filter(col("ca_country") == lit("United States"))
          .select("ca_address_sk", "ca_state"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(ca, on=col("ss_addr_sk") == col("ca_address_sk"))
    demo = (((col("cd_marital_status") == lit("M"))
             & (col("cd_education_status") == lit("4 yr Degree"))
             & col("ss_sales_price").between(lit(100.0), lit(150.0)))
            | ((col("cd_marital_status") == lit("D"))
               & (col("cd_education_status") == lit("2 yr Degree"))
               & col("ss_sales_price").between(lit(50.0), lit(100.0)))
            | ((col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))
               & col("ss_sales_price").between(lit(150.0), lit(200.0))))
    addr = ((col("ca_state").isin("CO", "OH", "TX")
             & col("ss_net_profit").between(lit(0), lit(2000)))
            | (col("ca_state").isin("OR", "MN", "KY")
               & col("ss_net_profit").between(lit(150), lit(3000)))
            | (col("ca_state").isin("VA", "CA", "MS")
               & col("ss_net_profit").between(lit(50), lit(25000))))
    return j.filter(demo & addr).agg(("sum", "ss_quantity", "sum_qty"))


def q48_pandas(t: Dict[str, "object"]):
    import pandas as pd

    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    ca = t["customer_address"]
    ca = ca[ca.ca_country == "United States"][["ca_address_sk", "ca_state"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk"]], left_on="ss_store_sk",
                right_on="s_store_sk")
    j = j.merge(t["customer_demographics"][
        ["cd_demo_sk", "cd_marital_status", "cd_education_status"]],
        left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "4 yr Degree")
             & j.ss_sales_price.between(100.0, 150.0))
            | ((j.cd_marital_status == "D")
               & (j.cd_education_status == "2 yr Degree")
               & j.ss_sales_price.between(50.0, 100.0))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(150.0, 200.0)))
    addr = ((j.ca_state.isin(["CO", "OH", "TX"])
             & j.ss_net_profit.between(0, 2000))
            | (j.ca_state.isin(["OR", "MN", "KY"])
               & j.ss_net_profit.between(150, 3000))
            | (j.ca_state.isin(["VA", "CA", "MS"])
               & j.ss_net_profit.between(50, 25000)))
    j = j[demo & addr]
    # min_count=1: SUM over zero rows is SQL NULL, not 0.
    return pd.DataFrame({"sum_qty": [j.ss_quantity.sum(min_count=1)]})


# ---------------------------------------------------------------------------
# q15 — catalog zip/state/price disjunct with SUBSTR over ca_zip
# ---------------------------------------------------------------------------


def q15(dfs: Dict[str, "object"]):
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_sales_price")
    dt = (dfs["date_dim"]
          .filter((col("d_qoy") == lit(2)) & (col("d_year") == lit(2001)))
          .select("d_date_sk"))
    cu = dfs["customer"].select("c_customer_sk", "c_current_addr_sk")
    ca = dfs["customer_address"].select("ca_address_sk", "ca_state",
                                        "ca_zip")
    j = cs.join(dt, on=col("cs_sold_date_sk") == col("d_date_sk"))
    j = j.join(cu, on=col("cs_bill_customer_sk") == col("c_customer_sk"))
    j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
    cond = (col("ca_zip").substr(1, 5).isin(
        "85669", "86197", "88274", "83405", "86475", "85392", "85460",
        "80348", "81792")
        | col("ca_state").isin("CA", "WA", "GA")
        | (col("cs_sales_price") > lit(500.0)))
    return (j.filter(cond)
            .group_by("ca_zip")
            .agg(("sum", "cs_sales_price", "sum_sales"))
            .sort("ca_zip").limit(100))


def q15_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[(d.d_qoy == 2) & (d.d_year == 2001)][["d_date_sk"]]
    j = t["catalog_sales"].merge(dt, left_on="cs_sold_date_sk",
                                 right_on="d_date_sk")
    j = j.merge(t["customer"][["c_customer_sk", "c_current_addr_sk"]],
                left_on="cs_bill_customer_sk", right_on="c_customer_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_state",
                                       "ca_zip"]],
                left_on="c_current_addr_sk", right_on="ca_address_sk")
    cond = (j.ca_zip.str[:5].isin(
        ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
         "80348", "81792"])
        | j.ca_state.isin(["CA", "WA", "GA"])
        | (j.cs_sales_price > 500.0))
    g = j[cond].groupby("ca_zip").agg(
        sum_sales=("cs_sales_price", "sum")).reset_index()
    return g.sort_values("ca_zip").head(100).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q26 — the catalog twin of q7 (demographic/promotion item averages)
# ---------------------------------------------------------------------------


def q26(dfs: Dict[str, "object"]):
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk", "cs_promo_sk",
        "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    cd = (dfs["customer_demographics"]
          .filter((col("cd_gender") == lit("M"))
                  & (col("cd_marital_status") == lit("S"))
                  & (col("cd_education_status") == lit("College")))
          .select("cd_demo_sk"))
    promo = (dfs["promotion"]
             .filter((col("p_channel_email") == lit("N"))
                     | (col("p_channel_event") == lit("N")))
             .select("p_promo_sk"))
    it = dfs["item"].select("i_item_sk", "i_item_id")
    j = cs.join(dt, on=col("cs_sold_date_sk") == col("d_date_sk"))
    j = j.join(cd, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(promo, on=col("cs_promo_sk") == col("p_promo_sk"))
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    return (j.group_by("i_item_id")
            .agg(("avg", "cs_quantity", "agg1"),
                 ("avg", "cs_list_price", "agg2"),
                 ("avg", "cs_coupon_amt", "agg3"),
                 ("avg", "cs_sales_price", "agg4"))
            .sort("i_item_id").limit(100))


def q26_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    c = t["customer_demographics"]
    cd = c[(c.cd_gender == "M") & (c.cd_marital_status == "S")
           & (c.cd_education_status == "College")][["cd_demo_sk"]]
    p = t["promotion"]
    promo = p[(p.p_channel_email == "N")
              | (p.p_channel_event == "N")][["p_promo_sk"]]
    j = t["catalog_sales"].merge(dt, left_on="cs_sold_date_sk",
                                 right_on="d_date_sk")
    j = j.merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(promo, left_on="cs_promo_sk", right_on="p_promo_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id"]],
                left_on="cs_item_sk", right_on="i_item_sk")
    g = j.groupby("i_item_id").agg(
        agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
        agg3=("cs_coupon_amt", "mean"),
        agg4=("cs_sales_price", "mean")).reset_index()
    return g.sort_values("i_item_id").head(100).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q43 — weekly store pivot: SUM(CASE WHEN d_day_name = ... ) per weekday
# ---------------------------------------------------------------------------

_DAY_COLS = (("sun_sales", "Sunday"), ("mon_sales", "Monday"),
             ("tue_sales", "Tuesday"), ("wed_sales", "Wednesday"),
             ("thu_sales", "Thursday"), ("fri_sales", "Friday"),
             ("sat_sales", "Saturday"))


def q43(dfs: Dict[str, "object"]):
    from hyperspace_tpu.plan.expr import when

    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_store_sk",
                                   "ss_sales_price")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk", "d_day_name"))
    st = (dfs["store"].filter(col("s_gmt_offset") == lit(-5.0))
          .select("s_store_sk", "s_store_id", "s_store_name"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    aggs = [("sum", when(col("d_day_name") == lit(day),
                         col("ss_sales_price")), alias)
            for alias, day in _DAY_COLS]
    return (j.group_by("s_store_name", "s_store_id")
            .agg(*aggs)
            .sort("s_store_name", "s_store_id",
                  *[alias for alias, _ in _DAY_COLS])
            .limit(100))


def q43_pandas(t: Dict[str, "object"]):
    import numpy as np

    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk", "d_day_name"]]
    s = t["store"]
    st = s[s.s_gmt_offset == -5.0][["s_store_sk", "s_store_id",
                                    "s_store_name"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    for alias, day in _DAY_COLS:
        j[alias] = np.where(j.d_day_name == day, j.ss_sales_price, np.nan)
    # min_count=1: a (store, weekday) group with no matching rows is SQL
    # NULL (the framework's no-ELSE CASE semantics), not 0.0.
    g = j.groupby(["s_store_name", "s_store_id"]).agg(
        **{alias: (alias, lambda s: s.sum(min_count=1))
           for alias, _ in _DAY_COLS}).reset_index()
    return (g.sort_values(["s_store_name", "s_store_id"]
                          + [alias for alias, _ in _DAY_COLS])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q50 — return-lag buckets: SUM(CASE WHEN returned - sold <= N ...) pivot
# over the ss JOIN sr ticket identity (the q17/q25 index pair serves it)
# ---------------------------------------------------------------------------

_Q50_STORE_COLS = ("s_store_name", "s_company_id", "s_street_number",
                   "s_street_name", "s_street_type", "s_suite_number",
                   "s_city", "s_county", "s_state", "s_zip")


def q50(dfs: Dict[str, "object"]):
    from hyperspace_tpu.plan.expr import when

    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_store_sk", "ss_ticket_number", "ss_item_sk",
        "ss_customer_sk")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_ticket_number", "sr_item_sk",
        "sr_customer_sk")
    j = ss.join(sr, on=((col("ss_ticket_number") == col("sr_ticket_number"))
                        & (col("ss_item_sk") == col("sr_item_sk"))
                        & (col("ss_customer_sk") == col("sr_customer_sk"))))
    d2 = (dfs["date_dim"]
          .filter((col("d_year") == lit(2001)) & (col("d_moy") == lit(8)))
          .select("d_date_sk"))
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk"))
    d1 = dfs["date_dim"].select("d_date_sk")
    # Drop d2's key before the second date join or the names collide.
    j = j.select("ss_sold_date_sk", "ss_store_sk", "sr_returned_date_sk")
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk"))
    st = dfs["store"].select("s_store_sk", *_Q50_STORE_COLS)
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    lag = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    buckets = [
        ("days_30", when(lag <= lit(30), lit(1)).otherwise(lit(0))),
        ("days_31_60", when((lag > lit(30)) & (lag <= lit(60)),
                            lit(1)).otherwise(lit(0))),
        ("days_61_90", when((lag > lit(60)) & (lag <= lit(90)),
                            lit(1)).otherwise(lit(0))),
        ("days_91_120", when((lag > lit(90)) & (lag <= lit(120)),
                             lit(1)).otherwise(lit(0))),
        ("days_over_120", when(lag > lit(120), lit(1)).otherwise(lit(0))),
    ]
    return (j.group_by(*_Q50_STORE_COLS)
            .agg(*[("sum", e, alias) for alias, e in buckets])
            .sort(*_Q50_STORE_COLS).limit(100))


def q50_pandas(t: Dict[str, "object"]):
    import numpy as np

    j = t["store_sales"][["ss_sold_date_sk", "ss_store_sk",
                          "ss_ticket_number", "ss_item_sk",
                          "ss_customer_sk"]].merge(
        t["store_returns"][["sr_returned_date_sk", "sr_ticket_number",
                            "sr_item_sk", "sr_customer_sk"]],
        left_on=["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
        right_on=["sr_ticket_number", "sr_item_sk", "sr_customer_sk"])
    d = t["date_dim"]
    d2 = d[(d.d_year == 2001) & (d.d_moy == 8)][["d_date_sk"]]
    j = j.merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j[["ss_sold_date_sk", "ss_store_sk", "sr_returned_date_sk"]]
    j = j.merge(d[["d_date_sk"]], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", *_Q50_STORE_COLS]],
                left_on="ss_store_sk", right_on="s_store_sk")
    lag = j.sr_returned_date_sk - j.ss_sold_date_sk
    j = j.assign(
        days_30=np.where(lag <= 30, 1, 0),
        days_31_60=np.where((lag > 30) & (lag <= 60), 1, 0),
        days_61_90=np.where((lag > 60) & (lag <= 90), 1, 0),
        days_91_120=np.where((lag > 90) & (lag <= 120), 1, 0),
        days_over_120=np.where(lag > 120, 1, 0))
    g = j.groupby(list(_Q50_STORE_COLS)).agg(
        days_30=("days_30", "sum"), days_31_60=("days_31_60", "sum"),
        days_61_90=("days_61_90", "sum"),
        days_91_120=("days_91_120", "sum"),
        days_over_120=("days_over_120", "sum")).reset_index()
    return (g.sort_values(list(_Q50_STORE_COLS))
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q28 / q88 / q61 — the scalar-subquery assembly family: independent one-row
# aggregates crossed into a single result row (CROSS JOIN in the official
# text's FROM-list-of-subqueries form)
# ---------------------------------------------------------------------------

# (bucket tag, qty_lo, qty_hi, lp_lo, coupon_lo, whole_lo) — official q28
# band parameters: list_price +10, coupon +1000, wholesale +20.
_Q28_BUCKETS = (("b1", 0, 5, 8, 459, 57), ("b2", 6, 10, 90, 2323, 31),
                ("b3", 11, 15, 142, 12214, 79),
                ("b4", 16, 20, 135, 6071, 38),
                ("b5", 21, 25, 122, 836, 17), ("b6", 26, 30, 154, 7326, 7))


def q28(dfs: Dict[str, "object"]):
    out = None
    for tag, qlo, qhi, lp, cp, wc in _Q28_BUCKETS:
        b = (dfs["store_sales"]
             .select("ss_quantity", "ss_list_price", "ss_coupon_amt",
                     "ss_wholesale_cost")
             .filter(col("ss_quantity").between(lit(qlo), lit(qhi))
                     & (col("ss_list_price").between(lit(float(lp)),
                                                     lit(float(lp + 10)))
                        | col("ss_coupon_amt").between(lit(float(cp)),
                                                       lit(float(cp + 1000)))
                        | col("ss_wholesale_cost").between(
                            lit(float(wc)), lit(float(wc + 20)))))
             .agg(("avg", "ss_list_price", f"{tag}_lp"),
                  ("count", "ss_list_price", f"{tag}_cnt"),
                  ("count_distinct", "ss_list_price", f"{tag}_cntd")))
        out = b if out is None else out.join(b, how="cross")
    return out.limit(100)


def q28_pandas(t: Dict[str, "object"]):
    import pandas as pd

    ss = t["store_sales"]
    row = {}
    for tag, qlo, qhi, lp, cp, wc in _Q28_BUCKETS:
        b = ss[ss.ss_quantity.between(qlo, qhi)
               & (ss.ss_list_price.between(lp, lp + 10)
                  | ss.ss_coupon_amt.between(cp, cp + 1000)
                  | ss.ss_wholesale_cost.between(wc, wc + 20))]
        row[f"{tag}_lp"] = b.ss_list_price.mean()
        row[f"{tag}_cnt"] = b.ss_list_price.count()
        row[f"{tag}_cntd"] = b.ss_list_price.nunique()
    return pd.DataFrame([row])


# Official q88 half-hour windows 8:30 .. 12:30 (t_hour, minute-half).
_Q88_BANDS = (("h8_30", 8, ">="), ("h9", 9, "<"), ("h9_30", 9, ">="),
              ("h10", 10, "<"), ("h10_30", 10, ">="), ("h11", 11, "<"),
              ("h11_30", 11, ">="), ("h12", 12, "<"))


def q88(dfs: Dict[str, "object"]):
    hd = (dfs["household_demographics"]
          .filter(((col("hd_dep_count") == lit(4))
                   & (col("hd_vehicle_count") <= lit(6)))
                  | ((col("hd_dep_count") == lit(2))
                     & (col("hd_vehicle_count") <= lit(4)))
                  | ((col("hd_dep_count") == lit(0))
                     & (col("hd_vehicle_count") <= lit(2))))
          .select("hd_demo_sk"))
    st = (dfs["store"].filter(col("s_store_name") == lit("ese"))
          .select("s_store_sk"))
    out = None
    for tag, hour, half in _Q88_BANDS:
        minute = (col("t_minute") >= lit(30) if half == ">="
                  else col("t_minute") < lit(30))
        td = (dfs["time_dim"]
              .filter((col("t_hour") == lit(hour)) & minute)
              .select("t_time_sk"))
        ss = dfs["store_sales"].select("ss_sold_time_sk", "ss_hdemo_sk",
                                       "ss_store_sk")
        j = ss.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
        j = j.join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
        j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
        b = j.agg(("count", "*", tag))
        out = b if out is None else out.join(b, how="cross")
    return out


def q88_pandas(t: Dict[str, "object"]):
    import pandas as pd

    h = t["household_demographics"]
    hd = h[((h.hd_dep_count == 4) & (h.hd_vehicle_count <= 6))
           | ((h.hd_dep_count == 2) & (h.hd_vehicle_count <= 4))
           | ((h.hd_dep_count == 0) & (h.hd_vehicle_count <= 2))][
               ["hd_demo_sk"]]
    s = t["store"]
    st = s[s.s_store_name == "ese"][["s_store_sk"]]
    row = {}
    for tag, hour, half in _Q88_BANDS:
        td = t["time_dim"]
        td = td[(td.t_hour == hour)
                & (td.t_minute >= 30 if half == ">="
                   else td.t_minute < 30)][["t_time_sk"]]
        j = t["store_sales"].merge(hd, left_on="ss_hdemo_sk",
                                   right_on="hd_demo_sk")
        j = j.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
        j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        row[tag] = len(j)
    return pd.DataFrame([row])


def q61(dfs: Dict[str, "object"]):
    """Promotional-channel revenue share. Probes 2000-11 instead of the
    official 1998-11 (the generator concentrates sales in 1999-2001 —
    same adjustment q19 makes)."""

    def channel_sales(with_promo: bool):
        ss = dfs["store_sales"].select(
            "ss_sold_date_sk", "ss_store_sk", "ss_promo_sk",
            "ss_customer_sk", "ss_item_sk", "ss_ext_sales_price")
        dt = (dfs["date_dim"]
              .filter((col("d_year") == lit(2000))
                      & (col("d_moy") == lit(11)))
              .select("d_date_sk"))
        st = (dfs["store"].filter(col("s_gmt_offset") == lit(-5.0))
              .select("s_store_sk"))
        it = (dfs["item"].filter(col("i_category") == lit("Jewelry"))
              .select("i_item_sk"))
        cu = dfs["customer"].select("c_customer_sk", "c_current_addr_sk")
        ca = (dfs["customer_address"]
              .filter(col("ca_gmt_offset") == lit(-5.0))
              .select("ca_address_sk"))
        j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
        j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
        if with_promo:
            promo = (dfs["promotion"]
                     .filter((col("p_channel_dmail") == lit("Y"))
                             | (col("p_channel_email") == lit("Y"))
                             | (col("p_channel_tv") == lit("Y")))
                     .select("p_promo_sk"))
            j = j.join(promo, on=col("ss_promo_sk") == col("p_promo_sk"))
        j = j.join(cu, on=col("ss_customer_sk") == col("c_customer_sk"))
        j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
        j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
        alias = "promotions" if with_promo else "total"
        return j.agg(("sum", "ss_ext_sales_price", alias))

    p = channel_sales(True)
    tot = channel_sales(False)
    return (p.join(tot, how="cross")
            .select("promotions", "total",
                    ((col("promotions") / col("total"))
                     * lit(100.0)).alias("share")))


def q61_pandas(t: Dict[str, "object"]):
    import pandas as pd

    def channel_sales(with_promo: bool):
        d = t["date_dim"]
        dt = d[(d.d_year == 2000) & (d.d_moy == 11)][["d_date_sk"]]
        s = t["store"]
        st = s[s.s_gmt_offset == -5.0][["s_store_sk"]]
        i = t["item"]
        it = i[i.i_category == "Jewelry"][["i_item_sk"]]
        ca = t["customer_address"]
        ca = ca[ca.ca_gmt_offset == -5.0][["ca_address_sk"]]
        j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                                   right_on="d_date_sk")
        j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        if with_promo:
            p = t["promotion"]
            promo = p[(p.p_channel_dmail == "Y") | (p.p_channel_email == "Y")
                      | (p.p_channel_tv == "Y")][["p_promo_sk"]]
            j = j.merge(promo, left_on="ss_promo_sk", right_on="p_promo_sk")
        j = j.merge(t["customer"][["c_customer_sk", "c_current_addr_sk"]],
                    left_on="ss_customer_sk", right_on="c_customer_sk")
        j = j.merge(ca, left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        return j.ss_ext_sales_price.sum()

    promotions = channel_sales(True)
    total = channel_sales(False)
    return pd.DataFrame([{"promotions": promotions, "total": total,
                          "share": promotions / total * 100.0}])


# ---------------------------------------------------------------------------
# q53 / q63 / q89 / q98 — the window family: grouped sums compared against
# their AVG/SUM OVER (PARTITION BY ...), deviation filters, share ratios.
# Date predicates use d_year/d_moy (the generator has no d_month_seq /
# d_date); item brand literals use the generator's brand_NN domain.
# ---------------------------------------------------------------------------

_Q53_DISJUNCT_ARGS = (
    (("Books", "Children", "Electronics"),
     ("personal", "portable", "reference", "self-help"),
     ("brand_01", "brand_03", "brand_05", "brand_07")),
    (("Women", "Music", "Men"),
     ("accessories", "classical", "fragrances", "pants"),
     ("brand_02", "brand_04", "brand_06", "brand_08")),
)


def _item_disjunct_expr():
    (c1, k1, b1), (c2, k2, b2) = _Q53_DISJUNCT_ARGS
    return ((col("i_category").isin(*c1) & col("i_class").isin(*k1)
             & col("i_brand").isin(*b1))
            | (col("i_category").isin(*c2) & col("i_class").isin(*k2)
               & col("i_brand").isin(*b2)))


def _item_disjunct_mask(i):
    (c1, k1, b1), (c2, k2, b2) = _Q53_DISJUNCT_ARGS
    return ((i.i_category.isin(c1) & i.i_class.isin(k1)
             & i.i_brand.isin(b1))
            | (i.i_category.isin(c2) & i.i_class.isin(k2)
               & i.i_brand.isin(b2)))


def _abs(e):
    from hyperspace_tpu.plan.expr import when
    return when(e < lit(0.0), lit(0.0) - e).otherwise(e)


def _q53_shape(dfs, key_col: str, period_col: str, avg_alias: str):
    """Shared q53/q63 body: quarterly/monthly sums per item key vs the
    key's average over periods, rows deviating >10% from it."""
    ss = dfs["store_sales"].select("ss_item_sk", "ss_sold_date_sk",
                                   "ss_store_sk", "ss_sales_price")
    it = (dfs["item"]
          .filter(_item_disjunct_expr())
          .select("i_item_sk", key_col))
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk", period_col))
    st = dfs["store"].select("s_store_sk")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    g = (j.group_by(key_col, period_col)
         .agg(("sum", "ss_sales_price", "sum_sales")))
    w = g.window([key_col], **{avg_alias: ("avg", "sum_sales")})
    dev = _abs(col("sum_sales") - col(avg_alias)) / col(avg_alias)
    return (w.filter((col(avg_alias) > lit(0.0)) & (dev > lit(0.1)))
            .select(key_col, "sum_sales", avg_alias)
            .sort(avg_alias, "sum_sales", key_col).limit(100))


def _q53_shape_pandas(t, key_col: str, left_key: str, period_col: str,
                      avg_alias: str):
    i = t["item"]
    it = i[_item_disjunct_mask(i)][["i_item_sk", key_col]]
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk", period_col]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["store"][["s_store_sk"]], left_on="ss_store_sk",
                right_on="s_store_sk")
    g = (j.groupby([key_col, period_col])
         .agg(sum_sales=("ss_sales_price", "sum")).reset_index())
    g[avg_alias] = g.groupby(key_col)["sum_sales"].transform("mean")
    g = g[(g[avg_alias] > 0)
          & ((g.sum_sales - g[avg_alias]).abs() / g[avg_alias] > 0.1)]
    return (g[[key_col, "sum_sales", avg_alias]]
            .sort_values([avg_alias, "sum_sales", key_col])
            .head(100).reset_index(drop=True))


def q53(dfs: Dict[str, "object"]):
    return _q53_shape(dfs, "i_manufact_id", "d_qoy", "avg_quarterly_sales")


def q53_pandas(t: Dict[str, "object"]):
    return _q53_shape_pandas(t, "i_manufact_id", "ss_item_sk", "d_qoy",
                             "avg_quarterly_sales")


def q63(dfs: Dict[str, "object"]):
    return _q53_shape(dfs, "i_manager_id", "d_moy", "avg_monthly_sales")


def q63_pandas(t: Dict[str, "object"]):
    return _q53_shape_pandas(t, "i_manager_id", "ss_item_sk", "d_moy",
                             "avg_monthly_sales")


_Q89_KEYS = ["i_category", "i_class", "i_brand", "s_store_name",
             "s_company_name"]


def q89(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_item_sk", "ss_sold_date_sk",
                                   "ss_store_sk", "ss_sales_price")
    it = (dfs["item"]
          .filter(_item_disjunct_expr())
          .select("i_item_sk", "i_category", "i_class", "i_brand"))
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk", "d_moy"))
    st = dfs["store"].select("s_store_sk", "s_store_name",
                             "s_company_name")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    g = (j.group_by(*(_Q89_KEYS + ["d_moy"]))
         .agg(("sum", "ss_sales_price", "sum_sales")))
    w = g.window(["i_category", "i_brand", "s_store_name",
                  "s_company_name"],
                 avg_monthly_sales=("avg", "sum_sales"))
    dev = (_abs(col("sum_sales") - col("avg_monthly_sales"))
           / col("avg_monthly_sales"))
    return (w.filter((col("avg_monthly_sales") > lit(0.0))
                     & (dev > lit(0.1)))
            .select(*(_Q89_KEYS + ["d_moy", "sum_sales",
                                   "avg_monthly_sales"]),
                    (col("sum_sales")
                     - col("avg_monthly_sales")).alias("delta"))
            .sort("delta", "s_store_name", *_Q89_KEYS, "d_moy")
            .limit(100).select(*(_Q89_KEYS + ["d_moy", "sum_sales",
                                              "avg_monthly_sales"])))


def q89_pandas(t: Dict[str, "object"]):
    i = t["item"]
    it = i[_item_disjunct_mask(i)][["i_item_sk", "i_category", "i_class",
                                    "i_brand"]]
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk", "d_moy"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_name",
                            "s_company_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    g = (j.groupby(_Q89_KEYS + ["d_moy"])
         .agg(sum_sales=("ss_sales_price", "sum")).reset_index())
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name",
         "s_company_name"])["sum_sales"].transform("mean")
    g = g[(g.avg_monthly_sales > 0)
          & ((g.sum_sales - g.avg_monthly_sales).abs()
             / g.avg_monthly_sales > 0.1)]
    g = g.assign(delta=g.sum_sales - g.avg_monthly_sales)
    g = (g.sort_values(["delta", "s_store_name"] + _Q89_KEYS + ["d_moy"])
         .head(100).reset_index(drop=True))
    return g[_Q89_KEYS + ["d_moy", "sum_sales", "avg_monthly_sales"]]


_Q98_KEYS = ["i_item_id", "i_item_desc", "i_category", "i_class",
             "i_current_price"]


def q98(dfs: Dict[str, "object"]):
    """Item revenue share of its class. Probes d_year=2000, d_moy=5 (a
    ~31-day window like the official 30-day d_date range, which the
    generator's date_dim does not carry)."""
    ss = dfs["store_sales"].select("ss_item_sk", "ss_sold_date_sk",
                                   "ss_ext_sales_price")
    it = (dfs["item"]
          .filter(col("i_category").isin("Sports", "Books", "Home"))
          .select("i_item_sk", *_Q98_KEYS))
    dt = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(5)))
          .select("d_date_sk"))
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    g = (j.group_by(*_Q98_KEYS)
         .agg(("sum", "ss_ext_sales_price", "itemrevenue")))
    w = g.window(["i_class"], class_revenue=("sum", "itemrevenue"))
    return (w.select(*_Q98_KEYS, "itemrevenue",
                     ((col("itemrevenue") * lit(100.0))
                      / col("class_revenue")).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio", "itemrevenue"))


def q98_pandas(t: Dict[str, "object"]):
    i = t["item"]
    it = i[i.i_category.isin(["Sports", "Books", "Home"])][
        ["i_item_sk"] + _Q98_KEYS]
    d = t["date_dim"]
    dt = d[(d.d_year == 2000) & (d.d_moy == 5)][["d_date_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    g = (j.groupby(_Q98_KEYS)
         .agg(itemrevenue=("ss_ext_sales_price", "sum")).reset_index())
    g["revenueratio"] = (g.itemrevenue * 100.0
                         / g.groupby("i_class")["itemrevenue"]
                         .transform("sum"))
    return (g[_Q98_KEYS + ["itemrevenue", "revenueratio"]]
            .sort_values(["i_category", "i_class", "i_item_id",
                          "i_item_desc", "revenueratio", "itemrevenue"])
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q65 — stores' under-performing items: per-(store, item) revenue joined
# against the store's average item revenue (aggregated-subquery join; the
# shared inner aggregate executes ONCE via the engine's subtree reuse).
# Probes d_year=2000 for the official d_month_seq window (not generated).
# ---------------------------------------------------------------------------


def q65(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_store_sk",
                                   "ss_item_sk", "ss_sales_price")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    inner = (ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
             .group_by("ss_store_sk", "ss_item_sk")
             .agg(("sum", "ss_sales_price", "revenue")))
    sb = (inner.group_by("ss_store_sk")
          .agg(("avg", "revenue", "ave")))
    j = inner.join(sb, on=col("ss_store_sk") == col("ss_store_sk"))
    j = j.filter(col("revenue") <= col("ave") * lit(0.1))
    st = dfs["store"].select("s_store_sk", "s_store_name")
    it = dfs["item"].select("i_item_sk", "i_item_desc", "i_current_price",
                            "i_wholesale_cost", "i_brand")
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.select("s_store_name", "i_item_desc", "revenue",
                     "i_current_price", "i_wholesale_cost", "i_brand")
            .sort("s_store_name", "i_item_desc", "revenue").limit(100))


def q65_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    inner = (t["store_sales"]
             .merge(dt, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .groupby(["ss_store_sk", "ss_item_sk"])
             .agg(revenue=("ss_sales_price", "sum")).reset_index())
    sb = (inner.groupby("ss_store_sk")
          .agg(ave=("revenue", "mean")).reset_index())
    j = inner.merge(sb, on="ss_store_sk")
    j = j[j.revenue <= 0.1 * j.ave]
    j = j.merge(t["store"][["s_store_sk", "s_store_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_desc", "i_current_price",
                           "i_wholesale_cost", "i_brand"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    return (j[["s_store_name", "i_item_desc", "revenue",
               "i_current_price", "i_wholesale_cost", "i_brand"]]
            .sort_values(["s_store_name", "i_item_desc", "revenue"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q67 — ROLLUP over 8 item/date/store columns + rank per category.
# ROLLUP(c1..c8) is expressed as its definition: the UNION of 9 grouping
# granularities, coarser branches projecting typed NULLs for the dropped
# columns; the 9 branches share ONE joined subtree (engine subtree reuse).
# Probes d_year=2000 for the official d_month_seq window (not generated).
# ---------------------------------------------------------------------------

_Q67_ROLLUP = (("i_category", "string"), ("i_class", "string"),
               ("i_brand", "string"), ("i_product_name", "string"),
               ("d_year", "int64"), ("d_qoy", "int64"), ("d_moy", "int64"),
               ("s_store_id", "string"))


def q67(dfs: Dict[str, "object"]):
    from hyperspace_tpu.engine.dataframe import DataFrame
    from hyperspace_tpu.plan.expr import null
    from hyperspace_tpu.plan.nodes import Union

    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_store_sk", "ss_quantity",
                                   "ss_sales_price")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk", "d_year", "d_qoy", "d_moy"))
    st = dfs["store"].select("s_store_sk", "s_store_id")
    it = dfs["item"].select("i_item_sk", "i_category", "i_class",
                            "i_brand", "i_product_name")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    sales = (col("ss_sales_price") * col("ss_quantity")).alias("_sales")
    j = j.select(*[name for name, _ in _Q67_ROLLUP], sales)

    names = [name for name, _ in _Q67_ROLLUP]
    branches = []
    for depth in range(len(_Q67_ROLLUP), -1, -1):
        keep = names[:depth]
        if keep:
            g = j.group_by(*keep).agg(("sum", "_sales", "sumsales"))
        else:
            g = j.agg(("sum", "_sales", "sumsales"))
        entries = list(keep) + [null(dtype).alias(name)
                                for name, dtype in _Q67_ROLLUP[depth:]]
        branches.append(g.select(*entries, "sumsales").plan)
    u = DataFrame(Union(branches), j.session)
    w = u.window(["i_category"], order_by=["-sumsales"],
                 rk=("rank", "*"))
    return (w.filter(col("rk") <= lit(100))
            .sort(*names, "sumsales", "rk").limit(100))


def q67_pandas(t: Dict[str, "object"]):
    import numpy as np
    import pandas as pd

    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk", "d_year", "d_qoy", "d_moy"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_id"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_category", "i_class", "i_brand",
                           "i_product_name"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    j = j.assign(_sales=j.ss_sales_price * j.ss_quantity)
    names = [name for name, _ in _Q67_ROLLUP]
    parts = []
    for depth in range(len(names), -1, -1):
        keep = names[:depth]
        if keep:
            g = (j.groupby(keep).agg(sumsales=("_sales", "sum"))
                 .reset_index())
        else:
            g = pd.DataFrame({"sumsales": [j._sales.sum()]})
        for name in names[depth:]:
            g[name] = np.nan
        parts.append(g[names + ["sumsales"]])
    u = pd.concat(parts, ignore_index=True)
    u["rk"] = (u.groupby("i_category", dropna=False)["sumsales"]
               .rank(method="min", ascending=False).astype("int64"))
    u = u[u.rk <= 100]
    # Engine Sort is ascending nulls-FIRST; mirror it for the limit.
    u = u.sort_values(names + ["sumsales", "rk"], na_position="first")
    return u.head(100).reset_index(drop=True)


from hyperspace_tpu.tpcds.queries_ext import QUERIES_EXT  # noqa: E402

QUERIES: Dict[str, Tuple[Callable, Callable]] = {
    "q3": (q3, q3_pandas),
    "q7": (q7, q7_pandas),
    "q13": (q13, q13_pandas),
    "q15": (q15, q15_pandas),
    "q17": (q17, q17_pandas),
    "q19": (q19, q19_pandas),
    "q25": (q25, q25_pandas),
    "q26": (q26, q26_pandas),
    "q28": (q28, q28_pandas),
    "q42": (q42, q42_pandas),
    "q43": (q43, q43_pandas),
    "q48": (q48, q48_pandas),
    "q50": (q50, q50_pandas),
    "q52": (q52, q52_pandas),
    "q53": (q53, q53_pandas),
    "q55": (q55, q55_pandas),
    "q61": (q61, q61_pandas),
    "q63": (q63, q63_pandas),
    "q64": (q64, q64_pandas),
    "q65": (q65, q65_pandas),
    "q67": (q67, q67_pandas),
    "q68": (q68, q68_pandas),
    "q79": (q79, q79_pandas),
    "q88": (q88, q88_pandas),
    "q89": (q89, q89_pandas),
    "q96": (q96, q96_pandas),
    "q98": (q98, q98_pandas),
}
QUERIES.update(QUERIES_EXT)

from hyperspace_tpu.tpcds.queries_ext2 import QUERIES_EXT2  # noqa: E402

QUERIES.update(QUERIES_EXT2)

from hyperspace_tpu.tpcds.queries_ext3 import QUERIES_EXT3  # noqa: E402

QUERIES.update(QUERIES_EXT3)
