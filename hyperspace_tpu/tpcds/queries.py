"""TPC-DS q17 / q25 / q64 on the framework DataFrame API, with pandas
oracles.

Each query is expressed as a join tree the rewrite rules can accelerate:
the innermost join is a linear scan pair (JoinIndexRule's applicability,
reference `JoinIndexRule.scala:210-211`), dimension filters run before
their joins (FilterIndexRule + bucket pruning serve them), and dimension
key columns are projected away immediately after each join so the thrice-
joined date_dim never collides on output names.

The pandas oracle for each query doubles as the CPU baseline and the
correctness check: `bench_tpcds.py` and `tests/test_tpcds.py` assert
sorted-result equality between rules-on, rules-off, and the oracle —
the reference's own E2E guarantee
(`E2EHyperspaceRulesTests.scala:330-346`).

q64 is structurally faithful at reduced width: the cs_ui HAVING subquery,
the cross_sales aggregation, and the year-over-year self-join of the
aggregate are all present; low-cardinality demographic dimensions the
subset generator does not model are omitted.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from hyperspace_tpu.plan.expr import col, lit


# ---------------------------------------------------------------------------
# q17 — quarterly store/catalog behaviour of returned items
# ---------------------------------------------------------------------------


def q17(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_quantity")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_return_quantity")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
        "cs_quantity")
    d1 = (dfs["date_dim"].filter(col("d_quarter_name") == lit("2000Q1"))
          .select("d_date_sk"))
    d23q = col("d_quarter_name").isin("2000Q1", "2000Q2", "2000Q3")
    d2 = dfs["date_dim"].filter(d23q).select("d_date_sk")
    d3 = dfs["date_dim"].filter(d23q).select("d_date_sk")
    store = dfs["store"].select("s_store_sk", "s_state")
    item = dfs["item"].select("i_item_sk", "i_item_id", "i_item_desc")

    j = ss.join(sr, on=(col("ss_customer_sk") == col("sr_customer_sk"))
                & (col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(cs, on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_returned_date_sk",
        "sr_return_quantity", "cs_sold_date_sk", "cs_quantity")
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_return_quantity",
        "cs_sold_date_sk", "cs_quantity")
    j = j.join(d3, on=col("cs_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_return_quantity",
        "cs_quantity")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    out = (j.group_by("i_item_id", "i_item_desc", "s_state").agg(
        ("count", "ss_quantity", "store_sales_quantitycount"),
        ("avg", "ss_quantity", "store_sales_quantityave"),
        ("stddev", "ss_quantity", "store_sales_quantitystdev"),
        ("count", "sr_return_quantity", "store_returns_quantitycount"),
        ("avg", "sr_return_quantity", "store_returns_quantityave"),
        ("stddev", "sr_return_quantity", "store_returns_quantitystdev"),
        ("count", "cs_quantity", "catalog_sales_quantitycount"),
        ("avg", "cs_quantity", "catalog_sales_quantityave"),
        ("stddev", "cs_quantity", "catalog_sales_quantitystdev"))
        .sort("i_item_id", "i_item_desc", "s_state").limit(100))
    return out


def q17_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    d1 = d[d.d_quarter_name == "2000Q1"][["d_date_sk"]]
    d23 = d[d.d_quarter_name.isin(["2000Q1", "2000Q2", "2000Q3"])][["d_date_sk"]]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = j.merge(t["catalog_sales"],
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_state"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id", "i_item_desc"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_state"]).agg(
        store_sales_quantitycount=("ss_quantity", "count"),
        store_sales_quantityave=("ss_quantity", "mean"),
        store_sales_quantitystdev=("ss_quantity", "std"),
        store_returns_quantitycount=("sr_return_quantity", "count"),
        store_returns_quantityave=("sr_return_quantity", "mean"),
        store_returns_quantitystdev=("sr_return_quantity", "std"),
        catalog_sales_quantitycount=("cs_quantity", "count"),
        catalog_sales_quantityave=("cs_quantity", "mean"),
        catalog_sales_quantitystdev=("cs_quantity", "std"),
    ).reset_index()
    return (g.sort_values(["i_item_id", "i_item_desc", "s_state"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q25 — net profit flow of returned items, April..October
# ---------------------------------------------------------------------------


def q25(dfs: Dict[str, "object"]):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_net_profit")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_net_loss")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
        "cs_net_profit")
    d1 = (dfs["date_dim"]
          .filter((col("d_moy") == lit(4)) & (col("d_year") == lit(2000)))
          .select("d_date_sk"))
    d23f = ((col("d_moy") >= lit(4)) & (col("d_moy") <= lit(10))
            & (col("d_year") == lit(2000)))
    d2 = dfs["date_dim"].filter(d23f).select("d_date_sk")
    d3 = dfs["date_dim"].filter(d23f).select("d_date_sk")
    store = dfs["store"].select("s_store_sk", "s_store_id", "s_store_name")
    item = dfs["item"].select("i_item_sk", "i_item_id", "i_item_desc")

    j = ss.join(sr, on=(col("ss_customer_sk") == col("sr_customer_sk"))
                & (col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(cs, on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_returned_date_sk",
        "sr_net_loss", "cs_sold_date_sk", "cs_net_profit")
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_net_loss",
        "cs_sold_date_sk", "cs_net_profit")
    j = j.join(d3, on=col("cs_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_net_profit", "sr_net_loss",
        "cs_net_profit")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    out = (j.group_by("i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name").agg(
        ("sum", "ss_net_profit", "store_sales_profit"),
        ("sum", "sr_net_loss", "store_returns_loss"),
        ("sum", "cs_net_profit", "catalog_sales_profit"))
        .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
        .limit(100))
    return out


def q25_pandas(t: Dict[str, "object"]):
    d = t["date_dim"]
    d1 = d[(d.d_moy == 4) & (d.d_year == 2000)][["d_date_sk"]]
    d23 = d[(d.d_moy >= 4) & (d.d_moy <= 10) & (d.d_year == 2000)][["d_date_sk"]]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = j.merge(t["catalog_sales"],
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(d23, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_id", "s_store_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id", "i_item_desc"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"]).agg(
        store_sales_profit=("ss_net_profit", "sum"),
        store_returns_loss=("sr_net_loss", "sum"),
        catalog_sales_profit=("cs_net_profit", "sum")).reset_index()
    return (g.sort_values(["i_item_id", "i_item_desc", "s_store_id",
                           "s_store_name"]).head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q64 — year-over-year cross-channel sales of returned items (reduced width)
# ---------------------------------------------------------------------------

_Q64_COLORS = ("plum", "puff", "misty")


def _q64_cs_ui(dfs):
    """Catalog sales whose list-price total exceeds 2x the refund total —
    the HAVING subquery of q64 (filter over an aggregate)."""
    cs = dfs["catalog_sales"].select("cs_item_sk", "cs_order_number",
                                     "cs_ext_list_price")
    cr = dfs["catalog_returns"].select(
        "cr_item_sk", "cr_order_number", "cr_refunded_cash",
        "cr_reversed_charge", "cr_store_credit")
    j = cs.join(cr, on=(col("cs_item_sk") == col("cr_item_sk"))
                & (col("cs_order_number") == col("cr_order_number")))
    agg = j.group_by("cs_item_sk").agg(
        ("sum", "cs_ext_list_price", "sale"),
        ("sum", "cr_refunded_cash", "refund_cash"),
        ("sum", "cr_reversed_charge", "refund_charge"),
        ("sum", "cr_store_credit", "refund_credit"))
    having = (col("sale") > ((col("refund_cash") + col("refund_charge")
                              + col("refund_credit")) * lit(2.0)))
    return agg.filter(having).select("cs_item_sk")


def _q64_cross_sales(dfs, year: int):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_wholesale_cost", "ss_list_price")
    sr = dfs["store_returns"].select("sr_item_sk", "sr_ticket_number")
    dy = (dfs["date_dim"].filter(col("d_year") == lit(year))
          .select("d_date_sk"))
    store = dfs["store"].select("s_store_sk", "s_store_name", "s_zip")
    item = (dfs["item"]
            .filter(col("i_color").isin(*_Q64_COLORS)
                    & (col("i_current_price") >= lit(20.0))
                    & (col("i_current_price") <= lit(85.0)))
            .select("i_item_sk", "i_product_name"))
    customer = dfs["customer"].select("c_customer_sk")

    j = ss.join(sr, on=(col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(_q64_cs_ui(dfs), on=col("ss_item_sk") == col("cs_item_sk"))
    j = j.join(dy, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_customer_sk", "ss_store_sk", "ss_wholesale_cost",
        "ss_list_price")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(customer, on=col("ss_customer_sk") == col("c_customer_sk"))
    return j.group_by("i_product_name", "s_store_name", "s_zip").agg(
        ("count", "*", "cnt"),
        ("sum", "ss_wholesale_cost", "s1"),
        ("sum", "ss_list_price", "s2"))


def q64(dfs: Dict[str, "object"]):
    cs1 = _q64_cross_sales(dfs, 2000)
    cs2 = _q64_cross_sales(dfs, 2001)
    j = cs1.join(cs2, on=(col("i_product_name") == col("i_product_name"))
                 & (col("s_store_name") == col("s_store_name"))
                 & (col("s_zip") == col("s_zip")))
    # Self-join duplicates take the _r suffix on the cs2 side.
    j = j.filter(col("cnt_r") <= col("cnt"))
    return (j.select("i_product_name", "s_store_name", "s_zip",
                     "cnt", "s1", "s2", "cnt_r", "s1_r", "s2_r")
            .sort("i_product_name", "s_store_name", "s_zip").limit(100))


def _q64_cs_ui_pandas(t):
    j = t["catalog_sales"].merge(
        t["catalog_returns"], left_on=["cs_item_sk", "cs_order_number"],
        right_on=["cr_item_sk", "cr_order_number"])
    g = j.groupby("cs_item_sk").agg(
        sale=("cs_ext_list_price", "sum"),
        refund_cash=("cr_refunded_cash", "sum"),
        refund_charge=("cr_reversed_charge", "sum"),
        refund_credit=("cr_store_credit", "sum")).reset_index()
    keep = g[g.sale > 2.0 * (g.refund_cash + g.refund_charge
                             + g.refund_credit)]
    return keep[["cs_item_sk"]]


def _q64_cross_sales_pandas(t, year: int):
    d = t["date_dim"]
    dy = d[d.d_year == year][["d_date_sk"]]
    it = t["item"]
    it = it[it.i_color.isin(list(_Q64_COLORS))
            & (it.i_current_price >= 20.0) & (it.i_current_price <= 85.0)]
    j = t["store_sales"].merge(
        t["store_returns"][["sr_item_sk", "sr_ticket_number"]],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    j = j.merge(_q64_cs_ui_pandas(t), left_on="ss_item_sk",
                right_on="cs_item_sk")
    j = j.merge(dy, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_name", "s_zip"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(it[["i_item_sk", "i_product_name"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["customer"][["c_customer_sk"]],
                left_on="ss_customer_sk", right_on="c_customer_sk")
    return j.groupby(["i_product_name", "s_store_name", "s_zip"]).agg(
        cnt=("ss_item_sk", "size"),
        s1=("ss_wholesale_cost", "sum"),
        s2=("ss_list_price", "sum")).reset_index()


def q64_pandas(t: Dict[str, "object"]):
    cs1 = _q64_cross_sales_pandas(t, 2000)
    cs2 = _q64_cross_sales_pandas(t, 2001)
    j = cs1.merge(cs2, on=["i_product_name", "s_store_name", "s_zip"],
                  suffixes=("", "_r"))
    j = j[j.cnt_r <= j.cnt]
    out = j[["i_product_name", "s_store_name", "s_zip",
             "cnt", "s1", "s2", "cnt_r", "s1_r", "s2_r"]]
    return (out.sort_values(["i_product_name", "s_store_name", "s_zip"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# Index set + registry
# ---------------------------------------------------------------------------


def create_indexes(hs, dfs) -> None:
    """The covering indexes the three queries can use: the ss JOIN sr
    pairs for JoinIndexRule (both key orders used by q17/q25 vs q64), the
    cs_ui pair for q64, and the date_dim quarter filter for
    FilterIndexRule + bucket pruning."""
    from hyperspace_tpu import IndexConfig

    hs.create_index(dfs["store_sales"], IndexConfig(
        "idx_ss_ret", ["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        ["ss_sold_date_sk", "ss_store_sk", "ss_quantity", "ss_net_profit"]))
    hs.create_index(dfs["store_returns"], IndexConfig(
        "idx_sr_ret", ["sr_customer_sk", "sr_item_sk", "sr_ticket_number"],
        ["sr_returned_date_sk", "sr_return_quantity", "sr_net_loss"]))
    hs.create_index(dfs["store_sales"], IndexConfig(
        "idx_ss_ticket", ["ss_item_sk", "ss_ticket_number"],
        ["ss_sold_date_sk", "ss_customer_sk", "ss_store_sk",
         "ss_wholesale_cost", "ss_list_price"]))
    hs.create_index(dfs["store_returns"], IndexConfig(
        "idx_sr_ticket", ["sr_item_sk", "sr_ticket_number"], []))
    hs.create_index(dfs["catalog_sales"], IndexConfig(
        "idx_cs_order", ["cs_item_sk", "cs_order_number"],
        ["cs_ext_list_price"]))
    hs.create_index(dfs["catalog_returns"], IndexConfig(
        "idx_cr_order", ["cr_item_sk", "cr_order_number"],
        ["cr_refunded_cash", "cr_reversed_charge", "cr_store_credit"]))
    hs.create_index(dfs["date_dim"], IndexConfig(
        "idx_dd_quarter", ["d_quarter_name"], ["d_date_sk"]))


QUERIES: Dict[str, Tuple[Callable, Callable]] = {
    "q17": (q17, q17_pandas),
    "q25": (q25, q25_pandas),
    "q64": (q64, q64_pandas),
}
