"""Round-5 TPC-DS additions: the web channel, inventory, set-operation
and scalar-subquery families — closing the reference serde's
all-TPC-DS-serializable property (`index/serde/package.scala:46-49`) at
the ENGINE level: every query here executes end to end three ways
(rules on / rules off / pandas oracle) like the rest of the suite.

Shapes follow the official queries with this generator's parameter
choices (years 1999-2001 carry the sales mass; dimension values follow
`generator.py`'s vocabularies). Idioms covered beyond the round-4 set:
UNION-of-channels re-aggregation (q2/q33/q56/q60/q71/q83), year-over-year
self-joins on week/quarter sequences (q2/q31/q59), growth-ratio
cross-channel comparisons (q11/q74), INTERSECT/EXCEPT customer overlap
(q8/q38/q87), scalar subqueries (q54/q58/q92), inventory before/after
pivots (q21/q22/q37/q39/q82), rank windows over aggregates (q44/q49/q86),
ship-lag CASE pivots (q62/q99), and EXISTS/NOT-EXISTS channel probes
(q35/q69/q94/q16)."""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from hyperspace_tpu.plan.expr import CaseWhen, col, lit
from hyperspace_tpu.tpcds.queries_ext import _rollup_union


def _sum_case(cond, value, alias):
    return ("sum", CaseWhen([(cond, value)]), alias)


# ---------------------------------------------------------------------------
# q2 — ws+cs weekly sums, year-over-year by week_seq offset
# ---------------------------------------------------------------------------


_DAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
         "Saturday"]


def q2(dfs):
    ws = dfs["web_sales"].select(
        col("ws_sold_date_sk").alias("sold_date_sk"),
        col("ws_ext_sales_price").alias("sales_price"))
    cs = dfs["catalog_sales"].select(
        col("cs_sold_date_sk").alias("sold_date_sk"),
        col("cs_ext_sales_price").alias("sales_price"))
    wscs = ws.union(cs)
    d = dfs["date_dim"].select("d_date_sk", "d_week_seq", "d_day_name",
                               "d_year")
    j = wscs.join(d, on=col("sold_date_sk") == col("d_date_sk"))
    aggs = [_sum_case(col("d_day_name") == lit(day), col("sales_price"),
                      day[:3].lower() + "_sales")
            for day in _DAYS]
    y1 = (j.filter(col("d_year") == lit(1999)).group_by("d_week_seq")
          .agg(*aggs))
    y2 = (j.filter(col("d_year") == lit(2000)).group_by("d_week_seq")
          .agg(*aggs))
    y2 = y2.select(*[col(c).alias(c + "2") for c in y2.columns])
    y2 = y2.with_column("wk_join", col("d_week_seq2") - lit(52))
    jj = y1.join(y2, on=col("d_week_seq") == col("wk_join"))
    out = jj.select(
        "d_week_seq",
        *[(col(day[:3].lower() + "_sales")
           / col(day[:3].lower() + "_sales2")).alias(
               "r_" + day[:3].lower()) for day in _DAYS])
    return out.sort("d_week_seq").limit(100)


def q2_pandas(t):
    ws = t["web_sales"][["ws_sold_date_sk", "ws_ext_sales_price"]].rename(
        columns={"ws_sold_date_sk": "sold_date_sk",
                 "ws_ext_sales_price": "sales_price"})
    cs = t["catalog_sales"][
        ["cs_sold_date_sk", "cs_ext_sales_price"]].rename(
        columns={"cs_sold_date_sk": "sold_date_sk",
                 "cs_ext_sales_price": "sales_price"})
    wscs = pd.concat([ws, cs], ignore_index=True)
    j = wscs.merge(t["date_dim"][["d_date_sk", "d_week_seq", "d_day_name",
                                  "d_year"]],
                   left_on="sold_date_sk", right_on="d_date_sk")

    def pivot(frame):
        g = (frame.groupby(["d_week_seq", "d_day_name"])["sales_price"]
             .sum().unstack("d_day_name"))
        out = pd.DataFrame(index=g.index)
        for day in _DAYS:
            out[day[:3].lower() + "_sales"] = (g[day] if day in g.columns
                                               else float("nan"))
        return out.reset_index()

    y1 = pivot(j[j.d_year == 1999])
    y2 = pivot(j[j.d_year == 2000])
    y2 = y2.rename(columns={c: c + "2" for c in y2.columns})
    jj = y1.merge(y2, left_on=y1.d_week_seq,
                  right_on=y2.d_week_seq2 - 52)
    out = pd.DataFrame({"d_week_seq": jj.d_week_seq})
    for day in _DAYS:
        k = day[:3].lower()
        out["r_" + k] = jj[k + "_sales"] / jj[k + "_sales2"]
    return out.sort_values("d_week_seq").head(100).reset_index(drop=True)


# ---------------------------------------------------------------------------
# q11 / q74 — cross-channel (store vs web) customer growth ratios
# ---------------------------------------------------------------------------


def _year_total(dfs, fact, cust_col, date_col, price_col, year, alias):
    f = dfs[fact].select(cust_col, date_col, price_col)
    d = (dfs["date_dim"].filter(col("d_year") == lit(year))
         .select("d_date_sk"))
    j = f.join(d, on=col(date_col) == col("d_date_sk"))
    return (j.group_by(cust_col)
            .agg(("sum", price_col, alias))
            .select(col(cust_col).alias(alias + "_cust"), alias))


def q11(dfs):
    s1 = _year_total(dfs, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_list_price", 1999, "ss1")
    s2 = _year_total(dfs, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_list_price", 2000, "ss2")
    w1 = _year_total(dfs, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_list_price", 1999, "ws1")
    w2 = _year_total(dfs, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_list_price", 2000, "ws2")
    j = s1.join(s2, on=col("ss1_cust") == col("ss2_cust"))
    j = j.join(w1, on=col("ss1_cust") == col("ws1_cust"))
    j = j.join(w2, on=col("ss1_cust") == col("ws2_cust"))
    j = j.filter((col("ss1") > lit(0)) & (col("ws1") > lit(0)))
    j = j.filter(col("ws2") / col("ws1") > col("ss2") / col("ss1"))
    c = dfs["customer"].select("c_customer_sk", "c_customer_id",
                               "c_first_name", "c_last_name",
                               "c_preferred_cust_flag")
    j = j.join(c, on=col("ss1_cust") == col("c_customer_sk"))
    return (j.select("c_customer_id", "c_first_name", "c_last_name",
                     "c_preferred_cust_flag")
            .sort("c_customer_id", "c_first_name", "c_last_name",
                  "c_preferred_cust_flag").limit(100))


def _year_total_pd(t, fact, cust_col, date_col, price_col, year, alias):
    d = t["date_dim"]
    dd = d[d.d_year == year][["d_date_sk"]]
    j = t[fact][[cust_col, date_col, price_col]].merge(
        dd, left_on=date_col, right_on="d_date_sk")
    g = j.groupby(cust_col, as_index=False)[price_col].sum()
    return g.rename(columns={cust_col: alias + "_cust", price_col: alias})


def q11_pandas(t):
    s1 = _year_total_pd(t, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk", "ss_ext_list_price", 1999, "ss1")
    s2 = _year_total_pd(t, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk", "ss_ext_list_price", 2000, "ss2")
    w1 = _year_total_pd(t, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk", "ws_ext_list_price", 1999, "ws1")
    w2 = _year_total_pd(t, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk", "ws_ext_list_price", 2000, "ws2")
    j = s1.merge(s2, left_on="ss1_cust", right_on="ss2_cust")
    j = j.merge(w1, left_on="ss1_cust", right_on="ws1_cust")
    j = j.merge(w2, left_on="ss1_cust", right_on="ws2_cust")
    j = j[(j.ss1 > 0) & (j.ws1 > 0)]
    j = j[j.ws2 / j.ws1 > j.ss2 / j.ss1]
    j = j.merge(t["customer"][["c_customer_sk", "c_customer_id",
                               "c_first_name", "c_last_name",
                               "c_preferred_cust_flag"]],
                left_on="ss1_cust", right_on="c_customer_sk")
    return (j[["c_customer_id", "c_first_name", "c_last_name",
               "c_preferred_cust_flag"]]
            .sort_values(["c_customer_id", "c_first_name", "c_last_name",
                          "c_preferred_cust_flag"])
            .head(100).reset_index(drop=True))


def q74(dfs):
    """q11's sibling: quantity-based totals, AVG instead of SUM."""
    s1 = _year_total(dfs, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_net_profit", 1999, "ss1")
    s2 = _year_total(dfs, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_net_profit", 2000, "ss2")
    w1 = _year_total(dfs, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_net_profit", 1999, "ws1")
    w2 = _year_total(dfs, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_net_profit", 2000, "ws2")
    j = s1.join(s2, on=col("ss1_cust") == col("ss2_cust"))
    j = j.join(w1, on=col("ss1_cust") == col("ws1_cust"))
    j = j.join(w2, on=col("ss1_cust") == col("ws2_cust"))
    j = j.filter((col("ss1") > lit(0)) & (col("ws1") > lit(0)))
    j = j.filter(col("ws2") / col("ws1") > col("ss2") / col("ss1"))
    c = dfs["customer"].select("c_customer_sk", "c_customer_id",
                               "c_first_name", "c_last_name")
    j = j.join(c, on=col("ss1_cust") == col("c_customer_sk"))
    return (j.select("c_customer_id", "c_first_name", "c_last_name")
            .sort("c_customer_id", "c_first_name", "c_last_name")
            .limit(100))


def q74_pandas(t):
    s1 = _year_total_pd(t, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk", "ss_net_profit", 1999, "ss1")
    s2 = _year_total_pd(t, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk", "ss_net_profit", 2000, "ss2")
    w1 = _year_total_pd(t, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk", "ws_net_profit", 1999, "ws1")
    w2 = _year_total_pd(t, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk", "ws_net_profit", 2000, "ws2")
    j = s1.merge(s2, left_on="ss1_cust", right_on="ss2_cust")
    j = j.merge(w1, left_on="ss1_cust", right_on="ws1_cust")
    j = j.merge(w2, left_on="ss1_cust", right_on="ws2_cust")
    j = j[(j.ss1 > 0) & (j.ws1 > 0)]
    j = j[j.ws2 / j.ws1 > j.ss2 / j.ss1]
    j = j.merge(t["customer"][["c_customer_sk", "c_customer_id",
                               "c_first_name", "c_last_name"]],
                left_on="ss1_cust", right_on="c_customer_sk")
    return (j[["c_customer_id", "c_first_name", "c_last_name"]]
            .sort_values(["c_customer_id", "c_first_name", "c_last_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q12 — web revenue share within class (window sum over partition)
# ---------------------------------------------------------------------------


def q12(dfs):
    ws = dfs["web_sales"].select("ws_item_sk", "ws_sold_date_sk",
                                 "ws_ext_sales_price")
    it = (dfs["item"].filter(col("i_category").isin(
        "Books", "Home", "Sports"))
        .select("i_item_sk", "i_item_id", "i_item_desc", "i_category",
                "i_class", "i_current_price"))
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_moy") == lit(2)))
         .select("d_date_sk"))
    j = ws.join(it, on=col("ws_item_sk") == col("i_item_sk"))
    j = j.join(d, on=col("ws_sold_date_sk") == col("d_date_sk"))
    g = (j.group_by("i_item_id", "i_item_desc", "i_category", "i_class",
                    "i_current_price")
         .agg(("sum", "ws_ext_sales_price", "itemrevenue")))
    w = g.window(["i_class"], revenue_class=("sum", "itemrevenue"))
    out = w.select(
        "i_item_id", "i_item_desc", "i_category", "i_class",
        "i_current_price", "itemrevenue",
        (col("itemrevenue") * lit(100.0)
         / col("revenue_class")).alias("revenueratio"))
    return out.sort("i_category", "i_class", "i_item_id", "i_item_desc",
                    "revenueratio").limit(100)


def q12_pandas(t):
    it = t["item"]
    it = it[it.i_category.isin(["Books", "Home", "Sports"])][
        ["i_item_sk", "i_item_id", "i_item_desc", "i_category", "i_class",
         "i_current_price"]]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == 2)][["d_date_sk"]]
    j = t["web_sales"][["ws_item_sk", "ws_sold_date_sk",
                        "ws_ext_sales_price"]].merge(
        it, left_on="ws_item_sk", right_on="i_item_sk")
    j = j.merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                   "i_current_price"], as_index=False).agg(
        itemrevenue=("ws_ext_sales_price", "sum"))
    g["revenueratio"] = (g.itemrevenue * 100.0
                         / g.groupby("i_class").itemrevenue.transform(
                             "sum"))
    return (g.sort_values(["i_category", "i_class", "i_item_id",
                           "i_item_desc", "revenueratio"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q18 — catalog buyer demographics, 4-level ROLLUP of averages
# ---------------------------------------------------------------------------


def q18(dfs):
    cd1 = (dfs["customer_demographics"]
           .filter((col("cd_gender") == lit("F"))
                   & (col("cd_education_status") == lit("Unknown")))
           .select("cd_demo_sk"))
    cd2 = dfs["customer_demographics"].select(
        col("cd_demo_sk").alias("cd2_demo_sk"),
        col("cd_dep_count").alias("cd2_dep_count"))
    c = (dfs["customer"].filter(col("c_birth_month").isin(1, 6, 8, 9))
         .select("c_customer_sk", "c_current_cdemo_sk",
                 "c_current_addr_sk", "c_birth_year"))
    ca = dfs["customer_address"].select("ca_address_sk", "ca_country",
                                        "ca_state", "ca_county")
    d = (dfs["date_dim"].filter(col("d_year") == lit(2000))
         .select("d_date_sk"))
    it = dfs["item"].select("i_item_sk", "i_item_id")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
        "cs_bill_customer_sk", "cs_quantity", "cs_list_price",
        "cs_coupon_amt", "cs_sales_price", "cs_net_profit")
    j = cs.join(cd1, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(c, on=col("cs_bill_customer_sk") == col("c_customer_sk"))
    j = j.join(cd2, on=col("c_current_cdemo_sk") == col("cd2_demo_sk"))
    j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
    j = j.join(d, on=col("cs_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    u = _rollup_union(
        j, [("i_item_id", "string"), ("ca_country", "string"),
            ("ca_state", "string"), ("ca_county", "string")],
        {"agg1": ("avg", "cs_quantity"),
         "agg2": ("avg", "cs_list_price"),
         "agg3": ("avg", "cs_coupon_amt"),
         "agg4": ("avg", "cs_sales_price"),
         "agg5": ("avg", "cs_net_profit"),
         "agg6": ("avg", "c_birth_year"),
         "agg7": ("avg", "cd2_dep_count")}, j.session)
    return (u.select("i_item_id", "ca_country", "ca_state", "ca_county",
                     "agg1", "agg2", "agg3", "agg4", "agg5", "agg6",
                     "agg7")
            .sort("ca_country", "ca_state", "ca_county", "i_item_id")
            .limit(100))


def q18_pandas(t):
    cd = t["customer_demographics"]
    cd1 = cd[(cd.cd_gender == "F")
             & (cd.cd_education_status == "Unknown")][["cd_demo_sk"]]
    cd2 = cd[["cd_demo_sk", "cd_dep_count"]].rename(
        columns={"cd_demo_sk": "cd2_demo_sk",
                 "cd_dep_count": "cd2_dep_count"})
    c = t["customer"]
    c = c[c.c_birth_month.isin([1, 6, 8, 9])][
        ["c_customer_sk", "c_current_cdemo_sk", "c_current_addr_sk",
         "c_birth_year"]]
    d = t["date_dim"]
    dd = d[d.d_year == 2000][["d_date_sk"]]
    j = t["catalog_sales"].merge(cd1, left_on="cs_bill_cdemo_sk",
                                 right_on="cd_demo_sk")
    j = j.merge(c, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
    j = j.merge(cd2, left_on="c_current_cdemo_sk", right_on="cd2_demo_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_country",
                                       "ca_state", "ca_county"]],
                left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id"]],
                left_on="cs_item_sk", right_on="i_item_sk")
    measures = {"agg1": "cs_quantity", "agg2": "cs_list_price",
                "agg3": "cs_coupon_amt", "agg4": "cs_sales_price",
                "agg5": "cs_net_profit", "agg6": "c_birth_year",
                "agg7": "cd2_dep_count"}
    levels = ["i_item_id", "ca_country", "ca_state", "ca_county"]
    outs = []
    for depth in range(len(levels), -1, -1):
        keys = levels[:depth]
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                **{a: (src, "mean") for a, src in measures.items()})
        else:
            g = pd.DataFrame({a: [j[src].mean()]
                              for a, src in measures.items()})
        for name in levels:
            if name not in g.columns:
                g[name] = np.nan
        outs.append(g[levels + list(measures)])
    u = pd.concat(outs, ignore_index=True)
    # Engine ascending sort is nulls-FIRST; the rollup's subtotal rows
    # carry null keys, so the limit must cut the same rows.
    return (u.sort_values(["ca_country", "ca_state", "ca_county",
                           "i_item_id"], na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q30 — web returners above 1.2x their state's average return
# ---------------------------------------------------------------------------


def q30(dfs):
    wr = dfs["web_returns"].select("wr_returning_customer_sk",
                                   "wr_returned_date_sk",
                                   "wr_refunded_addr_sk", "wr_return_amt")
    d = (dfs["date_dim"].filter(col("d_year") == lit(2000))
         .select("d_date_sk"))
    ca = dfs["customer_address"].select("ca_address_sk", "ca_state")
    j = wr.join(d, on=col("wr_returned_date_sk") == col("d_date_sk"))
    j = j.join(ca, on=col("wr_refunded_addr_sk") == col("ca_address_sk"))
    ctr = (j.group_by("wr_returning_customer_sk", "ca_state")
           .agg(("sum", "wr_return_amt", "ctr_total_return")))
    avg_state = (ctr.group_by("ca_state")
                 .agg(("avg", "ctr_total_return", "state_avg"))
                 .select(col("ca_state").alias("avg_state"), "state_avg"))
    jj = ctr.join(avg_state, on=col("ca_state") == col("avg_state"))
    jj = jj.filter(col("ctr_total_return")
                   > col("state_avg") * lit(1.2))
    c = dfs["customer"].select("c_customer_sk", "c_customer_id",
                               "c_salutation", "c_first_name",
                               "c_last_name", "c_preferred_cust_flag",
                               "c_birth_month")
    jj = jj.join(c, on=col("wr_returning_customer_sk")
                 == col("c_customer_sk"))
    return (jj.select("c_customer_id", "c_salutation", "c_first_name",
                      "c_last_name", "c_preferred_cust_flag",
                      "c_birth_month", "ctr_total_return")
            .sort("c_customer_id", "c_salutation", "c_first_name",
                  "c_last_name", "c_preferred_cust_flag", "c_birth_month",
                  "ctr_total_return").limit(100))


def q30_pandas(t):
    d = t["date_dim"]
    dd = d[d.d_year == 2000][["d_date_sk"]]
    j = t["web_returns"].merge(dd, left_on="wr_returned_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_state"]],
                left_on="wr_refunded_addr_sk", right_on="ca_address_sk")
    ctr = j.groupby(["wr_returning_customer_sk", "ca_state"],
                    as_index=False).agg(
        ctr_total_return=("wr_return_amt", "sum"))
    avg_state = ctr.groupby("ca_state", as_index=False).agg(
        state_avg=("ctr_total_return", "mean"))
    jj = ctr.merge(avg_state, on="ca_state")
    jj = jj[jj.ctr_total_return > jj.state_avg * 1.2]
    jj = jj.merge(t["customer"][["c_customer_sk", "c_customer_id",
                                 "c_salutation", "c_first_name",
                                 "c_last_name", "c_preferred_cust_flag",
                                 "c_birth_month"]],
                  left_on="wr_returning_customer_sk",
                  right_on="c_customer_sk")
    return (jj[["c_customer_id", "c_salutation", "c_first_name",
                "c_last_name", "c_preferred_cust_flag", "c_birth_month",
                "ctr_total_return"]]
            .sort_values(["c_customer_id", "c_salutation", "c_first_name",
                          "c_last_name", "c_preferred_cust_flag",
                          "c_birth_month", "ctr_total_return"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q31 — county quarterly growth: web outpacing store
# ---------------------------------------------------------------------------


def _county_q(dfs, fact, addr_col, date_col, price_col, qoy, alias):
    f = dfs[fact].select(addr_col, date_col, price_col)
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_qoy") == lit(qoy)))
         .select("d_date_sk"))
    ca = dfs["customer_address"].select("ca_address_sk", "ca_county")
    j = f.join(d, on=col(date_col) == col("d_date_sk"))
    j = j.join(ca, on=col(addr_col) == col("ca_address_sk"))
    return (j.group_by("ca_county").agg(("sum", price_col, alias))
            .select(col("ca_county").alias(alias + "_cty"), alias))


def q31(dfs):
    ss1 = _county_q(dfs, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                    "ss_ext_sales_price", 1, "ss1")
    ss2 = _county_q(dfs, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                    "ss_ext_sales_price", 2, "ss2")
    ss3 = _county_q(dfs, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                    "ss_ext_sales_price", 3, "ss3")
    ws1 = _county_q(dfs, "web_sales", "ws_bill_addr_sk",
                    "ws_sold_date_sk", "ws_ext_sales_price", 1, "ws1")
    ws2 = _county_q(dfs, "web_sales", "ws_bill_addr_sk",
                    "ws_sold_date_sk", "ws_ext_sales_price", 2, "ws2")
    ws3 = _county_q(dfs, "web_sales", "ws_bill_addr_sk",
                    "ws_sold_date_sk", "ws_ext_sales_price", 3, "ws3")
    j = ss1.join(ss2, on=col("ss1_cty") == col("ss2_cty"))
    j = j.join(ss3, on=col("ss1_cty") == col("ss3_cty"))
    j = j.join(ws1, on=col("ss1_cty") == col("ws1_cty"))
    j = j.join(ws2, on=col("ss1_cty") == col("ws2_cty"))
    j = j.join(ws3, on=col("ss1_cty") == col("ws3_cty"))
    j = j.filter((col("ss1") > lit(0)) & (col("ss2") > lit(0))
                 & (col("ws1") > lit(0)) & (col("ws2") > lit(0)))
    # One growth comparison (official ANDs q2->q3 as well; with this
    # generator's four counties that conjunction can select zero rows).
    j = j.filter(col("ws2") / col("ws1") > col("ss2") / col("ss1"))
    return (j.select(col("ss1_cty").alias("ca_county"),
                     (col("ws2") / col("ws1")).alias("web_q1_q2"),
                     (col("ss2") / col("ss1")).alias("store_q1_q2"),
                     (col("ws3") / col("ws2")).alias("web_q2_q3"),
                     (col("ss3") / col("ss2")).alias("store_q2_q3"))
            .sort("ca_county"))


def _county_q_pd(t, fact, addr_col, date_col, price_col, qoy, alias):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy == qoy)][["d_date_sk"]]
    j = t[fact][[addr_col, date_col, price_col]].merge(
        dd, left_on=date_col, right_on="d_date_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_county"]],
                left_on=addr_col, right_on="ca_address_sk")
    g = j.groupby("ca_county", as_index=False)[price_col].sum()
    return g.rename(columns={"ca_county": alias + "_cty",
                             price_col: alias})


def q31_pandas(t):
    ss1 = _county_q_pd(t, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                       "ss_ext_sales_price", 1, "ss1")
    ss2 = _county_q_pd(t, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                       "ss_ext_sales_price", 2, "ss2")
    ss3 = _county_q_pd(t, "store_sales", "ss_addr_sk", "ss_sold_date_sk",
                       "ss_ext_sales_price", 3, "ss3")
    ws1 = _county_q_pd(t, "web_sales", "ws_bill_addr_sk",
                       "ws_sold_date_sk", "ws_ext_sales_price", 1, "ws1")
    ws2 = _county_q_pd(t, "web_sales", "ws_bill_addr_sk",
                       "ws_sold_date_sk", "ws_ext_sales_price", 2, "ws2")
    ws3 = _county_q_pd(t, "web_sales", "ws_bill_addr_sk",
                       "ws_sold_date_sk", "ws_ext_sales_price", 3, "ws3")
    j = ss1.merge(ss2, left_on="ss1_cty", right_on="ss2_cty")
    j = j.merge(ss3, left_on="ss1_cty", right_on="ss3_cty")
    j = j.merge(ws1, left_on="ss1_cty", right_on="ws1_cty")
    j = j.merge(ws2, left_on="ss1_cty", right_on="ws2_cty")
    j = j.merge(ws3, left_on="ss1_cty", right_on="ws3_cty")
    j = j[(j.ss1 > 0) & (j.ss2 > 0) & (j.ws1 > 0) & (j.ws2 > 0)]
    j = j[j.ws2 / j.ws1 > j.ss2 / j.ss1]
    out = pd.DataFrame({
        "ca_county": j.ss1_cty,
        "web_q1_q2": j.ws2 / j.ws1, "store_q1_q2": j.ss2 / j.ss1,
        "web_q2_q3": j.ws3 / j.ws2, "store_q2_q3": j.ss3 / j.ss2})
    return out.sort_values("ca_county").reset_index(drop=True)


# ---------------------------------------------------------------------------
# q33 — 3-channel manufacturer revenue for one category/month/gmt
# ---------------------------------------------------------------------------


def _q33_channel(dfs, fact, item_col, date_col, addr_col, price_col):
    manufact = (dfs["item"].filter(col("i_category") == lit("Books"))
                .select("i_manufact_id").distinct())
    it = dfs["item"].select("i_item_sk",
                            col("i_manufact_id").alias("manu"))
    it = it.join(manufact, on=col("manu") == col("i_manufact_id"),
                 how="left_semi")
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_moy") == lit(5)))
         .select("d_date_sk"))
    ca = (dfs["customer_address"].filter(col("ca_gmt_offset")
                                         == lit(-5.0))
          .select("ca_address_sk"))
    f = dfs[fact].select(item_col, date_col, addr_col, price_col)
    j = f.join(d, on=col(date_col) == col("d_date_sk"))
    j = j.join(ca, on=col(addr_col) == col("ca_address_sk"))
    j = j.join(it, on=col(item_col) == col("i_item_sk"))
    return (j.group_by("manu")
            .agg(("sum", price_col, "total_sales"))
            .select("manu", "total_sales"))


def q33(dfs):
    ss = _q33_channel(dfs, "store_sales", "ss_item_sk",
                      "ss_sold_date_sk", "ss_addr_sk",
                      "ss_ext_sales_price")
    cs = _q33_channel(dfs, "catalog_sales", "cs_item_sk",
                      "cs_sold_date_sk", "cs_bill_addr_sk",
                      "cs_ext_sales_price")
    ws = _q33_channel(dfs, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                      "ws_bill_addr_sk", "ws_ext_sales_price")
    u = ss.union(cs).union(ws)
    return (u.group_by("manu").agg(("sum", "total_sales", "total_sales"))
            .sort("total_sales", "manu").limit(100))


def _q33_channel_pd(t, fact, item_col, date_col, addr_col, price_col):
    it = t["item"]
    manu = it[it.i_category == "Books"].i_manufact_id.unique()
    itt = it[it.i_manufact_id.isin(manu)][["i_item_sk", "i_manufact_id"]]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == 5)][["d_date_sk"]]
    ca = t["customer_address"]
    caa = ca[ca.ca_gmt_offset == -5.0][["ca_address_sk"]]
    j = t[fact][[item_col, date_col, addr_col, price_col]].merge(
        dd, left_on=date_col, right_on="d_date_sk")
    j = j.merge(caa, left_on=addr_col, right_on="ca_address_sk")
    j = j.merge(itt, left_on=item_col, right_on="i_item_sk")
    g = j.groupby("i_manufact_id", as_index=False)[price_col].sum()
    return g.rename(columns={"i_manufact_id": "manu",
                             price_col: "total_sales"})


def q33_pandas(t):
    u = pd.concat([
        _q33_channel_pd(t, "store_sales", "ss_item_sk", "ss_sold_date_sk",
                        "ss_addr_sk", "ss_ext_sales_price"),
        _q33_channel_pd(t, "catalog_sales", "cs_item_sk",
                        "cs_sold_date_sk", "cs_bill_addr_sk",
                        "cs_ext_sales_price"),
        _q33_channel_pd(t, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                        "ws_bill_addr_sk", "ws_ext_sales_price")],
        ignore_index=True)
    g = u.groupby("manu", as_index=False).total_sales.sum()
    return (g.sort_values(["total_sales", "manu"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q59 — store weekly sales, this year vs 52 weeks later
# ---------------------------------------------------------------------------


_WEEKDAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]


def q59(dfs):
    ss = dfs["store_sales"].select("ss_store_sk", "ss_sold_date_sk",
                                   "ss_sales_price")
    d = dfs["date_dim"].select("d_date_sk", "d_week_seq", "d_day_name")
    j = ss.join(d, on=col("ss_sold_date_sk") == col("d_date_sk"))
    aggs = [_sum_case(col("d_day_name") == lit(day),
                      col("ss_sales_price"),
                      day[:3].lower() + "_sales")
            for day in _WEEKDAYS]
    wss = j.group_by("d_week_seq", "ss_store_sk").agg(*aggs)
    st = dfs["store"].select("s_store_sk", "s_store_id", "s_store_name")
    # Year 1: weeks 53..104 (1999); year 2: +52.
    y1 = (wss.filter((col("d_week_seq") >= lit(53))
                     & (col("d_week_seq") <= lit(104)))
          .join(st, on=col("ss_store_sk") == col("s_store_sk")))
    y2 = wss.filter((col("d_week_seq") >= lit(105))
                    & (col("d_week_seq") <= lit(156)))
    y2 = y2.select(col("d_week_seq").alias("wk2"),
                   col("ss_store_sk").alias("store2"),
                   *[col(day[:3].lower() + "_sales").alias(
                       day[:3].lower() + "_sales2")
                     for day in _WEEKDAYS])
    y2 = y2.with_column("wk_join", col("wk2") - lit(52))
    jj = y1.join(y2, on=(col("ss_store_sk") == col("store2"))
                 & (col("d_week_seq") == col("wk_join")))
    out = jj.select(
        "s_store_name", "s_store_id", "d_week_seq",
        *[(col(day[:3].lower() + "_sales")
           / col(day[:3].lower() + "_sales2")).alias(
               "r_" + day[:3].lower()) for day in _WEEKDAYS])
    return (out.sort("s_store_name", "s_store_id", "d_week_seq")
            .limit(100))


def q59_pandas(t):
    j = t["store_sales"][["ss_store_sk", "ss_sold_date_sk",
                          "ss_sales_price"]].merge(
        t["date_dim"][["d_date_sk", "d_week_seq", "d_day_name"]],
        left_on="ss_sold_date_sk", right_on="d_date_sk")
    g = (j.groupby(["d_week_seq", "ss_store_sk", "d_day_name"])
         ["ss_sales_price"].sum().unstack("d_day_name"))
    wss = pd.DataFrame(index=g.index)
    for day in _WEEKDAYS:
        wss[day[:3].lower() + "_sales"] = (g[day] if day in g.columns
                                           else float("nan"))
    wss = wss.reset_index()
    st = t["store"][["s_store_sk", "s_store_id", "s_store_name"]]
    y1 = wss[(wss.d_week_seq >= 53) & (wss.d_week_seq <= 104)].merge(
        st, left_on="ss_store_sk", right_on="s_store_sk")
    y2 = wss[(wss.d_week_seq >= 105) & (wss.d_week_seq <= 156)].copy()
    y2 = y2.rename(columns={"d_week_seq": "wk2", "ss_store_sk": "store2",
                            **{day[:3].lower() + "_sales":
                               day[:3].lower() + "_sales2"
                               for day in _WEEKDAYS}})
    jj = y1.assign(_k=y1.d_week_seq + 52).merge(
        y2, left_on=["ss_store_sk", "_k"], right_on=["store2", "wk2"])
    res = pd.DataFrame({
        "s_store_name": jj.s_store_name, "s_store_id": jj.s_store_id,
        "d_week_seq": jj.d_week_seq})
    for day in _WEEKDAYS:
        k = day[:3].lower()
        res["r_" + k] = jj[k + "_sales"] / jj[k + "_sales2"]
    return (res.sort_values(["s_store_name", "s_store_id", "d_week_seq"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q84 — store returners by city and income band
# ---------------------------------------------------------------------------


def q84(dfs):
    ca = (dfs["customer_address"]
          .filter(col("ca_city").isin("Springfield_00", "Springfield_01",
                                      "Greenville_00", "Greenville_01"))
          .select("ca_address_sk"))
    ib = (dfs["income_band"]
          .filter((col("ib_lower_bound") >= lit(10000))
                  & (col("ib_upper_bound") <= lit(160000)))
          .select("ib_income_band_sk"))
    hd = dfs["household_demographics"].select("hd_demo_sk",
                                              "hd_income_band_sk")
    hd = hd.join(ib, on=col("hd_income_band_sk")
                 == col("ib_income_band_sk"), how="left_semi")
    c = dfs["customer"].select("c_customer_sk", "c_customer_id",
                               "c_first_name", "c_last_name",
                               "c_current_addr_sk", "c_current_cdemo_sk",
                               "c_current_hdemo_sk")
    c = c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    c = c.join(hd, on=col("c_current_hdemo_sk") == col("hd_demo_sk"),
               how="left_semi")
    cd = dfs["customer_demographics"].select("cd_demo_sk")
    sr = dfs["store_returns"].select("sr_cdemo_sk")
    j = c.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(sr, on=col("cd_demo_sk") == col("sr_cdemo_sk"))
    return (j.select("c_customer_id", "c_last_name", "c_first_name")
            .sort("c_customer_id", "c_last_name", "c_first_name")
            .limit(100))


def q84_pandas(t):
    ca = t["customer_address"]
    caa = ca[ca.ca_city.isin(["Springfield_00", "Springfield_01",
                              "Greenville_00", "Greenville_01"])][
        ["ca_address_sk"]]
    ib = t["income_band"]
    ibb = ib[(ib.ib_lower_bound >= 10000)
             & (ib.ib_upper_bound <= 160000)][["ib_income_band_sk"]]
    hd = t["household_demographics"]
    hdd = hd[hd.hd_income_band_sk.isin(
        ibb.ib_income_band_sk)][["hd_demo_sk"]]
    c = t["customer"]
    c = c[c.c_current_addr_sk.isin(caa.ca_address_sk)
          & c.c_current_hdemo_sk.isin(hdd.hd_demo_sk)]
    j = c.merge(t["customer_demographics"][["cd_demo_sk"]],
                left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    j = j.merge(t["store_returns"][["sr_cdemo_sk"]],
                left_on="cd_demo_sk", right_on="sr_cdemo_sk")
    return (j[["c_customer_id", "c_last_name", "c_first_name"]]
            .sort_values(["c_customer_id", "c_last_name", "c_first_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q86 — web rollup by category/class with rank within parent
# ---------------------------------------------------------------------------


def q86(dfs):
    d = (dfs["date_dim"].filter((col("d_month_seq") >= lit(24))
                                & (col("d_month_seq") <= lit(35)))
         .select("d_date_sk"))
    ws = dfs["web_sales"].select("ws_sold_date_sk", "ws_item_sk",
                                 "ws_net_paid")
    it = dfs["item"].select("i_item_sk", "i_category", "i_class")
    j = ws.join(d, on=col("ws_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ws_item_sk") == col("i_item_sk"))
    u = _rollup_union(j, [("i_category", "string"),
                          ("i_class", "string")],
                      {"total_sum": ("sum", "ws_net_paid")}, j.session,
                      with_parent=True)
    w = u.window(["lochierarchy", "_parent"], order_by=["-total_sum"],
                 rank_within_parent=("rank", "*"))
    return (w.select("total_sum", "i_category", "i_class",
                     "lochierarchy", "rank_within_parent")
            .sort("-lochierarchy", "i_category", "i_class",
                  "rank_within_parent").limit(100))


def q86_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 35)][["d_date_sk"]]
    j = t["web_sales"][["ws_sold_date_sk", "ws_item_sk",
                        "ws_net_paid"]].merge(
        dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["item"][["i_item_sk", "i_category", "i_class"]],
                left_on="ws_item_sk", right_on="i_item_sk")
    outs = []
    for depth, keys in ((0, ["i_category", "i_class"]),
                        (1, ["i_category"]), (2, [])):
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                total_sum=("ws_net_paid", "sum"))
        else:
            g = pd.DataFrame({"total_sum": [j.ws_net_paid.sum()]})
        g["lochierarchy"] = depth
        for name in ("i_category", "i_class"):
            if name not in g.columns:
                g[name] = np.nan
        g["_parent"] = g["i_category"].where(g.lochierarchy == 0, np.nan)
        outs.append(g[["i_category", "i_class", "lochierarchy", "_parent",
                       "total_sum"]])
    u = pd.concat(outs, ignore_index=True)
    u["rank_within_parent"] = (
        u.groupby(["lochierarchy", "_parent"], dropna=False)["total_sum"]
        .rank(method="min", ascending=False).astype("int64"))
    return (u[["total_sum", "i_category", "i_class", "lochierarchy",
               "rank_within_parent"]]
            .sort_values(["lochierarchy", "i_category", "i_class",
                          "rank_within_parent"],
                         ascending=[False, True, True, True],
                         na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q21 — inventory before/after a pivot date, per warehouse x item
# ---------------------------------------------------------------------------


def q21(dfs):
    inv = dfs["inventory"].select("inv_item_sk", "inv_warehouse_sk",
                                  "inv_date_sk", "inv_quantity_on_hand")
    w = dfs["warehouse"].select("w_warehouse_sk", "w_warehouse_name")
    it = (dfs["item"].filter((col("i_current_price") >= lit(20.0))
                             & (col("i_current_price") <= lit(60.0)))
          .select("i_item_sk", "i_item_id"))
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(700))
                                & (col("d_date_sk") <= lit(760)))
         .select("d_date_sk"))
    j = inv.join(it, on=col("inv_item_sk") == col("i_item_sk"))
    j = j.join(w, on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
    j = j.join(d, on=col("inv_date_sk") == col("d_date_sk"))
    g = (j.group_by("w_warehouse_name", "i_item_id").agg(
        _sum_case(col("inv_date_sk") < lit(730),
                  col("inv_quantity_on_hand"), "inv_before"),
        _sum_case(col("inv_date_sk") >= lit(730),
                  col("inv_quantity_on_hand"), "inv_after")))
    g = g.filter((col("inv_before") > lit(0))
                 & (col("inv_after") / col("inv_before") >= lit(2.0 / 3))
                 & (col("inv_after") / col("inv_before") <= lit(1.5)))
    return (g.select("w_warehouse_name", "i_item_id", "inv_before",
                     "inv_after")
            .sort("w_warehouse_name", "i_item_id").limit(100))


def q21_pandas(t):
    it = t["item"]
    itt = it[(it.i_current_price >= 20.0)
             & (it.i_current_price <= 60.0)][["i_item_sk", "i_item_id"]]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 700) & (d.d_date_sk <= 760)][["d_date_sk"]]
    j = t["inventory"].merge(itt, left_on="inv_item_sk",
                             right_on="i_item_sk")
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_warehouse_name"]],
                left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(dd, left_on="inv_date_sk", right_on="d_date_sk")
    j["before"] = j.inv_quantity_on_hand.where(j.inv_date_sk < 730)
    j["after"] = j.inv_quantity_on_hand.where(j.inv_date_sk >= 730)
    g = j.groupby(["w_warehouse_name", "i_item_id"], as_index=False).agg(
        inv_before=("before", "sum"), inv_after=("after", "sum"))
    g = g[(g.inv_before > 0) & (g.inv_after / g.inv_before >= 2.0 / 3)
          & (g.inv_after / g.inv_before <= 1.5)]
    return (g.sort_values(["w_warehouse_name", "i_item_id"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q22 — inventory average on-hand, product-hierarchy ROLLUP
# ---------------------------------------------------------------------------


def q22(dfs):
    inv = dfs["inventory"].select("inv_item_sk", "inv_date_sk",
                                  "inv_quantity_on_hand")
    d = (dfs["date_dim"].filter((col("d_month_seq") >= lit(24))
                                & (col("d_month_seq") <= lit(35)))
         .select("d_date_sk"))
    it = dfs["item"].select("i_item_sk", "i_product_name", "i_brand",
                            "i_class", "i_category")
    j = inv.join(d, on=col("inv_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("inv_item_sk") == col("i_item_sk"))
    u = _rollup_union(j, [("i_product_name", "string"),
                          ("i_brand", "string"), ("i_class", "string"),
                          ("i_category", "string")],
                      {"qoh": ("avg", "inv_quantity_on_hand")}, j.session)
    return (u.select("i_product_name", "i_brand", "i_class", "i_category",
                     "qoh")
            .sort("qoh", "i_product_name", "i_brand", "i_class",
                  "i_category").limit(100))


def q22_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 35)][["d_date_sk"]]
    j = t["inventory"].merge(dd, left_on="inv_date_sk",
                             right_on="d_date_sk")
    j = j.merge(t["item"][["i_item_sk", "i_product_name", "i_brand",
                           "i_class", "i_category"]],
                left_on="inv_item_sk", right_on="i_item_sk")
    levels = ["i_product_name", "i_brand", "i_class", "i_category"]
    outs = []
    for depth in range(len(levels), -1, -1):
        keys = levels[:depth]
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                qoh=("inv_quantity_on_hand", "mean"))
        else:
            g = pd.DataFrame({"qoh": [j.inv_quantity_on_hand.mean()]})
        for name in levels:
            if name not in g.columns:
                g[name] = np.nan
        outs.append(g[levels + ["qoh"]])
    u = pd.concat(outs, ignore_index=True)
    return (u.sort_values(["qoh"] + levels, na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q37 / q82 — in-stock items in a price band, sold via catalog / store
# ---------------------------------------------------------------------------


def _instock(dfs, fact, item_col):
    it = (dfs["item"].filter((col("i_current_price") >= lit(20.0))
                             & (col("i_current_price") <= lit(60.0)))
          .select("i_item_sk", "i_item_id", "i_item_desc",
                  "i_current_price"))
    inv = (dfs["inventory"]
           .filter((col("inv_quantity_on_hand") >= lit(100))
                   & (col("inv_quantity_on_hand") <= lit(500)))
           .select("inv_item_sk", "inv_date_sk"))
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(700))
                                & (col("d_date_sk") <= lit(760)))
         .select("d_date_sk"))
    f = dfs[fact].select(item_col)
    j = it.join(inv, on=col("i_item_sk") == col("inv_item_sk"))
    j = j.join(d, on=col("inv_date_sk") == col("d_date_sk"))
    j = j.join(f, on=col("i_item_sk") == col(item_col), how="left_semi")
    return (j.group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(("count", "*", "cnt"))
            .select("i_item_id", "i_item_desc", "i_current_price")
            .sort("i_item_id", "i_item_desc", "i_current_price")
            .limit(100))


def q37(dfs):
    return _instock(dfs, "catalog_sales", "cs_item_sk")


def q82(dfs):
    return _instock(dfs, "store_sales", "ss_item_sk")


def _instock_pd(t, fact, item_col):
    it = t["item"]
    itt = it[(it.i_current_price >= 20.0) & (it.i_current_price <= 60.0)][
        ["i_item_sk", "i_item_id", "i_item_desc", "i_current_price"]]
    inv = t["inventory"]
    invv = inv[(inv.inv_quantity_on_hand >= 100)
               & (inv.inv_quantity_on_hand <= 500)][
        ["inv_item_sk", "inv_date_sk"]]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 700) & (d.d_date_sk <= 760)][["d_date_sk"]]
    j = itt.merge(invv, left_on="i_item_sk", right_on="inv_item_sk")
    j = j.merge(dd, left_on="inv_date_sk", right_on="d_date_sk")
    j = j[j.i_item_sk.isin(t[fact][item_col])]
    g = (j.groupby(["i_item_id", "i_item_desc", "i_current_price"],
                   as_index=False).size())
    return (g[["i_item_id", "i_item_desc", "i_current_price"]]
            .sort_values(["i_item_id", "i_item_desc", "i_current_price"])
            .head(100).reset_index(drop=True))


def q37_pandas(t):
    return _instock_pd(t, "catalog_sales", "cs_item_sk")


def q82_pandas(t):
    return _instock_pd(t, "store_sales", "ss_item_sk")


# ---------------------------------------------------------------------------
# q39 — inventory coefficient of variation, consecutive months
# ---------------------------------------------------------------------------


def _inv_month_stats(dfs, moy, tag):
    inv = dfs["inventory"].select("inv_item_sk", "inv_warehouse_sk",
                                  "inv_date_sk", "inv_quantity_on_hand")
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_moy") == lit(moy)))
         .select("d_date_sk"))
    j = inv.join(d, on=col("inv_date_sk") == col("d_date_sk"))
    g = (j.group_by("inv_item_sk", "inv_warehouse_sk")
         .agg(("avg", "inv_quantity_on_hand", "mean_qoh"),
              ("stddev", "inv_quantity_on_hand", "std_qoh")))
    g = g.filter((col("mean_qoh") > lit(0))
                 & (col("std_qoh") / col("mean_qoh") >= lit(1.0)))
    return g.select(col("inv_item_sk").alias(tag + "_item"),
                    col("inv_warehouse_sk").alias(tag + "_wh"),
                    col("mean_qoh").alias(tag + "_mean"),
                    (col("std_qoh") / col("mean_qoh")).alias(tag + "_cov"))


def q39(dfs):
    m1 = _inv_month_stats(dfs, 3, "m1")
    m2 = _inv_month_stats(dfs, 4, "m2")
    j = m1.join(m2, on=(col("m1_item") == col("m2_item"))
                & (col("m1_wh") == col("m2_wh")))
    return (j.select("m1_item", "m1_wh", "m1_mean", "m1_cov", "m2_mean",
                     "m2_cov")
            .sort("m1_item", "m1_wh", "m1_mean", "m1_cov", "m2_mean",
                  "m2_cov").limit(100))


def _inv_month_stats_pd(t, moy, tag):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == moy)][["d_date_sk"]]
    j = t["inventory"].merge(dd, left_on="inv_date_sk",
                             right_on="d_date_sk")
    g = j.groupby(["inv_item_sk", "inv_warehouse_sk"],
                  as_index=False).agg(
        mean_qoh=("inv_quantity_on_hand", "mean"),
        std_qoh=("inv_quantity_on_hand", "std"))
    g = g[(g.mean_qoh > 0) & (g.std_qoh / g.mean_qoh >= 1.0)]
    out = pd.DataFrame({
        tag + "_item": g.inv_item_sk, tag + "_wh": g.inv_warehouse_sk,
        tag + "_mean": g.mean_qoh, tag + "_cov": g.std_qoh / g.mean_qoh})
    return out


def q39_pandas(t):
    m1 = _inv_month_stats_pd(t, 3, "m1")
    m2 = _inv_month_stats_pd(t, 4, "m2")
    j = m1.merge(m2, left_on=["m1_item", "m1_wh"],
                 right_on=["m2_item", "m2_wh"])
    return (j[["m1_item", "m1_wh", "m1_mean", "m1_cov", "m2_mean",
               "m2_cov"]]
            .sort_values(["m1_item", "m1_wh", "m1_mean", "m1_cov",
                          "m2_mean", "m2_cov"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q38 / q87 — cross-channel customer-date overlap (INTERSECT / EXCEPT)
# ---------------------------------------------------------------------------


def _channel_tuples(dfs, fact, cust_col, date_col):
    f = dfs[fact].select(cust_col, date_col)
    d = (dfs["date_dim"].filter((col("d_month_seq") >= lit(24))
                                & (col("d_month_seq") <= lit(35)))
         .select("d_date_sk", "d_week_seq"))
    c = dfs["customer"].select("c_customer_sk", "c_last_name",
                               "c_first_name")
    j = f.join(d, on=col(date_col) == col("d_date_sk"))
    j = j.join(c, on=col(cust_col) == col("c_customer_sk"))
    return j.select("c_last_name", "c_first_name", "d_week_seq")


def q38(dfs):
    ss = _channel_tuples(dfs, "store_sales", "ss_customer_sk",
                         "ss_sold_date_sk")
    cs = _channel_tuples(dfs, "catalog_sales", "cs_bill_customer_sk",
                         "cs_sold_date_sk")
    ws = _channel_tuples(dfs, "web_sales", "ws_bill_customer_sk",
                         "ws_sold_date_sk")
    hot = ss.intersect(cs).intersect(ws)
    return hot.agg(("count", "*", "cnt"))


def q87(dfs):
    ss = _channel_tuples(dfs, "store_sales", "ss_customer_sk",
                         "ss_sold_date_sk")
    cs = _channel_tuples(dfs, "catalog_sales", "cs_bill_customer_sk",
                         "cs_sold_date_sk")
    ws = _channel_tuples(dfs, "web_sales", "ws_bill_customer_sk",
                         "ws_sold_date_sk")
    cool = ss.except_(cs).except_(ws)
    return cool.agg(("count", "*", "cnt"))


def _channel_tuples_pd(t, fact, cust_col, date_col):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 35)][
        ["d_date_sk", "d_week_seq"]]
    j = t[fact][[cust_col, date_col]].merge(
        dd, left_on=date_col, right_on="d_date_sk")
    j = j.merge(t["customer"][["c_customer_sk", "c_last_name",
                               "c_first_name"]],
                left_on=cust_col, right_on="c_customer_sk")
    return set(map(tuple, j[["c_last_name", "c_first_name",
                             "d_week_seq"]].values))


def q38_pandas(t):
    ss = _channel_tuples_pd(t, "store_sales", "ss_customer_sk",
                            "ss_sold_date_sk")
    cs = _channel_tuples_pd(t, "catalog_sales", "cs_bill_customer_sk",
                            "cs_sold_date_sk")
    ws = _channel_tuples_pd(t, "web_sales", "ws_bill_customer_sk",
                            "ws_sold_date_sk")
    return pd.DataFrame({"cnt": [len(ss & cs & ws)]})


def q87_pandas(t):
    ss = _channel_tuples_pd(t, "store_sales", "ss_customer_sk",
                            "ss_sold_date_sk")
    cs = _channel_tuples_pd(t, "catalog_sales", "cs_bill_customer_sk",
                            "cs_sold_date_sk")
    ws = _channel_tuples_pd(t, "web_sales", "ws_bill_customer_sk",
                            "ws_sold_date_sk")
    return pd.DataFrame({"cnt": [len((ss - cs) - ws)]})


# ---------------------------------------------------------------------------
# q92 — web excess discount (q32's web sibling)
# ---------------------------------------------------------------------------


def q92(dfs):
    it = dfs["item"].filter(col("i_manufact_id") == lit(77)) \
        .select("i_item_sk")
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    ws = dfs["web_sales"].select("ws_item_sk", "ws_sold_date_sk",
                                 "ws_ext_discount_amt")
    win = ws.join(dt, on=col("ws_sold_date_sk") == col("d_date_sk"))
    avg_disc = (win.group_by("ws_item_sk")
                .agg(("avg", "ws_ext_discount_amt", "avg_disc")))
    avg_disc = avg_disc.select(col("ws_item_sk").alias("avg_item_sk"),
                               "avg_disc")
    j = win.join(it, on=col("ws_item_sk") == col("i_item_sk"))
    j = j.join(avg_disc, on=col("ws_item_sk") == col("avg_item_sk"))
    j = j.filter(col("ws_ext_discount_amt") > col("avg_disc") * lit(1.3))
    return j.agg(("sum", "ws_ext_discount_amt", "excess_discount_amount"))


def q92_pandas(t):
    it = t["item"][t["item"].i_manufact_id == 77][["i_item_sk"]]
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    win = t["web_sales"].merge(dt, left_on="ws_sold_date_sk",
                               right_on="d_date_sk")
    avg_disc = win.groupby("ws_item_sk", as_index=False).agg(
        avg_disc=("ws_ext_discount_amt", "mean"))
    j = win.merge(it, left_on="ws_item_sk", right_on="i_item_sk")
    j = j.merge(avg_disc, on="ws_item_sk")
    j = j[j.ws_ext_discount_amt > 1.3 * j.avg_disc]
    return pd.DataFrame(
        {"excess_discount_amount": [j.ws_ext_discount_amt.sum()]})


# ---------------------------------------------------------------------------
# q62 / q99 — shipping-lag day buckets (web / catalog)
# ---------------------------------------------------------------------------


def _lag_buckets(lag, prefix):
    one = lit(1)
    return [
        ("sum", CaseWhen([(lag <= lit(30), one)]), prefix + "30_days"),
        ("sum", CaseWhen([((lag > lit(30)) & (lag <= lit(60)), one)]),
         prefix + "31_60_days"),
        ("sum", CaseWhen([((lag > lit(60)) & (lag <= lit(90)), one)]),
         prefix + "61_90_days"),
        ("sum", CaseWhen([((lag > lit(90)) & (lag <= lit(120)), one)]),
         prefix + "91_120_days"),
        ("sum", CaseWhen([(lag > lit(120), one)]),
         prefix + "gt120_days"),
    ]


def q62(dfs):
    ws = dfs["web_sales"].select("ws_ship_date_sk", "ws_sold_date_sk",
                                 "ws_warehouse_sk", "ws_ship_mode_sk",
                                 "ws_web_site_sk")
    d = (dfs["date_dim"].filter((col("d_month_seq") >= lit(24))
                                & (col("d_month_seq") <= lit(35)))
         .select("d_date_sk"))
    w = dfs["warehouse"].select("w_warehouse_sk", "w_warehouse_name")
    sm = dfs["ship_mode"].select("sm_ship_mode_sk", "sm_type")
    web = dfs["web_site"].select("web_site_sk", "web_name")
    j = ws.join(d, on=col("ws_ship_date_sk") == col("d_date_sk"))
    j = j.join(w, on=col("ws_warehouse_sk") == col("w_warehouse_sk"))
    j = j.join(sm, on=col("ws_ship_mode_sk") == col("sm_ship_mode_sk"))
    j = j.join(web, on=col("ws_web_site_sk") == col("web_site_sk"))
    lag = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    g = (j.group_by("w_warehouse_name", "sm_type", "web_name")
         .agg(*_lag_buckets(lag, "d")))
    return (g.sort("w_warehouse_name", "sm_type", "web_name")
            .limit(100))


def q99(dfs):
    cs = dfs["catalog_sales"].select(
        "cs_ship_date_sk", "cs_sold_date_sk", "cs_warehouse_sk",
        "cs_ship_mode_sk", "cs_call_center_sk")
    d = (dfs["date_dim"].filter((col("d_month_seq") >= lit(24))
                                & (col("d_month_seq") <= lit(35)))
         .select("d_date_sk"))
    w = dfs["warehouse"].select("w_warehouse_sk", "w_warehouse_name")
    sm = dfs["ship_mode"].select("sm_ship_mode_sk", "sm_type")
    cc = dfs["call_center"].select("cc_call_center_sk", "cc_name")
    j = cs.join(d, on=col("cs_ship_date_sk") == col("d_date_sk"))
    j = j.join(w, on=col("cs_warehouse_sk") == col("w_warehouse_sk"))
    j = j.join(sm, on=col("cs_ship_mode_sk") == col("sm_ship_mode_sk"))
    j = j.join(cc, on=col("cs_call_center_sk") == col("cc_call_center_sk"))
    lag = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    g = (j.group_by("w_warehouse_name", "sm_type", "cc_name")
         .agg(*_lag_buckets(lag, "d")))
    return (g.sort("w_warehouse_name", "sm_type", "cc_name")
            .limit(100))


def _lag_buckets_pd(j, lag, g_keys, prefix):
    j = j.copy()
    j["_lag"] = lag
    one = 1.0
    j[prefix + "30_days"] = np.where(j._lag <= 30, one, np.nan)
    j[prefix + "31_60_days"] = np.where((j._lag > 30) & (j._lag <= 60),
                                        one, np.nan)
    j[prefix + "61_90_days"] = np.where((j._lag > 60) & (j._lag <= 90),
                                        one, np.nan)
    j[prefix + "91_120_days"] = np.where((j._lag > 90) & (j._lag <= 120),
                                         one, np.nan)
    j[prefix + "gt120_days"] = np.where(j._lag > 120, one, np.nan)
    cols = [prefix + s for s in ("30_days", "31_60_days", "61_90_days",
                                 "91_120_days", "gt120_days")]
    g = j.groupby(g_keys, as_index=False)[cols].sum(min_count=1)
    return g


def q62_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 35)][["d_date_sk"]]
    j = t["web_sales"].merge(dd, left_on="ws_ship_date_sk",
                             right_on="d_date_sk")
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_warehouse_name"]],
                left_on="ws_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(t["ship_mode"][["sm_ship_mode_sk", "sm_type"]],
                left_on="ws_ship_mode_sk", right_on="sm_ship_mode_sk")
    j = j.merge(t["web_site"][["web_site_sk", "web_name"]],
                left_on="ws_web_site_sk", right_on="web_site_sk")
    g = _lag_buckets_pd(j, j.ws_ship_date_sk - j.ws_sold_date_sk,
                        ["w_warehouse_name", "sm_type", "web_name"], "d")
    return (g.sort_values(["w_warehouse_name", "sm_type", "web_name"])
            .head(100).reset_index(drop=True))


def q99_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 35)][["d_date_sk"]]
    j = t["catalog_sales"].merge(dd, left_on="cs_ship_date_sk",
                                 right_on="d_date_sk")
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_warehouse_name"]],
                left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(t["ship_mode"][["sm_ship_mode_sk", "sm_type"]],
                left_on="cs_ship_mode_sk", right_on="sm_ship_mode_sk")
    j = j.merge(t["call_center"][["cc_call_center_sk", "cc_name"]],
                left_on="cs_call_center_sk", right_on="cc_call_center_sk")
    g = _lag_buckets_pd(j, j.cs_ship_date_sk - j.cs_sold_date_sk,
                        ["w_warehouse_name", "sm_type", "cc_name"], "d")
    return (g.sort_values(["w_warehouse_name", "sm_type", "cc_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q54 — revenue segments of cross-channel buyers (scalar subqueries)
# ---------------------------------------------------------------------------


def q54(dfs):
    from hyperspace_tpu.plan.expr import Floor

    it = (dfs["item"].filter((col("i_category") == lit("Books"))
                             & (col("i_class") == lit("personal")))
          .select("i_item_sk"))
    d0 = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                 & (col("d_moy") == lit(5)))
          .select("d_date_sk"))
    cs = dfs["catalog_sales"].select(
        col("cs_bill_customer_sk").alias("cust"),
        col("cs_item_sk").alias("item"),
        col("cs_sold_date_sk").alias("sold"))
    ws = dfs["web_sales"].select(
        col("ws_bill_customer_sk").alias("cust"),
        col("ws_item_sk").alias("item"),
        col("ws_sold_date_sk").alias("sold"))
    u = cs.union(ws)
    u = u.join(it, on=col("item") == col("i_item_sk"), how="left_semi")
    u = u.join(d0, on=col("sold") == col("d_date_sk"), how="left_semi")
    my_customers = u.select("cust").distinct()

    # The official month window arrives via SCALAR SUBQUERIES:
    # d_month_seq between (select distinct d_month_seq+1 ..) and (.. +3).
    base = dfs["date_dim"].filter((col("d_year") == lit(2000))
                                  & (col("d_moy") == lit(5)))
    lo = (base.select((col("d_month_seq") + lit(1)).alias("m"))
          .distinct()).as_scalar()
    hi = (base.select((col("d_month_seq") + lit(3)).alias("m"))
          .distinct()).as_scalar()
    dr = (dfs["date_dim"].filter((col("d_month_seq") >= lo)
                                 & (col("d_month_seq") <= hi))
          .select("d_date_sk"))
    ss = dfs["store_sales"].select("ss_customer_sk", "ss_sold_date_sk",
                                   "ss_ext_sales_price")
    rev = ss.join(my_customers, on=col("ss_customer_sk") == col("cust"))
    rev = rev.join(dr, on=col("ss_sold_date_sk") == col("d_date_sk"),
                   how="left_semi")
    per_cust = (rev.group_by("cust")
                .agg(("sum", "ss_ext_sales_price", "revenue")))
    seg = per_cust.select(
        Floor(col("revenue") / lit(50.0)).alias("segment"))
    out = (seg.group_by("segment").agg(("count", "*", "num_customers"))
           .sort("segment", "num_customers").limit(100))
    return out


def q54_pandas(t):
    it = t["item"]
    itt = it[(it.i_category == "Books")
             & (it.i_class == "personal")][["i_item_sk"]]
    d = t["date_dim"]
    d0 = d[(d.d_year == 2000) & (d.d_moy == 5)]
    cs = t["catalog_sales"][["cs_bill_customer_sk", "cs_item_sk",
                             "cs_sold_date_sk"]].rename(
        columns={"cs_bill_customer_sk": "cust", "cs_item_sk": "item",
                 "cs_sold_date_sk": "sold"})
    ws = t["web_sales"][["ws_bill_customer_sk", "ws_item_sk",
                         "ws_sold_date_sk"]].rename(
        columns={"ws_bill_customer_sk": "cust", "ws_item_sk": "item",
                 "ws_sold_date_sk": "sold"})
    u = pd.concat([cs, ws], ignore_index=True)
    u = u[u["item"].isin(itt.i_item_sk) & u["sold"].isin(d0.d_date_sk)]
    my_customers = u[["cust"]].drop_duplicates()
    lo = int((d0.d_month_seq + 1).drop_duplicates().iloc[0])
    hi = int((d0.d_month_seq + 3).drop_duplicates().iloc[0])
    dr = d[(d.d_month_seq >= lo) & (d.d_month_seq <= hi)][["d_date_sk"]]
    rev = t["store_sales"].merge(my_customers, left_on="ss_customer_sk",
                                 right_on="cust")
    rev = rev[rev.ss_sold_date_sk.isin(dr.d_date_sk)]
    per_cust = rev.groupby("cust", as_index=False).agg(
        revenue=("ss_ext_sales_price", "sum"))
    per_cust["segment"] = np.floor(
        per_cust.revenue / 50.0).astype("int64")
    g = per_cust.groupby("segment", as_index=False).agg(
        num_customers=("cust", "size"))
    return (g[["segment", "num_customers"]]
            .sort_values(["segment", "num_customers"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q35 — demographics of customers active in store AND (web OR catalog)
# ---------------------------------------------------------------------------


def q35(dfs):
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_qoy") < lit(4)))
         .select("d_date_sk"))
    ss_c = (dfs["store_sales"].select("ss_customer_sk", "ss_sold_date_sk")
            .join(d, on=col("ss_sold_date_sk") == col("d_date_sk"),
                  how="left_semi").select("ss_customer_sk"))
    ws_c = (dfs["web_sales"]
            .select("ws_bill_customer_sk", "ws_sold_date_sk")
            .join(d, on=col("ws_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("ws_bill_customer_sk").alias("wsc")).distinct())
    cs_c = (dfs["catalog_sales"]
            .select("cs_bill_customer_sk", "cs_sold_date_sk")
            .join(d, on=col("cs_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("cs_bill_customer_sk").alias("csc")).distinct())
    c = dfs["customer"].select("c_customer_sk", "c_current_addr_sk",
                               "c_current_cdemo_sk")
    c = c.join(ss_c, on=col("c_customer_sk") == col("ss_customer_sk"),
               how="left_semi")
    # EXISTS ws OR EXISTS cs: outer-join markers, then an OR filter
    # (semi joins only compose conjunctively).
    c = c.join(ws_c, on=col("c_customer_sk") == col("wsc"), how="left")
    c = c.join(cs_c, on=col("c_customer_sk") == col("csc"), how="left")
    c = c.filter(col("wsc").is_not_null() | col("csc").is_not_null())
    ca = dfs["customer_address"].select("ca_address_sk", "ca_state")
    cd = dfs["customer_demographics"].select(
        "cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count",
        "cd_dep_employed_count", "cd_dep_college_count")
    j = c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
    j = j.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    g = (j.group_by("ca_state", "cd_gender", "cd_marital_status",
                    "cd_dep_count", "cd_dep_employed_count",
                    "cd_dep_college_count")
         .agg(("count", "*", "cnt1"),
              ("avg", "cd_dep_count", "avg_dep"),
              ("max", "cd_dep_employed_count", "max_emp"),
              ("sum", "cd_dep_college_count", "sum_col")))
    return (g.sort("ca_state", "cd_gender", "cd_marital_status",
                   "cd_dep_count", "cd_dep_employed_count",
                   "cd_dep_college_count").limit(100))


def q35_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy < 4)][["d_date_sk"]]
    ss_c = t["store_sales"][t["store_sales"].ss_sold_date_sk.isin(
        dd.d_date_sk)].ss_customer_sk.unique()
    ws_c = t["web_sales"][t["web_sales"].ws_sold_date_sk.isin(
        dd.d_date_sk)].ws_bill_customer_sk.unique()
    cs_c = t["catalog_sales"][t["catalog_sales"].cs_sold_date_sk.isin(
        dd.d_date_sk)].cs_bill_customer_sk.unique()
    c = t["customer"]
    c = c[c.c_customer_sk.isin(ss_c)
          & (c.c_customer_sk.isin(ws_c) | c.c_customer_sk.isin(cs_c))]
    j = c.merge(t["customer_address"][["ca_address_sk", "ca_state"]],
                left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(t["customer_demographics"][
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_dep_count",
         "cd_dep_employed_count", "cd_dep_college_count"]],
        left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    g = j.groupby(["ca_state", "cd_gender", "cd_marital_status",
                   "cd_dep_count", "cd_dep_employed_count",
                   "cd_dep_college_count"], as_index=False).agg(
        cnt1=("cd_demo_sk", "size"), avg_dep=("cd_dep_count", "mean"),
        max_emp=("cd_dep_employed_count", "max"),
        sum_col=("cd_dep_college_count", "sum"))
    return (g.sort_values(["ca_state", "cd_gender", "cd_marital_status",
                           "cd_dep_count", "cd_dep_employed_count",
                           "cd_dep_college_count"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q44 — best/worst items by average store profit (rank windows)
# ---------------------------------------------------------------------------


def q44(dfs):
    ss = (dfs["store_sales"].filter(col("ss_store_sk") == lit(4))
          .select("ss_item_sk", "ss_net_profit"))
    avg_p = (ss.group_by("ss_item_sk")
             .agg(("avg", "ss_net_profit", "rank_col"))
             .with_column("one", lit(1)))
    asc = (avg_p.window(["one"], order_by=["rank_col"],
                        rnk=("rank", "*"))
           .filter(col("rnk") <= lit(10))
           .select("rnk", col("ss_item_sk").alias("asc_item")))
    desc = (avg_p.window(["one"], order_by=["-rank_col"],
                         rnk=("rank", "*"))
            .filter(col("rnk") <= lit(10))
            .select(col("rnk").alias("rnk_d"),
                    col("ss_item_sk").alias("desc_item")))
    i1 = dfs["item"].select("i_item_sk",
                            col("i_product_name").alias(
                                "best_performing"))
    i2 = dfs["item"].select(col("i_item_sk").alias("i2_sk"),
                            col("i_product_name").alias(
                                "worst_performing"))
    j = asc.join(desc, on=col("rnk") == col("rnk_d"))
    j = j.join(i1, on=col("asc_item") == col("i_item_sk"))
    j = j.join(i2, on=col("desc_item") == col("i2_sk"))
    return (j.select("rnk", "best_performing", "worst_performing")
            .sort("rnk").limit(100))


def q44_pandas(t):
    ss = t["store_sales"]
    ss = ss[ss.ss_store_sk == 4][["ss_item_sk", "ss_net_profit"]]
    avg_p = ss.groupby("ss_item_sk", as_index=False).agg(
        rank_col=("ss_net_profit", "mean"))
    avg_p["rnk"] = avg_p.rank_col.rank(method="min").astype("int64")
    avg_p["rnk_d"] = avg_p.rank_col.rank(
        method="min", ascending=False).astype("int64")
    asc = avg_p[avg_p.rnk <= 10][["rnk", "ss_item_sk"]].rename(
        columns={"ss_item_sk": "asc_item"})
    desc = avg_p[avg_p.rnk_d <= 10][["rnk_d", "ss_item_sk"]].rename(
        columns={"ss_item_sk": "desc_item"})
    j = asc.merge(desc, left_on="rnk", right_on="rnk_d")
    it = t["item"]
    j = j.merge(it[["i_item_sk", "i_product_name"]].rename(
        columns={"i_product_name": "best_performing"}),
        left_on="asc_item", right_on="i_item_sk")
    j = j.merge(it[["i_item_sk", "i_product_name"]].rename(
        columns={"i_item_sk": "i2_sk",
                 "i_product_name": "worst_performing"}),
        left_on="desc_item", right_on="i2_sk")
    return (j[["rnk", "best_performing", "worst_performing"]]
            .sort_values("rnk").head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q45 — web sales by zip/city: listed zips OR listed items
# ---------------------------------------------------------------------------


_Q45_ZIPS = ["10000", "10037", "10074", "10111", "10148"]


def q45(dfs):
    ws = dfs["web_sales"].select("ws_item_sk", "ws_bill_customer_sk",
                                 "ws_sold_date_sk", "ws_sales_price")
    c = dfs["customer"].select("c_customer_sk", "c_current_addr_sk")
    ca = dfs["customer_address"].select("ca_address_sk", "ca_city",
                                        "ca_zip")
    it = dfs["item"].select("i_item_sk", "i_item_id")
    sub = (dfs["item"].filter(col("i_item_sk").isin(2, 3, 5, 7, 11, 13,
                                                    17, 19, 23, 29))
           .select(col("i_item_id").alias("sub_item_id")).distinct())
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_qoy") == lit(2)))
         .select("d_date_sk"))
    j = ws.join(c, on=col("ws_bill_customer_sk") == col("c_customer_sk"))
    j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
    j = j.join(d, on=col("ws_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ws_item_sk") == col("i_item_sk"))
    j = j.join(sub, on=col("i_item_id") == col("sub_item_id"),
               how="left")
    zips = col("ca_zip").substr(1, 5).isin(*_Q45_ZIPS)
    j = j.filter(zips | col("sub_item_id").is_not_null())
    return (j.group_by("ca_zip", "ca_city")
            .agg(("sum", "ws_sales_price", "total"))
            .sort("ca_zip", "ca_city").limit(100))


def q45_pandas(t):
    it = t["item"]
    sub = it[it.i_item_sk.isin([2, 3, 5, 7, 11, 13, 17, 19, 23,
                                29])].i_item_id.unique()
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy == 2)][["d_date_sk"]]
    j = t["web_sales"].merge(
        t["customer"][["c_customer_sk", "c_current_addr_sk"]],
        left_on="ws_bill_customer_sk", right_on="c_customer_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_city",
                                       "ca_zip"]],
                left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it[["i_item_sk", "i_item_id"]],
                left_on="ws_item_sk", right_on="i_item_sk")
    j = j[j.ca_zip.str[:5].isin(_Q45_ZIPS) | j.i_item_id.isin(sub)]
    g = j.groupby(["ca_zip", "ca_city"], as_index=False).agg(
        total=("ws_sales_price", "sum"))
    return (g.sort_values(["ca_zip", "ca_city"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q56 / q60 — 3-channel item revenue for a color set / category
# ---------------------------------------------------------------------------


def _3chan_by_item(dfs, item_filter_df):
    def chan(fact, item_col, date_col, addr_col, price_col):
        it = dfs["item"].select("i_item_sk", "i_item_id")
        it = it.join(item_filter_df,
                     on=col("i_item_id") == col("flt_item_id"),
                     how="left_semi")
        d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                    & (col("d_moy") == lit(2)))
             .select("d_date_sk"))
        ca = (dfs["customer_address"]
              .filter(col("ca_gmt_offset") == lit(-5.0))
              .select("ca_address_sk"))
        f = dfs[fact].select(item_col, date_col, addr_col, price_col)
        j = f.join(d, on=col(date_col) == col("d_date_sk"))
        j = j.join(ca, on=col(addr_col) == col("ca_address_sk"))
        j = j.join(it, on=col(item_col) == col("i_item_sk"))
        return (j.group_by("i_item_id")
                .agg(("sum", price_col, "total_sales"))
                .select("i_item_id", "total_sales"))

    ss = chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
              "ss_addr_sk", "ss_ext_sales_price")
    cs = chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
              "cs_bill_addr_sk", "cs_ext_sales_price")
    ws = chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
              "ws_bill_addr_sk", "ws_ext_sales_price")
    u = ss.union(cs).union(ws)
    return (u.group_by("i_item_id")
            .agg(("sum", "total_sales", "total_sales"))
            .sort("total_sales", "i_item_id").limit(100))


def q56(dfs):
    flt = (dfs["item"].filter(col("i_color").isin("plum", "puff",
                                                  "misty"))
           .select(col("i_item_id").alias("flt_item_id")).distinct())
    return _3chan_by_item(dfs, flt)


def q60(dfs):
    flt = (dfs["item"].filter(col("i_category") == lit("Music"))
           .select(col("i_item_id").alias("flt_item_id")).distinct())
    return _3chan_by_item(dfs, flt)


def _3chan_by_item_pd(t, item_ids):
    def chan(fact, item_col, date_col, addr_col, price_col):
        it = t["item"]
        itt = it[it.i_item_id.isin(item_ids)][["i_item_sk", "i_item_id"]]
        d = t["date_dim"]
        dd = d[(d.d_year == 2000) & (d.d_moy == 2)][["d_date_sk"]]
        ca = t["customer_address"]
        caa = ca[ca.ca_gmt_offset == -5.0][["ca_address_sk"]]
        j = t[fact][[item_col, date_col, addr_col, price_col]].merge(
            dd, left_on=date_col, right_on="d_date_sk")
        j = j.merge(caa, left_on=addr_col, right_on="ca_address_sk")
        j = j.merge(itt, left_on=item_col, right_on="i_item_sk")
        g = j.groupby("i_item_id", as_index=False)[price_col].sum()
        return g.rename(columns={price_col: "total_sales"})

    u = pd.concat([
        chan("store_sales", "ss_item_sk", "ss_sold_date_sk", "ss_addr_sk",
             "ss_ext_sales_price"),
        chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_bill_addr_sk", "cs_ext_sales_price"),
        chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_bill_addr_sk", "ws_ext_sales_price")],
        ignore_index=True)
    g = u.groupby("i_item_id", as_index=False).total_sales.sum()
    return (g.sort_values(["total_sales", "i_item_id"])
            .head(100).reset_index(drop=True))


def q56_pandas(t):
    it = t["item"]
    ids = it[it.i_color.isin(["plum", "puff", "misty"])].i_item_id.unique()
    return _3chan_by_item_pd(t, ids)


def q60_pandas(t):
    it = t["item"]
    ids = it[it.i_category == "Music"].i_item_id.unique()
    return _3chan_by_item_pd(t, ids)


# ---------------------------------------------------------------------------
# q69 — store-only customers' demographics (anti web/catalog)
# ---------------------------------------------------------------------------


def q69(dfs):
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_moy") >= lit(1))
                                & (col("d_moy") <= lit(3)))
         .select("d_date_sk"))
    ss_c = (dfs["store_sales"].select("ss_customer_sk", "ss_sold_date_sk")
            .join(d, on=col("ss_sold_date_sk") == col("d_date_sk"),
                  how="left_semi").select("ss_customer_sk"))
    ws_c = (dfs["web_sales"]
            .select("ws_bill_customer_sk", "ws_sold_date_sk")
            .join(d, on=col("ws_sold_date_sk") == col("d_date_sk"),
                  how="left_semi").select("ws_bill_customer_sk"))
    cs_c = (dfs["catalog_sales"]
            .select("cs_bill_customer_sk", "cs_sold_date_sk")
            .join(d, on=col("cs_sold_date_sk") == col("d_date_sk"),
                  how="left_semi").select("cs_bill_customer_sk"))
    ca = (dfs["customer_address"].filter(col("ca_state").isin(
        "TX", "OH", "KY")).select("ca_address_sk"))
    c = dfs["customer"].select("c_customer_sk", "c_current_addr_sk",
                               "c_current_cdemo_sk")
    c = c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    c = c.join(ss_c, on=col("c_customer_sk") == col("ss_customer_sk"),
               how="left_semi")
    c = c.join(ws_c, on=col("c_customer_sk") == col("ws_bill_customer_sk"),
               how="left_anti")
    c = c.join(cs_c, on=col("c_customer_sk") == col("cs_bill_customer_sk"),
               how="left_anti")
    cd = dfs["customer_demographics"].select(
        "cd_demo_sk", "cd_gender", "cd_marital_status",
        "cd_education_status", "cd_purchase_estimate", "cd_credit_rating")
    j = c.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    g = (j.group_by("cd_gender", "cd_marital_status",
                    "cd_education_status", "cd_purchase_estimate",
                    "cd_credit_rating")
         .agg(("count", "*", "cnt1")))
    return (g.sort("cd_gender", "cd_marital_status",
                   "cd_education_status", "cd_purchase_estimate",
                   "cd_credit_rating").limit(100))


def q69_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy >= 1) & (d.d_moy <= 3)][
        ["d_date_sk"]]
    ss_c = t["store_sales"][t["store_sales"].ss_sold_date_sk.isin(
        dd.d_date_sk)].ss_customer_sk.unique()
    ws_c = t["web_sales"][t["web_sales"].ws_sold_date_sk.isin(
        dd.d_date_sk)].ws_bill_customer_sk.unique()
    cs_c = t["catalog_sales"][t["catalog_sales"].cs_sold_date_sk.isin(
        dd.d_date_sk)].cs_bill_customer_sk.unique()
    ca = t["customer_address"]
    caa = ca[ca.ca_state.isin(["TX", "OH", "KY"])].ca_address_sk
    c = t["customer"]
    c = c[c.c_current_addr_sk.isin(caa) & c.c_customer_sk.isin(ss_c)
          & ~c.c_customer_sk.isin(ws_c) & ~c.c_customer_sk.isin(cs_c)]
    j = c.merge(t["customer_demographics"],
                left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    g = j.groupby(["cd_gender", "cd_marital_status",
                   "cd_education_status", "cd_purchase_estimate",
                   "cd_credit_rating"], as_index=False).agg(
        cnt1=("cd_demo_sk", "size"))
    return (g.sort_values(["cd_gender", "cd_marital_status",
                           "cd_education_status", "cd_purchase_estimate",
                           "cd_credit_rating"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q71 — brand revenue by hour across 3 channels (time_dim join)
# ---------------------------------------------------------------------------


def q71(dfs):
    it = (dfs["item"].filter(col("i_manager_id") == lit(1))
          .select("i_item_sk", "i_brand_id", "i_brand"))
    d = (dfs["date_dim"].filter((col("d_year") == lit(2000))
                                & (col("d_moy") == lit(12)))
         .select("d_date_sk"))

    def chan(fact, price_col, item_col, date_col, time_col):
        f = dfs[fact].select(item_col, date_col, time_col, price_col)
        j = f.join(d, on=col(date_col) == col("d_date_sk"))
        return j.select(col(price_col).alias("ext_price"),
                        col(item_col).alias("sold_item_sk"),
                        col(time_col).alias("time_sk"))

    u = chan("web_sales", "ws_ext_sales_price", "ws_item_sk",
             "ws_sold_date_sk", "ws_sold_time_sk")
    u = u.union(chan("catalog_sales", "cs_ext_sales_price", "cs_item_sk",
                     "cs_sold_date_sk", "cs_sold_time_sk"))
    u = u.union(chan("store_sales", "ss_ext_sales_price", "ss_item_sk",
                     "ss_sold_date_sk", "ss_sold_time_sk"))
    tm = (dfs["time_dim"].filter(col("t_hour").isin(8, 9, 19, 20))
          .select("t_time_sk", "t_hour", "t_minute"))
    j = u.join(it, on=col("sold_item_sk") == col("i_item_sk"))
    j = j.join(tm, on=col("time_sk") == col("t_time_sk"))
    g = (j.group_by("i_brand_id", "i_brand", "t_hour", "t_minute")
         .agg(("sum", "ext_price", "ext_price")))
    return (g.sort("-ext_price", "i_brand_id", "t_hour", "t_minute")
            .limit(100))


def q71_pandas(t):
    it = t["item"]
    itt = it[it.i_manager_id == 1][["i_item_sk", "i_brand_id", "i_brand"]]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == 12)][["d_date_sk"]]

    def chan(fact, price_col, item_col, date_col, time_col):
        j = t[fact][[item_col, date_col, time_col, price_col]].merge(
            dd, left_on=date_col, right_on="d_date_sk")
        return pd.DataFrame({"ext_price": j[price_col],
                             "sold_item_sk": j[item_col],
                             "time_sk": j[time_col]})

    u = pd.concat([
        chan("web_sales", "ws_ext_sales_price", "ws_item_sk",
             "ws_sold_date_sk", "ws_sold_time_sk"),
        chan("catalog_sales", "cs_ext_sales_price", "cs_item_sk",
             "cs_sold_date_sk", "cs_sold_time_sk"),
        chan("store_sales", "ss_ext_sales_price", "ss_item_sk",
             "ss_sold_date_sk", "ss_sold_time_sk")], ignore_index=True)
    tm = t["time_dim"]
    tmm = tm[tm.t_hour.isin([8, 9, 19, 20])][["t_time_sk", "t_hour",
                                              "t_minute"]]
    j = u.merge(itt, left_on="sold_item_sk", right_on="i_item_sk")
    j = j.merge(tmm, left_on="time_sk", right_on="t_time_sk")
    g = j.groupby(["i_brand_id", "i_brand", "t_hour", "t_minute"],
                  as_index=False).agg(ext_price=("ext_price", "sum"))
    return (g.sort_values(["ext_price", "i_brand_id", "t_hour",
                           "t_minute"],
                          ascending=[False, True, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q90 — web am/pm order ratio
# ---------------------------------------------------------------------------


def q90(dfs):
    ws = dfs["web_sales"].select("ws_sold_time_sk", "ws_ship_hdemo_sk",
                                 "ws_web_page_sk")
    hd = (dfs["household_demographics"]
          .filter(col("hd_dep_count") == lit(2)).select("hd_demo_sk"))
    wp = (dfs["web_page"].filter((col("wp_char_count") >= lit(4000))
                                 & (col("wp_char_count") <= lit(5200)))
          .select("wp_web_page_sk"))
    tm = dfs["time_dim"].select("t_time_sk", "t_hour")
    j = ws.join(hd, on=col("ws_ship_hdemo_sk") == col("hd_demo_sk"),
                how="left_semi")
    j = j.join(wp, on=col("ws_web_page_sk") == col("wp_web_page_sk"),
               how="left_semi")
    j = j.join(tm, on=col("ws_sold_time_sk") == col("t_time_sk"))
    g = j.agg(
        ("sum", CaseWhen([(col("t_hour").isin(8, 9), lit(1))]), "amc"),
        ("sum", CaseWhen([(col("t_hour").isin(19, 20), lit(1))]), "pmc"))
    return g.select((col("amc") / col("pmc")).alias("am_pm_ratio"))


def q90_pandas(t):
    hd = t["household_demographics"]
    hdd = hd[hd.hd_dep_count == 2].hd_demo_sk
    wp = t["web_page"]
    wpp = wp[(wp.wp_char_count >= 4000)
             & (wp.wp_char_count <= 5200)].wp_web_page_sk
    j = t["web_sales"]
    j = j[j.ws_ship_hdemo_sk.isin(hdd) & j.ws_web_page_sk.isin(wpp)]
    j = j.merge(t["time_dim"][["t_time_sk", "t_hour"]],
                left_on="ws_sold_time_sk", right_on="t_time_sk")
    amc = float((j.t_hour.isin([8, 9])).sum())
    pmc = float((j.t_hour.isin([19, 20])).sum())
    return pd.DataFrame({"am_pm_ratio": [amc / pmc]})


# ---------------------------------------------------------------------------
# q94 — multi-warehouse web orders never returned
# ---------------------------------------------------------------------------


def q94(dfs):
    ws = dfs["web_sales"].select(
        "ws_order_number", "ws_ship_date_sk", "ws_ship_addr_sk",
        "ws_web_site_sk", "ws_warehouse_sk", "ws_ext_ship_cost",
        "ws_net_profit")
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(730))
                                & (col("d_date_sk") <= lit(790)))
         .select("d_date_sk"))
    ca = (dfs["customer_address"].filter(col("ca_state") == lit("TX"))
          .select("ca_address_sk"))
    web = (dfs["web_site"].filter(col("web_company_name") == lit("pri"))
           .select("web_site_sk"))
    multi_wh = (dfs["web_sales"]
                .select("ws_order_number", "ws_warehouse_sk")
                .group_by("ws_order_number")
                .agg(("count_distinct", "ws_warehouse_sk", "nwh"))
                .filter(col("nwh") > lit(1))
                .select(col("ws_order_number").alias("mw_order")))
    wr = dfs["web_returns"].select(
        col("wr_order_number").alias("ret_order"))
    j = ws.join(d, on=col("ws_ship_date_sk") == col("d_date_sk"),
                how="left_semi")
    j = j.join(ca, on=col("ws_ship_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(web, on=col("ws_web_site_sk") == col("web_site_sk"),
               how="left_semi")
    j = j.join(multi_wh, on=col("ws_order_number") == col("mw_order"),
               how="left_semi")
    j = j.join(wr, on=col("ws_order_number") == col("ret_order"),
               how="left_anti")
    return j.agg(("count_distinct", "ws_order_number", "order_count"),
                 ("sum", "ws_ext_ship_cost", "total_shipping_cost"),
                 ("sum", "ws_net_profit", "total_net_profit"))


def q94_pandas(t):
    ws = t["web_sales"]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 730) & (d.d_date_sk <= 790)].d_date_sk
    ca = t["customer_address"]
    caa = ca[ca.ca_state == "TX"].ca_address_sk
    web = t["web_site"]
    webb = web[web.web_company_name == "pri"].web_site_sk
    nwh = ws.groupby("ws_order_number").ws_warehouse_sk.nunique()
    multi = nwh[nwh > 1].index
    j = ws[ws.ws_ship_date_sk.isin(dd) & ws.ws_ship_addr_sk.isin(caa)
           & ws.ws_web_site_sk.isin(webb)
           & ws.ws_order_number.isin(multi)
           & ~ws.ws_order_number.isin(t["web_returns"].wr_order_number)]
    return pd.DataFrame({
        "order_count": [j.ws_order_number.nunique()],
        # min_count=1: SQL SUM over zero rows is NULL, not 0.
        "total_shipping_cost": [j.ws_ext_ship_cost.sum(min_count=1)],
        "total_net_profit": [j.ws_net_profit.sum(min_count=1)]})


QUERIES_EXT2 = {
    "q2": (q2, q2_pandas),
    "q11": (q11, q11_pandas),
    "q12": (q12, q12_pandas),
    "q18": (q18, q18_pandas),
    "q30": (q30, q30_pandas),
    "q31": (q31, q31_pandas),
    "q33": (q33, q33_pandas),
    "q59": (q59, q59_pandas),
    "q74": (q74, q74_pandas),
    "q84": (q84, q84_pandas),
    "q86": (q86, q86_pandas),
    "q21": (q21, q21_pandas),
    "q22": (q22, q22_pandas),
    "q37": (q37, q37_pandas),
    "q38": (q38, q38_pandas),
    "q39": (q39, q39_pandas),
    "q54": (q54, q54_pandas),
    "q62": (q62, q62_pandas),
    "q82": (q82, q82_pandas),
    "q87": (q87, q87_pandas),
    "q92": (q92, q92_pandas),
    "q99": (q99, q99_pandas),
    "q35": (q35, q35_pandas),
    "q44": (q44, q44_pandas),
    "q45": (q45, q45_pandas),
    "q56": (q56, q56_pandas),
    "q60": (q60, q60_pandas),
    "q69": (q69, q69_pandas),
    "q71": (q71, q71_pandas),
    "q90": (q90, q90_pandas),
    "q94": (q94, q94_pandas),
}
