"""Round-5 TPC-DS completion: the final 26 queries (q4 q5 q8 q9 q10 q14
q16 q23 q24 q40 q47 q49 q51 q57 q58 q66 q72 q75 q76 q77 q78 q80 q83 q85
q91 q95) — with these the engine runs ALL 99 TPC-DS queries end to end
three ways (rules on / rules off / pandas oracle), completing the
reference serde's all-TPC-DS property (`index/serde/package.scala:46-49`)
at the ENGINE level.

Shapes follow the official queries over this generator's reduced schema
(`generator.py`); where an official column is absent the closest
generated measure substitutes CONSISTENTLY in engine and oracle (e.g.
ss_coupon_amt stands in for ss_ext_discount_amt in q4's profit formula).
Idioms newly covered here: 3-channel year-over-year growth chains with
>2-way self-joins (q4/q74), channel rollup reports (q5/q77/q80),
zip-prefix INTERSECT (q8), projection-level scalar subqueries (q9),
OR-of-EXISTS via channel union (q10), cross-channel frequent-item and
best-customer filters (q14/q23), paired-purchase self joins (q24/q64),
monthly-deviation series with neighbor self-joins standing in for
LAG/LEAD (q47/q57), windowed cumulative medians (q51), rank-of-ratio
windows (q49), shipping pivot reports (q66), inventory week-over-week
(q72), channel-vs-returns anti semantics (q78/q87), and multi-warehouse
shipment probes (q95/q94)."""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from hyperspace_tpu.plan.expr import CaseWhen, col, lit
from hyperspace_tpu.tpcds.queries_ext import _rollup_union


def _sum_case(cond, value, alias):
    return ("sum", CaseWhen([(cond, value)]), alias)


# ---------------------------------------------------------------------------
# q4 — 3-channel year-over-year growth (the q11 family's full form)
# ---------------------------------------------------------------------------


def _q4_channel(dfs, table, date_col, cust_col, formula_cols, tag):
    prefix = {"store_sales": "ss", "catalog_sales": "cs",
              "web_sales": "ws"}[table]
    a, b, c2, d2 = formula_cols
    s = dfs[table].select(
        col(cust_col).alias("cust_sk"), col(date_col).alias("sold_date"),
        ((col(a) - col(b) + col(c2) - col(d2)) / lit(2.0)).alias("profit"))
    dd = dfs["date_dim"].select("d_date_sk", "d_year")
    j = s.join(dd, on=col("sold_date") == col("d_date_sk"))
    cust = dfs["customer"].select(
        col("c_customer_sk").alias("cc_sk"), "c_customer_id",
        "c_first_name", "c_last_name")
    j = j.join(cust, on=col("cust_sk") == col("cc_sk"))
    return (j.group_by("c_customer_id", "c_first_name", "c_last_name",
                       "d_year")
            .agg(("sum", "profit", f"year_total_{tag}")))


def q4(dfs):
    st = _q4_channel(dfs, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk",
                     ("ss_ext_list_price", "ss_ext_wholesale_cost",
                      "ss_ext_sales_price", "ss_coupon_amt"), "s")
    ct = _q4_channel(dfs, "catalog_sales", "cs_sold_date_sk",
                     "cs_bill_customer_sk",
                     ("cs_ext_list_price", "cs_ext_discount_amt",
                      "cs_ext_sales_price", "cs_coupon_amt"), "c")
    wt = _q4_channel(dfs, "web_sales", "ws_sold_date_sk",
                     "ws_bill_customer_sk",
                     ("ws_ext_list_price", "ws_ext_discount_amt",
                      "ws_ext_sales_price", "ws_ext_wholesale_cost"), "w")

    def year(df2, yr, tag, keep_names=False):
        cols = [col("c_customer_id").alias(f"id_{tag}"),
                col(f"year_total_{df2._tag}").alias(f"total_{tag}")]
        if keep_names:
            cols += ["c_first_name", "c_last_name"]
        return df2.filter(col("d_year") == lit(yr)).select(*cols)

    # tag the channel frames so `year` can pick the right total column
    st._tag, ct._tag, wt._tag = "s", "c", "w"
    s1 = year(st, 1999, "s1", keep_names=True)
    s2 = year(st, 2000, "s2")
    c1 = year(ct, 1999, "c1")
    c2_ = year(ct, 2000, "c2")
    w1 = year(wt, 1999, "w1")
    w2 = year(wt, 2000, "w2")
    j = s1.join(s2, on=col("id_s1") == col("id_s2"))
    j = j.join(c1, on=col("id_s1") == col("id_c1"))
    j = j.join(c2_, on=col("id_s1") == col("id_c2"))
    j = j.join(w1, on=col("id_s1") == col("id_w1"))
    j = j.join(w2, on=col("id_s1") == col("id_w2"))
    j = j.filter((col("total_s1") > lit(0)) & (col("total_c1") > lit(0))
                 & (col("total_w1") > lit(0)))
    j = j.filter((col("total_c2") / col("total_c1"))
                 > (col("total_s2") / col("total_s1")))
    j = j.filter((col("total_c2") / col("total_c1"))
                 > (col("total_w2") / col("total_w1")))
    return (j.select(col("id_s1").alias("customer_id"), "c_first_name",
                     "c_last_name")
            .sort("customer_id", "c_first_name", "c_last_name").limit(100))


def _q4_pd_channel(t, table, date_col, cust_col, formula_cols):
    a, b, c2, d2 = formula_cols
    s = t[table].copy()
    s["profit"] = (s[a] - s[b] + s[c2] - s[d2]) / 2.0
    d = t["date_dim"][["d_date_sk", "d_year"]]
    j = s.merge(d, left_on=date_col, right_on="d_date_sk")
    cust = t["customer"][["c_customer_sk", "c_customer_id", "c_first_name",
                          "c_last_name"]]
    j = j.merge(cust, left_on=cust_col, right_on="c_customer_sk")
    return j.groupby(["c_customer_id", "c_first_name", "c_last_name",
                      "d_year"], as_index=False).agg(
        year_total=("profit", "sum"))


def q4_pandas(t):
    st = _q4_pd_channel(t, "store_sales", "ss_sold_date_sk",
                        "ss_customer_sk",
                        ("ss_ext_list_price", "ss_ext_wholesale_cost",
                         "ss_ext_sales_price", "ss_coupon_amt"))
    ct = _q4_pd_channel(t, "catalog_sales", "cs_sold_date_sk",
                        "cs_bill_customer_sk",
                        ("cs_ext_list_price", "cs_ext_discount_amt",
                         "cs_ext_sales_price", "cs_coupon_amt"))
    wt = _q4_pd_channel(t, "web_sales", "ws_sold_date_sk",
                        "ws_bill_customer_sk",
                        ("ws_ext_list_price", "ws_ext_discount_amt",
                         "ws_ext_sales_price", "ws_ext_wholesale_cost"))

    def yr(df, y):
        return df[df.d_year == y].set_index("c_customer_id").year_total

    s1, s2 = yr(st, 1999), yr(st, 2000)
    c1, c2_ = yr(ct, 1999), yr(ct, 2000)
    w1, w2 = yr(wt, 1999), yr(wt, 2000)
    ids = s1[s1 > 0].index
    ids = ids.intersection(c1[c1 > 0].index).intersection(w1[w1 > 0].index)
    ids = ids.intersection(s2.index).intersection(c2_.index) \
             .intersection(w2.index)
    keep = [i for i in ids
            if (c2_[i] / c1[i] > s2[i] / s1[i])
            and (c2_[i] / c1[i] > w2[i] / w1[i])]
    names = (t["customer"].drop_duplicates("c_customer_id")
             .set_index("c_customer_id"))
    out = pd.DataFrame({
        "customer_id": keep,
        "c_first_name": [names.c_first_name[i] for i in keep],
        "c_last_name": [names.c_last_name[i] for i in keep]})
    return (out.sort_values(["customer_id", "c_first_name", "c_last_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q5 — channel sales/returns/profit ROLLUP report
# ---------------------------------------------------------------------------

_Q5_LO, _Q5_HI = 731, 744  # 14-day report window


def q5(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q5_LO))
                  & (col("d_date_sk") <= lit(_Q5_HI)))
          .select("d_date_sk"))

    def channel(sales, s_date, s_id, s_sales, s_profit,
                rets, r_date, r_id, r_ret, r_loss, dim, dim_sk, dim_id,
                label):
        s = dfs[sales].select(
            col(s_date).alias("date_sk"), col(s_id).alias("id_sk"),
            col(s_sales).alias("sales_price"),
            col(s_profit).alias("profit"),
            (col(s_sales) * lit(0.0)).alias("return_amt"),
            (col(s_sales) * lit(0.0)).alias("net_loss"))
        r = dfs[rets].select(
            col(r_date).alias("date_sk"), col(r_id).alias("id_sk"),
            (col(r_ret) * lit(0.0)).alias("sales_price"),
            (col(r_ret) * lit(0.0)).alias("profit"),
            col(r_ret).alias("return_amt"), col(r_loss).alias("net_loss"))
        u = s.union(r)
        u = u.join(dd, on=col("date_sk") == col("d_date_sk"))
        dmf = dfs[dim].select(col(dim_sk).alias("dim_sk"),
                              col(dim_id).alias("id"))
        u = u.join(dmf, on=col("id_sk") == col("dim_sk"))
        return (u.group_by("id")
                .agg(("sum", "sales_price", "sales"),
                     ("sum", "return_amt", "returns_"),
                     ("sum", col("profit") - col("net_loss"), "profit"))
                .with_column("channel", lit(label)))

    st = channel("store_sales", "ss_sold_date_sk", "ss_store_sk",
                 "ss_ext_sales_price", "ss_net_profit",
                 "store_returns", "sr_returned_date_sk", "sr_store_sk",
                 "sr_return_amt", "sr_net_loss",
                 "store", "s_store_sk", "s_store_id", "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit",
                 "catalog_returns", "cr_returned_date_sk",
                 "cr_catalog_page_sk", "cr_return_amount", "cr_net_loss",
                 "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                 "ws_ext_sales_price", "ws_net_profit",
                 "web_returns", "wr_returned_date_sk", "wr_web_page_sk",
                 "wr_return_amt", "wr_net_loss",
                 "web_site", "web_site_sk", "web_site_id", "web channel")
    # web returns key on web_page in the official query; this generator's
    # wr carries wr_web_page_sk (reduced schema) — the web channel's
    # returns roll up under the page's site via the same id join shape.
    u = st.union(ct).union(wt)
    roll = _rollup_union(u, [("channel", "string"), ("id", "string")],
                         {"sales": ("sum", "sales"),
                          "returns_": ("sum", "returns_"),
                          "profit": ("sum", "profit")}, u.session)
    return (roll.select("channel", "id", "sales", "returns_", "profit")
            .sort("channel", "id").limit(100))


def q5_pandas(t):
    lo, hi = _Q5_LO, _Q5_HI

    def channel(sales, s_date, s_id, s_sales, s_profit,
                rets, r_date, r_id, r_ret, r_loss, dim, dim_sk, dim_id,
                label):
        s = t[sales]
        s = s[(s[s_date] >= lo) & (s[s_date] <= hi)]
        r = t[rets]
        r = r[(r[r_date] >= lo) & (r[r_date] <= hi)]
        dimt = t[dim][[dim_sk, dim_id]]
        sj = s.merge(dimt, left_on=s_id, right_on=dim_sk)
        rj = r.merge(dimt, left_on=r_id, right_on=dim_sk)
        sa = sj.groupby(dim_id).agg(sales=(s_sales, "sum"),
                                    profit=(s_profit, "sum"))
        ra = rj.groupby(dim_id).agg(returns_=(r_ret, "sum"),
                                    net_loss=(r_loss, "sum"))
        m = sa.join(ra, how="outer").fillna(0.0)
        m["profit"] = m["profit"] - m["net_loss"]
        m = m.drop(columns=["net_loss"]).reset_index(names="id")
        m["channel"] = label
        return m

    st = channel("store_sales", "ss_sold_date_sk", "ss_store_sk",
                 "ss_ext_sales_price", "ss_net_profit",
                 "store_returns", "sr_returned_date_sk", "sr_store_sk",
                 "sr_return_amt", "sr_net_loss",
                 "store", "s_store_sk", "s_store_id", "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit",
                 "catalog_returns", "cr_returned_date_sk",
                 "cr_catalog_page_sk", "cr_return_amount", "cr_net_loss",
                 "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                 "ws_ext_sales_price", "ws_net_profit",
                 "web_returns", "wr_returned_date_sk", "wr_web_page_sk",
                 "wr_return_amt", "wr_net_loss",
                 "web_site", "web_site_sk", "web_site_id", "web channel")
    u = pd.concat([st, ct, wt], ignore_index=True)
    leaf = u.groupby(["channel", "id"], as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid = u.groupby("channel", as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid["id"] = np.nan
    top = pd.DataFrame({"channel": [np.nan], "id": [np.nan],
                        "sales": [u.sales.sum()],
                        "returns_": [u.returns_.sum()],
                        "profit": [u.profit.sum()]})
    out = pd.concat([leaf, mid, top], ignore_index=True)
    # ORDER BY ASC places NULL subtotal rows FIRST (Spark semantics, which
    # the engine's SortExec follows).
    return (out[["channel", "id", "sales", "returns_", "profit"]]
            .sort_values(["channel", "id"], na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q8 — store sales where store zip-3 matches (list INTERSECT preferred
# customers' zips)
# ---------------------------------------------------------------------------

_Q8_ZIPS = ["356", "354", "350", "358", "352"]


def q8(dfs):
    zip_list = (dfs["customer_address"]
                .select(col("ca_zip").substr(1, 3).alias("zip3"))
                .filter(col("zip3").isin(*[lit(z) for z in _Q8_ZIPS]))
                .distinct())
    pref = (dfs["customer"].filter(col("c_preferred_cust_flag") == lit("Y"))
            .select("c_current_addr_sk"))
    pref_zips = (pref.join(dfs["customer_address"].select(
        "ca_address_sk", "ca_zip"),
        on=col("c_current_addr_sk") == col("ca_address_sk"))
        .select(col("ca_zip").substr(1, 3).alias("zip3"))
        .distinct())
    zips = zip_list.intersect(pref_zips)
    zips = zips.select(col("zip3").alias("match_zip3"))
    ss = dfs["store_sales"].select("ss_store_sk", "ss_sold_date_sk",
                                   "ss_net_profit")
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_qoy") == lit(1)))
          .select("d_date_sk"))
    j = ss.join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
    st = dfs["store"].select("s_store_sk", "s_store_name",
                             col("s_zip").substr(1, 3).alias("s_zip3"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(zips, on=col("s_zip3") == col("match_zip3"),
               how="left_semi")
    return (j.group_by("s_store_name")
            .agg(("sum", "ss_net_profit", "net_profit"))
            .sort("s_store_name").limit(100))


def q8_pandas(t):
    ca = t["customer_address"]
    zip3 = ca.ca_zip.str[:3]
    in_list = set(zip3[zip3.isin(_Q8_ZIPS)])
    cust = t["customer"]
    pref = cust[cust.c_preferred_cust_flag == "Y"]
    pj = pref.merge(ca[["ca_address_sk", "ca_zip"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
    pref_zips = set(pj.ca_zip.str[:3])
    match = in_list & pref_zips
    ss = t["store_sales"]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy == 1)].d_date_sk
    j = ss[ss.ss_sold_date_sk.isin(dd)]
    st = t["store"].copy()
    st["s_zip3"] = st.s_zip.str[:3]
    j = j.merge(st[["s_store_sk", "s_store_name", "s_zip3"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.s_zip3.isin(match)]
    return (j.groupby("s_store_name", as_index=False)
            .agg(net_profit=("ss_net_profit", "sum"))
            .sort_values("s_store_name").head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q9 — CASE over bucket-count scalar subqueries, projected from reason
# ---------------------------------------------------------------------------


def q9(dfs):
    ss = dfs["store_sales"]

    def bucket(lo, hi, i):
        rng_f = ((col("ss_quantity") >= lit(lo))
                 & (col("ss_quantity") <= lit(hi)))
        cnt = ss.filter(rng_f).agg(("count", "*", "cnt")).as_scalar()
        then = ss.filter(rng_f).agg(
            ("avg", "ss_ext_tax", "a")).as_scalar()
        els = ss.filter(rng_f).agg(
            ("avg", "ss_net_profit", "a")).as_scalar()
        return CaseWhen([(cnt > lit(20_000 * i), then)],
                        otherwise=els).alias(f"bucket{i}")

    r = dfs["reason"].filter(col("r_reason_sk") == lit(1))
    return r.select(*[bucket(1 + 20 * (i - 1), 20 * i, i)
                      for i in range(1, 6)])


def q9_pandas(t):
    ss = t["store_sales"]
    out = {}
    for i in range(1, 6):
        lo, hi = 1 + 20 * (i - 1), 20 * i
        b = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
        if len(b) > 20_000 * i:
            out[f"bucket{i}"] = [b.ss_ext_tax.mean()]
        else:
            out[f"bucket{i}"] = [b.ss_net_profit.mean()]
    return pd.DataFrame(out)


# ---------------------------------------------------------------------------
# q10 — county customers active in store AND (web OR catalog), by
# demographics
# ---------------------------------------------------------------------------


def q10(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") >= lit(1))
                  & (col("d_moy") <= lit(4)))
          .select("d_date_sk"))
    ss_c = (dfs["store_sales"].select("ss_customer_sk", "ss_sold_date_sk")
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("ss_customer_sk").alias("active_sk")))
    ws_c = (dfs["web_sales"]
            .select("ws_bill_customer_sk", "ws_sold_date_sk")
            .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("ws_bill_customer_sk").alias("other_sk")))
    cs_c = (dfs["catalog_sales"]
            .select("cs_bill_customer_sk", "cs_sold_date_sk")
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("cs_bill_customer_sk").alias("other_sk")))
    either = ws_c.union(cs_c)  # OR of the two EXISTS
    c = dfs["customer"].select("c_customer_sk", "c_current_addr_sk",
                               "c_current_cdemo_sk")
    ca = (dfs["customer_address"]
          .filter(col("ca_county").isin(lit("Walker County"),
                                        lit("Richland County"),
                                        lit("Gaines County")))
          .select("ca_address_sk"))
    j = c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(ss_c, on=col("c_customer_sk") == col("active_sk"),
               how="left_semi")
    j = j.join(either, on=col("c_customer_sk") == col("other_sk"),
               how="left_semi")
    cd = dfs["customer_demographics"]
    j = j.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    return (j.group_by("cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate",
                       "cd_credit_rating")
            .agg(("count", "*", "cnt"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating").limit(100))


def q10_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy >= 1) & (d.d_moy <= 4)].d_date_sk
    ss = t["store_sales"]
    ss_c = set(ss[ss.ss_sold_date_sk.isin(dd)].ss_customer_sk)
    ws = t["web_sales"]
    ws_c = set(ws[ws.ws_sold_date_sk.isin(dd)].ws_bill_customer_sk)
    cs = t["catalog_sales"]
    cs_c = set(cs[cs.cs_sold_date_sk.isin(dd)].cs_bill_customer_sk)
    ca = t["customer_address"]
    counties = ca[ca.ca_county.isin(["Walker County", "Richland County",
                                     "Gaines County"])].ca_address_sk
    c = t["customer"]
    j = c[c.c_current_addr_sk.isin(counties)
          & c.c_customer_sk.isin(ss_c)
          & c.c_customer_sk.isin(ws_c | cs_c)]
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating"]
    return (j.groupby(keys, as_index=False).agg(cnt=("c_customer_sk",
                                                     "count"))
            .sort_values(keys).head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q16 — catalog orders from county call centers: shipped in window,
# multi-warehouse, never returned (q94's catalog twin)
# ---------------------------------------------------------------------------


def q16(dfs):
    cs = dfs["catalog_sales"].select(
        "cs_order_number", "cs_ship_date_sk", "cs_ship_addr_sk",
        "cs_call_center_sk", "cs_warehouse_sk", "cs_ext_ship_cost",
        "cs_net_profit")
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(760))
                                & (col("d_date_sk") <= lit(820)))
         .select("d_date_sk"))
    ca = (dfs["customer_address"].filter(col("ca_state") == lit("CA"))
          .select("ca_address_sk"))
    cc = (dfs["call_center"]
          .filter(col("cc_county").isin(lit("Williamson County"),
                                        lit("Walker County")))
          .select("cc_call_center_sk"))
    multi_wh = (dfs["catalog_sales"]
                .select("cs_order_number", "cs_warehouse_sk")
                .group_by("cs_order_number")
                .agg(("count_distinct", "cs_warehouse_sk", "nwh"))
                .filter(col("nwh") > lit(1))
                .select(col("cs_order_number").alias("mw_order")))
    cr = dfs["catalog_returns"].select(
        col("cr_order_number").alias("ret_order"))
    j = cs.join(d, on=col("cs_ship_date_sk") == col("d_date_sk"),
                how="left_semi")
    j = j.join(ca, on=col("cs_ship_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(cc, on=col("cs_call_center_sk") == col("cc_call_center_sk"),
               how="left_semi")
    j = j.join(multi_wh, on=col("cs_order_number") == col("mw_order"),
               how="left_semi")
    j = j.join(cr, on=col("cs_order_number") == col("ret_order"),
               how="left_anti")
    return j.agg(("count_distinct", "cs_order_number", "order_count"),
                 ("sum", "cs_ext_ship_cost", "total_shipping_cost"),
                 ("sum", "cs_net_profit", "total_net_profit"))


def q16_pandas(t):
    cs = t["catalog_sales"]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 760) & (d.d_date_sk <= 820)].d_date_sk
    ca = t["customer_address"]
    caa = ca[ca.ca_state == "CA"].ca_address_sk
    cc = t["call_center"]
    ccc = cc[cc.cc_county.isin(["Williamson County",
                                "Walker County"])].cc_call_center_sk
    nwh = cs.groupby("cs_order_number").cs_warehouse_sk.nunique()
    multi = nwh[nwh > 1].index
    j = cs[cs.cs_ship_date_sk.isin(dd) & cs.cs_ship_addr_sk.isin(caa)
           & cs.cs_call_center_sk.isin(ccc)
           & cs.cs_order_number.isin(multi)
           & ~cs.cs_order_number.isin(
               t["catalog_returns"].cr_order_number)]
    return pd.DataFrame({
        "order_count": [j.cs_order_number.nunique()],
        "total_shipping_cost": [j.cs_ext_ship_cost.sum(min_count=1)],
        "total_net_profit": [j.cs_net_profit.sum(min_count=1)]})


# ---------------------------------------------------------------------------
# q40 — catalog sales value before/after a date by warehouse/item, with
# returns netted out
# ---------------------------------------------------------------------------

_Q40_SPLIT = 800


def q40(dfs):
    cs = dfs["catalog_sales"].select("cs_order_number", "cs_item_sk",
                                     "cs_sold_date_sk", "cs_warehouse_sk",
                                     "cs_sales_price")
    cr = dfs["catalog_returns"].select(
        col("cr_order_number").alias("r_order"),
        col("cr_item_sk").alias("r_item"), "cr_refunded_cash")
    j = cs.join(cr, on=(col("cs_order_number") == col("r_order"))
                & (col("cs_item_sk") == col("r_item")), how="left_outer")
    w = dfs["warehouse"].select("w_warehouse_sk", "w_state")
    j = j.join(w, on=col("cs_warehouse_sk") == col("w_warehouse_sk"))
    it = (dfs["item"]
          .filter((col("i_current_price") >= lit(0.99))
                  & (col("i_current_price") <= lit(1.49)))
          .select("i_item_sk", "i_item_id"))
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q40_SPLIT - 30))
                  & (col("d_date_sk") <= lit(_Q40_SPLIT + 30)))
          .select("d_date_sk"))
    j = j.join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
    value = (col("cs_sales_price")
             - CaseWhen([(col("cr_refunded_cash").is_not_null(),
                          col("cr_refunded_cash"))], otherwise=lit(0.0)))
    before = CaseWhen([(col("cs_sold_date_sk") < lit(_Q40_SPLIT), value)])
    after = CaseWhen([(col("cs_sold_date_sk") >= lit(_Q40_SPLIT), value)])
    return (j.group_by("w_state", "i_item_id")
            .agg(("sum", before, "sales_before"),
                 ("sum", after, "sales_after"))
            .sort("w_state", "i_item_id").limit(100))


def q40_pandas(t):
    cs = t["catalog_sales"]
    cr = t["catalog_returns"][["cr_order_number", "cr_item_sk",
                               "cr_refunded_cash"]]
    j = cs.merge(cr, how="left",
                 left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"])
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_state"]],
                left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
    it = t["item"]
    it = it[(it.i_current_price >= 0.99) & (it.i_current_price <= 1.49)]
    j = j.merge(it[["i_item_sk", "i_item_id"]], left_on="cs_item_sk",
                right_on="i_item_sk")
    j = j[(j.cs_sold_date_sk >= _Q40_SPLIT - 30)
          & (j.cs_sold_date_sk <= _Q40_SPLIT + 30)]
    value = j.cs_sales_price - j.cr_refunded_cash.fillna(0.0)
    j = j.assign(
        sales_before=value.where(j.cs_sold_date_sk < _Q40_SPLIT),
        sales_after=value.where(j.cs_sold_date_sk >= _Q40_SPLIT))
    # SQL SUM over an all-NULL group is NULL, not 0 (matches the engine).
    return (j.groupby(["w_state", "i_item_id"], as_index=False)
            .agg(sales_before=("sales_before",
                               lambda s: s.sum(min_count=1)),
                 sales_after=("sales_after",
                              lambda s: s.sum(min_count=1)))
            .sort_values(["w_state", "i_item_id"]).head(100)
            .reset_index(drop=True))


QUERIES_EXT3: Dict[str, tuple] = {
    "q4": (q4, q4_pandas),
    "q5": (q5, q5_pandas),
    "q8": (q8, q8_pandas),
    "q9": (q9, q9_pandas),
    "q10": (q10, q10_pandas),
    "q16": (q16, q16_pandas),
    "q40": (q40, q40_pandas),
}


# ---------------------------------------------------------------------------
# q47 / q57 — monthly sales deviating from the partition average, with
# prior/next month via rank self-joins (LAG/LEAD expressed relationally)
# ---------------------------------------------------------------------------


def _q47_v1(dfs, sales, date_col, sk_col, measure, extra_dims):
    """Monthly sums + partition avg + month rank for q47 (store dims) /
    q57 (call-center dims). `extra_dims` = [(dim_df_name, dim_sk, dim join
    col, [dim out cols])]."""
    dim_join_cols = [join_col for _, _, join_col, _ in extra_dims]
    s = dfs[sales].select(col(date_col).alias("date_sk"),
                          col(sk_col).alias("item_sk"),
                          col(measure).alias("amt"), *dim_join_cols)
    dd = dfs["date_dim"].select("d_date_sk", "d_year", "d_moy")
    j = s.join(dd, on=col("date_sk") == col("d_date_sk"))
    it = dfs["item"].select("i_item_sk", "i_category", "i_brand")
    j = j.join(it, on=col("item_sk") == col("i_item_sk"))
    dim_cols = []
    for dim, dim_sk, join_col, out_cols in extra_dims:
        dmf = dfs[dim].select(dim_sk, *out_cols)
        j = j.join(dmf, on=col(join_col) == col(dim_sk))
        dim_cols.extend(out_cols)
    part = ["i_category", "i_brand"] + dim_cols
    sums = (j.group_by(*part, "d_year", "d_moy")
            .agg(("sum", "amt", "sum_sales")))
    v1 = sums.window(part + ["d_year"],
                     avg_monthly_sales=("avg", "sum_sales"))
    v1 = v1.window(part, order_by=["d_year", "d_moy"], rn=("rank", "*"))
    return v1, part


def _q47_build(dfs, sales, date_col, sk_col, join_extra, measure):
    v1, part = _q47_v1(dfs, sales, date_col, sk_col, measure, join_extra)
    # LAG/LEAD as rank-offset self-joins: the offset is projected into a
    # column first (equi-joins compare columns directly).
    lag = v1.select(*[col(c).alias(f"lag_{c}") for c in part],
                    (col("rn") + lit(1)).alias("lag_rn"),
                    col("sum_sales").alias("psum"))
    lead = v1.select(*[col(c).alias(f"lead_{c}") for c in part],
                     (col("rn") - lit(1)).alias("lead_rn"),
                     col("sum_sales").alias("nsum"))
    j = v1.filter((col("d_year") == lit(2000))
                  & (col("avg_monthly_sales") > lit(0)))
    onl = None
    for c in part:
        e = col(c) == col(f"lag_{c}")
        onl = e if onl is None else (onl & e)
    onl = onl & (col("rn") == col("lag_rn"))
    j = j.join(lag, on=onl)
    onr = None
    for c in part:
        e = col(c) == col(f"lead_{c}")
        onr = e if onr is None else (onr & e)
    onr = onr & (col("rn") == col("lead_rn"))
    j = j.join(lead, on=onr)
    dev = (col("sum_sales") - col("avg_monthly_sales"))
    j = j.filter((dev / col("avg_monthly_sales") > lit(0.1))
                 | (dev / col("avg_monthly_sales") < lit(-0.1)))
    return (j.select(*part, "d_year", "d_moy", "sum_sales",
                     "avg_monthly_sales", "psum", "nsum")
            .sort(*part, "d_year", "d_moy").limit(100))


def q47(dfs):
    return _q47_build(
        dfs, "store_sales", "ss_sold_date_sk", "ss_item_sk",
        [("store", "s_store_sk", "ss_store_sk",
          ["s_store_name", "s_company_name"])], "ss_sales_price")


def _q47_pd(t, sales, date_col, sk_col, store_merge, measure):
    s = t[sales]
    d = t["date_dim"][["d_date_sk", "d_year", "d_moy"]]
    j = s.merge(d, left_on=date_col, right_on="d_date_sk")
    it = t["item"][["i_item_sk", "i_category", "i_brand"]]
    j = j.merge(it, left_on=sk_col, right_on="i_item_sk")
    dim_cols = []
    for dim, dim_sk, join_col, out_cols in store_merge:
        j = j.merge(t[dim][[dim_sk] + out_cols], left_on=join_col,
                    right_on=dim_sk)
        dim_cols.extend(out_cols)
    part = ["i_category", "i_brand"] + dim_cols
    sums = j.groupby(part + ["d_year", "d_moy"], as_index=False).agg(
        sum_sales=(measure, "sum"))
    sums["avg_monthly_sales"] = sums.groupby(
        part + ["d_year"]).sum_sales.transform("mean")
    sums = sums.sort_values(part + ["d_year", "d_moy"])
    sums["rn"] = sums.groupby(part).cumcount() + 1
    lag = sums[part + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "psum", "rn": "lag_rn"})
    lead = sums[part + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "nsum", "rn": "lead_rn"})
    v = sums[(sums.d_year == 2000) & (sums.avg_monthly_sales > 0)]
    lag = lag.assign(rn=lag.lag_rn + 1)
    lead = lead.assign(rn=lead.lead_rn - 1)
    j2 = v.merge(lag, on=part + ["rn"]).merge(lead, on=part + ["rn"])
    dev = (j2.sum_sales - j2.avg_monthly_sales) / j2.avg_monthly_sales
    j2 = j2[(dev > 0.1) | (dev < -0.1)]
    out = j2[part + ["d_year", "d_moy", "sum_sales", "avg_monthly_sales",
                     "psum", "nsum"]]
    return (out.sort_values(part + ["d_year", "d_moy"]).head(100)
            .reset_index(drop=True))


def q47_pandas(t):
    return _q47_pd(t, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                   [("store", "s_store_sk", "ss_store_sk",
                     ["s_store_name", "s_company_name"])],
                   "ss_sales_price")


def q57(dfs):
    return _q47_build(
        dfs, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
        [("call_center", "cc_call_center_sk", "cs_call_center_sk",
          ["cc_name"])], "cs_sales_price")


def q57_pandas(t):
    return _q47_pd(t, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                   [("call_center", "cc_call_center_sk",
                     "cs_call_center_sk", ["cc_name"])], "cs_sales_price")


# ---------------------------------------------------------------------------
# q49 — worst return ratios per channel, rank-of-ratio windows, union
# ---------------------------------------------------------------------------


def _q49_channel(dfs, label, sales, s_item, s_order, s_date, s_qty, s_paid,
                 rets, r_item, r_order, r_qty, r_amt):
    s = dfs[sales].select(
        col(s_item).alias("item"), col(s_order).alias("order_"),
        col(s_date).alias("date_sk"), col(s_qty).alias("qty"),
        col(s_paid).alias("paid"))
    r = dfs[rets].select(
        col(r_item).alias("r_item"), col(r_order).alias("r_order"),
        col(r_qty).alias("ret_qty"), col(r_amt).alias("ret_amt"))
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(12)))
          .select("d_date_sk"))
    j = s.join(dd, on=col("date_sk") == col("d_date_sk"), how="left_semi")
    j = j.filter((col("qty") > lit(0)) & (col("paid") > lit(0)))
    j = j.join(r, on=(col("order_") == col("r_order"))
               & (col("item") == col("r_item")), how="left_outer")
    coal_q = CaseWhen([(col("ret_qty").is_not_null(), col("ret_qty"))],
                      otherwise=lit(0))
    coal_a = CaseWhen([(col("ret_amt").is_not_null(), col("ret_amt"))],
                      otherwise=lit(0.0))
    g = (j.group_by("item")
         .agg(("sum", coal_q, "ret_q"), ("sum", "qty", "qty_sum"),
              ("sum", coal_a, "ret_a"), ("sum", "paid", "paid_sum")))
    g = g.with_column("return_ratio",
                      col("ret_q") / col("qty_sum"))
    g = g.with_column("currency_ratio",
                      col("ret_a") / col("paid_sum"))
    g = g.with_column("one", lit(1))
    g = g.window(["one"], order_by=["return_ratio"],
                 return_rank=("dense_rank", "*"))
    g = g.window(["one"], order_by=["currency_ratio"],
                 currency_rank=("dense_rank", "*"))
    g = g.filter((col("return_rank") <= lit(10))
                 | (col("currency_rank") <= lit(10)))
    return g.select(lit(label).alias("channel"), "item",
                    "return_ratio", "return_rank", "currency_rank")


def q49(dfs):
    w = _q49_channel(dfs, "web", "web_sales", "ws_item_sk",
                     "ws_order_number", "ws_sold_date_sk", "ws_quantity",
                     "ws_net_paid", "web_returns", "wr_item_sk",
                     "wr_order_number", "wr_return_quantity",
                     "wr_return_amt")
    c = _q49_channel(dfs, "catalog", "catalog_sales", "cs_item_sk",
                     "cs_order_number", "cs_sold_date_sk", "cs_quantity",
                     "cs_net_paid", "catalog_returns", "cr_item_sk",
                     "cr_order_number", "cr_return_quantity",
                     "cr_return_amount")
    s = _q49_channel(dfs, "store", "store_sales", "ss_item_sk",
                     "ss_ticket_number", "ss_sold_date_sk", "ss_quantity",
                     "ss_net_paid", "store_returns", "sr_item_sk",
                     "sr_ticket_number", "sr_return_quantity",
                     "sr_return_amt")
    u = w.union(c).union(s).distinct()
    return (u.sort("channel", "return_rank", "currency_rank", "item")
            .limit(100))


def _q49_pd_channel(t, label, sales, s_item, s_order, s_date, s_qty,
                    s_paid, rets, r_item, r_order, r_qty, r_amt):
    s = t[sales]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == 12)].d_date_sk
    j = s[s[s_date].isin(dd) & (s[s_qty] > 0) & (s[s_paid] > 0)]
    r = t[rets][[r_item, r_order, r_qty, r_amt]]
    j = j.merge(r, how="left", left_on=[s_order, s_item],
                right_on=[r_order, r_item])
    g = j.groupby(s_item).agg(
        ret_q=(r_qty, lambda x: x.fillna(0).sum()),
        qty_sum=(s_qty, "sum"),
        ret_a=(r_amt, lambda x: x.fillna(0).sum()),
        paid_sum=(s_paid, "sum"))
    # fillna-inside-agg misses rows where the LEFT side had no match at
    # all (NaN group contributions are dropped); recompute robustly:
    g["ret_q"] = j.assign(v=j[r_qty].fillna(0)).groupby(s_item).v.sum()
    g["ret_a"] = j.assign(v=j[r_amt].fillna(0.0)).groupby(s_item).v.sum()
    g = g.reset_index(names="item")
    g["return_ratio"] = g.ret_q / g.qty_sum
    g["currency_ratio"] = g.ret_a / g.paid_sum
    g["return_rank"] = g.return_ratio.rank(method="dense").astype(int)
    g["currency_rank"] = g.currency_ratio.rank(method="dense").astype(int)
    g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
    g = g.assign(channel=label)
    return g[["channel", "item", "return_ratio", "return_rank",
              "currency_rank"]]


def q49_pandas(t):
    w = _q49_pd_channel(t, "web", "web_sales", "ws_item_sk",
                        "ws_order_number", "ws_sold_date_sk",
                        "ws_quantity", "ws_net_paid", "web_returns",
                        "wr_item_sk", "wr_order_number",
                        "wr_return_quantity", "wr_return_amt")
    c = _q49_pd_channel(t, "catalog", "catalog_sales", "cs_item_sk",
                        "cs_order_number", "cs_sold_date_sk",
                        "cs_quantity", "cs_net_paid", "catalog_returns",
                        "cr_item_sk", "cr_order_number",
                        "cr_return_quantity", "cr_return_amount")
    s = _q49_pd_channel(t, "store", "store_sales", "ss_item_sk",
                        "ss_ticket_number", "ss_sold_date_sk",
                        "ss_quantity", "ss_net_paid", "store_returns",
                        "sr_item_sk", "sr_ticket_number",
                        "sr_return_quantity", "sr_return_amt")
    u = pd.concat([w, c, s], ignore_index=True).drop_duplicates()
    return (u.sort_values(["channel", "return_rank", "currency_rank",
                           "item"]).head(100).reset_index(drop=True))


QUERIES_EXT3.update({
    "q47": (q47, q47_pandas),
    "q49": (q49, q49_pandas),
    "q57": (q57, q57_pandas),
})
