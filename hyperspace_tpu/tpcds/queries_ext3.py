"""Round-5 TPC-DS completion: the final 26 queries (q4 q5 q8 q9 q10 q14
q16 q23 q24 q40 q47 q49 q51 q57 q58 q66 q72 q75 q76 q77 q78 q80 q83 q85
q91 q95) — with these the engine runs ALL 99 TPC-DS queries end to end
three ways (rules on / rules off / pandas oracle), completing the
reference serde's all-TPC-DS property (`index/serde/package.scala:46-49`)
at the ENGINE level.

Shapes follow the official queries over this generator's reduced schema
(`generator.py`); where an official column is absent the closest
generated measure substitutes CONSISTENTLY in engine and oracle (e.g.
ss_coupon_amt stands in for ss_ext_discount_amt in q4's profit formula).
Idioms newly covered here: 3-channel year-over-year growth chains with
>2-way self-joins (q4/q74), channel rollup reports (q5/q77/q80),
zip-prefix INTERSECT (q8), projection-level scalar subqueries (q9),
OR-of-EXISTS via channel union (q10), cross-channel frequent-item and
best-customer filters (q14/q23), paired-purchase self joins (q24/q64),
monthly-deviation series with neighbor self-joins standing in for
LAG/LEAD (q47/q57), windowed cumulative medians (q51), rank-of-ratio
windows (q49), shipping pivot reports (q66), inventory week-over-week
(q72), channel-vs-returns anti semantics (q78/q87), and multi-warehouse
shipment probes (q95/q94)."""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from hyperspace_tpu.plan.expr import CaseWhen, col, lit
from hyperspace_tpu.tpcds.queries_ext import _rollup_union


def _sum_case(cond, value, alias):
    return ("sum", CaseWhen([(cond, value)]), alias)


# ---------------------------------------------------------------------------
# q4 — 3-channel year-over-year growth (the q11 family's full form)
# ---------------------------------------------------------------------------


def _q4_channel(dfs, table, date_col, cust_col, formula_cols, tag):
    prefix = {"store_sales": "ss", "catalog_sales": "cs",
              "web_sales": "ws"}[table]
    a, b, c2, d2 = formula_cols
    s = dfs[table].select(
        col(cust_col).alias("cust_sk"), col(date_col).alias("sold_date"),
        ((col(a) - col(b) + col(c2) - col(d2)) / lit(2.0)).alias("profit"))
    dd = dfs["date_dim"].select("d_date_sk", "d_year")
    j = s.join(dd, on=col("sold_date") == col("d_date_sk"))
    cust = dfs["customer"].select(
        col("c_customer_sk").alias("cc_sk"), "c_customer_id",
        "c_first_name", "c_last_name")
    j = j.join(cust, on=col("cust_sk") == col("cc_sk"))
    return (j.group_by("c_customer_id", "c_first_name", "c_last_name",
                       "d_year")
            .agg(("sum", "profit", f"year_total_{tag}")))


def q4(dfs):
    st = _q4_channel(dfs, "store_sales", "ss_sold_date_sk",
                     "ss_customer_sk",
                     ("ss_ext_list_price", "ss_ext_wholesale_cost",
                      "ss_ext_sales_price", "ss_coupon_amt"), "s")
    ct = _q4_channel(dfs, "catalog_sales", "cs_sold_date_sk",
                     "cs_bill_customer_sk",
                     ("cs_ext_list_price", "cs_ext_discount_amt",
                      "cs_ext_sales_price", "cs_coupon_amt"), "c")
    wt = _q4_channel(dfs, "web_sales", "ws_sold_date_sk",
                     "ws_bill_customer_sk",
                     ("ws_ext_list_price", "ws_ext_discount_amt",
                      "ws_ext_sales_price", "ws_ext_wholesale_cost"), "w")

    def year(df2, yr, tag, keep_names=False):
        cols = [col("c_customer_id").alias(f"id_{tag}"),
                col(f"year_total_{df2._tag}").alias(f"total_{tag}")]
        if keep_names:
            cols += ["c_first_name", "c_last_name"]
        return df2.filter(col("d_year") == lit(yr)).select(*cols)

    # tag the channel frames so `year` can pick the right total column
    st._tag, ct._tag, wt._tag = "s", "c", "w"
    s1 = year(st, 1999, "s1", keep_names=True)
    s2 = year(st, 2000, "s2")
    c1 = year(ct, 1999, "c1")
    c2_ = year(ct, 2000, "c2")
    w1 = year(wt, 1999, "w1")
    w2 = year(wt, 2000, "w2")
    j = s1.join(s2, on=col("id_s1") == col("id_s2"))
    j = j.join(c1, on=col("id_s1") == col("id_c1"))
    j = j.join(c2_, on=col("id_s1") == col("id_c2"))
    j = j.join(w1, on=col("id_s1") == col("id_w1"))
    j = j.join(w2, on=col("id_s1") == col("id_w2"))
    j = j.filter((col("total_s1") > lit(0)) & (col("total_c1") > lit(0))
                 & (col("total_w1") > lit(0)))
    j = j.filter((col("total_c2") / col("total_c1"))
                 > (col("total_s2") / col("total_s1")))
    j = j.filter((col("total_c2") / col("total_c1"))
                 > (col("total_w2") / col("total_w1")))
    return (j.select(col("id_s1").alias("customer_id"), "c_first_name",
                     "c_last_name")
            .sort("customer_id", "c_first_name", "c_last_name").limit(100))


def _q4_pd_channel(t, table, date_col, cust_col, formula_cols):
    a, b, c2, d2 = formula_cols
    s = t[table].copy()
    s["profit"] = (s[a] - s[b] + s[c2] - s[d2]) / 2.0
    d = t["date_dim"][["d_date_sk", "d_year"]]
    j = s.merge(d, left_on=date_col, right_on="d_date_sk")
    cust = t["customer"][["c_customer_sk", "c_customer_id", "c_first_name",
                          "c_last_name"]]
    j = j.merge(cust, left_on=cust_col, right_on="c_customer_sk")
    return j.groupby(["c_customer_id", "c_first_name", "c_last_name",
                      "d_year"], as_index=False).agg(
        year_total=("profit", "sum"))


def q4_pandas(t):
    st = _q4_pd_channel(t, "store_sales", "ss_sold_date_sk",
                        "ss_customer_sk",
                        ("ss_ext_list_price", "ss_ext_wholesale_cost",
                         "ss_ext_sales_price", "ss_coupon_amt"))
    ct = _q4_pd_channel(t, "catalog_sales", "cs_sold_date_sk",
                        "cs_bill_customer_sk",
                        ("cs_ext_list_price", "cs_ext_discount_amt",
                         "cs_ext_sales_price", "cs_coupon_amt"))
    wt = _q4_pd_channel(t, "web_sales", "ws_sold_date_sk",
                        "ws_bill_customer_sk",
                        ("ws_ext_list_price", "ws_ext_discount_amt",
                         "ws_ext_sales_price", "ws_ext_wholesale_cost"))

    def yr(df, y):
        return df[df.d_year == y].set_index("c_customer_id").year_total

    s1, s2 = yr(st, 1999), yr(st, 2000)
    c1, c2_ = yr(ct, 1999), yr(ct, 2000)
    w1, w2 = yr(wt, 1999), yr(wt, 2000)
    ids = s1[s1 > 0].index
    ids = ids.intersection(c1[c1 > 0].index).intersection(w1[w1 > 0].index)
    ids = ids.intersection(s2.index).intersection(c2_.index) \
             .intersection(w2.index)
    keep = [i for i in ids
            if (c2_[i] / c1[i] > s2[i] / s1[i])
            and (c2_[i] / c1[i] > w2[i] / w1[i])]
    names = (t["customer"].drop_duplicates("c_customer_id")
             .set_index("c_customer_id"))
    out = pd.DataFrame({
        "customer_id": keep,
        "c_first_name": [names.c_first_name[i] for i in keep],
        "c_last_name": [names.c_last_name[i] for i in keep]})
    return (out.sort_values(["customer_id", "c_first_name", "c_last_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q5 — channel sales/returns/profit ROLLUP report
# ---------------------------------------------------------------------------

_Q5_LO, _Q5_HI = 731, 744  # 14-day report window


def q5(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q5_LO))
                  & (col("d_date_sk") <= lit(_Q5_HI)))
          .select("d_date_sk"))

    def channel(sales, s_date, s_id, s_sales, s_profit,
                rets, r_date, r_id, r_ret, r_loss, dim, dim_sk, dim_id,
                label):
        s = dfs[sales].select(
            col(s_date).alias("date_sk"), col(s_id).alias("id_sk"),
            col(s_sales).alias("sales_price"),
            col(s_profit).alias("profit"),
            (col(s_sales) * lit(0.0)).alias("return_amt"),
            (col(s_sales) * lit(0.0)).alias("net_loss"))
        r = dfs[rets].select(
            col(r_date).alias("date_sk"), col(r_id).alias("id_sk"),
            (col(r_ret) * lit(0.0)).alias("sales_price"),
            (col(r_ret) * lit(0.0)).alias("profit"),
            col(r_ret).alias("return_amt"), col(r_loss).alias("net_loss"))
        u = s.union(r)
        u = u.join(dd, on=col("date_sk") == col("d_date_sk"))
        dmf = dfs[dim].select(col(dim_sk).alias("dim_sk"),
                              col(dim_id).alias("id"))
        u = u.join(dmf, on=col("id_sk") == col("dim_sk"))
        return (u.group_by("id")
                .agg(("sum", "sales_price", "sales"),
                     ("sum", "return_amt", "returns_"),
                     ("sum", col("profit") - col("net_loss"), "profit"))
                .with_column("channel", lit(label)))

    st = channel("store_sales", "ss_sold_date_sk", "ss_store_sk",
                 "ss_ext_sales_price", "ss_net_profit",
                 "store_returns", "sr_returned_date_sk", "sr_store_sk",
                 "sr_return_amt", "sr_net_loss",
                 "store", "s_store_sk", "s_store_id", "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit",
                 "catalog_returns", "cr_returned_date_sk",
                 "cr_catalog_page_sk", "cr_return_amount", "cr_net_loss",
                 "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                 "ws_ext_sales_price", "ws_net_profit",
                 "web_returns", "wr_returned_date_sk", "wr_web_page_sk",
                 "wr_return_amt", "wr_net_loss",
                 "web_site", "web_site_sk", "web_site_id", "web channel")
    # web returns key on web_page in the official query; this generator's
    # wr carries wr_web_page_sk (reduced schema) — the web channel's
    # returns roll up under the page's site via the same id join shape.
    u = st.union(ct).union(wt)
    roll = _rollup_union(u, [("channel", "string"), ("id", "string")],
                         {"sales": ("sum", "sales"),
                          "returns_": ("sum", "returns_"),
                          "profit": ("sum", "profit")}, u.session)
    return (roll.select("channel", "id", "sales", "returns_", "profit")
            .sort("channel", "id").limit(100))


def q5_pandas(t):
    lo, hi = _Q5_LO, _Q5_HI

    def channel(sales, s_date, s_id, s_sales, s_profit,
                rets, r_date, r_id, r_ret, r_loss, dim, dim_sk, dim_id,
                label):
        s = t[sales]
        s = s[(s[s_date] >= lo) & (s[s_date] <= hi)]
        r = t[rets]
        r = r[(r[r_date] >= lo) & (r[r_date] <= hi)]
        dimt = t[dim][[dim_sk, dim_id]]
        sj = s.merge(dimt, left_on=s_id, right_on=dim_sk)
        rj = r.merge(dimt, left_on=r_id, right_on=dim_sk)
        sa = sj.groupby(dim_id).agg(sales=(s_sales, "sum"),
                                    profit=(s_profit, "sum"))
        ra = rj.groupby(dim_id).agg(returns_=(r_ret, "sum"),
                                    net_loss=(r_loss, "sum"))
        m = sa.join(ra, how="outer").fillna(0.0)
        m["profit"] = m["profit"] - m["net_loss"]
        m = m.drop(columns=["net_loss"]).reset_index(names="id")
        m["channel"] = label
        return m

    st = channel("store_sales", "ss_sold_date_sk", "ss_store_sk",
                 "ss_ext_sales_price", "ss_net_profit",
                 "store_returns", "sr_returned_date_sk", "sr_store_sk",
                 "sr_return_amt", "sr_net_loss",
                 "store", "s_store_sk", "s_store_id", "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit",
                 "catalog_returns", "cr_returned_date_sk",
                 "cr_catalog_page_sk", "cr_return_amount", "cr_net_loss",
                 "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                 "ws_ext_sales_price", "ws_net_profit",
                 "web_returns", "wr_returned_date_sk", "wr_web_page_sk",
                 "wr_return_amt", "wr_net_loss",
                 "web_site", "web_site_sk", "web_site_id", "web channel")
    u = pd.concat([st, ct, wt], ignore_index=True)
    leaf = u.groupby(["channel", "id"], as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid = u.groupby("channel", as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid["id"] = np.nan
    top = pd.DataFrame({"channel": [np.nan], "id": [np.nan],
                        "sales": [u.sales.sum()],
                        "returns_": [u.returns_.sum()],
                        "profit": [u.profit.sum()]})
    out = pd.concat([leaf, mid, top], ignore_index=True)
    # ORDER BY ASC places NULL subtotal rows FIRST (Spark semantics, which
    # the engine's SortExec follows).
    return (out[["channel", "id", "sales", "returns_", "profit"]]
            .sort_values(["channel", "id"], na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q8 — store sales where store zip-3 matches (list INTERSECT preferred
# customers' zips)
# ---------------------------------------------------------------------------

_Q8_ZIPS = ["356", "354", "350", "358", "352"]


def q8(dfs):
    zip_list = (dfs["customer_address"]
                .select(col("ca_zip").substr(1, 3).alias("zip3"))
                .filter(col("zip3").isin(*[lit(z) for z in _Q8_ZIPS]))
                .distinct())
    pref = (dfs["customer"].filter(col("c_preferred_cust_flag") == lit("Y"))
            .select("c_current_addr_sk"))
    pref_zips = (pref.join(dfs["customer_address"].select(
        "ca_address_sk", "ca_zip"),
        on=col("c_current_addr_sk") == col("ca_address_sk"))
        .select(col("ca_zip").substr(1, 3).alias("zip3"))
        .distinct())
    zips = zip_list.intersect(pref_zips)
    zips = zips.select(col("zip3").alias("match_zip3"))
    ss = dfs["store_sales"].select("ss_store_sk", "ss_sold_date_sk",
                                   "ss_net_profit")
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_qoy") == lit(1)))
          .select("d_date_sk"))
    j = ss.join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
    st = dfs["store"].select("s_store_sk", "s_store_name",
                             col("s_zip").substr(1, 3).alias("s_zip3"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(zips, on=col("s_zip3") == col("match_zip3"),
               how="left_semi")
    return (j.group_by("s_store_name")
            .agg(("sum", "ss_net_profit", "net_profit"))
            .sort("s_store_name").limit(100))


def q8_pandas(t):
    ca = t["customer_address"]
    zip3 = ca.ca_zip.str[:3]
    in_list = set(zip3[zip3.isin(_Q8_ZIPS)])
    cust = t["customer"]
    pref = cust[cust.c_preferred_cust_flag == "Y"]
    pj = pref.merge(ca[["ca_address_sk", "ca_zip"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
    pref_zips = set(pj.ca_zip.str[:3])
    match = in_list & pref_zips
    ss = t["store_sales"]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy == 1)].d_date_sk
    j = ss[ss.ss_sold_date_sk.isin(dd)]
    st = t["store"].copy()
    st["s_zip3"] = st.s_zip.str[:3]
    j = j.merge(st[["s_store_sk", "s_store_name", "s_zip3"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.s_zip3.isin(match)]
    return (j.groupby("s_store_name", as_index=False)
            .agg(net_profit=("ss_net_profit", "sum"))
            .sort_values("s_store_name").head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q9 — CASE over bucket-count scalar subqueries, projected from reason
# ---------------------------------------------------------------------------


def q9(dfs):
    ss = dfs["store_sales"]

    def bucket(lo, hi, i):
        rng_f = ((col("ss_quantity") >= lit(lo))
                 & (col("ss_quantity") <= lit(hi)))
        cnt = ss.filter(rng_f).agg(("count", "*", "cnt")).as_scalar()
        then = ss.filter(rng_f).agg(
            ("avg", "ss_ext_tax", "a")).as_scalar()
        els = ss.filter(rng_f).agg(
            ("avg", "ss_net_profit", "a")).as_scalar()
        return CaseWhen([(cnt > lit(20_000 * i), then)],
                        otherwise=els).alias(f"bucket{i}")

    r = dfs["reason"].filter(col("r_reason_sk") == lit(1))
    return r.select(*[bucket(1 + 20 * (i - 1), 20 * i, i)
                      for i in range(1, 6)])


def q9_pandas(t):
    ss = t["store_sales"]
    out = {}
    for i in range(1, 6):
        lo, hi = 1 + 20 * (i - 1), 20 * i
        b = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
        if len(b) > 20_000 * i:
            out[f"bucket{i}"] = [b.ss_ext_tax.mean()]
        else:
            out[f"bucket{i}"] = [b.ss_net_profit.mean()]
    return pd.DataFrame(out)


# ---------------------------------------------------------------------------
# q10 — county customers active in store AND (web OR catalog), by
# demographics
# ---------------------------------------------------------------------------


def q10(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") >= lit(1))
                  & (col("d_moy") <= lit(4)))
          .select("d_date_sk"))
    ss_c = (dfs["store_sales"].select("ss_customer_sk", "ss_sold_date_sk")
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("ss_customer_sk").alias("active_sk")))
    ws_c = (dfs["web_sales"]
            .select("ws_bill_customer_sk", "ws_sold_date_sk")
            .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("ws_bill_customer_sk").alias("other_sk")))
    cs_c = (dfs["catalog_sales"]
            .select("cs_bill_customer_sk", "cs_sold_date_sk")
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"),
                  how="left_semi")
            .select(col("cs_bill_customer_sk").alias("other_sk")))
    either = ws_c.union(cs_c)  # OR of the two EXISTS
    c = dfs["customer"].select("c_customer_sk", "c_current_addr_sk",
                               "c_current_cdemo_sk")
    ca = (dfs["customer_address"]
          .filter(col("ca_county").isin(lit("Walker County"),
                                        lit("Richland County"),
                                        lit("Gaines County")))
          .select("ca_address_sk"))
    j = c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(ss_c, on=col("c_customer_sk") == col("active_sk"),
               how="left_semi")
    j = j.join(either, on=col("c_customer_sk") == col("other_sk"),
               how="left_semi")
    cd = dfs["customer_demographics"]
    j = j.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    return (j.group_by("cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate",
                       "cd_credit_rating")
            .agg(("count", "*", "cnt"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating").limit(100))


def q10_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy >= 1) & (d.d_moy <= 4)].d_date_sk
    ss = t["store_sales"]
    ss_c = set(ss[ss.ss_sold_date_sk.isin(dd)].ss_customer_sk)
    ws = t["web_sales"]
    ws_c = set(ws[ws.ws_sold_date_sk.isin(dd)].ws_bill_customer_sk)
    cs = t["catalog_sales"]
    cs_c = set(cs[cs.cs_sold_date_sk.isin(dd)].cs_bill_customer_sk)
    ca = t["customer_address"]
    counties = ca[ca.ca_county.isin(["Walker County", "Richland County",
                                     "Gaines County"])].ca_address_sk
    c = t["customer"]
    j = c[c.c_current_addr_sk.isin(counties)
          & c.c_customer_sk.isin(ss_c)
          & c.c_customer_sk.isin(ws_c | cs_c)]
    j = j.merge(t["customer_demographics"], left_on="c_current_cdemo_sk",
                right_on="cd_demo_sk")
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating"]
    return (j.groupby(keys, as_index=False).agg(cnt=("c_customer_sk",
                                                     "count"))
            .sort_values(keys).head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q16 — catalog orders from county call centers: shipped in window,
# multi-warehouse, never returned (q94's catalog twin)
# ---------------------------------------------------------------------------


def q16(dfs):
    cs = dfs["catalog_sales"].select(
        "cs_order_number", "cs_ship_date_sk", "cs_ship_addr_sk",
        "cs_call_center_sk", "cs_warehouse_sk", "cs_ext_ship_cost",
        "cs_net_profit")
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(760))
                                & (col("d_date_sk") <= lit(820)))
         .select("d_date_sk"))
    ca = (dfs["customer_address"].filter(col("ca_state") == lit("CA"))
          .select("ca_address_sk"))
    cc = (dfs["call_center"]
          .filter(col("cc_county").isin(lit("Williamson County"),
                                        lit("Walker County")))
          .select("cc_call_center_sk"))
    multi_wh = (dfs["catalog_sales"]
                .select("cs_order_number", "cs_warehouse_sk")
                .group_by("cs_order_number")
                .agg(("count_distinct", "cs_warehouse_sk", "nwh"))
                .filter(col("nwh") > lit(1))
                .select(col("cs_order_number").alias("mw_order")))
    cr = dfs["catalog_returns"].select(
        col("cr_order_number").alias("ret_order"))
    j = cs.join(d, on=col("cs_ship_date_sk") == col("d_date_sk"),
                how="left_semi")
    j = j.join(ca, on=col("cs_ship_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(cc, on=col("cs_call_center_sk") == col("cc_call_center_sk"),
               how="left_semi")
    j = j.join(multi_wh, on=col("cs_order_number") == col("mw_order"),
               how="left_semi")
    j = j.join(cr, on=col("cs_order_number") == col("ret_order"),
               how="left_anti")
    return j.agg(("count_distinct", "cs_order_number", "order_count"),
                 ("sum", "cs_ext_ship_cost", "total_shipping_cost"),
                 ("sum", "cs_net_profit", "total_net_profit"))


def q16_pandas(t):
    cs = t["catalog_sales"]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 760) & (d.d_date_sk <= 820)].d_date_sk
    ca = t["customer_address"]
    caa = ca[ca.ca_state == "CA"].ca_address_sk
    cc = t["call_center"]
    ccc = cc[cc.cc_county.isin(["Williamson County",
                                "Walker County"])].cc_call_center_sk
    nwh = cs.groupby("cs_order_number").cs_warehouse_sk.nunique()
    multi = nwh[nwh > 1].index
    j = cs[cs.cs_ship_date_sk.isin(dd) & cs.cs_ship_addr_sk.isin(caa)
           & cs.cs_call_center_sk.isin(ccc)
           & cs.cs_order_number.isin(multi)
           & ~cs.cs_order_number.isin(
               t["catalog_returns"].cr_order_number)]
    return pd.DataFrame({
        "order_count": [j.cs_order_number.nunique()],
        "total_shipping_cost": [j.cs_ext_ship_cost.sum(min_count=1)],
        "total_net_profit": [j.cs_net_profit.sum(min_count=1)]})


# ---------------------------------------------------------------------------
# q40 — catalog sales value before/after a date by warehouse/item, with
# returns netted out
# ---------------------------------------------------------------------------

_Q40_SPLIT = 800


def q40(dfs):
    cs = dfs["catalog_sales"].select("cs_order_number", "cs_item_sk",
                                     "cs_sold_date_sk", "cs_warehouse_sk",
                                     "cs_sales_price")
    cr = dfs["catalog_returns"].select(
        col("cr_order_number").alias("r_order"),
        col("cr_item_sk").alias("r_item"), "cr_refunded_cash")
    j = cs.join(cr, on=(col("cs_order_number") == col("r_order"))
                & (col("cs_item_sk") == col("r_item")), how="left_outer")
    w = dfs["warehouse"].select("w_warehouse_sk", "w_state")
    j = j.join(w, on=col("cs_warehouse_sk") == col("w_warehouse_sk"))
    it = (dfs["item"]
          .filter((col("i_current_price") >= lit(0.99))
                  & (col("i_current_price") <= lit(1.49)))
          .select("i_item_sk", "i_item_id"))
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q40_SPLIT - 30))
                  & (col("d_date_sk") <= lit(_Q40_SPLIT + 30)))
          .select("d_date_sk"))
    j = j.join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
    value = (col("cs_sales_price")
             - CaseWhen([(col("cr_refunded_cash").is_not_null(),
                          col("cr_refunded_cash"))], otherwise=lit(0.0)))
    before = CaseWhen([(col("cs_sold_date_sk") < lit(_Q40_SPLIT), value)])
    after = CaseWhen([(col("cs_sold_date_sk") >= lit(_Q40_SPLIT), value)])
    return (j.group_by("w_state", "i_item_id")
            .agg(("sum", before, "sales_before"),
                 ("sum", after, "sales_after"))
            .sort("w_state", "i_item_id").limit(100))


def q40_pandas(t):
    cs = t["catalog_sales"]
    cr = t["catalog_returns"][["cr_order_number", "cr_item_sk",
                               "cr_refunded_cash"]]
    j = cs.merge(cr, how="left",
                 left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"])
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_state"]],
                left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
    it = t["item"]
    it = it[(it.i_current_price >= 0.99) & (it.i_current_price <= 1.49)]
    j = j.merge(it[["i_item_sk", "i_item_id"]], left_on="cs_item_sk",
                right_on="i_item_sk")
    j = j[(j.cs_sold_date_sk >= _Q40_SPLIT - 30)
          & (j.cs_sold_date_sk <= _Q40_SPLIT + 30)]
    value = j.cs_sales_price - j.cr_refunded_cash.fillna(0.0)
    j = j.assign(
        sales_before=value.where(j.cs_sold_date_sk < _Q40_SPLIT),
        sales_after=value.where(j.cs_sold_date_sk >= _Q40_SPLIT))
    # SQL SUM over an all-NULL group is NULL, not 0 (matches the engine).
    return (j.groupby(["w_state", "i_item_id"], as_index=False)
            .agg(sales_before=("sales_before",
                               lambda s: s.sum(min_count=1)),
                 sales_after=("sales_after",
                              lambda s: s.sum(min_count=1)))
            .sort_values(["w_state", "i_item_id"]).head(100)
            .reset_index(drop=True))


QUERIES_EXT3: Dict[str, tuple] = {
    "q4": (q4, q4_pandas),
    "q5": (q5, q5_pandas),
    "q8": (q8, q8_pandas),
    "q9": (q9, q9_pandas),
    "q10": (q10, q10_pandas),
    "q16": (q16, q16_pandas),
    "q40": (q40, q40_pandas),
}


# ---------------------------------------------------------------------------
# q47 / q57 — monthly sales deviating from the partition average, with
# prior/next month via rank self-joins (LAG/LEAD expressed relationally)
# ---------------------------------------------------------------------------


def _q47_v1(dfs, sales, date_col, sk_col, measure, extra_dims):
    """Monthly sums + partition avg + month rank for q47 (store dims) /
    q57 (call-center dims). `extra_dims` = [(dim_df_name, dim_sk, dim join
    col, [dim out cols])]."""
    dim_join_cols = [join_col for _, _, join_col, _ in extra_dims]
    s = dfs[sales].select(col(date_col).alias("date_sk"),
                          col(sk_col).alias("item_sk"),
                          col(measure).alias("amt"), *dim_join_cols)
    dd = dfs["date_dim"].select("d_date_sk", "d_year", "d_moy")
    j = s.join(dd, on=col("date_sk") == col("d_date_sk"))
    it = dfs["item"].select("i_item_sk", "i_category", "i_brand")
    j = j.join(it, on=col("item_sk") == col("i_item_sk"))
    dim_cols = []
    for dim, dim_sk, join_col, out_cols in extra_dims:
        dmf = dfs[dim].select(dim_sk, *out_cols)
        j = j.join(dmf, on=col(join_col) == col(dim_sk))
        dim_cols.extend(out_cols)
    part = ["i_category", "i_brand"] + dim_cols
    sums = (j.group_by(*part, "d_year", "d_moy")
            .agg(("sum", "amt", "sum_sales")))
    v1 = sums.window(part + ["d_year"],
                     avg_monthly_sales=("avg", "sum_sales"))
    v1 = v1.window(part, order_by=["d_year", "d_moy"], rn=("rank", "*"))
    return v1, part


def _q47_build(dfs, sales, date_col, sk_col, join_extra, measure):
    v1, part = _q47_v1(dfs, sales, date_col, sk_col, measure, join_extra)
    # LAG/LEAD as rank-offset self-joins: the offset is projected into a
    # column first (equi-joins compare columns directly).
    lag = v1.select(*[col(c).alias(f"lag_{c}") for c in part],
                    (col("rn") + lit(1)).alias("lag_rn"),
                    col("sum_sales").alias("psum"))
    lead = v1.select(*[col(c).alias(f"lead_{c}") for c in part],
                     (col("rn") - lit(1)).alias("lead_rn"),
                     col("sum_sales").alias("nsum"))
    j = v1.filter((col("d_year") == lit(2000))
                  & (col("avg_monthly_sales") > lit(0)))
    onl = None
    for c in part:
        e = col(c) == col(f"lag_{c}")
        onl = e if onl is None else (onl & e)
    onl = onl & (col("rn") == col("lag_rn"))
    j = j.join(lag, on=onl)
    onr = None
    for c in part:
        e = col(c) == col(f"lead_{c}")
        onr = e if onr is None else (onr & e)
    onr = onr & (col("rn") == col("lead_rn"))
    j = j.join(lead, on=onr)
    dev = (col("sum_sales") - col("avg_monthly_sales"))
    j = j.filter((dev / col("avg_monthly_sales") > lit(0.1))
                 | (dev / col("avg_monthly_sales") < lit(-0.1)))
    return (j.select(*part, "d_year", "d_moy", "sum_sales",
                     "avg_monthly_sales", "psum", "nsum")
            .sort(*part, "d_year", "d_moy").limit(100))


def q47(dfs):
    return _q47_build(
        dfs, "store_sales", "ss_sold_date_sk", "ss_item_sk",
        [("store", "s_store_sk", "ss_store_sk",
          ["s_store_name", "s_company_name"])], "ss_sales_price")


def _q47_pd(t, sales, date_col, sk_col, store_merge, measure):
    s = t[sales]
    d = t["date_dim"][["d_date_sk", "d_year", "d_moy"]]
    j = s.merge(d, left_on=date_col, right_on="d_date_sk")
    it = t["item"][["i_item_sk", "i_category", "i_brand"]]
    j = j.merge(it, left_on=sk_col, right_on="i_item_sk")
    dim_cols = []
    for dim, dim_sk, join_col, out_cols in store_merge:
        j = j.merge(t[dim][[dim_sk] + out_cols], left_on=join_col,
                    right_on=dim_sk)
        dim_cols.extend(out_cols)
    part = ["i_category", "i_brand"] + dim_cols
    sums = j.groupby(part + ["d_year", "d_moy"], as_index=False).agg(
        sum_sales=(measure, "sum"))
    sums["avg_monthly_sales"] = sums.groupby(
        part + ["d_year"]).sum_sales.transform("mean")
    sums = sums.sort_values(part + ["d_year", "d_moy"])
    sums["rn"] = sums.groupby(part).cumcount() + 1
    lag = sums[part + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "psum", "rn": "lag_rn"})
    lead = sums[part + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "nsum", "rn": "lead_rn"})
    v = sums[(sums.d_year == 2000) & (sums.avg_monthly_sales > 0)]
    lag = lag.assign(rn=lag.lag_rn + 1)
    lead = lead.assign(rn=lead.lead_rn - 1)
    j2 = v.merge(lag, on=part + ["rn"]).merge(lead, on=part + ["rn"])
    dev = (j2.sum_sales - j2.avg_monthly_sales) / j2.avg_monthly_sales
    j2 = j2[(dev > 0.1) | (dev < -0.1)]
    out = j2[part + ["d_year", "d_moy", "sum_sales", "avg_monthly_sales",
                     "psum", "nsum"]]
    return (out.sort_values(part + ["d_year", "d_moy"]).head(100)
            .reset_index(drop=True))


def q47_pandas(t):
    return _q47_pd(t, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                   [("store", "s_store_sk", "ss_store_sk",
                     ["s_store_name", "s_company_name"])],
                   "ss_sales_price")


def q57(dfs):
    return _q47_build(
        dfs, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
        [("call_center", "cc_call_center_sk", "cs_call_center_sk",
          ["cc_name"])], "cs_sales_price")


def q57_pandas(t):
    return _q47_pd(t, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                   [("call_center", "cc_call_center_sk",
                     "cs_call_center_sk", ["cc_name"])], "cs_sales_price")


# ---------------------------------------------------------------------------
# q49 — worst return ratios per channel, rank-of-ratio windows, union
# ---------------------------------------------------------------------------


def _q49_channel(dfs, label, sales, s_item, s_order, s_date, s_qty, s_paid,
                 rets, r_item, r_order, r_qty, r_amt):
    s = dfs[sales].select(
        col(s_item).alias("item"), col(s_order).alias("order_"),
        col(s_date).alias("date_sk"), col(s_qty).alias("qty"),
        col(s_paid).alias("paid"))
    r = dfs[rets].select(
        col(r_item).alias("r_item"), col(r_order).alias("r_order"),
        col(r_qty).alias("ret_qty"), col(r_amt).alias("ret_amt"))
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(12)))
          .select("d_date_sk"))
    j = s.join(dd, on=col("date_sk") == col("d_date_sk"), how="left_semi")
    j = j.filter((col("qty") > lit(0)) & (col("paid") > lit(0)))
    j = j.join(r, on=(col("order_") == col("r_order"))
               & (col("item") == col("r_item")), how="left_outer")
    coal_q = CaseWhen([(col("ret_qty").is_not_null(), col("ret_qty"))],
                      otherwise=lit(0))
    coal_a = CaseWhen([(col("ret_amt").is_not_null(), col("ret_amt"))],
                      otherwise=lit(0.0))
    g = (j.group_by("item")
         .agg(("sum", coal_q, "ret_q"), ("sum", "qty", "qty_sum"),
              ("sum", coal_a, "ret_a"), ("sum", "paid", "paid_sum")))
    g = g.with_column("return_ratio",
                      col("ret_q") / col("qty_sum"))
    g = g.with_column("currency_ratio",
                      col("ret_a") / col("paid_sum"))
    g = g.with_column("one", lit(1))
    g = g.window(["one"], order_by=["return_ratio"],
                 return_rank=("dense_rank", "*"))
    g = g.window(["one"], order_by=["currency_ratio"],
                 currency_rank=("dense_rank", "*"))
    g = g.filter((col("return_rank") <= lit(10))
                 | (col("currency_rank") <= lit(10)))
    return g.select(lit(label).alias("channel"), "item",
                    "return_ratio", "return_rank", "currency_rank")


def q49(dfs):
    w = _q49_channel(dfs, "web", "web_sales", "ws_item_sk",
                     "ws_order_number", "ws_sold_date_sk", "ws_quantity",
                     "ws_net_paid", "web_returns", "wr_item_sk",
                     "wr_order_number", "wr_return_quantity",
                     "wr_return_amt")
    c = _q49_channel(dfs, "catalog", "catalog_sales", "cs_item_sk",
                     "cs_order_number", "cs_sold_date_sk", "cs_quantity",
                     "cs_net_paid", "catalog_returns", "cr_item_sk",
                     "cr_order_number", "cr_return_quantity",
                     "cr_return_amount")
    s = _q49_channel(dfs, "store", "store_sales", "ss_item_sk",
                     "ss_ticket_number", "ss_sold_date_sk", "ss_quantity",
                     "ss_net_paid", "store_returns", "sr_item_sk",
                     "sr_ticket_number", "sr_return_quantity",
                     "sr_return_amt")
    u = w.union(c).union(s).distinct()
    return (u.sort("channel", "return_rank", "currency_rank", "item")
            .limit(100))


def _q49_pd_channel(t, label, sales, s_item, s_order, s_date, s_qty,
                    s_paid, rets, r_item, r_order, r_qty, r_amt):
    s = t[sales]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_moy == 12)].d_date_sk
    j = s[s[s_date].isin(dd) & (s[s_qty] > 0) & (s[s_paid] > 0)]
    r = t[rets][[r_item, r_order, r_qty, r_amt]]
    j = j.merge(r, how="left", left_on=[s_order, s_item],
                right_on=[r_order, r_item])
    g = j.groupby(s_item).agg(
        ret_q=(r_qty, lambda x: x.fillna(0).sum()),
        qty_sum=(s_qty, "sum"),
        ret_a=(r_amt, lambda x: x.fillna(0).sum()),
        paid_sum=(s_paid, "sum"))
    # fillna-inside-agg misses rows where the LEFT side had no match at
    # all (NaN group contributions are dropped); recompute robustly:
    g["ret_q"] = j.assign(v=j[r_qty].fillna(0)).groupby(s_item).v.sum()
    g["ret_a"] = j.assign(v=j[r_amt].fillna(0.0)).groupby(s_item).v.sum()
    g = g.reset_index(names="item")
    g["return_ratio"] = g.ret_q / g.qty_sum
    g["currency_ratio"] = g.ret_a / g.paid_sum
    g["return_rank"] = g.return_ratio.rank(method="dense").astype(int)
    g["currency_rank"] = g.currency_ratio.rank(method="dense").astype(int)
    g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
    g = g.assign(channel=label)
    return g[["channel", "item", "return_ratio", "return_rank",
              "currency_rank"]]


def q49_pandas(t):
    w = _q49_pd_channel(t, "web", "web_sales", "ws_item_sk",
                        "ws_order_number", "ws_sold_date_sk",
                        "ws_quantity", "ws_net_paid", "web_returns",
                        "wr_item_sk", "wr_order_number",
                        "wr_return_quantity", "wr_return_amt")
    c = _q49_pd_channel(t, "catalog", "catalog_sales", "cs_item_sk",
                        "cs_order_number", "cs_sold_date_sk",
                        "cs_quantity", "cs_net_paid", "catalog_returns",
                        "cr_item_sk", "cr_order_number",
                        "cr_return_quantity", "cr_return_amount")
    s = _q49_pd_channel(t, "store", "store_sales", "ss_item_sk",
                        "ss_ticket_number", "ss_sold_date_sk",
                        "ss_quantity", "ss_net_paid", "store_returns",
                        "sr_item_sk", "sr_ticket_number",
                        "sr_return_quantity", "sr_return_amt")
    u = pd.concat([w, c, s], ignore_index=True).drop_duplicates()
    return (u.sort_values(["channel", "return_rank", "currency_rank",
                           "item"]).head(100).reset_index(drop=True))


QUERIES_EXT3.update({
    "q47": (q47, q47_pandas),
    "q49": (q49, q49_pandas),
    "q57": (q57, q57_pandas),
})


# ---------------------------------------------------------------------------
# q51 — web vs store cumulative daily revenue per item (running-sum +
# running-max windows over a FULL OUTER join)
# ---------------------------------------------------------------------------


def q51(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_month_seq") >= lit(24))
                  & (col("d_month_seq") <= lit(27)))
          .select("d_date_sk"))

    def daily(sales, item, date, price, tag):
        s = dfs[sales].select(col(item).alias(f"{tag}_item"),
                              col(date).alias("date_sk"),
                              col(price).alias("price"))
        s = s.join(dd, on=col("date_sk") == col("d_date_sk"),
                   how="left_semi")
        g = (s.group_by(f"{tag}_item", "date_sk")
             .agg(("sum", "price", f"{tag}_day")))
        return g.window([f"{tag}_item"], order_by=["date_sk"],
                        **{f"{tag}_cume": ("sum", f"{tag}_day")}) \
                .select(f"{tag}_item", col("date_sk").alias(f"{tag}_date"),
                        f"{tag}_cume")

    web = daily("web_sales", "ws_item_sk", "ws_sold_date_sk",
                "ws_sales_price", "web")
    store = daily("store_sales", "ss_item_sk", "ss_sold_date_sk",
                  "ss_sales_price", "store")
    j = web.join(store, on=(col("web_item") == col("store_item"))
                 & (col("web_date") == col("store_date")),
                 how="full_outer")
    item_sk = CaseWhen([(col("web_item").is_not_null(), col("web_item"))],
                       otherwise=col("store_item"))
    date_sk = CaseWhen([(col("web_date").is_not_null(), col("web_date"))],
                       otherwise=col("store_date"))
    j = j.select(item_sk.alias("item_sk"), date_sk.alias("d_date_sk2"),
                 "web_cume", "store_cume")
    j = j.window(["item_sk"], order_by=["d_date_sk2"],
                 web_cumulative=("max", "web_cume"),
                 store_cumulative=("max", "store_cume"))
    j = j.filter(col("web_cumulative") > col("store_cumulative"))
    return (j.select("item_sk", "d_date_sk2", "web_cumulative",
                     "store_cumulative")
            .sort("item_sk", "d_date_sk2").limit(100))


def q51_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_month_seq >= 24) & (d.d_month_seq <= 27)].d_date_sk

    def daily(sales, item, date, price, tag):
        s = t[sales]
        s = s[s[date].isin(dd)]
        g = (s.groupby([item, date], as_index=False)
             .agg(day=(price, "sum"))
             .rename(columns={item: f"{tag}_item", date: f"{tag}_date"}))
        g = g.sort_values([f"{tag}_item", f"{tag}_date"])
        g[f"{tag}_cume"] = g.groupby(f"{tag}_item").day.cumsum()
        return g[[f"{tag}_item", f"{tag}_date", f"{tag}_cume"]]

    web = daily("web_sales", "ws_item_sk", "ws_sold_date_sk",
                "ws_sales_price", "web")
    store = daily("store_sales", "ss_item_sk", "ss_sold_date_sk",
                  "ss_sales_price", "store")
    j = web.merge(store, how="outer",
                  left_on=["web_item", "web_date"],
                  right_on=["store_item", "store_date"])
    j["item_sk"] = j.web_item.fillna(j.store_item)
    j["d_date_sk2"] = j.web_date.fillna(j.store_date)
    j = j.sort_values(["item_sk", "d_date_sk2"], kind="stable")
    # SQL MAX OVER skips NULLs and carries the running max through them;
    # pandas cummax leaves NaN at NaN rows — forward-fill per partition.
    j["web_cumulative"] = j.groupby("item_sk").web_cume.cummax()
    j["web_cumulative"] = j.groupby("item_sk").web_cumulative.ffill()
    j["store_cumulative"] = j.groupby("item_sk").store_cume.cummax()
    j["store_cumulative"] = j.groupby("item_sk").store_cumulative.ffill()
    j = j[j.web_cumulative > j.store_cumulative]
    out = j[["item_sk", "d_date_sk2", "web_cumulative",
             "store_cumulative"]]
    return (out.sort_values(["item_sk", "d_date_sk2"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q58 — items with balanced revenue across all three channels for one
# report week (scalar-subquery week lookup)
# ---------------------------------------------------------------------------

_Q58_DATE = 740


def q58(dfs):
    # Official q58 brackets one report WEEK via a date_dim subquery; this
    # generator's weekly density is too thin for 3-channel overlap, so
    # the same scalar-subquery shape looks up the date's MONTH (and the
    # balance band widens 0.9/1.1 -> 0.7/1.3), oracle in lockstep.
    month = (dfs["date_dim"].filter(col("d_date_sk") == lit(_Q58_DATE))
             .select("d_month_seq").as_scalar())
    wk_days = (dfs["date_dim"].filter(col("d_month_seq") == month)
               .select("d_date_sk"))

    def rev(sales, item, date, price, tag):
        s = dfs[sales].select(col(item).alias("item_sk"),
                              col(date).alias("date_sk"),
                              col(price).alias("price"))
        s = s.join(wk_days, on=col("date_sk") == col("d_date_sk"),
                   how="left_semi")
        it = dfs["item"].select("i_item_sk", "i_item_id")
        s = s.join(it, on=col("item_sk") == col("i_item_sk"))
        return (s.group_by("i_item_id")
                .agg(("sum", "price", f"{tag}_rev"))
                .select(col("i_item_id").alias(f"{tag}_id"),
                        f"{tag}_rev"))

    ss = rev("store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_ext_sales_price", "ss")
    cs = rev("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_ext_sales_price", "cs")
    ws = rev("web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_ext_sales_price", "ws")
    j = ss.join(cs, on=col("ss_id") == col("cs_id"))
    j = j.join(ws, on=col("ss_id") == col("ws_id"))
    avg3 = ((col("ss_rev") + col("cs_rev") + col("ws_rev")) / lit(3.0))
    j = j.with_column("rev_avg", avg3)
    for c in ("ss_rev", "cs_rev", "ws_rev"):
        j = j.filter((col(c) >= col("rev_avg") * lit(0.7))
                     & (col(c) <= col("rev_avg") * lit(1.3)))
    return (j.select(col("ss_id").alias("item_id"), "ss_rev", "cs_rev",
                     "ws_rev", "rev_avg")
            .sort("item_id", "ss_rev").limit(100))


def q58_pandas(t):
    d = t["date_dim"]
    month = d[d.d_date_sk == _Q58_DATE].d_month_seq.iloc[0]
    wk_days = d[d.d_month_seq == month].d_date_sk

    def rev(sales, item, date, price, tag):
        s = t[sales]
        s = s[s[date].isin(wk_days)]
        it = t["item"][["i_item_sk", "i_item_id"]]
        s = s.merge(it, left_on=item, right_on="i_item_sk")
        return (s.groupby("i_item_id", as_index=False)
                .agg(**{f"{tag}_rev": (price, "sum")}))

    ss = rev("store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_ext_sales_price", "ss")
    cs = rev("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_ext_sales_price", "cs")
    ws = rev("web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_ext_sales_price", "ws")
    j = ss.merge(cs, on="i_item_id").merge(ws, on="i_item_id")
    j["rev_avg"] = (j.ss_rev + j.cs_rev + j.ws_rev) / 3.0
    for c in ("ss_rev", "cs_rev", "ws_rev"):
        j = j[(j[c] >= 0.7 * j.rev_avg) & (j[c] <= 1.3 * j.rev_avg)]
    j = j.rename(columns={"i_item_id": "item_id"})
    return (j[["item_id", "ss_rev", "cs_rev", "ws_rev", "rev_avg"]]
            .sort_values(["item_id", "ss_rev"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q66 — warehouse 12-month shipping pivot over web + catalog, by carrier
# ---------------------------------------------------------------------------


def _q66_channel(dfs, sales, date_col, time_col, sm_col, wh_col, price,
                 qty):
    s = dfs[sales].select(col(date_col).alias("date_sk"),
                          col(time_col).alias("time_sk"),
                          col(sm_col).alias("sm_sk"),
                          col(wh_col).alias("wh_sk"),
                          col(price).alias("price"),
                          col(qty).alias("qty"))
    dd = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk", "d_moy"))
    s = s.join(dd, on=col("date_sk") == col("d_date_sk"))
    # time keys are seconds-of-day in this generator: the official
    # t_hour-window time_dim join expresses directly as a range filter.
    s = s.filter((col("time_sk") >= lit(9 * 3600))
                 & (col("time_sk") < lit(18 * 3600)))
    sm = (dfs["ship_mode"]
          .filter(col("sm_carrier").isin("UPS", "FedEx"))
          .select("sm_ship_mode_sk"))
    s = s.join(sm, on=col("sm_sk") == col("sm_ship_mode_sk"),
               how="left_semi")
    w = dfs["warehouse"].select("w_warehouse_sk", "w_warehouse_name",
                                "w_warehouse_sq_ft", "w_city", "w_county",
                                "w_state", "w_country")
    s = s.join(w, on=col("wh_sk") == col("w_warehouse_sk"))
    aggs = []
    for m in range(1, 13):
        aggs.append(_sum_case(col("d_moy") == lit(m),
                              col("price") * col("qty"), f"m{m}_sales"))
    return (s.group_by("w_warehouse_name", "w_warehouse_sq_ft", "w_city",
                       "w_county", "w_state", "w_country")
            .agg(*aggs))


def q66(dfs):
    ws = _q66_channel(dfs, "web_sales", "ws_sold_date_sk",
                      "ws_sold_time_sk", "ws_ship_mode_sk",
                      "ws_warehouse_sk", "ws_ext_sales_price",
                      "ws_quantity")
    cs = _q66_channel(dfs, "catalog_sales", "cs_sold_date_sk",
                      "cs_sold_time_sk", "cs_ship_mode_sk",
                      "cs_warehouse_sk", "cs_sales_price", "cs_quantity")
    u = ws.union(cs)
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country"]
    aggs = [("sum", f"m{m}_sales", f"m{m}_sales") for m in range(1, 13)]
    return (u.group_by(*keys).agg(*aggs)
            .sort("w_warehouse_name").limit(100))


def _q66_pd_channel(t, sales, date_col, time_col, sm_col, wh_col, price,
                    qty):
    s = t[sales]
    d = t["date_dim"]
    dd = d[d.d_year == 2000][["d_date_sk", "d_moy"]]
    s = s.merge(dd, left_on=date_col, right_on="d_date_sk")
    s = s[(s[time_col] >= 9 * 3600) & (s[time_col] < 18 * 3600)]
    sm = t["ship_mode"]
    smm = sm[sm.sm_carrier.isin(["UPS", "FedEx"])].sm_ship_mode_sk
    s = s[s[sm_col].isin(smm)]
    w = t["warehouse"]
    s = s.merge(w, left_on=wh_col, right_on="w_warehouse_sk")
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country"]
    val = s[price] * s[qty]
    for m in range(1, 13):
        s[f"m{m}_sales"] = val.where(s.d_moy == m)
    return s.groupby(keys, as_index=False).agg(
        **{f"m{m}_sales": (f"m{m}_sales", lambda x: x.sum(min_count=1))
           for m in range(1, 13)})


def q66_pandas(t):
    ws = _q66_pd_channel(t, "web_sales", "ws_sold_date_sk",
                         "ws_sold_time_sk", "ws_ship_mode_sk",
                         "ws_warehouse_sk", "ws_ext_sales_price",
                         "ws_quantity")
    cs = _q66_pd_channel(t, "catalog_sales", "cs_sold_date_sk",
                         "cs_sold_time_sk", "cs_ship_mode_sk",
                         "cs_warehouse_sk", "cs_sales_price",
                         "cs_quantity")
    u = pd.concat([ws, cs], ignore_index=True)
    keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
            "w_state", "w_country"]
    out = u.groupby(keys, as_index=False).agg(
        **{f"m{m}_sales": (f"m{m}_sales",
                           lambda x: x.sum(min_count=1))
           for m in range(1, 13)})
    return (out.sort_values("w_warehouse_name").head(100)
            .reset_index(drop=True))


QUERIES_EXT3.update({
    "q51": (q51, q51_pandas),
    "q58": (q58, q58_pandas),
    "q66": (q66, q66_pandas),
})


# ---------------------------------------------------------------------------
# q72 — catalog orders vs inventory in the order's week (promo split)
# ---------------------------------------------------------------------------


def q72(dfs):
    cs = dfs["catalog_sales"].select(
        "cs_item_sk", "cs_sold_date_sk", "cs_ship_date_sk", "cs_promo_sk",
        "cs_bill_customer_sk", "cs_quantity", "cs_order_number")
    d1 = dfs["date_dim"].select("d_date_sk", "d_week_seq")
    j = cs.join(d1, on=col("cs_sold_date_sk") == col("d_date_sk"))
    hd = (dfs["household_demographics"]
          .filter(col("hd_buy_potential") == lit(">10000"))
          .select("hd_demo_sk"))
    cust = dfs["customer"].select("c_customer_sk", "c_current_hdemo_sk")
    j = j.join(cust, on=col("cs_bill_customer_sk") == col("c_customer_sk"))
    j = j.join(hd, on=col("c_current_hdemo_sk") == col("hd_demo_sk"),
               how="left_semi")
    inv = dfs["inventory"].select(
        col("inv_item_sk").alias("i_item"), "inv_warehouse_sk",
        "inv_quantity_on_hand", col("inv_date_sk").alias("inv_date"))
    d2 = dfs["date_dim"].select(col("d_date_sk").alias("d2_sk"),
                                col("d_week_seq").alias("inv_week"))
    inv = inv.join(d2, on=col("inv_date") == col("d2_sk"))
    j = j.join(inv, on=(col("cs_item_sk") == col("i_item"))
               & (col("d_week_seq") == col("inv_week")))
    j = j.filter(col("inv_quantity_on_hand") < col("cs_quantity"))
    # ship more than 3 days after sale (non-equi predicate as a filter)
    j = j.filter(col("cs_ship_date_sk") > col("cs_sold_date_sk") + lit(3))
    w = dfs["warehouse"].select("w_warehouse_sk", "w_warehouse_name")
    j = j.join(w, on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
    it = dfs["item"].select("i_item_sk", "i_item_desc")
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    p = dfs["promotion"].select(col("p_promo_sk").alias("pp_sk"))
    j = j.join(p, on=col("cs_promo_sk") == col("pp_sk"),
               how="left_outer")
    no_promo = CaseWhen([(col("pp_sk").is_null(), lit(1))],
                        otherwise=lit(0))
    promo = CaseWhen([(col("pp_sk").is_not_null(), lit(1))],
                     otherwise=lit(0))
    return (j.group_by("i_item_desc", "w_warehouse_name", "d_week_seq")
            .agg(("sum", no_promo, "no_promo"), ("sum", promo, "promo"),
                 ("count", "*", "total_cnt"))
            .sort("-total_cnt", "i_item_desc", "w_warehouse_name",
                  "d_week_seq").limit(100))


def q72_pandas(t):
    cs = t["catalog_sales"]
    d = t["date_dim"][["d_date_sk", "d_week_seq"]]
    j = cs.merge(d, left_on="cs_sold_date_sk", right_on="d_date_sk")
    hd = t["household_demographics"]
    hdd = hd[hd.hd_buy_potential == ">10000"].hd_demo_sk
    cust = t["customer"][["c_customer_sk", "c_current_hdemo_sk"]]
    j = j.merge(cust, left_on="cs_bill_customer_sk",
                right_on="c_customer_sk")
    j = j[j.c_current_hdemo_sk.isin(hdd)]
    inv = t["inventory"].merge(
        d.rename(columns={"d_date_sk": "d2_sk", "d_week_seq": "inv_week"}),
        left_on="inv_date_sk", right_on="d2_sk")
    j = j.merge(inv, left_on=["cs_item_sk", "d_week_seq"],
                right_on=["inv_item_sk", "inv_week"])
    j = j[j.inv_quantity_on_hand < j.cs_quantity]
    j = j[j.cs_ship_date_sk > j.cs_sold_date_sk + 3]
    j = j.merge(t["warehouse"][["w_warehouse_sk", "w_warehouse_name"]],
                left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_desc"]],
                left_on="cs_item_sk", right_on="i_item_sk")
    promos = set(t["promotion"].p_promo_sk)
    j = j.assign(promo=j.cs_promo_sk.isin(promos).astype(int))
    j["no_promo"] = 1 - j.promo
    out = j.groupby(["i_item_desc", "w_warehouse_name", "d_week_seq"],
                    as_index=False).agg(
        no_promo=("no_promo", "sum"), promo=("promo", "sum"),
        total_cnt=("promo", "count"))
    return (out.sort_values(["total_cnt", "i_item_desc",
                             "w_warehouse_name", "d_week_seq"],
                            ascending=[False, True, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q75 — yearly item-dimension sales (net of returns) vs prior year,
# manufacturers that shrank
# ---------------------------------------------------------------------------


def _q75_channel(dfs, sales, s_item, s_order, s_date, s_qty, s_price,
                 rets, r_item, r_order, r_qty, r_amt):
    s = dfs[sales].select(
        col(s_item).alias("item_sk"), col(s_order).alias("order_"),
        col(s_date).alias("date_sk"), col(s_qty).alias("qty"),
        col(s_price).alias("amt"))
    it = (dfs["item"].filter(col("i_category") == lit("Books"))
          .select("i_item_sk", "i_brand_id", "i_class",
                  "i_category_id", "i_manufact_id"))
    s = s.join(it, on=col("item_sk") == col("i_item_sk"))
    dd = dfs["date_dim"].select("d_date_sk", "d_year")
    s = s.join(dd, on=col("date_sk") == col("d_date_sk"))
    r = dfs[rets].select(
        col(r_item).alias("r_item"), col(r_order).alias("r_order"),
        col(r_qty).alias("r_qty"), col(r_amt).alias("r_amt"))
    s = s.join(r, on=(col("order_") == col("r_order"))
               & (col("item_sk") == col("r_item")), how="left_outer")
    net_q = (col("qty") - CaseWhen(
        [(col("r_qty").is_not_null(), col("r_qty"))], otherwise=lit(0)))
    net_a = (col("amt") - CaseWhen(
        [(col("r_amt").is_not_null(), col("r_amt"))],
        otherwise=lit(0.0)))
    return s.select("d_year", "i_brand_id", "i_class", "i_category_id",
                    "i_manufact_id", net_q.alias("sales_cnt"),
                    net_a.alias("sales_amt"))


def q75(dfs):
    cs = _q75_channel(dfs, "catalog_sales", "cs_item_sk",
                      "cs_order_number", "cs_sold_date_sk", "cs_quantity",
                      "cs_ext_sales_price", "catalog_returns",
                      "cr_item_sk", "cr_order_number",
                      "cr_return_quantity", "cr_return_amount")
    ss = _q75_channel(dfs, "store_sales", "ss_item_sk",
                      "ss_ticket_number", "ss_sold_date_sk", "ss_quantity",
                      "ss_ext_sales_price", "store_returns", "sr_item_sk",
                      "sr_ticket_number", "sr_return_quantity",
                      "sr_return_amt")
    ws = _q75_channel(dfs, "web_sales", "ws_item_sk", "ws_order_number",
                      "ws_sold_date_sk", "ws_quantity",
                      "ws_ext_sales_price", "web_returns", "wr_item_sk",
                      "wr_order_number", "wr_return_quantity",
                      "wr_return_amt")
    u = cs.union(ss).union(ws)
    keys = ["d_year", "i_brand_id", "i_class", "i_category_id",
            "i_manufact_id"]
    tot = u.group_by(*keys).agg(("sum", "sales_cnt", "sales_cnt"),
                                ("sum", "sales_amt", "sales_amt"))
    prev = tot.filter(col("d_year") == lit(1999)).select(
        *[col(k).alias(f"p_{k}") for k in keys],
        col("sales_cnt").alias("prev_cnt"),
        col("sales_amt").alias("prev_amt"))
    curr = tot.filter(col("d_year") == lit(2000))
    on = None
    for k in keys[1:]:
        e = col(k) == col(f"p_{k}")
        on = e if on is None else (on & e)
    j = curr.join(prev, on=on)
    j = j.filter((col("sales_cnt") * lit(10))
                 < (col("prev_cnt") * lit(9)))  # ratio < 0.9
    return (j.select(col("p_d_year").alias("prev_year"),
                     col("d_year").alias("year_"), "i_brand_id",
                     "i_class", "i_category_id", "i_manufact_id",
                     "prev_cnt", "sales_cnt", "prev_amt", "sales_amt")
            .sort("sales_cnt", "i_brand_id", "i_class",
                  "i_manufact_id").limit(100))


def _q75_pd_channel(t, sales, s_item, s_order, s_date, s_qty, s_price,
                    rets, r_item, r_order, r_qty, r_amt):
    s = t[sales]
    it = t["item"]
    it = it[it.i_category == "Books"][["i_item_sk", "i_brand_id",
                                      "i_class", "i_category_id",
                                      "i_manufact_id"]]
    s = s.merge(it, left_on=s_item, right_on="i_item_sk")
    d = t["date_dim"][["d_date_sk", "d_year"]]
    s = s.merge(d, left_on=s_date, right_on="d_date_sk")
    r = t[rets][[r_item, r_order, r_qty, r_amt]]
    s = s.merge(r, how="left", left_on=[s_order, s_item],
                right_on=[r_order, r_item])
    s["sales_cnt"] = s[s_qty] - s[r_qty].fillna(0)
    s["sales_amt"] = s[s_price] - s[r_amt].fillna(0.0)
    return s[["d_year", "i_brand_id", "i_class", "i_category_id",
              "i_manufact_id", "sales_cnt", "sales_amt"]]


def q75_pandas(t):
    cs = _q75_pd_channel(t, "catalog_sales", "cs_item_sk",
                         "cs_order_number", "cs_sold_date_sk",
                         "cs_quantity", "cs_ext_sales_price",
                         "catalog_returns", "cr_item_sk",
                         "cr_order_number", "cr_return_quantity",
                         "cr_return_amount")
    ss = _q75_pd_channel(t, "store_sales", "ss_item_sk",
                         "ss_ticket_number", "ss_sold_date_sk",
                         "ss_quantity", "ss_ext_sales_price",
                         "store_returns", "sr_item_sk",
                         "sr_ticket_number", "sr_return_quantity",
                         "sr_return_amt")
    ws = _q75_pd_channel(t, "web_sales", "ws_item_sk", "ws_order_number",
                         "ws_sold_date_sk", "ws_quantity",
                         "ws_ext_sales_price", "web_returns",
                         "wr_item_sk", "wr_order_number",
                         "wr_return_quantity", "wr_return_amt")
    u = pd.concat([cs, ss, ws], ignore_index=True)
    keys = ["d_year", "i_brand_id", "i_class", "i_category_id",
            "i_manufact_id"]
    tot = u.groupby(keys, as_index=False).agg(
        sales_cnt=("sales_cnt", "sum"), sales_amt=("sales_amt", "sum"))
    prev = tot[tot.d_year == 1999].rename(columns={
        "d_year": "prev_year", "sales_cnt": "prev_cnt",
        "sales_amt": "prev_amt"})
    curr = tot[tot.d_year == 2000]
    j = curr.merge(prev, on=keys[1:])
    j = j[j.sales_cnt * 10 < j.prev_cnt * 9]
    j = j.rename(columns={"d_year": "year_"})
    out = j[["prev_year", "year_", "i_brand_id", "i_class",
             "i_category_id", "i_manufact_id", "prev_cnt", "sales_cnt",
             "prev_amt", "sales_amt"]]
    return (out.sort_values(["sales_cnt", "i_brand_id", "i_class",
                             "i_manufact_id"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q76 — rows sold with NULL dimension keys, by channel
# ---------------------------------------------------------------------------


def q76(dfs):
    def channel(sales, null_col, item, date, price, label, col_name):
        s = (dfs[sales].filter(col(null_col).is_null())
             .select(col(item).alias("item_sk"),
                     col(date).alias("date_sk"),
                     col(price).alias("ext_sales_price")))
        it = dfs["item"].select("i_item_sk", "i_category")
        s = s.join(it, on=col("item_sk") == col("i_item_sk"))
        dd = dfs["date_dim"].select("d_date_sk", "d_year", "d_qoy")
        s = s.join(dd, on=col("date_sk") == col("d_date_sk"))
        return s.select(lit(label).alias("channel"),
                        lit(col_name).alias("col_name"), "d_year",
                        "d_qoy", "i_category", "ext_sales_price")

    ss = channel("store_sales", "ss_store_sk", "ss_item_sk",
                 "ss_sold_date_sk", "ss_ext_sales_price", "store",
                 "ss_store_sk")
    ws = channel("web_sales", "ws_ship_customer_sk", "ws_item_sk",
                 "ws_sold_date_sk", "ws_ext_sales_price", "web",
                 "ws_ship_customer_sk")
    cs = channel("catalog_sales", "cs_ship_addr_sk", "cs_item_sk",
                 "cs_sold_date_sk", "cs_ext_sales_price", "catalog",
                 "cs_ship_addr_sk")
    u = ss.union(ws).union(cs)
    return (u.group_by("channel", "col_name", "d_year", "d_qoy",
                       "i_category")
            .agg(("count", "*", "sales_cnt"),
                 ("sum", "ext_sales_price", "sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy", "i_category")
            .limit(100))


def q76_pandas(t):
    def channel(sales, null_col, item, date, price, label, col_name):
        s = t[sales]
        s = s[s[null_col].isna()]
        s = s.merge(t["item"][["i_item_sk", "i_category"]],
                    left_on=item, right_on="i_item_sk")
        s = s.merge(t["date_dim"][["d_date_sk", "d_year", "d_qoy"]],
                    left_on=date, right_on="d_date_sk")
        out = s[["d_year", "d_qoy", "i_category", price]].rename(
            columns={price: "ext_sales_price"})
        out.insert(0, "col_name", col_name)
        out.insert(0, "channel", label)
        return out

    u = pd.concat([
        channel("store_sales", "ss_store_sk", "ss_item_sk",
                "ss_sold_date_sk", "ss_ext_sales_price", "store",
                "ss_store_sk"),
        channel("web_sales", "ws_ship_customer_sk", "ws_item_sk",
                "ws_sold_date_sk", "ws_ext_sales_price", "web",
                "ws_ship_customer_sk"),
        channel("catalog_sales", "cs_ship_addr_sk", "cs_item_sk",
                "cs_sold_date_sk", "cs_ext_sales_price", "catalog",
                "cs_ship_addr_sk"),
    ], ignore_index=True)
    out = u.groupby(["channel", "col_name", "d_year", "d_qoy",
                     "i_category"], as_index=False).agg(
        sales_cnt=("ext_sales_price", "count"),
        sales_amt=("ext_sales_price", "sum"))
    return (out.sort_values(["channel", "col_name", "d_year", "d_qoy",
                             "i_category"]).head(100)
            .reset_index(drop=True))


QUERIES_EXT3.update({
    "q72": (q72, q72_pandas),
    "q75": (q75, q75_pandas),
    "q76": (q76, q76_pandas),
})


# ---------------------------------------------------------------------------
# q77 — per-channel profit ROLLUP (sales left-joined with returns totals)
# ---------------------------------------------------------------------------

_Q77_LO, _Q77_HI = 731, 760


def q77(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q77_LO))
                  & (col("d_date_sk") <= lit(_Q77_HI)))
          .select("d_date_sk"))

    def sums(table, date_col, key_col, alias_key, measures):
        s = dfs[table].join(
            dd, on=col(date_col) == col("d_date_sk"), how="left_semi")
        # Official q77 inner-joins each channel's dimension, which drops
        # NULL keys (ss_store_sk is nullable); the oracle's groupby does
        # the same.
        s = s.filter(col(key_col).is_not_null())
        aggs = [("sum", src, alias) for alias, src in measures.items()]
        return (s.group_by(key_col).agg(*aggs)
                .select(col(key_col).alias(alias_key),
                        *measures.keys()))

    ss = sums("store_sales", "ss_sold_date_sk", "ss_store_sk", "s_sk",
              {"sales": "ss_ext_sales_price", "profit": "ss_net_profit"})
    sr = sums("store_returns", "sr_returned_date_sk", "sr_store_sk",
              "r_sk", {"returns_": "sr_return_amt",
                       "profit_loss": "sr_net_loss"})
    st = ss.join(sr, on=col("s_sk") == col("r_sk"), how="left_outer")
    coal = lambda c, z: CaseWhen([(col(c).is_not_null(), col(c))],
                                 otherwise=lit(z))
    st = st.select(lit("store channel").alias("channel"),
                   col("s_sk").alias("id"), "sales",
                   coal("returns_", 0.0).alias("returns_"),
                   (col("profit")
                    - coal("profit_loss", 0.0)).alias("profit"))

    cs = sums("catalog_sales", "cs_sold_date_sk", "cs_call_center_sk",
              "cs_sk", {"sales": "cs_ext_sales_price",
                        "profit": "cs_net_profit"})
    cr = (dfs["catalog_returns"]
          .join(dd, on=col("cr_returned_date_sk") == col("d_date_sk"),
                how="left_semi")
          .agg(("sum", "cr_return_amount", "returns_"),
               ("sum", "cr_net_loss", "profit_loss")))
    ct = cs.join(cr, how="cross")
    ct = ct.select(lit("catalog channel").alias("channel"),
                   col("cs_sk").alias("id"), "sales",
                   coal("returns_", 0.0).alias("returns_"),
                   (col("profit")
                    - coal("profit_loss", 0.0)).alias("profit"))

    ws = sums("web_sales", "ws_sold_date_sk", "ws_web_page_sk", "w_sk",
              {"sales": "ws_ext_sales_price", "profit": "ws_net_profit"})
    wr = sums("web_returns", "wr_returned_date_sk", "wr_web_page_sk",
              "wr_sk", {"returns_": "wr_return_amt",
                        "profit_loss": "wr_net_loss"})
    wt = ws.join(wr, on=col("w_sk") == col("wr_sk"), how="left_outer")
    wt = wt.select(lit("web channel").alias("channel"),
                   col("w_sk").alias("id"), "sales",
                   coal("returns_", 0.0).alias("returns_"),
                   (col("profit")
                    - coal("profit_loss", 0.0)).alias("profit"))

    u = st.union(ct).union(wt)
    roll = _rollup_union(u, [("channel", "string"), ("id", "int64")],
                         {"sales": ("sum", "sales"),
                          "returns_": ("sum", "returns_"),
                          "profit": ("sum", "profit")}, u.session)
    return (roll.select("channel", "id", "sales", "returns_", "profit")
            .sort("channel", "id").limit(100))


def q77_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= _Q77_LO) & (d.d_date_sk <= _Q77_HI)].d_date_sk

    def sums(table, date_col, key_col, measures):
        s = t[table]
        s = s[s[date_col].isin(dd)]
        return s.groupby(key_col).agg(
            **{alias: (src, "sum") for alias, src in measures.items()})

    ss = sums("store_sales", "ss_sold_date_sk", "ss_store_sk",
              {"sales": "ss_ext_sales_price", "profit": "ss_net_profit"})
    sr = sums("store_returns", "sr_returned_date_sk", "sr_store_sk",
              {"returns_": "sr_return_amt", "profit_loss": "sr_net_loss"})
    st = ss.join(sr, how="left")
    st = pd.DataFrame({
        "channel": "store channel", "id": st.index,
        "sales": st.sales.values,
        "returns_": st.returns_.fillna(0.0).values,
        "profit": (st.profit - st.profit_loss.fillna(0.0)).values})

    cs = sums("catalog_sales", "cs_sold_date_sk", "cs_call_center_sk",
              {"sales": "cs_ext_sales_price", "profit": "cs_net_profit"})
    crt = t["catalog_returns"]
    crt = crt[crt.cr_returned_date_sk.isin(dd)]
    cr_ret = crt.cr_return_amount.sum(min_count=1)
    cr_loss = crt.cr_net_loss.sum(min_count=1)
    ct = pd.DataFrame({
        "channel": "catalog channel", "id": cs.index,
        "sales": cs.sales.values,
        "returns_": (0.0 if pd.isna(cr_ret) else cr_ret),
        "profit": (cs.profit
                   - (0.0 if pd.isna(cr_loss) else cr_loss)).values})

    ws = sums("web_sales", "ws_sold_date_sk", "ws_web_page_sk",
              {"sales": "ws_ext_sales_price", "profit": "ws_net_profit"})
    wr = sums("web_returns", "wr_returned_date_sk", "wr_web_page_sk",
              {"returns_": "wr_return_amt", "profit_loss": "wr_net_loss"})
    wt = ws.join(wr, how="left")
    wt = pd.DataFrame({
        "channel": "web channel", "id": wt.index,
        "sales": wt.sales.values,
        "returns_": wt.returns_.fillna(0.0).values,
        "profit": (wt.profit - wt.profit_loss.fillna(0.0)).values})

    u = pd.concat([st, ct, wt], ignore_index=True)
    leaf = u.groupby(["channel", "id"], as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid = u.groupby("channel", as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid["id"] = np.nan
    top = pd.DataFrame({"channel": [np.nan], "id": [np.nan],
                        "sales": [u.sales.sum()],
                        "returns_": [u.returns_.sum()],
                        "profit": [u.profit.sum()]})
    out = pd.concat([leaf, mid, top], ignore_index=True)
    return (out[["channel", "id", "sales", "returns_", "profit"]]
            .sort_values(["channel", "id"], na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q78 — yearly per-(item, customer) channel sums EXCLUDING returned rows,
# store vs web+catalog ratio
# ---------------------------------------------------------------------------


def _q78_channel(dfs, sales, s_item, s_cust, s_order, s_date, s_qty,
                 s_wc, s_sp, rets, r_item, r_order, tag):
    s = dfs[sales].select(
        col(s_item).alias("item"), col(s_cust).alias("cust"),
        col(s_order).alias("order_"), col(s_date).alias("date_sk"),
        col(s_qty).alias("qty"), col(s_wc).alias("wc"),
        col(s_sp).alias("sp"))
    r = dfs[rets].select(col(r_item).alias("r_item"),
                         col(r_order).alias("r_order"))
    s = s.join(r, on=(col("order_") == col("r_order"))
               & (col("item") == col("r_item")), how="left_anti")
    dd = dfs["date_dim"].select("d_date_sk", "d_year")
    s = s.join(dd, on=col("date_sk") == col("d_date_sk"))
    return (s.group_by("d_year", "item", "cust")
            .agg(("sum", "qty", f"{tag}_qty"), ("sum", "wc", f"{tag}_wc"),
                 ("sum", "sp", f"{tag}_sp"))
            .select(col("d_year").alias(f"{tag}_year"),
                    col("item").alias(f"{tag}_item"),
                    col("cust").alias(f"{tag}_cust"),
                    f"{tag}_qty", f"{tag}_wc", f"{tag}_sp"))


def q78(dfs):
    ss = _q78_channel(dfs, "store_sales", "ss_item_sk", "ss_customer_sk",
                      "ss_ticket_number", "ss_sold_date_sk",
                      "ss_quantity", "ss_wholesale_cost",
                      "ss_sales_price", "store_returns", "sr_item_sk",
                      "sr_ticket_number", "ss")
    ws = _q78_channel(dfs, "web_sales", "ws_item_sk",
                      "ws_bill_customer_sk", "ws_order_number",
                      "ws_sold_date_sk", "ws_quantity",
                      "ws_wholesale_cost", "ws_sales_price",
                      "web_returns", "wr_item_sk", "wr_order_number",
                      "ws")
    cs = _q78_channel(dfs, "catalog_sales", "cs_item_sk",
                      "cs_bill_customer_sk", "cs_order_number",
                      "cs_sold_date_sk", "cs_quantity",
                      "cs_list_price", "cs_sales_price",
                      "catalog_returns", "cr_item_sk", "cr_order_number",
                      "cs")
    j = ss.join(ws, on=(col("ss_year") == col("ws_year"))
                & (col("ss_item") == col("ws_item"))
                & (col("ss_cust") == col("ws_cust")), how="left_outer")
    j = j.join(cs, on=(col("ss_year") == col("cs_year"))
               & (col("ss_item") == col("cs_item"))
               & (col("ss_cust") == col("cs_cust")), how="left_outer")
    coal = lambda c: CaseWhen([(col(c).is_not_null(), col(c))],
                              otherwise=lit(0))
    other = (coal("ws_qty") + coal("cs_qty"))
    j = j.with_column("other_chan_qty", other)
    j = j.filter((col("ss_year") == lit(2000))
                 & (col("other_chan_qty") > lit(0)))
    j = j.with_column("ratio", col("ss_qty") / col("other_chan_qty"))
    return (j.select("ss_year", "ss_item", "ss_cust", "ratio", "ss_qty",
                     "ss_wc", "ss_sp", "other_chan_qty")
            .sort("-ss_qty", "-ss_wc", "-ss_sp", "ss_item", "ss_cust")
            .limit(100))


def _q78_pd_channel(t, sales, s_item, s_cust, s_order, s_date, s_qty,
                    s_wc, s_sp, rets, r_item, r_order, tag):
    s = t[sales]
    r = t[rets][[r_item, r_order]].drop_duplicates()
    m = s.merge(r, how="left", left_on=[s_order, s_item],
                right_on=[r_order, r_item], indicator=True)
    m = m[m._merge == "left_only"]
    d = t["date_dim"][["d_date_sk", "d_year"]]
    m = m.merge(d, left_on=s_date, right_on="d_date_sk")
    g = m.groupby(["d_year", s_item, s_cust], as_index=False).agg(
        **{f"{tag}_qty": (s_qty, "sum"), f"{tag}_wc": (s_wc, "sum"),
           f"{tag}_sp": (s_sp, "sum")})
    return g.rename(columns={"d_year": f"{tag}_year",
                             s_item: f"{tag}_item",
                             s_cust: f"{tag}_cust"})


def q78_pandas(t):
    ss = _q78_pd_channel(t, "store_sales", "ss_item_sk",
                         "ss_customer_sk", "ss_ticket_number",
                         "ss_sold_date_sk", "ss_quantity",
                         "ss_wholesale_cost", "ss_sales_price",
                         "store_returns", "sr_item_sk",
                         "sr_ticket_number", "ss")
    ws = _q78_pd_channel(t, "web_sales", "ws_item_sk",
                         "ws_bill_customer_sk", "ws_order_number",
                         "ws_sold_date_sk", "ws_quantity",
                         "ws_wholesale_cost", "ws_sales_price",
                         "web_returns", "wr_item_sk", "wr_order_number",
                         "ws")
    cs = _q78_pd_channel(t, "catalog_sales", "cs_item_sk",
                         "cs_bill_customer_sk", "cs_order_number",
                         "cs_sold_date_sk", "cs_quantity",
                         "cs_list_price", "cs_sales_price",
                         "catalog_returns", "cr_item_sk",
                         "cr_order_number", "cs")
    j = ss.merge(ws, how="left",
                 left_on=["ss_year", "ss_item", "ss_cust"],
                 right_on=["ws_year", "ws_item", "ws_cust"])
    j = j.merge(cs, how="left",
                left_on=["ss_year", "ss_item", "ss_cust"],
                right_on=["cs_year", "cs_item", "cs_cust"])
    j["other_chan_qty"] = j.ws_qty.fillna(0) + j.cs_qty.fillna(0)
    j = j[(j.ss_year == 2000) & (j.other_chan_qty > 0)]
    j["ratio"] = j.ss_qty / j.other_chan_qty
    out = j[["ss_year", "ss_item", "ss_cust", "ratio", "ss_qty", "ss_wc",
             "ss_sp", "other_chan_qty"]]
    return (out.sort_values(["ss_qty", "ss_wc", "ss_sp", "ss_item",
                             "ss_cust"],
                            ascending=[False, False, False, True, True])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q83 — returned quantities per item across the 3 channels for the weeks
# of three report dates
# ---------------------------------------------------------------------------

_Q83_DATES = (740, 780, 820)


def q83(dfs):
    d = dfs["date_dim"]
    weeks = (d.filter(col("d_date_sk").isin(*[lit(x) for x in _Q83_DATES]))
             .select("d_week_seq"))
    days = (d.select(col("d_date_sk").alias("wk_date"), "d_week_seq")
            .join(weeks, on="d_week_seq", how="left_semi"))

    def rets(table, r_item, r_date, r_qty, tag):
        r = dfs[table].select(col(r_item).alias("item_sk"),
                              col(r_date).alias("date_sk"),
                              col(r_qty).alias("qty"))
        r = r.join(days, on=col("date_sk") == col("wk_date"),
                   how="left_semi")
        it = dfs["item"].select("i_item_sk", "i_item_id")
        r = r.join(it, on=col("item_sk") == col("i_item_sk"))
        return (r.group_by("i_item_id")
                .agg(("sum", "qty", f"{tag}_qty"))
                .select(col("i_item_id").alias(f"{tag}_id"),
                        f"{tag}_qty"))

    sr = rets("store_returns", "sr_item_sk", "sr_returned_date_sk",
              "sr_return_quantity", "sr")
    cr = rets("catalog_returns", "cr_item_sk", "cr_returned_date_sk",
              "cr_return_quantity", "cr")
    wr = rets("web_returns", "wr_item_sk", "wr_returned_date_sk",
              "wr_return_quantity", "wr")
    j = sr.join(cr, on=col("sr_id") == col("cr_id"))
    j = j.join(wr, on=col("sr_id") == col("wr_id"))
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty"))
    j = j.with_column("total_qty", total)
    j = j.with_column("average", col("total_qty") / lit(3.0))
    return (j.select(col("sr_id").alias("item_id"), "sr_qty", "cr_qty",
                     "wr_qty", "average")
            .sort("item_id", "sr_qty").limit(100))


def q83_pandas(t):
    d = t["date_dim"]
    weeks = d[d.d_date_sk.isin(_Q83_DATES)].d_week_seq
    days = d[d.d_week_seq.isin(weeks)].d_date_sk

    def rets(table, r_item, r_date, r_qty, tag):
        r = t[table]
        r = r[r[r_date].isin(days)]
        r = r.merge(t["item"][["i_item_sk", "i_item_id"]],
                    left_on=r_item, right_on="i_item_sk")
        return (r.groupby("i_item_id", as_index=False)
                .agg(**{f"{tag}_qty": (r_qty, "sum")}))

    sr = rets("store_returns", "sr_item_sk", "sr_returned_date_sk",
              "sr_return_quantity", "sr")
    cr = rets("catalog_returns", "cr_item_sk", "cr_returned_date_sk",
              "cr_return_quantity", "cr")
    wr = rets("web_returns", "wr_item_sk", "wr_returned_date_sk",
              "wr_return_quantity", "wr")
    j = sr.merge(cr, on="i_item_id").merge(wr, on="i_item_id")
    j["average"] = (j.sr_qty + j.cr_qty + j.wr_qty) / 3.0
    j = j.rename(columns={"i_item_id": "item_id"})
    return (j[["item_id", "sr_qty", "cr_qty", "wr_qty", "average"]]
            .sort_values(["item_id", "sr_qty"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q91 — call-center catalog-return losses by manager/demographics
# ---------------------------------------------------------------------------


def q91(dfs):
    cr = dfs["catalog_returns"].select("cr_call_center_sk",
                                       "cr_returned_date_sk",
                                       "cr_returning_customer_sk",
                                       "cr_net_loss")
    # Official q91 brackets one month; the generator's catalog-return
    # density needs a quarter for a non-empty report at test scales.
    dd = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_qoy") == lit(4)))
          .select("d_date_sk"))
    j = cr.join(dd, on=col("cr_returned_date_sk") == col("d_date_sk"),
                how="left_semi")
    cc = dfs["call_center"].select("cc_call_center_sk", "cc_call_center_id",
                                   "cc_name", "cc_manager")
    j = j.join(cc, on=col("cr_call_center_sk") == col("cc_call_center_sk"))
    cust = dfs["customer"].select("c_customer_sk", "c_current_cdemo_sk",
                                  "c_current_hdemo_sk",
                                  "c_current_addr_sk")
    j = j.join(cust,
               on=col("cr_returning_customer_sk") == col("c_customer_sk"))
    cd = (dfs["customer_demographics"]
          .filter(((col("cd_marital_status") == lit("M"))
                   & (col("cd_education_status") == lit("Primary")))
                  | ((col("cd_marital_status") == lit("S"))
                     & (col("cd_education_status") == lit("College")))
                  | ((col("cd_marital_status") == lit("W"))
                     & (col("cd_education_status")
                        == lit("Advanced Degree"))))
          .select("cd_demo_sk", "cd_marital_status",
                  "cd_education_status"))
    j = j.join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
    hd = (dfs["household_demographics"]
          .filter(col("hd_buy_potential").isin("unknown", ">10000"))
          .select("hd_demo_sk"))
    j = j.join(hd, on=col("c_current_hdemo_sk") == col("hd_demo_sk"),
               how="left_semi")
    ca = (dfs["customer_address"]
          .filter(col("ca_gmt_offset") == lit(-5.0))
          .select("ca_address_sk"))
    j = j.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    return (j.group_by("cc_call_center_id", "cc_name", "cc_manager",
                       "cd_marital_status", "cd_education_status")
            .agg(("sum", "cr_net_loss", "returns_loss"))
            .sort("-returns_loss", "cc_call_center_id").limit(100))


def q91_pandas(t):
    cr = t["catalog_returns"]
    d = t["date_dim"]
    dd = d[(d.d_year == 2000) & (d.d_qoy == 4)].d_date_sk
    j = cr[cr.cr_returned_date_sk.isin(dd)]
    j = j.merge(t["call_center"], left_on="cr_call_center_sk",
                right_on="cc_call_center_sk")
    j = j.merge(t["customer"], left_on="cr_returning_customer_sk",
                right_on="c_customer_sk")
    cd = t["customer_demographics"]
    cd = cd[((cd.cd_marital_status == "M")
             & (cd.cd_education_status == "Primary"))
            | ((cd.cd_marital_status == "S")
               & (cd.cd_education_status == "College"))
            | ((cd.cd_marital_status == "W")
               & (cd.cd_education_status == "Advanced Degree"))]
    j = j.merge(cd[["cd_demo_sk", "cd_marital_status",
                    "cd_education_status"]],
                left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    hd = t["household_demographics"]
    j = j[j.c_current_hdemo_sk.isin(
        hd[hd.hd_buy_potential.isin(["unknown", ">10000"])].hd_demo_sk)]
    ca = t["customer_address"]
    j = j[j.c_current_addr_sk.isin(
        ca[ca.ca_gmt_offset == -5.0].ca_address_sk)]
    out = j.groupby(["cc_call_center_id", "cc_name", "cc_manager",
                     "cd_marital_status", "cd_education_status"],
                    as_index=False).agg(
        returns_loss=("cr_net_loss", "sum"))
    return (out.sort_values(["returns_loss", "cc_call_center_id"],
                            ascending=[False, True]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q95 — web orders shipped from multiple warehouses AND returned (q94's
# sibling: both probes are IN-subqueries)
# ---------------------------------------------------------------------------


def q95(dfs):
    ws = dfs["web_sales"].select(
        "ws_order_number", "ws_ship_date_sk", "ws_ship_addr_sk",
        "ws_web_site_sk", "ws_ext_ship_cost", "ws_net_profit")
    d = (dfs["date_dim"].filter((col("d_date_sk") >= lit(730))
                                & (col("d_date_sk") <= lit(790)))
         .select("d_date_sk"))
    ca = (dfs["customer_address"].filter(col("ca_state") == lit("TX"))
          .select("ca_address_sk"))
    web = (dfs["web_site"].filter(col("web_company_name") == lit("pri"))
           .select("web_site_sk"))
    # ws_wh: orders shipped from >1 warehouse (ws1/ws2 self-join form)
    multi_wh = (dfs["web_sales"]
                .select("ws_order_number", "ws_warehouse_sk")
                .group_by("ws_order_number")
                .agg(("count_distinct", "ws_warehouse_sk", "nwh"))
                .filter(col("nwh") > lit(1))
                .select(col("ws_order_number").alias("mw_order")))
    # returned multi-warehouse orders
    wr_orders = (dfs["web_returns"]
                 .select(col("wr_order_number").alias("ret_order"))
                 .join(multi_wh, on=col("ret_order") == col("mw_order"),
                       how="left_semi"))
    j = ws.join(d, on=col("ws_ship_date_sk") == col("d_date_sk"),
                how="left_semi")
    j = j.join(ca, on=col("ws_ship_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    j = j.join(web, on=col("ws_web_site_sk") == col("web_site_sk"),
               how="left_semi")
    j = j.join(multi_wh, on=col("ws_order_number") == col("mw_order"),
               how="left_semi")
    j = j.join(wr_orders, on=col("ws_order_number") == col("ret_order"),
               how="left_semi")
    return j.agg(("count_distinct", "ws_order_number", "order_count"),
                 ("sum", "ws_ext_ship_cost", "total_shipping_cost"),
                 ("sum", "ws_net_profit", "total_net_profit"))


def q95_pandas(t):
    ws = t["web_sales"]
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= 730) & (d.d_date_sk <= 790)].d_date_sk
    ca = t["customer_address"]
    caa = ca[ca.ca_state == "TX"].ca_address_sk
    web = t["web_site"]
    webb = web[web.web_company_name == "pri"].web_site_sk
    nwh = ws.groupby("ws_order_number").ws_warehouse_sk.nunique()
    multi = set(nwh[nwh > 1].index)
    wr = t["web_returns"]
    ret_multi = set(wr[wr.wr_order_number.isin(multi)].wr_order_number)
    j = ws[ws.ws_ship_date_sk.isin(dd) & ws.ws_ship_addr_sk.isin(caa)
           & ws.ws_web_site_sk.isin(webb)
           & ws.ws_order_number.isin(multi)
           & ws.ws_order_number.isin(ret_multi)]
    return pd.DataFrame({
        "order_count": [j.ws_order_number.nunique()],
        "total_shipping_cost": [j.ws_ext_ship_cost.sum(min_count=1)],
        "total_net_profit": [j.ws_net_profit.sum(min_count=1)]})


QUERIES_EXT3.update({
    "q77": (q77, q77_pandas),
    "q78": (q78, q78_pandas),
    "q83": (q83, q83_pandas),
    "q91": (q91, q91_pandas),
    "q95": (q95, q95_pandas),
})


# ---------------------------------------------------------------------------
# q80 — 3-channel sales/returns/profit ROLLUP with promotion filter
# ---------------------------------------------------------------------------

_Q80_LO, _Q80_HI = 731, 760


def q80(dfs):
    dd = (dfs["date_dim"]
          .filter((col("d_date_sk") >= lit(_Q80_LO))
                  & (col("d_date_sk") <= lit(_Q80_HI)))
          .select("d_date_sk"))
    it = (dfs["item"].filter(col("i_current_price") > lit(50))
          .select("i_item_sk"))
    pr = (dfs["promotion"].filter(col("p_channel_tv") == lit("N"))
          .select("p_promo_sk"))

    def channel(sales, s_date, s_item, s_promo, s_key, s_price, s_profit,
                rets, r_key_cols, s_key_cols, r_amt, r_loss, dim, dim_sk,
                dim_id, label):
        s = dfs[sales]
        s = s.join(dd, on=col(s_date) == col("d_date_sk"), how="left_semi")
        s = s.join(it, on=col(s_item) == col("i_item_sk"), how="left_semi")
        s = s.join(pr, on=col(s_promo) == col("p_promo_sk"),
                   how="left_semi")
        r = dfs[rets].select(*[col(c).alias(f"r{i}")
                               for i, c in enumerate(r_key_cols)],
                             col(r_amt).alias("ret_amt"),
                             col(r_loss).alias("ret_loss"))
        on = None
        for i, c in enumerate(s_key_cols):
            e = col(c) == col(f"r{i}")
            on = e if on is None else (on & e)
        s = s.join(r, on=on, how="left_outer")
        coal = lambda c, z: CaseWhen([(col(c).is_not_null(), col(c))],
                                     otherwise=lit(z))
        dmf = dfs[dim].select(col(dim_sk).alias("dim_sk"),
                              col(dim_id).alias("id"))
        s = s.join(dmf, on=col(s_key) == col("dim_sk"))
        return (s.group_by("id")
                .agg(("sum", s_price, "sales"),
                     ("sum", coal("ret_amt", 0.0), "returns_"),
                     ("sum", col(s_profit) - coal("ret_loss", 0.0),
                      "profit"))
                .with_column("channel", lit(label)))

    st = channel("store_sales", "ss_sold_date_sk", "ss_item_sk",
                 "ss_promo_sk", "ss_store_sk", "ss_ext_sales_price",
                 "ss_net_profit", "store_returns",
                 ["sr_item_sk", "sr_ticket_number"],
                 ["ss_item_sk", "ss_ticket_number"], "sr_return_amt",
                 "sr_net_loss", "store", "s_store_sk", "s_store_id",
                 "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_promo_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit", "catalog_returns",
                 ["cr_item_sk", "cr_order_number"],
                 ["cs_item_sk", "cs_order_number"], "cr_return_amount",
                 "cr_net_loss", "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_promo_sk", "ws_web_site_sk", "ws_ext_sales_price",
                 "ws_net_profit", "web_returns",
                 ["wr_item_sk", "wr_order_number"],
                 ["ws_item_sk", "ws_order_number"], "wr_return_amt",
                 "wr_net_loss", "web_site", "web_site_sk", "web_site_id",
                 "web channel")
    u = st.union(ct).union(wt)
    roll = _rollup_union(u, [("channel", "string"), ("id", "string")],
                         {"sales": ("sum", "sales"),
                          "returns_": ("sum", "returns_"),
                          "profit": ("sum", "profit")}, u.session)
    return (roll.select("channel", "id", "sales", "returns_", "profit")
            .sort("channel", "id").limit(100))


def q80_pandas(t):
    d = t["date_dim"]
    dd = d[(d.d_date_sk >= _Q80_LO) & (d.d_date_sk <= _Q80_HI)].d_date_sk
    it = t["item"]
    itt = it[it.i_current_price > 50].i_item_sk
    pr = t["promotion"]
    prr = pr[pr.p_channel_tv == "N"].p_promo_sk

    def channel(sales, s_date, s_item, s_promo, s_key, s_price, s_profit,
                rets, r_key_cols, s_key_cols, r_amt, r_loss, dim, dim_sk,
                dim_id, label):
        s = t[sales]
        s = s[s[s_date].isin(dd) & s[s_item].isin(itt)
              & s[s_promo].isin(prr)]
        r = t[rets][r_key_cols + [r_amt, r_loss]]
        s = s.merge(r, how="left", left_on=s_key_cols,
                    right_on=r_key_cols)
        dmf = t[dim][[dim_sk, dim_id]]
        s = s.merge(dmf, left_on=s_key, right_on=dim_sk)
        g = s.groupby(dim_id).agg(
            sales=(s_price, "sum"))
        g["returns_"] = s.assign(v=s[r_amt].fillna(0.0)) \
            .groupby(dim_id).v.sum()
        g["profit"] = (s.assign(v=s[s_profit] - s[r_loss].fillna(0.0))
                       .groupby(dim_id).v.sum())
        g = g.reset_index(names="id")
        g["channel"] = label
        return g

    st = channel("store_sales", "ss_sold_date_sk", "ss_item_sk",
                 "ss_promo_sk", "ss_store_sk", "ss_ext_sales_price",
                 "ss_net_profit", "store_returns",
                 ["sr_item_sk", "sr_ticket_number"],
                 ["ss_item_sk", "ss_ticket_number"], "sr_return_amt",
                 "sr_net_loss", "store", "s_store_sk", "s_store_id",
                 "store channel")
    ct = channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_promo_sk", "cs_catalog_page_sk",
                 "cs_ext_sales_price", "cs_net_profit",
                 "catalog_returns", ["cr_item_sk", "cr_order_number"],
                 ["cs_item_sk", "cs_order_number"], "cr_return_amount",
                 "cr_net_loss", "catalog_page", "cp_catalog_page_sk",
                 "cp_catalog_page_id", "catalog channel")
    wt = channel("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_promo_sk", "ws_web_site_sk", "ws_ext_sales_price",
                 "ws_net_profit", "web_returns",
                 ["wr_item_sk", "wr_order_number"],
                 ["ws_item_sk", "ws_order_number"], "wr_return_amt",
                 "wr_net_loss", "web_site", "web_site_sk", "web_site_id",
                 "web channel")
    u = pd.concat([st, ct, wt], ignore_index=True)
    leaf = u.groupby(["channel", "id"], as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid = u.groupby("channel", as_index=False).agg(
        sales=("sales", "sum"), returns_=("returns_", "sum"),
        profit=("profit", "sum"))
    mid["id"] = np.nan
    top = pd.DataFrame({"channel": [np.nan], "id": [np.nan],
                        "sales": [u.sales.sum()],
                        "returns_": [u.returns_.sum()],
                        "profit": [u.profit.sum()]})
    out = pd.concat([leaf, mid, top], ignore_index=True)
    return (out[["channel", "id", "sales", "returns_", "profit"]]
            .sort_values(["channel", "id"], na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q85 — web returns by reason with paired demographics and price bands
# ---------------------------------------------------------------------------


def q85(dfs):
    wr = dfs["web_returns"].select(
        "wr_item_sk", "wr_order_number", "wr_refunded_cdemo_sk",
        "wr_returning_cdemo_sk", "wr_refunded_addr_sk", "wr_reason_sk",
        "wr_return_quantity", "wr_refunded_cash", "wr_fee",
        "wr_web_page_sk")
    ws = dfs["web_sales"].select(
        col("ws_item_sk").alias("s_item"),
        col("ws_order_number").alias("s_order"), "ws_quantity",
        "ws_sales_price", "ws_net_profit", "ws_sold_date_sk")
    j = wr.join(ws, on=(col("wr_item_sk") == col("s_item"))
                & (col("wr_order_number") == col("s_order")))
    dd = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    j = j.join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"),
               how="left_semi")
    wp = dfs["web_page"].select("wp_web_page_sk")
    j = j.join(wp, on=col("wr_web_page_sk") == col("wp_web_page_sk"),
               how="left_semi")
    cd1 = dfs["customer_demographics"].select(
        col("cd_demo_sk").alias("cd1_sk"),
        col("cd_marital_status").alias("cd1_ms"),
        col("cd_education_status").alias("cd1_es"))
    cd2 = dfs["customer_demographics"].select(
        col("cd_demo_sk").alias("cd2_sk"),
        col("cd_marital_status").alias("cd2_ms"),
        col("cd_education_status").alias("cd2_es"))
    j = j.join(cd1, on=col("wr_refunded_cdemo_sk") == col("cd1_sk"))
    j = j.join(cd2, on=col("wr_returning_cdemo_sk") == col("cd2_sk"))
    j = j.filter((col("cd1_ms") == col("cd2_ms"))
                 & (col("cd1_es") == col("cd2_es")))
    band = (((col("cd1_ms") == lit("M")) & (col("cd1_es") == lit("College"))
             & (col("ws_sales_price") >= lit(100.0)))
            | ((col("cd1_ms") == lit("S"))
               & (col("cd1_es") == lit("Primary"))
               & (col("ws_sales_price") < lit(100.0)))
            | ((col("cd1_ms") == lit("W"))
               & (col("cd1_es") == lit("2 yr Degree"))))
    j = j.filter(band)
    ca = (dfs["customer_address"]
          .filter(col("ca_country") == lit("United States"))
          .select("ca_address_sk"))
    j = j.join(ca, on=col("wr_refunded_addr_sk") == col("ca_address_sk"),
               how="left_semi")
    r = dfs["reason"].select("r_reason_sk", "r_reason_desc")
    j = j.join(r, on=col("wr_reason_sk") == col("r_reason_sk"))
    return (j.group_by("r_reason_desc")
            .agg(("avg", "wr_return_quantity", "avg_qty"),
                 ("avg", "wr_refunded_cash", "avg_cash"),
                 ("avg", "wr_fee", "avg_fee"))
            .sort("r_reason_desc").limit(100))


def q85_pandas(t):
    wr = t["web_returns"]
    ws = t["web_sales"]
    j = wr.merge(ws, left_on=["wr_item_sk", "wr_order_number"],
                 right_on=["ws_item_sk", "ws_order_number"])
    d = t["date_dim"]
    dd = d[d.d_year == 2000].d_date_sk
    j = j[j.ws_sold_date_sk.isin(dd)]
    j = j[j.wr_web_page_sk.isin(t["web_page"].wp_web_page_sk)]
    cd = t["customer_demographics"]
    cd1 = cd[["cd_demo_sk", "cd_marital_status", "cd_education_status"]] \
        .rename(columns={"cd_demo_sk": "cd1_sk",
                         "cd_marital_status": "cd1_ms",
                         "cd_education_status": "cd1_es"})
    cd2 = cd[["cd_demo_sk", "cd_marital_status", "cd_education_status"]] \
        .rename(columns={"cd_demo_sk": "cd2_sk",
                         "cd_marital_status": "cd2_ms",
                         "cd_education_status": "cd2_es"})
    j = j.merge(cd1, left_on="wr_refunded_cdemo_sk", right_on="cd1_sk")
    j = j.merge(cd2, left_on="wr_returning_cdemo_sk", right_on="cd2_sk")
    j = j[(j.cd1_ms == j.cd2_ms) & (j.cd1_es == j.cd2_es)]
    band = (((j.cd1_ms == "M") & (j.cd1_es == "College")
             & (j.ws_sales_price >= 100.0))
            | ((j.cd1_ms == "S") & (j.cd1_es == "Primary")
               & (j.ws_sales_price < 100.0))
            | ((j.cd1_ms == "W") & (j.cd1_es == "2 yr Degree")))
    j = j[band]
    ca = t["customer_address"]
    j = j[j.wr_refunded_addr_sk.isin(
        ca[ca.ca_country == "United States"].ca_address_sk)]
    j = j.merge(t["reason"], left_on="wr_reason_sk",
                right_on="r_reason_sk")
    out = j.groupby("r_reason_desc", as_index=False).agg(
        avg_qty=("wr_return_quantity", "mean"),
        avg_cash=("wr_refunded_cash", "mean"),
        avg_fee=("wr_fee", "mean"))
    return (out.sort_values("r_reason_desc").head(100)
            .reset_index(drop=True))


QUERIES_EXT3.update({
    "q80": (q80, q80_pandas),
    "q85": (q85, q85_pandas),
})


# ---------------------------------------------------------------------------
# q24 — paired store-sales/returns net-paid by color vs 5% of the average
# (scalar subquery over the shared ssales subtree)
# ---------------------------------------------------------------------------


def _q24_ssales(dfs):
    ss = dfs["store_sales"].select("ss_ticket_number", "ss_item_sk",
                                   "ss_store_sk", "ss_customer_sk",
                                   "ss_net_paid")
    sr = dfs["store_returns"].select(
        col("sr_ticket_number").alias("r_ticket"),
        col("sr_item_sk").alias("r_item"))
    j = ss.join(sr, on=(col("ss_ticket_number") == col("r_ticket"))
                & (col("ss_item_sk") == col("r_item")))
    st = dfs["store"].select("s_store_sk", "s_store_name", "s_market_id")
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.filter(col("s_market_id") <= lit(5))
    it = dfs["item"].select("i_item_sk", "i_color")
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    c = dfs["customer"].select("c_customer_sk", "c_first_name",
                               "c_last_name", "c_birth_country")
    j = j.join(c, on=col("ss_customer_sk") == col("c_customer_sk"))
    j = j.filter(col("c_birth_country") != lit("UNITED STATES"))
    return (j.group_by("c_last_name", "c_first_name", "s_store_name",
                       "i_color")
            .agg(("sum", "ss_net_paid", "netpaid")))


def q24(dfs):
    ssales = _q24_ssales(dfs)
    avg_paid = _q24_ssales(dfs).agg(("avg", "netpaid", "a")).as_scalar()
    j = ssales.filter(col("i_color") == lit("red"))
    j = j.filter(col("netpaid") > avg_paid * lit(0.05))
    return (j.group_by("c_last_name", "c_first_name", "s_store_name")
            .agg(("sum", "netpaid", "paid"))
            .sort("c_last_name", "c_first_name", "s_store_name")
            .limit(100))


def q24_pandas(t):
    ss = t["store_sales"]
    sr = t["store_returns"][["sr_ticket_number", "sr_item_sk"]]
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"])
    st = t["store"]
    j = j.merge(st[st.s_market_id <= 5][["s_store_sk", "s_store_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_color"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    c = t["customer"]
    j = j.merge(c[["c_customer_sk", "c_first_name", "c_last_name",
                   "c_birth_country"]],
                left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j[j.c_birth_country != "UNITED STATES"]
    ssales = j.groupby(["c_last_name", "c_first_name", "s_store_name",
                        "i_color"], as_index=False).agg(
        netpaid=("ss_net_paid", "sum"))
    avg_paid = ssales.netpaid.mean()
    k = ssales[(ssales.i_color == "red")
               & (ssales.netpaid > 0.05 * avg_paid)]
    out = k.groupby(["c_last_name", "c_first_name", "s_store_name"],
                    as_index=False).agg(paid=("netpaid", "sum"))
    return (out.sort_values(["c_last_name", "c_first_name",
                             "s_store_name"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q23 — catalog+web sales of frequent items to the best store customers
# (two scalar subqueries + semi joins)
# ---------------------------------------------------------------------------


def q23(dfs):
    dd_years = (dfs["date_dim"]
                .filter((col("d_year") >= lit(1999))
                        & (col("d_year") <= lit(2001)))
                .select("d_date_sk"))
    ss = dfs["store_sales"].select("ss_item_sk", "ss_customer_sk",
                                   "ss_sold_date_sk", "ss_quantity",
                                   "ss_sales_price")
    ss_y = ss.join(dd_years, on=col("ss_sold_date_sk") == col("d_date_sk"),
                   how="left_semi")
    # frequent items: sold more than 1.5x the average per-item row count
    item_cnt = ss_y.group_by("ss_item_sk").agg(("count", "*", "cnt"))
    avg_cnt = (ss_y.group_by("ss_item_sk").agg(("count", "*", "cnt"))
               .agg(("avg", "cnt", "a")).as_scalar())
    frequent = (item_cnt.filter(col("cnt") > avg_cnt * lit(1.5))
                .select(col("ss_item_sk").alias("freq_item")))
    # best customers: store spend above half the max customer spend
    cust_tot = (ss_y.group_by("ss_customer_sk")
                .agg(("sum", col("ss_quantity") * col("ss_sales_price"),
                      "csales")))
    max_sales = (ss_y.group_by("ss_customer_sk")
                 .agg(("sum", col("ss_quantity") * col("ss_sales_price"),
                       "csales"))
                 .agg(("max", "csales", "m")).as_scalar())
    best = (cust_tot.filter(col("csales") > max_sales * lit(0.5))
            .select(col("ss_customer_sk").alias("best_cust")))
    dd_month = (dfs["date_dim"]
                .filter((col("d_year") == lit(2000))
                        & (col("d_moy") == lit(3)))
                .select("d_date_sk"))

    def channel(sales, s_item, s_cust, s_date, s_qty, s_price):
        s = dfs[sales].select(col(s_item).alias("item"),
                              col(s_cust).alias("cust"),
                              col(s_date).alias("date_sk"),
                              (col(s_qty) * col(s_price)).alias("sales"))
        s = s.join(dd_month, on=col("date_sk") == col("d_date_sk"),
                   how="left_semi")
        s = s.join(frequent, on=col("item") == col("freq_item"),
                   how="left_semi")
        s = s.join(best, on=col("cust") == col("best_cust"),
                   how="left_semi")
        return s.select("sales")

    cs = channel("catalog_sales", "cs_item_sk", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_quantity", "cs_sales_price")
    ws = channel("web_sales", "ws_item_sk", "ws_bill_customer_sk",
                 "ws_sold_date_sk", "ws_quantity", "ws_sales_price")
    return cs.union(ws).agg(("sum", "sales", "total_sales"))


def q23_pandas(t):
    d = t["date_dim"]
    dd_years = d[(d.d_year >= 1999) & (d.d_year <= 2001)].d_date_sk
    ss = t["store_sales"]
    ss_y = ss[ss.ss_sold_date_sk.isin(dd_years)]
    cnt = ss_y.groupby("ss_item_sk").size()
    frequent = set(cnt[cnt > 1.5 * cnt.mean()].index)
    tot = (ss_y.assign(v=ss_y.ss_quantity * ss_y.ss_sales_price)
           .groupby("ss_customer_sk").v.sum())
    best = set(tot[tot > 0.5 * tot.max()].index)
    dd_month = d[(d.d_year == 2000) & (d.d_moy == 3)].d_date_sk

    def channel(sales, s_item, s_cust, s_date, s_qty, s_price):
        s = t[sales]
        s = s[s[s_date].isin(dd_month) & s[s_item].isin(frequent)
              & s[s_cust].isin(best)]
        return (s[s_qty] * s[s_price]).sum(min_count=1)

    cs = channel("catalog_sales", "cs_item_sk", "cs_bill_customer_sk",
                 "cs_sold_date_sk", "cs_quantity", "cs_sales_price")
    ws = channel("web_sales", "ws_item_sk", "ws_bill_customer_sk",
                 "ws_sold_date_sk", "ws_quantity", "ws_sales_price")
    vals = [v for v in (cs, ws) if not pd.isna(v)]
    total = sum(vals) if vals else np.nan
    return pd.DataFrame({"total_sales": [total]})


# ---------------------------------------------------------------------------
# q14 — cross-channel items (2-way INTERSECT of item dimension tuples)
# with an average-sales scalar gate
# ---------------------------------------------------------------------------


def q14(dfs):
    dd_years = (dfs["date_dim"]
                .filter((col("d_year") >= lit(1999))
                        & (col("d_year") <= lit(2001)))
                .select("d_date_sk"))
    it = dfs["item"].select("i_item_sk", "i_brand_id", "i_class",
                            "i_category_id")

    def chan_items(sales, s_item, s_date):
        s = dfs[sales].select(col(s_item).alias("item"),
                              col(s_date).alias("date_sk"))
        s = s.join(dd_years, on=col("date_sk") == col("d_date_sk"),
                   how="left_semi")
        s = s.join(it, on=col("item") == col("i_item_sk"))
        return s.select("i_brand_id", "i_class", "i_category_id")

    iss = chan_items("store_sales", "ss_item_sk", "ss_sold_date_sk")
    ics = chan_items("catalog_sales", "cs_item_sk", "cs_sold_date_sk")
    iws = chan_items("web_sales", "ws_item_sk", "ws_sold_date_sk")
    cross = iss.intersect(ics).intersect(iws)
    cross = cross.select(col("i_brand_id").alias("x_brand"),
                         col("i_class").alias("x_class"),
                         col("i_category_id").alias("x_cat"))

    def chan_sales(sales, s_item, s_date, s_qty, s_price):
        s = dfs[sales].select(col(s_item).alias("item"),
                              col(s_date).alias("date_sk"),
                              (col(s_qty) * col(s_price)).alias("sales"))
        return s

    avg_sales = (chan_sales("store_sales", "ss_item_sk",
                            "ss_sold_date_sk", "ss_quantity",
                            "ss_list_price")
                 .union(chan_sales("catalog_sales", "cs_item_sk",
                                   "cs_sold_date_sk", "cs_quantity",
                                   "cs_list_price"))
                 .union(chan_sales("web_sales", "ws_item_sk",
                                   "ws_sold_date_sk", "ws_quantity",
                                   "ws_list_price"))
                 .join(dd_years, on=col("date_sk") == col("d_date_sk"),
                       how="left_semi")
                 .agg(("avg", "sales", "a")).as_scalar())

    dd_month = (dfs["date_dim"]
                .filter((col("d_year") == lit(2000))
                        & (col("d_moy") == lit(12)))
                .select("d_date_sk"))

    def channel_sum(sales, s_item, s_date, s_qty, s_price, label):
        s = dfs[sales].select(col(s_item).alias("item"),
                              col(s_date).alias("date_sk"),
                              (col(s_qty) * col(s_price)).alias("sales"))
        s = s.join(dd_month, on=col("date_sk") == col("d_date_sk"),
                   how="left_semi")
        s = s.join(it, on=col("item") == col("i_item_sk"))
        s = s.join(cross, on=(col("i_brand_id") == col("x_brand"))
                   & (col("i_class") == col("x_class"))
                   & (col("i_category_id") == col("x_cat")),
                   how="left_semi")
        g = (s.group_by("i_brand_id", "i_class", "i_category_id")
             .agg(("sum", "sales", "sales"), ("count", "*", "number_sales")))
        g = g.filter(col("sales") > avg_sales)
        return g.with_column("channel", lit(label))

    st = channel_sum("store_sales", "ss_item_sk", "ss_sold_date_sk",
                     "ss_quantity", "ss_list_price", "store")
    ct = channel_sum("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                     "cs_quantity", "cs_list_price", "catalog")
    wt = channel_sum("web_sales", "ws_item_sk", "ws_sold_date_sk",
                     "ws_quantity", "ws_list_price", "web")
    u = st.union(ct).union(wt)
    return (u.select("channel", "i_brand_id", "i_class", "i_category_id",
                     "sales", "number_sales")
            .sort("channel", "i_brand_id", "i_class", "i_category_id")
            .limit(100))


def q14_pandas(t):
    d = t["date_dim"]
    dd_years = d[(d.d_year >= 1999) & (d.d_year <= 2001)].d_date_sk
    it = t["item"][["i_item_sk", "i_brand_id", "i_class",
                    "i_category_id"]]

    def chan_items(sales, s_item, s_date):
        s = t[sales]
        s = s[s[s_date].isin(dd_years)]
        s = s.merge(it, left_on=s_item, right_on="i_item_sk")
        return set(map(tuple, s[["i_brand_id", "i_class",
                                 "i_category_id"]].values))

    cross = (chan_items("store_sales", "ss_item_sk", "ss_sold_date_sk")
             & chan_items("catalog_sales", "cs_item_sk", "cs_sold_date_sk")
             & chan_items("web_sales", "ws_item_sk", "ws_sold_date_sk"))

    allv = []
    for sales, s_item, s_date, s_qty, s_price in (
            ("store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_quantity", "ss_list_price"),
            ("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_quantity", "cs_list_price"),
            ("web_sales", "ws_item_sk", "ws_sold_date_sk", "ws_quantity",
             "ws_list_price")):
        s = t[sales]
        s = s[s[s_date].isin(dd_years)]
        allv.append(s[s_qty] * s[s_price])
    avg_sales = pd.concat(allv).mean()

    dd_month = d[(d.d_year == 2000) & (d.d_moy == 12)].d_date_sk
    frames = []
    for sales, s_item, s_date, s_qty, s_price, label in (
            ("store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_quantity", "ss_list_price", "store"),
            ("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_quantity", "cs_list_price", "catalog"),
            ("web_sales", "ws_item_sk", "ws_sold_date_sk", "ws_quantity",
             "ws_list_price", "web")):
        s = t[sales]
        s = s[s[s_date].isin(dd_month)]
        s = s.merge(it, left_on=s_item, right_on="i_item_sk")
        key = list(map(tuple, s[["i_brand_id", "i_class",
                                 "i_category_id"]].values))
        s = s[[k in cross for k in key]]
        s = s.assign(v=s[s_qty] * s[s_price])
        g = s.groupby(["i_brand_id", "i_class", "i_category_id"],
                      as_index=False).agg(sales=("v", "sum"),
                                          number_sales=("v", "count"))
        g = g[g.sales > avg_sales]
        g.insert(0, "channel", label)
        frames.append(g)
    u = pd.concat(frames, ignore_index=True)
    return (u.sort_values(["channel", "i_brand_id", "i_class",
                           "i_category_id"]).head(100)
            .reset_index(drop=True))


QUERIES_EXT3.update({
    "q14": (q14, q14_pandas),
    "q23": (q23, q23_pandas),
    "q24": (q24, q24_pandas),
})
