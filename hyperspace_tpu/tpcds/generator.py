"""Deterministic TPC-DS subset generator.

Generates the tables q17 / q25 / q64 need, at a row scale controlled by
`scale` (scale=1.0 approximates SF0.1 row counts for the fact tables).
Schemas follow the TPC-DS column names/types the queries reference; value
distributions are synthetic but respect the join topology: every foreign
key is drawn from the referenced table's key domain, and store_returns /
catalog_sales rows are derived from actual store_sales rows so the
ss JOIN sr JOIN cs chains produce realistic match rates.

Everything is seeded — same scale, same bytes.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

# Rows at scale=1.0 (fact tables ~ SF0.1 / 30; dimensions fixed).
_BASE = {
    "store_sales": 300_000,
    "date_dim": 73_049,     # 1998-01-01 .. 2197-12-31 in real TPC-DS
    "store": 12,
    "item": 2_000,
    "customer": 10_000,
    "promotion": 30,
}

TABLES = ("store_sales", "store_returns", "catalog_sales",
          "catalog_returns", "web_sales", "web_returns", "inventory",
          "date_dim", "store", "item", "customer", "promotion",
          "customer_demographics", "household_demographics",
          "customer_address", "time_dim", "reason", "income_band",
          "warehouse", "ship_mode", "web_site", "web_page", "call_center",
          "catalog_page")

_QUARTERS = ["%dQ%d" % (y, q) for y in range(1998, 2004)
             for q in range(1, 5)]


def _date_dim(n_dates: int):
    sk = np.arange(1, n_dates + 1, dtype=np.int64)
    # ~91-day quarters cycling through _QUARTERS; years 1998..2003.
    day = sk - 1
    year = 1998 + (day // 365)
    moy = 1 + (day % 365) // 31
    qoy = 1 + (moy - 1) // 3
    quarter_name = np.array(["%dQ%d" % (y, q) for y, q in
                             zip(year, np.minimum(qoy, 4))])
    _DAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
    return {
        "d_date_sk": sk,
        "d_year": year.astype(np.int64),
        "d_moy": np.minimum(moy, 12).astype(np.int64),
        "d_dom": (1 + (day % 365) % 31).astype(np.int64),
        "d_dow": (day % 7).astype(np.int64),
        "d_day_name": np.array([_DAYS[d] for d in (day % 7)]),
        "d_qoy": np.minimum(qoy, 4).astype(np.int64),
        "d_quarter_name": quarter_name,
        # Sequential month/week counters (official d_month_seq/d_week_seq
        # semantics: monotone over the calendar) — the year-over-year
        # self-join queries (q2/q59) and month-window subqueries (q54)
        # key on these.
        "d_month_seq": ((year - 1998) * 12
                        + np.minimum(moy, 12) - 1).astype(np.int64),
        "d_week_seq": (day // 7 + 1).astype(np.int64),
    }


def generate(out_dir: str, scale: float = 1.0,
             seed: int = 20260730) -> Dict[str, str]:
    """Write the table subset as parquet dirs under `out_dir`; returns
    {table: path}. Idempotent for a given (out_dir, scale, seed): existing
    table dirs are reused."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    # Columns added in later rounds draw from a SEPARATE stream: inserting
    # draws into `rng`'s sequence would silently reshuffle every
    # previously-generated table (and the constants the query suite's
    # filters were tuned against).
    rng2 = np.random.default_rng(seed + 1)
    n_ss = max(int(_BASE["store_sales"] * scale), 1000)
    n_dates = _BASE["date_dim"] // 20  # ~6 years of days
    n_item = max(int(_BASE["item"] * min(scale, 4)), 200)
    n_cust = max(int(_BASE["customer"] * min(scale, 4)), 500)
    n_store = _BASE["store"]
    n_promo = _BASE["promotion"]

    tables: Dict[str, dict] = {}
    tables["date_dim"] = _date_dim(n_dates)

    tables["store"] = {
        "s_store_sk": np.arange(1, n_store + 1, dtype=np.int64),
        "s_store_id": np.array(["S%04d" % i for i in range(n_store)]),
        # q96 filters s_store_name = 'ese' (real TPC-DS store names are
        # spelled-out digit fragments); give a third of stores that name.
        "s_store_name": np.array([["ese", "store_%d" % (i % 7),
                                   "ation"][i % 3]
                                  for i in range(n_store)]),
        "s_number_employees": (200 + 17 * np.arange(n_store) % 110
                               ).astype(np.int64),
        "s_city": np.array([["Midway", "Fairview", "Oakdale", "Riverside",
                             "Centerville"][i % 5] for i in range(n_store)]),
        "s_state": np.array([["TN", "CA", "WA", "NY", "TX"][i % 5]
                             for i in range(n_store)]),
        "s_zip": np.array(["%05d" % (35000 + 13 * i) for i in range(n_store)]),
        # q24's market-grouped store pairing join.
        "s_market_id": (1 + np.arange(n_store) % 10).astype(np.int64),
        # q50's full select list (street/county/company identity columns).
        "s_company_id": np.ones(n_store, dtype=np.int64),
        "s_street_number": np.array(["%d" % (100 + 7 * i)
                                     for i in range(n_store)]),
        "s_street_name": np.array([["Main", "Oak", "Park", "First"][i % 4]
                                   for i in range(n_store)]),
        "s_street_type": np.array([["St", "Ave", "Blvd"][i % 3]
                                   for i in range(n_store)]),
        "s_suite_number": np.array(["Suite %d" % (10 * i)
                                    for i in range(n_store)]),
        "s_county": np.array([["Williamson County", "Ziebach County"][i % 2]
                              for i in range(n_store)]),
        "s_gmt_offset": np.full(n_store, -5.0),
        "s_company_name": np.array(["Unknown"] * n_store),
    }

    _CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Sports",
                   "Music", "Women", "Men", "Children", "Shoes"]
    tables["item"] = {
        "i_item_sk": np.arange(1, n_item + 1, dtype=np.int64),
        "i_item_id": np.array(["I%08d" % (i % (n_item // 2 + 1))
                               for i in range(n_item)]),
        "i_item_desc": np.array(["desc_%d" % (i % 997) for i in range(n_item)]),
        "i_product_name": np.array(["prod_%d" % i for i in range(n_item)]),
        "i_current_price": np.round(rng.uniform(0.5, 100.0, n_item), 2),
        "i_wholesale_cost": np.round(rng.uniform(0.3, 80.0, n_item), 2),
        "i_brand_id": (1001001 + (np.arange(n_item) % 60) * 1000
                       ).astype(np.int64),
        "i_brand": np.array(["brand_%02d" % (i % 60) for i in range(n_item)]),
        "i_category_id": (1 + np.arange(n_item) % 10).astype(np.int64),
        "i_category": np.array([_CATEGORIES[i % 10] for i in range(n_item)]),
        "i_class": np.array([["personal", "portable", "reference",
                              "self-help", "accessories", "classical",
                              "fragrances", "pants"][i % 8]
                             for i in range(n_item)]),
        "i_manufact_id": (1 + np.arange(n_item) % 200).astype(np.int64),
        "i_manufact": np.array(["manufact_%03d" % (i % 200)
                                for i in range(n_item)]),
        "i_manager_id": (1 + np.arange(n_item) % 100).astype(np.int64),
        "i_color": np.array([["red", "blue", "green", "plum", "puff",
                              "misty", "navy", "orange"][i % 8]
                             for i in range(n_item)]),
        "i_units": np.array([["Oz", "Bunch", "Ton", "N/A", "Dozen", "Box",
                              "Pound", "Pallet"][i % 8]
                             for i in range(n_item)]),
        "i_size": np.array([["medium", "extra large", "N/A", "small",
                             "petite", "large"][i % 6]
                            for i in range(n_item)]),
    }

    n_addr = 1000  # ss_addr_sk / c_current_addr_sk domain
    tables["customer"] = {
        "c_customer_sk": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_customer_id": np.array(["C%010d" % i for i in range(n_cust)]),
        "c_current_addr_sk": rng.integers(1, n_addr + 1,
                                          n_cust).astype(np.int64),
        "c_current_cdemo_sk": rng.integers(1, 1001,
                                           n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, 1001,
                                           n_cust).astype(np.int64),
        "c_first_sales_date_sk": rng.integers(
            1, _BASE["date_dim"] // 20 + 1, n_cust).astype(np.int64),
        "c_first_shipto_date_sk": rng.integers(
            1, _BASE["date_dim"] // 20 + 1, n_cust).astype(np.int64),
        "c_first_name": np.array(["fn_%d" % (i % 400) for i in range(n_cust)]),
        "c_last_name": np.array(["ln_%d" % (i % 700) for i in range(n_cust)]),
        "c_preferred_cust_flag": np.array([["Y", "N"][i % 2]
                                           for i in range(n_cust)]),
        "c_birth_country": np.array([["UNITED STATES", "CANADA", "MEXICO",
                                      "GERMANY", "JAPAN"][i % 5]
                                     for i in range(n_cust)]),
        "c_birth_year": (1940 + np.arange(n_cust) % 60).astype(np.int64),
        "c_birth_month": (1 + np.arange(n_cust) % 12).astype(np.int64),
        "c_salutation": np.array([["Mr.", "Mrs.", "Ms.", "Dr."][i % 4]
                                  for i in range(n_cust)]),
        "c_email_address": np.array(["c%d@example.com" % i
                                     for i in range(n_cust)]),
    }

    tables["promotion"] = {
        "p_promo_sk": np.arange(1, n_promo + 1, dtype=np.int64),
        "p_promo_id": np.array(["P%06d" % i for i in range(n_promo)]),
        "p_channel_email": np.array([["N", "Y"][i % 2]
                                     for i in range(n_promo)]),
        "p_channel_event": np.array([["N", "N", "Y"][i % 3]
                                     for i in range(n_promo)]),
        # Staggered so (dmail OR email OR tv) is DISCRIMINATING: promos
        # with i % 4 == 2 match no channel, keeping q61's promotions sum
        # strictly below its total.
        "p_channel_dmail": np.array([["Y", "N", "N", "N"][i % 4]
                                     for i in range(n_promo)]),
        "p_channel_tv": np.array([["N", "N", "N", "Y"][i % 4]
                                  for i in range(n_promo)]),
    }

    # Demographic / address / time dimensions (fixed-size, like TPC-DS).
    n_demo = 1000  # ss_cdemo_sk / ss_hdemo_sk domain
    _GENDERS = ["M", "F"]
    _MARITAL = ["M", "S", "D", "W", "U"]
    _EDU = ["Primary", "Secondary", "College", "2 yr Degree",
            "4 yr Degree", "Advanced Degree", "Unknown"]
    tables["customer_demographics"] = {
        "cd_demo_sk": np.arange(1, n_demo + 1, dtype=np.int64),
        "cd_gender": np.array([_GENDERS[i % 2] for i in range(n_demo)]),
        "cd_marital_status": np.array([_MARITAL[(i // 2) % 5]
                                       for i in range(n_demo)]),
        "cd_education_status": np.array([_EDU[(i // 10) % 7]
                                         for i in range(n_demo)]),
        "cd_dep_count": (np.arange(n_demo) % 7).astype(np.int64),
        "cd_dep_employed_count": ((np.arange(n_demo) // 7) % 5
                                  ).astype(np.int64),
        "cd_dep_college_count": ((np.arange(n_demo) // 35) % 4
                                 ).astype(np.int64),
        "cd_purchase_estimate": (500 * (1 + np.arange(n_demo) % 20)
                                 ).astype(np.int64),
        "cd_credit_rating": np.array([["Low Risk", "Good", "Unknown",
                                       "High Risk"][i % 4]
                                      for i in range(n_demo)]),
    }
    tables["household_demographics"] = {
        "hd_demo_sk": np.arange(1, n_demo + 1, dtype=np.int64),
        "hd_dep_count": (np.arange(n_demo) % 10).astype(np.int64),
        "hd_vehicle_count": (np.arange(n_demo) % 6 - 1).astype(np.int64),
        # (i // 6) decouples from hd_vehicle_count's i % 6 cycle — the
        # q34/q73 filter ANDs buy_potential with vehicle_count > 0.
        "hd_income_band_sk": (1 + np.arange(n_demo) % 20).astype(np.int64),
        "hd_buy_potential": np.array([
            [">10000", "unknown", "1001-5000", "5001-10000", "501-1000",
             "0-500"][(i // 6) % 6] for i in range(n_demo)]),
    }
    tables["income_band"] = {
        "ib_income_band_sk": np.arange(1, 21, dtype=np.int64),
        "ib_lower_bound": (np.arange(20) * 10000).astype(np.int64),
        "ib_upper_bound": ((np.arange(20) + 1) * 10000 - 1).astype(np.int64),
    }
    _REASONS = ["reason 1", "reason 28", "Did not like the warranty",
                "Not the product that was ordred", "reason 55"]
    tables["reason"] = {
        "r_reason_sk": np.arange(1, len(_REASONS) + 1, dtype=np.int64),
        "r_reason_desc": np.array(_REASONS),
    }
    _CITIES = ["%s_%02d" % (base, i) for base in
               ("Springfield", "Greenville", "Franklin", "Clinton")
               for i in range(15)]
    _STATES = ["TX", "OH", "KY", "GA", "NM", "VA", "MO", "ND", "IN", "SC"]
    tables["customer_address"] = {
        "ca_address_sk": np.arange(1, n_addr + 1, dtype=np.int64),
        "ca_street_number": np.array(["%d" % (100 + 3 * i)
                                      for i in range(n_addr)]),
        "ca_street_name": np.array([["Main", "Oak", "Park", "First",
                                     "Elm", "Lake"][i % 6]
                                    for i in range(n_addr)]),
        "ca_city": np.array([_CITIES[i % len(_CITIES)]
                             for i in range(n_addr)]),
        "ca_zip": np.array(["%05d" % (10000 + 37 * i % 90000)
                            for i in range(n_addr)]),
        "ca_state": np.array([_STATES[i % len(_STATES)]
                              for i in range(n_addr)]),
        "ca_county": np.array([["Williamson County", "Ziebach County",
                                "Walker County", "Daviess County"][i % 4]
                               for i in range(n_addr)]),
        "ca_country": np.array(["United States"] * n_addr),
        "ca_gmt_offset": np.full(n_addr, -5.0),
        "ca_location_type": np.array([["apartment", "condo",
                                       "single family"][i % 3]
                                      for i in range(n_addr)]),
    }
    # Seconds 08:00:00 .. 20:59:59 (the selling day q96 probes).
    t_sk = np.arange(8 * 3600, 21 * 3600, dtype=np.int64)
    tables["time_dim"] = {
        "t_time_sk": t_sk,
        "t_hour": (t_sk // 3600).astype(np.int64),
        "t_minute": ((t_sk % 3600) // 60).astype(np.int64),
    }

    # -- store_sales ------------------------------------------------------
    # Sales concentrate in 1999-2001 (day 366..1460) so the year-filtered
    # queries (q17 2000Q1, q25 Apr-Oct 2000, q64 2000 vs 2001) see dense
    # data at every scale; date_dim itself still spans the full range.
    lo_day, hi_day = 366, min(1460, n_dates)
    # Rows group into multi-line TICKETS (one store visit: ticket-level
    # date/customer/store/demo/address shared by its rows, ~12 lines
    # Poisson-distributed) — the official layout the ticket-size band
    # queries (q34 counts 15-20, q73 counts 1-5) and per-ticket grouping
    # queries (q46/q68/q79) measure.
    n_ticket = max(n_ss // 12, 1)
    # Bimodal basket sizes: ~30% quick visits (1-5 lines), the rest full
    # carts (8-23) — both ticket-size bands (q73's 1-5, q34's 15-20)
    # carry mass at every scale. n_ss becomes the realized row total.
    sizes = np.where(rng.random(n_ticket) < 0.3,
                     rng.integers(1, 6, n_ticket),
                     rng.integers(8, 24, n_ticket))
    tick = np.repeat(np.arange(n_ticket, dtype=np.int64), sizes)
    n_ss = len(tick)
    t_date = rng.integers(lo_day, hi_day + 1, n_ticket).astype(np.int64)
    t_cust = rng.integers(1, n_cust + 1, n_ticket).astype(np.int64)
    t_store = rng.integers(1, n_store + 1, n_ticket).astype(np.int64)
    t_cdemo = rng.integers(1, n_demo + 1, n_ticket).astype(np.int64)
    t_hdemo = rng.integers(1, n_demo + 1, n_ticket).astype(np.int64)
    t_addr = rng.integers(1, n_addr + 1, n_ticket).astype(np.int64)
    ss_sold_date = t_date[tick]
    # Items WITHOUT replacement within a ticket ((item, ticket) is the
    # official PK the ss-sr identity joins key on): random per-ticket
    # base + within-ticket position, distinct for any basket <= n_item.
    starts_of = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    pos = np.arange(n_ss, dtype=np.int64) - np.repeat(starts_of, sizes)
    t_base = rng.integers(0, n_item, n_ticket).astype(np.int64)
    ss_item = 1 + (t_base[tick] + pos) % n_item
    ss_cust = t_cust[tick]
    ss_store = t_store[tick]
    ss_ticket = tick + 1
    ss_qty = rng.integers(1, 100, n_ss).astype(np.int64)
    ss_price = np.round(rng.uniform(1.0, 300.0, n_ss), 2)
    # ~2% of store rows carry a NULL store key (official store_sales has
    # nullable dimension FKs; the null-key report q76 depends on them).
    ss_store_null = rng2.random(n_ss) < 0.02
    tables["store_sales"] = {
        "ss_sold_date_sk": ss_sold_date,
        "ss_sold_time_sk": rng.integers(8 * 3600, 21 * 3600,
                                        n_ss).astype(np.int64),
        "ss_item_sk": ss_item,
        "ss_customer_sk": ss_cust,
        "ss_cdemo_sk": t_cdemo[tick],
        "ss_hdemo_sk": t_hdemo[tick],
        "ss_addr_sk": t_addr[tick],
        "ss_store_sk": pa.array(ss_store, mask=ss_store_null),
        "ss_promo_sk": rng.integers(1, n_promo + 1, n_ss).astype(np.int64),
        "ss_ticket_number": ss_ticket,
        "ss_quantity": ss_qty,
        "ss_wholesale_cost": np.round(ss_price * 0.6, 2),
        "ss_ext_wholesale_cost": np.round(ss_price * 0.6 * ss_qty, 2),
        "ss_list_price": np.round(ss_price * 1.2, 2),
        "ss_sales_price": ss_price,
        "ss_ext_sales_price": np.round(ss_price * ss_qty, 2),
        "ss_ext_list_price": np.round(ss_price * 1.2 * ss_qty, 2),
        "ss_ext_tax": np.round(ss_price * ss_qty * 0.08, 2),
        "ss_coupon_amt": np.round(
            np.where(rng.random(n_ss) < 0.3,
                     rng.uniform(0.0, 20.0, n_ss), 0.0), 2),
        # q24/q49/q78: what the customer actually paid.
        "ss_net_paid": np.round(ss_price * ss_qty * 0.97, 2),
        "ss_net_profit": np.round(ss_price * ss_qty * 0.1
                                  - rng.uniform(0, 50, n_ss), 2),
    }

    # -- store_returns: ~30% of sales return, tied to a real sale --------
    n_sr = n_ss * 3 // 10
    ret_pick = rng.choice(n_ss, n_sr, replace=False)
    ret_lag = rng.integers(1, 90, n_sr)
    sr_ret_qty = np.maximum(
        ss_qty[ret_pick] - rng.integers(0, 50, n_sr), 1).astype(np.int64)
    tables["store_returns"] = {
        "sr_returned_date_sk": np.minimum(ss_sold_date[ret_pick] + ret_lag,
                                          n_dates).astype(np.int64),
        "sr_item_sk": ss_item[ret_pick],
        "sr_customer_sk": ss_cust[ret_pick],
        "sr_cdemo_sk": rng.integers(1, n_demo + 1, n_sr).astype(np.int64),
        "sr_store_sk": ss_store[ret_pick],
        "sr_reason_sk": (1 + rng.integers(0, 5, n_sr)).astype(np.int64),
        "sr_ticket_number": ss_ticket[ret_pick],
        "sr_return_quantity": sr_ret_qty,
        "sr_return_amt": np.round(ss_price[ret_pick] * sr_ret_qty, 2),
        "sr_net_loss": np.round(rng.uniform(1.0, 200.0, n_sr), 2),
    }

    # -- catalog_sales: some to the same (customer, item) pairs ----------
    n_cs = n_ss * 6 // 10
    cs_follow = rng.random(n_cs) < 0.5  # half follow a store sale
    follow_pick = rng.choice(n_ss, n_cs, replace=True)
    cs_item = np.where(cs_follow, ss_item[follow_pick],
                       rng.integers(1, n_item + 1, n_cs)).astype(np.int64)
    cs_cust = np.where(cs_follow, ss_cust[follow_pick],
                       rng.integers(1, n_cust + 1, n_cs)).astype(np.int64)
    cs_date = np.minimum(
        np.where(cs_follow, ss_sold_date[follow_pick]
                 + rng.integers(1, 120, n_cs),
                 rng.integers(lo_day, hi_day + 1, n_cs)),
        n_dates).astype(np.int64)
    cs_qty = rng.integers(1, 100, n_cs).astype(np.int64)
    cs_order = np.arange(1, n_cs + 1, dtype=np.int64)
    cs_price = np.round(rng.uniform(1.0, 300.0, n_cs), 2)
    cs_page = rng2.integers(1, 101, n_cs).astype(np.int64)
    tables["catalog_sales"] = {
        "cs_sold_date_sk": cs_date,
        "cs_sold_time_sk": rng.integers(8 * 3600, 21 * 3600,
                                        n_cs).astype(np.int64),
        "cs_bill_customer_sk": cs_cust,
        "cs_bill_cdemo_sk": rng.integers(1, n_demo + 1,
                                         n_cs).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(1, n_addr + 1,
                                        n_cs).astype(np.int64),
        "cs_ship_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, n_cs).astype(np.int64),
            mask=rng2.random(n_cs) < 0.02),
        "cs_ship_date_sk": np.minimum(cs_date + rng.integers(1, 120, n_cs),
                                      n_dates).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, 6, n_cs).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(1, 21, n_cs).astype(np.int64),
        "cs_call_center_sk": rng.integers(1, 5, n_cs).astype(np.int64),
        "cs_catalog_page_sk": cs_page,
        "cs_item_sk": cs_item,
        "cs_promo_sk": rng.integers(1, n_promo + 1, n_cs).astype(np.int64),
        "cs_order_number": cs_order,
        "cs_quantity": cs_qty,
        "cs_list_price": np.round(cs_price * 1.2, 2),
        "cs_sales_price": cs_price,
        "cs_ext_sales_price": np.round(cs_price * cs_qty, 2),
        "cs_ext_discount_amt": np.round(
            np.where(rng.random(n_cs) < 0.4,
                     rng.uniform(0.0, 60.0, n_cs), 5.0), 2),
        "cs_coupon_amt": np.round(
            np.where(rng.random(n_cs) < 0.3,
                     rng.uniform(0.0, 20.0, n_cs), 0.0), 2),
        "cs_ext_list_price": np.round(rng.uniform(5.0, 500.0, n_cs), 2),
        # q16 (shipping-cost report) and q49/q75/q78 (net paid).
        "cs_ext_ship_cost": np.round(rng2.uniform(0.5, 30.0, n_cs), 2),
        "cs_net_paid": np.round(cs_price * cs_qty * 0.95, 2),
        "cs_net_profit": np.round(rng.uniform(-50.0, 300.0, n_cs), 2),
    }

    # -- catalog_returns: ~20% of catalog sales --------------------------
    n_cr = n_cs * 2 // 10
    cr_pick = rng.choice(n_cs, n_cr, replace=False)
    tables["catalog_returns"] = {
        "cr_item_sk": cs_item[cr_pick],
        "cr_order_number": cs_order[cr_pick],
        "cr_returning_customer_sk": cs_cust[cr_pick],
        "cr_returned_date_sk": np.minimum(
            cs_date[cr_pick] + rng.integers(1, 90, n_cr),
            n_dates).astype(np.int64),
        "cr_return_amt_inc_tax": np.round(rng.uniform(1.0, 300.0, n_cr), 2),
        "cr_refunded_cash": np.round(rng.uniform(1.0, 150.0, n_cr), 2),
        "cr_reversed_charge": np.round(rng.uniform(0.0, 40.0, n_cr), 2),
        "cr_store_credit": np.round(rng.uniform(0.0, 40.0, n_cr), 2),
        # q5/q49/q77/q80/q83/q91 (returns reports over the catalog channel).
        "cr_return_amount": np.round(rng2.uniform(1.0, 250.0, n_cr), 2),
        "cr_net_loss": np.round(rng2.uniform(0.5, 80.0, n_cr), 2),
        "cr_return_quantity": rng2.integers(1, 10, n_cr).astype(np.int64),
        "cr_call_center_sk": rng2.integers(1, 5, n_cr).astype(np.int64),
        "cr_reason_sk": rng2.integers(1, 6, n_cr).astype(np.int64),
        "cr_catalog_page_sk": cs_page[cr_pick],
    }

    # -- web channel (round-5 breadth: the 3-channel query families) -----
    n_wh = 5
    tables["warehouse"] = {
        "w_warehouse_sk": np.arange(1, n_wh + 1, dtype=np.int64),
        "w_warehouse_name": np.array(["Warehouse %d" % i
                                      for i in range(n_wh)]),
        "w_warehouse_sq_ft": (50_000 + 25_000 * np.arange(n_wh)
                              ).astype(np.int64),
        "w_city": np.array([["Midway", "Fairview"][i % 2]
                            for i in range(n_wh)]),
        "w_county": np.array([["Williamson County", "Ziebach County"][i % 2]
                              for i in range(n_wh)]),
        "w_state": np.array([["TN", "CA", "WA"][i % 3] for i in range(n_wh)]),
        "w_country": np.array(["United States"] * n_wh),
    }
    n_sm = 20
    tables["ship_mode"] = {
        "sm_ship_mode_sk": np.arange(1, n_sm + 1, dtype=np.int64),
        "sm_type": np.array([["EXPRESS", "NEXT DAY", "OVERNIGHT",
                              "REGULAR", "TWO DAY"][i % 5]
                             for i in range(n_sm)]),
        "sm_code": np.array([["AIR", "SURFACE", "SEA"][i % 3]
                             for i in range(n_sm)]),
        "sm_carrier": np.array([["UPS", "FEDEX", "AIRBORNE", "USPS"][i % 4]
                                for i in range(n_sm)]),
    }
    n_web = 4
    tables["web_site"] = {
        "web_site_sk": np.arange(1, n_web + 1, dtype=np.int64),
        "web_site_id": np.array(["WEB%04d" % i for i in range(n_web)]),
        "web_name": np.array(["site_%d" % i for i in range(n_web)]),
        "web_company_name": np.array([["pri", "ought"][i % 2]
                                      for i in range(n_web)]),
    }
    n_wp = 10
    tables["web_page"] = {
        "wp_web_page_sk": np.arange(1, n_wp + 1, dtype=np.int64),
        "wp_char_count": (4000 + 150 * np.arange(n_wp)).astype(np.int64),
    }
    n_cc = 4
    tables["call_center"] = {
        "cc_call_center_sk": np.arange(1, n_cc + 1, dtype=np.int64),
        "cc_call_center_id": np.array(["CC%04d" % i for i in range(n_cc)]),
        "cc_name": np.array(["center_%d" % i for i in range(n_cc)]),
        "cc_county": np.array([["Williamson County",
                                "Ziebach County"][i % 2]
                               for i in range(n_cc)]),
        "cc_manager": np.array(["mgr_%d" % i for i in range(n_cc)]),
    }
    n_cp = 100
    tables["catalog_page"] = {
        "cp_catalog_page_sk": np.arange(1, n_cp + 1, dtype=np.int64),
        "cp_catalog_page_id": np.array(["CP%08d" % i for i in range(n_cp)]),
    }

    # -- web_sales: ~40% of store volume; half follow a store sale so
    # cross-channel customer/item overlap exists (q38/q87 INTERSECT/
    # EXCEPT, q11/q74 year-total ratios key on it) ----------------------
    n_ws = n_ss * 4 // 10
    ws_follow = rng.random(n_ws) < 0.5
    wf_pick = rng.choice(n_ss, n_ws, replace=True)
    ws_item = np.where(ws_follow, ss_item[wf_pick],
                       rng.integers(1, n_item + 1, n_ws)).astype(np.int64)
    ws_cust = np.where(ws_follow, ss_cust[wf_pick],
                       rng.integers(1, n_cust + 1, n_ws)).astype(np.int64)
    ws_date = np.minimum(
        np.where(ws_follow, ss_sold_date[wf_pick]
                 + rng.integers(0, 60, n_ws),
                 rng.integers(lo_day, hi_day + 1, n_ws)),
        n_dates).astype(np.int64)
    ws_qty = rng.integers(1, 100, n_ws).astype(np.int64)
    # Multi-line orders (~3 lines each): per-line warehouses can then
    # differ within one order (q94/q95 probe exactly that).
    ws_order = (np.arange(n_ws, dtype=np.int64) // 3) + 1
    ws_price = np.round(rng.uniform(1.0, 300.0, n_ws), 2)
    tables["web_sales"] = {
        "ws_sold_date_sk": ws_date,
        "ws_sold_time_sk": rng.integers(8 * 3600, 21 * 3600,
                                        n_ws).astype(np.int64),
        "ws_ship_date_sk": np.minimum(ws_date + rng.integers(1, 120, n_ws),
                                      n_dates).astype(np.int64),
        "ws_item_sk": ws_item,
        "ws_bill_customer_sk": ws_cust,
        "ws_bill_addr_sk": rng.integers(1, n_addr + 1,
                                        n_ws).astype(np.int64),
        "ws_ship_customer_sk": pa.array(
            rng.integers(1, n_cust + 1, n_ws).astype(np.int64),
            mask=rng2.random(n_ws) < 0.02),
        "ws_ship_hdemo_sk": rng.integers(1, n_demo + 1,
                                         n_ws).astype(np.int64),
        "ws_ship_addr_sk": rng.integers(1, n_addr + 1,
                                        n_ws).astype(np.int64),
        "ws_web_page_sk": rng.integers(1, n_wp + 1, n_ws).astype(np.int64),
        "ws_web_site_sk": rng.integers(1, n_web + 1, n_ws).astype(np.int64),
        "ws_ship_mode_sk": rng.integers(1, n_sm + 1, n_ws).astype(np.int64),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n_ws).astype(np.int64),
        "ws_promo_sk": rng.integers(1, n_promo + 1, n_ws).astype(np.int64),
        "ws_order_number": ws_order,
        "ws_quantity": ws_qty,
        "ws_wholesale_cost": np.round(ws_price * 0.6, 2),
        "ws_list_price": np.round(ws_price * 1.2, 2),
        "ws_sales_price": ws_price,
        "ws_ext_sales_price": np.round(ws_price * ws_qty, 2),
        "ws_ext_list_price": np.round(ws_price * 1.2 * ws_qty, 2),
        "ws_ext_wholesale_cost": np.round(ws_price * 0.6 * ws_qty, 2),
        "ws_ext_discount_amt": np.round(
            np.where(rng.random(n_ws) < 0.4,
                     rng.uniform(0.0, 60.0, n_ws), 5.0), 2),
        "ws_ext_ship_cost": np.round(rng.uniform(0.5, 30.0, n_ws), 2),
        "ws_net_paid": np.round(ws_price * ws_qty * 0.95, 2),
        "ws_net_profit": np.round(ws_price * ws_qty * 0.1
                                  - rng.uniform(0, 50, n_ws), 2),
    }

    # -- web_returns: ~15% of web sales ----------------------------------
    n_wr = n_ws * 15 // 100
    wr_pick = rng.choice(n_ws, max(n_wr, 1), replace=False)
    n_wr = len(wr_pick)
    wr_qty = np.maximum(ws_qty[wr_pick] - rng.integers(0, 50, n_wr),
                        1).astype(np.int64)
    tables["web_returns"] = {
        "wr_returned_date_sk": np.minimum(
            ws_date[wr_pick] + rng.integers(1, 90, n_wr),
            n_dates).astype(np.int64),
        "wr_item_sk": ws_item[wr_pick],
        "wr_order_number": ws_order[wr_pick],
        "wr_returning_customer_sk": ws_cust[wr_pick],
        "wr_refunded_customer_sk": ws_cust[wr_pick],
        "wr_refunded_addr_sk": rng.integers(1, n_addr + 1,
                                            n_wr).astype(np.int64),
        "wr_returning_cdemo_sk": rng.integers(1, n_demo + 1,
                                              n_wr).astype(np.int64),
        "wr_refunded_cdemo_sk": rng.integers(1, n_demo + 1,
                                             n_wr).astype(np.int64),
        "wr_web_page_sk": rng.integers(1, n_wp + 1, n_wr).astype(np.int64),
        "wr_reason_sk": (1 + rng.integers(0, 5, n_wr)).astype(np.int64),
        "wr_return_quantity": wr_qty,
        "wr_return_amt": np.round(ws_price[wr_pick] * wr_qty, 2),
        "wr_fee": np.round(rng.uniform(0.5, 100.0, n_wr), 2),
        "wr_refunded_cash": np.round(rng.uniform(1.0, 150.0, n_wr), 2),
        "wr_net_loss": np.round(rng.uniform(1.0, 200.0, n_wr), 2),
    }
    # Returner == buyer for ~60% of returns (same demographics row) — the
    # correlation the paired-demographics probes (q85) measure. Post-hoc
    # fixup on rng2 so the main stream's draw sequence is untouched.
    _wr = tables["web_returns"]
    _wr["wr_returning_cdemo_sk"] = np.where(
        rng2.random(n_wr) < 0.6, _wr["wr_refunded_cdemo_sk"],
        _wr["wr_returning_cdemo_sk"]).astype(np.int64)

    # -- inventory: weekly on-hand snapshots over the dense sales window.
    # Size is items x weeks x warehouses (does NOT scale with `scale`
    # past the item cap — real TPC-DS inventory is similarly
    # item-bounded).
    inv_weeks = np.arange(lo_day, hi_day + 1, 7, dtype=np.int64)
    n_inv_items = min(n_item, 4000)
    inv_items = np.arange(1, n_inv_items + 1, dtype=np.int64)
    inv_wh = np.arange(1, n_wh + 1, dtype=np.int64)
    grid_d, grid_i, grid_w = np.meshgrid(inv_weeks, inv_items, inv_wh,
                                         indexing="ij")
    n_inv = grid_d.size
    tables["inventory"] = {
        "inv_date_sk": grid_d.reshape(-1),
        "inv_item_sk": grid_i.reshape(-1),
        "inv_warehouse_sk": grid_w.reshape(-1),
        "inv_quantity_on_hand": rng.integers(0, 1000,
                                             n_inv).astype(np.int64),
    }

    paths: Dict[str, str] = {}
    for name, cols in tables.items():
        path = os.path.join(out_dir, name)
        paths[name] = path
        if os.path.isdir(path) and os.listdir(path):
            continue  # already generated (deterministic)
        os.makedirs(path, exist_ok=True)
        pq.write_table(pa.table(cols), os.path.join(path, "part-0.parquet"))
    return paths
