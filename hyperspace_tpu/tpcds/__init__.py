"""TPC-DS benchmark subset (BASELINE.md rung 5).

A deterministic generator for the table subset q17/q25/q64 touch, the three
queries expressed on the framework's DataFrame API, and pandas oracle
implementations used both as correctness checks and as the CPU baseline
(the reference claims serde coverage of all TPC-DS queries,
`index/serde/package.scala:46-49`; the analog here is the IR/engine
executing these shapes end to end).
"""

from hyperspace_tpu.tpcds.generator import generate, TABLES  # noqa: F401
from hyperspace_tpu.tpcds.queries import QUERIES  # noqa: F401
