"""Round-4 TPC-DS additions: q1, q6, q20, q27, q29, q32, q34, q36, q41,
q46, q70, q73, q81, q93, q97 — pushing the suite past 40 queries.

Same contract as `queries.py`: each query is a rule-acceleratable join
tree with a pandas oracle, and the 3-way equality check (rules on ==
rules off == oracle) runs in `tests/test_tpcds.py` / `bench_tpcds.py`.
Shapes introduced here: per-group average join-backs with HAVING (q1 /
q6 / q32 / q81), ROLLUP as grouping-set unions with per-branch
`lochierarchy` and rank-within-parent windows (q27/q36/q70), ticket-
count band joins (q34/q73), item-only nested NOT-EXISTS-style counting
(q41), the q68-family city comparison (q46), reason-routed partial
returns over the ss-sr ticket identity (q93), and the store/catalog
FULL OUTER customer-item overlap (q97).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd

from hyperspace_tpu.plan.expr import col, lit, when


# ---------------------------------------------------------------------------
# q1 — customers returning more than 1.2x their store's average
# ---------------------------------------------------------------------------


def q1(dfs):
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_customer_sk", "sr_store_sk",
        "sr_return_amt")
    ctr = sr.join(dt, on=col("sr_returned_date_sk") == col("d_date_sk"))
    ctr = (ctr.group_by("sr_customer_sk", "sr_store_sk")
           .agg(("sum", "sr_return_amt", "ctr_total_return")))
    avg_store = (ctr.group_by("sr_store_sk")
                 .agg(("avg", "ctr_total_return", "ctr_avg")))
    avg_store = avg_store.select(
        col("sr_store_sk").alias("avg_store_sk"), "ctr_avg")
    st = dfs["store"].filter(col("s_state") == lit("TN")) \
        .select("s_store_sk")
    j = ctr.join(avg_store, on=col("sr_store_sk") == col("avg_store_sk"))
    j = j.filter(col("ctr_total_return") > col("ctr_avg") * lit(1.2))
    j = j.join(st, on=col("sr_store_sk") == col("s_store_sk"))
    j = j.join(dfs["customer"].select("c_customer_sk", "c_customer_id"),
               on=col("sr_customer_sk") == col("c_customer_sk"))
    return j.select("c_customer_id").sort("c_customer_id").limit(100)


def q1_pandas(t):
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    sr = t["store_returns"].merge(dt, left_on="sr_returned_date_sk",
                                  right_on="d_date_sk")
    ctr = sr.groupby(["sr_customer_sk", "sr_store_sk"],
                     as_index=False).agg(
        ctr_total_return=("sr_return_amt", "sum"))
    avg_store = ctr.groupby("sr_store_sk", as_index=False).agg(
        ctr_avg=("ctr_total_return", "mean"))
    j = ctr.merge(avg_store, on="sr_store_sk")
    j = j[j.ctr_total_return > 1.2 * j.ctr_avg]
    st = t["store"][t["store"].s_state == "TN"][["s_store_sk"]]
    j = j.merge(st, left_on="sr_store_sk", right_on="s_store_sk")
    j = j.merge(t["customer"], left_on="sr_customer_sk",
                right_on="c_customer_sk")
    return (j[["c_customer_id"]].sort_values("c_customer_id")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q6 — states where customers bought items priced >= 1.2x category average
# ---------------------------------------------------------------------------


def q6(dfs):
    dt = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(1)))
          .select("d_date_sk"))
    item = dfs["item"].select("i_item_sk", "i_category", "i_current_price")
    cat_avg = (item.group_by("i_category")
               .agg(("avg", "i_current_price", "cat_avg")))
    cat_avg = cat_avg.select(col("i_category").alias("avg_category"),
                             "cat_avg")
    it = item.join(cat_avg, on=col("i_category") == col("avg_category"))
    it = it.filter(col("i_current_price") > col("cat_avg") * lit(1.2)) \
        .select("i_item_sk")
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_customer_sk")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(dfs["customer"].select("c_customer_sk", "c_current_addr_sk"),
               on=col("ss_customer_sk") == col("c_customer_sk"))
    j = j.join(dfs["customer_address"].select("ca_address_sk", "ca_state"),
               on=col("c_current_addr_sk") == col("ca_address_sk"))
    return (j.group_by("ca_state").agg(("count", "*", "cnt"))
            .having(col("cnt") >= lit(10))
            .sort("cnt", "ca_state").limit(100))


def q6_pandas(t):
    d = t["date_dim"]
    dt = d[(d.d_year == 2000) & (d.d_moy == 1)][["d_date_sk"]]
    item = t["item"]
    cat_avg = item.groupby("i_category", as_index=False).agg(
        cat_avg=("i_current_price", "mean"))
    it = item.merge(cat_avg, on="i_category")
    it = it[it.i_current_price > 1.2 * it.cat_avg][["i_item_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    g = j.groupby("ca_state", as_index=False).agg(cnt=("ca_state", "size"))
    g = g[g.cnt >= 10]
    return (g.sort_values(["cnt", "ca_state"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q20 — catalog item revenue share of its class (q98's catalog twin)
# ---------------------------------------------------------------------------

_Q20_KEYS = ("i_item_id", "i_item_desc", "i_category", "i_class",
             "i_current_price")


def q20(dfs):
    cs = dfs["catalog_sales"].select("cs_item_sk", "cs_sold_date_sk",
                                    "cs_ext_sales_price")
    it = (dfs["item"]
          .filter(col("i_category").isin("Sports", "Books", "Home"))
          .select("i_item_sk", *_Q20_KEYS))
    dt = (dfs["date_dim"]
          .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(5)))
          .select("d_date_sk"))
    j = cs.join(dt, on=col("cs_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    g = (j.group_by(*_Q20_KEYS)
         .agg(("sum", "cs_ext_sales_price", "itemrevenue")))
    w = g.window(["i_class"], class_revenue=("sum", "itemrevenue"))
    return (w.select(*_Q20_KEYS, "itemrevenue",
                     ((col("itemrevenue") * lit(100.0))
                      / col("class_revenue")).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio"))


def q20_pandas(t):
    d = t["date_dim"]
    dt = d[(d.d_year == 2000) & (d.d_moy == 5)][["d_date_sk"]]
    it = t["item"]
    it = it[it.i_category.isin(["Sports", "Books", "Home"])]
    j = t["catalog_sales"].merge(dt, left_on="cs_sold_date_sk",
                                 right_on="d_date_sk")
    j = j.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    g = j.groupby(list(_Q20_KEYS), as_index=False).agg(
        itemrevenue=("cs_ext_sales_price", "sum"))
    g["class_revenue"] = g.groupby("i_class").itemrevenue.transform("sum")
    g["revenueratio"] = g.itemrevenue * 100.0 / g.class_revenue
    out = g[list(_Q20_KEYS) + ["itemrevenue", "revenueratio"]]
    return (out.sort_values(["i_category", "i_class", "i_item_id",
                             "i_item_desc", "revenueratio"])
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q29 — quantities of returned items flowing through catalog (q25 family)
# ---------------------------------------------------------------------------


def q29(dfs):
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk",
        "ss_ticket_number", "ss_quantity")
    sr = dfs["store_returns"].select(
        "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
        "sr_ticket_number", "sr_return_quantity")
    cs = dfs["catalog_sales"].select(
        "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
        "cs_quantity")
    d1 = (dfs["date_dim"]
          .filter((col("d_moy") == lit(9)) & (col("d_year") == lit(1999)))
          .select("d_date_sk"))
    d2 = (dfs["date_dim"]
          .filter((col("d_moy") >= lit(9)) & (col("d_moy") <= lit(12))
                  & (col("d_year") == lit(1999)))
          .select("d_date_sk"))
    d3 = (dfs["date_dim"]
          .filter(col("d_year").isin(1999, 2000, 2001))
          .select("d_date_sk"))
    store = dfs["store"].select("s_store_sk", "s_store_id", "s_store_name")
    item = dfs["item"].select("i_item_sk", "i_item_id", "i_item_desc")

    j = ss.join(sr, on=(col("ss_customer_sk") == col("sr_customer_sk"))
                & (col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")))
    j = j.join(cs, on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
    j = j.join(d1, on=col("ss_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_returned_date_sk",
        "sr_return_quantity", "cs_sold_date_sk", "cs_quantity")
    j = j.join(d2, on=col("sr_returned_date_sk") == col("d_date_sk")) \
        .select("ss_item_sk", "ss_store_sk", "ss_quantity",
                "sr_return_quantity", "cs_sold_date_sk", "cs_quantity")
    j = j.join(d3, on=col("cs_sold_date_sk") == col("d_date_sk")).select(
        "ss_item_sk", "ss_store_sk", "ss_quantity", "sr_return_quantity",
        "cs_quantity")
    j = j.join(store, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(item, on=col("ss_item_sk") == col("i_item_sk"))
    return (j.group_by("i_item_id", "i_item_desc", "s_store_id",
                       "s_store_name").agg(
        ("sum", "ss_quantity", "store_sales_quantity"),
        ("sum", "sr_return_quantity", "store_returns_quantity"),
        ("sum", "cs_quantity", "catalog_sales_quantity"))
        .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
        .limit(100))


def q29_pandas(t):
    d = t["date_dim"]
    d1 = d[(d.d_moy == 9) & (d.d_year == 1999)][["d_date_sk"]]
    d2 = d[(d.d_moy >= 9) & (d.d_moy <= 12)
           & (d.d_year == 1999)][["d_date_sk"]]
    d3 = d[d.d_year.isin([1999, 2000, 2001])][["d_date_sk"]]
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
        right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
    j = j.merge(t["catalog_sales"],
                left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j.merge(d3, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(t["store"][["s_store_sk", "s_store_id", "s_store_name"]],
                left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id", "i_item_desc"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id",
                   "s_store_name"], as_index=False).agg(
        store_sales_quantity=("ss_quantity", "sum"),
        store_returns_quantity=("sr_return_quantity", "sum"),
        catalog_sales_quantity=("cs_quantity", "sum"))
    return (g.sort_values(["i_item_id", "i_item_desc", "s_store_id",
                           "s_store_name"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q32 — excess catalog discounts (avg * 1.3 join-back)
# ---------------------------------------------------------------------------


def q32(dfs):
    it = dfs["item"].filter(col("i_manufact_id") == lit(77)) \
        .select("i_item_sk")
    # Full-year window (the official 90-day window is too sparse for
    # the single item manufact 77 carries at small generator scales).
    dt = (dfs["date_dim"].filter(col("d_year") == lit(2000))
          .select("d_date_sk"))
    cs = dfs["catalog_sales"].select("cs_item_sk", "cs_sold_date_sk",
                                     "cs_ext_discount_amt")
    win = cs.join(dt, on=col("cs_sold_date_sk") == col("d_date_sk"))
    avg_disc = (win.group_by("cs_item_sk")
                .agg(("avg", "cs_ext_discount_amt", "avg_disc")))
    avg_disc = avg_disc.select(col("cs_item_sk").alias("avg_item_sk"),
                               "avg_disc")
    j = win.join(it, on=col("cs_item_sk") == col("i_item_sk"))
    j = j.join(avg_disc, on=col("cs_item_sk") == col("avg_item_sk"))
    j = j.filter(col("cs_ext_discount_amt") > col("avg_disc") * lit(1.3))
    return j.agg(("sum", "cs_ext_discount_amt", "excess_discount_amount"))


def q32_pandas(t):
    it = t["item"][t["item"].i_manufact_id == 77][["i_item_sk"]]
    d = t["date_dim"]
    dt = d[d.d_year == 2000][["d_date_sk"]]
    win = t["catalog_sales"].merge(dt, left_on="cs_sold_date_sk",
                                   right_on="d_date_sk")
    avg_disc = win.groupby("cs_item_sk", as_index=False).agg(
        avg_disc=("cs_ext_discount_amt", "mean"))
    j = win.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(avg_disc, on="cs_item_sk")
    j = j[j.cs_ext_discount_amt > 1.3 * j.avg_disc]
    return pd.DataFrame(
        {"excess_discount_amount": [j.cs_ext_discount_amt.sum()]})


# ---------------------------------------------------------------------------
# q34 / q73 — ticket-size band analysis (counts per ticket joined back)
# ---------------------------------------------------------------------------


def _ticket_counts(dfs, dom_filter, hd_filter, store_filter):
    dt = dfs["date_dim"].filter(dom_filter).select("d_date_sk")
    st = dfs["store"].filter(store_filter).select("s_store_sk")
    hd = dfs["household_demographics"].filter(hd_filter) \
        .select("hd_demo_sk")
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
        "ss_ticket_number")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    return (j.group_by("ss_ticket_number", "ss_customer_sk")
            .agg(("count", "*", "cnt")))


def _ticket_counts_pandas(t, dmask, hmask, smask):
    dt = t["date_dim"][dmask][["d_date_sk"]]
    st = t["store"][smask][["s_store_sk"]]
    hd = t["household_demographics"][hmask][["hd_demo_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    return j.groupby(["ss_ticket_number", "ss_customer_sk"],
                     as_index=False).agg(cnt=("ss_ticket_number", "size"))


def q34(dfs):
    dom = (((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3)))
           | ((col("d_dom") >= lit(25)) & (col("d_dom") <= lit(28)))) \
        & col("d_year").isin(1999, 2000, 2001)
    hd = (col("hd_buy_potential").isin(">10000", "unknown")
          & (col("hd_vehicle_count") > lit(0)))
    counts = _ticket_counts(dfs, dom, hd,
                            col("s_county") == lit("Williamson County"))
    counts = counts.having((col("cnt") >= lit(15)) & (col("cnt") <= lit(20)))
    j = counts.join(dfs["customer"].select("c_customer_sk",
                                           "c_customer_id"),
                    on=col("ss_customer_sk") == col("c_customer_sk"))
    return (j.select("c_customer_id", "ss_ticket_number", "cnt")
            .sort("c_customer_id", "ss_ticket_number").limit(1000))


def q34_pandas(t):
    d = t["date_dim"]
    dmask = (((d.d_dom >= 1) & (d.d_dom <= 3))
             | ((d.d_dom >= 25) & (d.d_dom <= 28))) \
        & d.d_year.isin([1999, 2000, 2001])
    h = t["household_demographics"]
    hmask = h.hd_buy_potential.isin([">10000", "unknown"]) \
        & (h.hd_vehicle_count > 0)
    smask = t["store"].s_county == "Williamson County"
    counts = _ticket_counts_pandas(t, dmask, hmask, smask)
    counts = counts[(counts.cnt >= 15) & (counts.cnt <= 20)]
    j = counts.merge(t["customer"], left_on="ss_customer_sk",
                     right_on="c_customer_sk")
    return (j[["c_customer_id", "ss_ticket_number", "cnt"]]
            .sort_values(["c_customer_id", "ss_ticket_number"])
            .head(1000).reset_index(drop=True))


def q73(dfs):
    dom = ((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
           & col("d_year").isin(1999, 2000, 2001))
    hd = (col("hd_buy_potential").isin(">10000", "unknown")
          & (col("hd_vehicle_count") > lit(0)))
    counts = _ticket_counts(dfs, dom, hd,
                            col("s_county") == lit("Ziebach County"))
    counts = counts.having((col("cnt") >= lit(1)) & (col("cnt") <= lit(5)))
    j = counts.join(dfs["customer"].select("c_customer_sk",
                                           "c_customer_id"),
                    on=col("ss_customer_sk") == col("c_customer_sk"))
    return (j.select("c_customer_id", "ss_ticket_number", "cnt")
            .sort("-cnt", "c_customer_id", "ss_ticket_number").limit(1000))


def q73_pandas(t):
    d = t["date_dim"]
    dmask = (d.d_dom >= 1) & (d.d_dom <= 2) \
        & d.d_year.isin([1999, 2000, 2001])
    h = t["household_demographics"]
    hmask = h.hd_buy_potential.isin([">10000", "unknown"]) \
        & (h.hd_vehicle_count > 0)
    smask = t["store"].s_county == "Ziebach County"
    counts = _ticket_counts_pandas(t, dmask, hmask, smask)
    counts = counts[(counts.cnt >= 1) & (counts.cnt <= 5)]
    j = counts.merge(t["customer"], left_on="ss_customer_sk",
                     right_on="c_customer_sk")
    return (j[["c_customer_id", "ss_ticket_number", "cnt"]]
            .sort_values(["cnt", "c_customer_id", "ss_ticket_number"],
                         ascending=[False, True, True])
            .head(1000).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q27 / q36 / q70 — ROLLUP families (grouping-set unions + per-branch
# lochierarchy, q36/q70 with rank-within-parent windows)
# ---------------------------------------------------------------------------


def _rollup_union(j, levels, measures, session, with_parent=False):
    """UNION of len(levels)+1 grouping sets over `levels` (prefixes, like
    ROLLUP); `measures` maps alias -> (func, input). Adds the
    `lochierarchy` literal per branch (grouping depth, official
    grouping()+grouping() output). `with_parent` adds the official
    rank-partition column `_parent` (the CASE WHEN grouping(leaf)=0 THEN
    <parent level> END): the parent key on LEAF rows, NULL on every
    subtotal row — so all subtotals of one lochierarchy rank against
    each other in one partition."""
    from hyperspace_tpu.engine.dataframe import DataFrame
    from hyperspace_tpu.plan.expr import null
    from hyperspace_tpu.plan.nodes import Union

    names = [name for name, _ in levels]
    branches = []
    for depth in range(len(levels), -1, -1):
        keep = names[:depth]
        aggs = [(func, src, alias) for alias, (func, src) in
                measures.items()]
        if keep:
            g = j.group_by(*keep).agg(*aggs)
        else:
            g = j.agg(*aggs)
        entries = (list(keep)
                   + [null(dtype).alias(name)
                      for name, dtype in levels[depth:]]
                   + [lit(len(levels) - depth).alias("lochierarchy")])
        if with_parent:
            if depth == len(levels):
                entries.append(col(names[-2]).alias("_parent"))
            else:
                entries.append(null(levels[-2][1]).alias("_parent"))
        entries += list(measures)
        branches.append(g.select(*entries).plan)
    return DataFrame(Union(branches), session)


def q27(dfs):
    cd = (dfs["customer_demographics"]
          .filter((col("cd_gender") == lit("M"))
                  & (col("cd_marital_status") == lit("S"))
                  & (col("cd_education_status") == lit("College")))
          .select("cd_demo_sk"))
    dt = dfs["date_dim"].filter(col("d_year") == lit(2000)) \
        .select("d_date_sk")
    st = dfs["store"].filter(col("s_state").isin("TN", "CA")) \
        .select("s_store_sk", "s_state")
    it = dfs["item"].select("i_item_sk", "i_item_id")
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_cdemo_sk",
        "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    j = ss.join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
    j = j.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    u = _rollup_union(j, [("i_item_id", "string"), ("s_state", "string")],
                      {"agg1": ("avg", "ss_quantity"),
                       "agg2": ("avg", "ss_list_price"),
                       "agg3": ("avg", "ss_coupon_amt"),
                       "agg4": ("avg", "ss_sales_price")}, j.session)
    return (u.select("i_item_id", "s_state", "agg1", "agg2", "agg3",
                     "agg4")
            .sort("i_item_id", "s_state").limit(100))


def q27_pandas(t):
    cd = t["customer_demographics"]
    cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
            & (cd.cd_education_status == "College")][["cd_demo_sk"]]
    dt = t["date_dim"][t["date_dim"].d_year == 2000][["d_date_sk"]]
    st = t["store"][t["store"].s_state.isin(["TN", "CA"])][
        ["s_store_sk", "s_state"]]
    j = t["store_sales"].merge(cd, left_on="ss_cdemo_sk",
                               right_on="cd_demo_sk")
    j = j.merge(dt, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(t["item"][["i_item_sk", "i_item_id"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    outs = []
    for keys in (["i_item_id", "s_state"], ["i_item_id"], []):
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean"))
        else:
            g = pd.DataFrame({"agg1": [j.ss_quantity.mean()],
                              "agg2": [j.ss_list_price.mean()],
                              "agg3": [j.ss_coupon_amt.mean()],
                              "agg4": [j.ss_sales_price.mean()]})
        for c in ("i_item_id", "s_state"):
            if c not in g.columns:
                g[c] = np.nan
        outs.append(g[["i_item_id", "s_state", "agg1", "agg2", "agg3",
                       "agg4"]])
    u = pd.concat(outs, ignore_index=True)
    return (u.sort_values(["i_item_id", "s_state"],
                          na_position="first")
            .head(100).reset_index(drop=True))


def q36(dfs):
    dt = dfs["date_dim"].filter(col("d_year") == lit(2000)) \
        .select("d_date_sk")
    st = dfs["store"].filter(col("s_state").isin("TN", "CA", "WA")) \
        .select("s_store_sk")
    it = dfs["item"].select("i_item_sk", "i_category", "i_class")
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_item_sk",
                                   "ss_store_sk", "ss_net_profit",
                                   "ss_ext_sales_price")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(it, on=col("ss_item_sk") == col("i_item_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    u = _rollup_union(j, [("i_category", "string"), ("i_class", "string")],
                      {"profit": ("sum", "ss_net_profit"),
                       "sales": ("sum", "ss_ext_sales_price")}, j.session,
                      with_parent=True)
    u = u.select("i_category", "i_class", "lochierarchy", "_parent",
                 (col("profit") / col("sales")).alias("gross_margin"))
    # Official rank partition: (lochierarchy, CASE WHEN grouping(leaf)=0
    # THEN i_category END) — subtotals of a level rank together.
    w = u.window(["lochierarchy", "_parent"],
                 order_by=["gross_margin"],
                 rank_within_parent=("rank", "*"))
    return (w.select("gross_margin", "i_category", "i_class",
                     "lochierarchy", "rank_within_parent")
            .sort("-lochierarchy", "i_category", "i_class",
                  "rank_within_parent").limit(100))


def q36_pandas(t):
    dt = t["date_dim"][t["date_dim"].d_year == 2000][["d_date_sk"]]
    st = t["store"][t["store"].s_state.isin(["TN", "CA", "WA"])][
        ["s_store_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(t["item"][["i_item_sk", "i_category", "i_class"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    outs = []
    for depth, keys in ((0, ["i_category", "i_class"]),
                        (1, ["i_category"]), (2, [])):
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                profit=("ss_net_profit", "sum"),
                sales=("ss_ext_sales_price", "sum"))
        else:
            g = pd.DataFrame({"profit": [j.ss_net_profit.sum()],
                              "sales": [j.ss_ext_sales_price.sum()]})
        for c in ("i_category", "i_class"):
            if c not in g.columns:
                g[c] = np.nan
        g["lochierarchy"] = depth
        outs.append(g)
    u = pd.concat(outs, ignore_index=True)
    u["gross_margin"] = u.profit / u.sales
    u["_parent"] = u.i_category.where(u.lochierarchy == 0, np.nan)
    u["rank_within_parent"] = u.groupby(
        ["lochierarchy", "_parent"], dropna=False).gross_margin.rank(
        method="min").astype("int64")
    out = u[["gross_margin", "i_category", "i_class", "lochierarchy",
             "rank_within_parent"]]
    return (out.sort_values(["lochierarchy", "i_category", "i_class",
                             "rank_within_parent"],
                            ascending=[False, True, True, True],
                            na_position="first")
            .head(100).reset_index(drop=True))


def q70(dfs):
    dt = dfs["date_dim"].filter(col("d_year") == lit(2000)) \
        .select("d_date_sk")
    ss = dfs["store_sales"].select("ss_sold_date_sk", "ss_store_sk",
                                   "ss_net_profit")
    st = dfs["store"].select("s_store_sk", "s_state", "s_county")
    base = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    base = base.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    # top-5 states by total profit (the official rank()<=5 subquery)
    top_states = (base.group_by("s_state")
                  .agg(("sum", "ss_net_profit", "state_profit"))
                  .sort("-state_profit", "s_state").limit(5)
                  .select(col("s_state").alias("top_state")))
    j = base.join(top_states, on=col("s_state") == col("top_state"),
                  how="left_semi")
    u = _rollup_union(j, [("s_state", "string"), ("s_county", "string")],
                      {"total_sum": ("sum", "ss_net_profit")}, j.session,
                      with_parent=True)
    w = u.window(["lochierarchy", "_parent"], order_by=["-total_sum"],
                 rank_within_parent=("rank", "*"))
    return (w.select("total_sum", "s_state", "s_county", "lochierarchy",
                     "rank_within_parent")
            .sort("-lochierarchy", "s_state", "rank_within_parent",
                  "s_county").limit(100))


def q70_pandas(t):
    dt = t["date_dim"][t["date_dim"].d_year == 2000][["d_date_sk"]]
    base = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                                  right_on="d_date_sk")
    base = base.merge(t["store"][["s_store_sk", "s_state", "s_county"]],
                      left_on="ss_store_sk", right_on="s_store_sk")
    sp = base.groupby("s_state", as_index=False).agg(
        state_profit=("ss_net_profit", "sum"))
    top = sp.sort_values(["state_profit", "s_state"],
                         ascending=[False, True]).head(5).s_state
    j = base[base.s_state.isin(top)]
    outs = []
    for depth, keys in ((0, ["s_state", "s_county"]), (1, ["s_state"]),
                        (2, [])):
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                total_sum=("ss_net_profit", "sum"))
        else:
            g = pd.DataFrame({"total_sum": [j.ss_net_profit.sum()]})
        for c in ("s_state", "s_county"):
            if c not in g.columns:
                g[c] = np.nan
        g["lochierarchy"] = depth
        outs.append(g)
    u = pd.concat(outs, ignore_index=True)
    u["_parent"] = u.s_state.where(u.lochierarchy == 0, np.nan)
    u["rank_within_parent"] = u.groupby(
        ["lochierarchy", "_parent"], dropna=False).total_sum.rank(
        method="min", ascending=False).astype("int64")
    out = u[["total_sum", "s_state", "s_county", "lochierarchy",
             "rank_within_parent"]]
    return (out.sort_values(["lochierarchy", "s_state",
                             "rank_within_parent", "s_county"],
                            ascending=[False, True, True, True],
                            na_position="first")
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q41 — distinct product names of manufacturers with qualifying variants
# ---------------------------------------------------------------------------


def q41(dfs):
    it = dfs["item"]
    variant = ((col("i_category") == lit("Women"))
               & col("i_color").isin("red", "orange")
               & col("i_units").isin("Oz", "Bunch")
               & col("i_size").isin("medium", "small")) | \
              ((col("i_category") == lit("Men"))
               & col("i_color").isin("navy", "blue")
               & col("i_units").isin("Ton", "Dozen")
               & col("i_size").isin("extra large", "petite"))
    qualifying = (it.filter((col("i_manufact_id") >= lit(1))
                            & (col("i_manufact_id") <= lit(120))
                            & variant)
                  .select("i_manufact").distinct())
    j = it.filter((col("i_manufact_id") >= lit(1))
                  & (col("i_manufact_id") <= lit(120)))
    j = j.join(qualifying, on=col("i_manufact") == col("i_manufact"),
               how="left_semi")
    return (j.select("i_product_name").distinct()
            .sort("i_product_name").limit(100))


def q41_pandas(t):
    it = t["item"]
    it = it[(it.i_manufact_id >= 1) & (it.i_manufact_id <= 120)]
    v = ((it.i_category == "Women") & it.i_color.isin(["red", "orange"])
         & it.i_units.isin(["Oz", "Bunch"])
         & it.i_size.isin(["medium", "small"])) | \
        ((it.i_category == "Men") & it.i_color.isin(["navy", "blue"])
         & it.i_units.isin(["Ton", "Dozen"])
         & it.i_size.isin(["extra large", "petite"]))
    manufs = it[v].i_manufact.unique()
    out = it[it.i_manufact.isin(manufs)][["i_product_name"]] \
        .drop_duplicates()
    return (out.sort_values("i_product_name").head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q46 — weekend city shoppers (q68 family: bought city <> current city)
# ---------------------------------------------------------------------------


def q46(dfs):
    dt = (dfs["date_dim"]
          .filter(col("d_dow").isin(0, 6)
                  & col("d_year").isin(1999, 2000, 2001))
          .select("d_date_sk"))
    st = (dfs["store"]
          .filter(col("s_city").isin("Fairview", "Midway"))
          .select("s_store_sk"))
    hd = (dfs["household_demographics"]
          .filter((col("hd_dep_count") == lit(4))
                  | (col("hd_vehicle_count") == lit(3)))
          .select("hd_demo_sk"))
    ss = dfs["store_sales"].select(
        "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
        "ss_customer_sk", "ss_ticket_number", "ss_coupon_amt",
        "ss_net_profit")
    j = ss.join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
    j = j.join(st, on=col("ss_store_sk") == col("s_store_sk"))
    j = j.join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
    j = j.join(dfs["customer_address"].select("ca_address_sk", "ca_city"),
               on=col("ss_addr_sk") == col("ca_address_sk"))
    g = (j.group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(("sum", "ss_coupon_amt", "amt"),
              ("sum", "ss_net_profit", "profit")))
    g = g.select("ss_ticket_number", "ss_customer_sk",
                 col("ca_city").alias("bought_city"), "amt", "profit")
    cust = dfs["customer"].select("c_customer_sk", "c_last_name",
                                  "c_first_name", "c_current_addr_sk")
    j2 = g.join(cust, on=col("ss_customer_sk") == col("c_customer_sk"))
    j2 = j2.join(dfs["customer_address"].select("ca_address_sk",
                                                "ca_city"),
                 on=col("c_current_addr_sk") == col("ca_address_sk"))
    j2 = j2.filter(col("ca_city") != col("bought_city"))
    return (j2.select("c_last_name", "c_first_name", "ca_city",
                      "bought_city", "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "ca_city", "bought_city",
                  "ss_ticket_number").limit(100))


def q46_pandas(t):
    d = t["date_dim"]
    dt = d[d.d_dow.isin([0, 6])
           & d.d_year.isin([1999, 2000, 2001])][["d_date_sk"]]
    st = t["store"][t["store"].s_city.isin(["Fairview", "Midway"])][
        ["s_store_sk"]]
    h = t["household_demographics"]
    hd = h[(h.hd_dep_count == 4) | (h.hd_vehicle_count == 3)][
        ["hd_demo_sk"]]
    j = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    j = j.merge(t["customer_address"][["ca_address_sk", "ca_city"]],
                left_on="ss_addr_sk", right_on="ca_address_sk")
    g = j.groupby(["ss_ticket_number", "ss_customer_sk", "ca_city"],
                  as_index=False).agg(amt=("ss_coupon_amt", "sum"),
                                      profit=("ss_net_profit", "sum"))
    g = g.rename(columns={"ca_city": "bought_city"})
    j2 = g.merge(t["customer"], left_on="ss_customer_sk",
                 right_on="c_customer_sk")
    j2 = j2.merge(t["customer_address"][["ca_address_sk", "ca_city"]],
                  left_on="c_current_addr_sk", right_on="ca_address_sk")
    j2 = j2[j2.ca_city != j2.bought_city]
    out = j2[["c_last_name", "c_first_name", "ca_city", "bought_city",
              "ss_ticket_number", "amt", "profit"]]
    return (out.sort_values(["c_last_name", "c_first_name", "ca_city",
                             "bought_city", "ss_ticket_number"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q81 — catalog returners above 1.2x their state's average (q1's twin)
# ---------------------------------------------------------------------------


def q81(dfs):
    dt = dfs["date_dim"].filter(col("d_year") == lit(2000)) \
        .select("d_date_sk")
    cr = dfs["catalog_returns"].select(
        "cr_returned_date_sk", "cr_returning_customer_sk",
        "cr_return_amt_inc_tax")
    cr = cr.join(dt, on=col("cr_returned_date_sk") == col("d_date_sk"))
    cust = dfs["customer"].select("c_customer_sk", "c_customer_id",
                                  "c_current_addr_sk")
    addr = dfs["customer_address"].select("ca_address_sk", "ca_state")
    j = cr.join(cust,
                on=col("cr_returning_customer_sk") == col("c_customer_sk"))
    j = j.join(addr, on=col("c_current_addr_sk") == col("ca_address_sk"))
    ctr = (j.group_by("c_customer_id", "ca_state")
           .agg(("sum", "cr_return_amt_inc_tax", "ctr_total_return")))
    avg_state = (ctr.group_by("ca_state")
                 .agg(("avg", "ctr_total_return", "ctr_avg")))
    avg_state = avg_state.select(col("ca_state").alias("avg_state"),
                                 "ctr_avg")
    out = ctr.join(avg_state, on=col("ca_state") == col("avg_state"))
    out = out.filter(col("ctr_total_return") > col("ctr_avg") * lit(1.2))
    return (out.select("c_customer_id", "ca_state", "ctr_total_return")
            .sort("c_customer_id", "ca_state").limit(100))


def q81_pandas(t):
    dt = t["date_dim"][t["date_dim"].d_year == 2000][["d_date_sk"]]
    cr = t["catalog_returns"].merge(dt, left_on="cr_returned_date_sk",
                                    right_on="d_date_sk")
    j = cr.merge(t["customer"], left_on="cr_returning_customer_sk",
                 right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    ctr = j.groupby(["c_customer_id", "ca_state"], as_index=False).agg(
        ctr_total_return=("cr_return_amt_inc_tax", "sum"))
    avg_state = ctr.groupby("ca_state", as_index=False).agg(
        ctr_avg=("ctr_total_return", "mean"))
    out = ctr.merge(avg_state, on="ca_state")
    out = out[out.ctr_total_return > 1.2 * out.ctr_avg]
    return (out[["c_customer_id", "ca_state", "ctr_total_return"]]
            .sort_values(["c_customer_id", "ca_state"])
            .head(100).reset_index(drop=True))


# ---------------------------------------------------------------------------
# q93 — actual sales after reason-routed returns (ss LEFT JOIN sr)
# ---------------------------------------------------------------------------


def q93(dfs):
    ss = dfs["store_sales"].select("ss_item_sk", "ss_ticket_number",
                                   "ss_customer_sk", "ss_quantity",
                                   "ss_sales_price")
    sr = dfs["store_returns"].select("sr_item_sk", "sr_ticket_number",
                                     "sr_reason_sk", "sr_return_quantity")
    reason = (dfs["reason"]
              .filter(col("r_reason_desc") == lit("Did not like the "
                                                  "warranty"))
              .select("r_reason_sk"))
    j = ss.join(sr, on=(col("ss_item_sk") == col("sr_item_sk"))
                & (col("ss_ticket_number") == col("sr_ticket_number")),
                how="left_outer")
    j = j.join(reason, on=col("sr_reason_sk") == col("r_reason_sk"))
    act = when(col("sr_return_quantity").is_not_null(),
               (col("ss_quantity") - col("sr_return_quantity"))
               * col("ss_sales_price")) \
        .otherwise(col("ss_quantity") * col("ss_sales_price"))
    g = (j.group_by("ss_customer_sk").agg(("sum", act, "sumsales")))
    return g.sort("sumsales", "ss_customer_sk").limit(100)


def q93_pandas(t):
    reason = t["reason"]
    rk = reason[reason.r_reason_desc
                == "Did not like the warranty"].r_reason_sk
    j = t["store_sales"].merge(
        t["store_returns"], how="left",
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    j = j[j.sr_reason_sk.isin(rk)]
    act = (j.ss_quantity - j.sr_return_quantity.fillna(0)) \
        * j.ss_sales_price
    act = act.where(j.sr_return_quantity.notna(),
                    j.ss_quantity * j.ss_sales_price)
    j = j.assign(act_sales=act)
    g = j.groupby("ss_customer_sk", as_index=False).agg(
        sumsales=("act_sales", "sum"))
    return (g.sort_values(["sumsales", "ss_customer_sk"]).head(100)
            .reset_index(drop=True))


# ---------------------------------------------------------------------------
# q97 — store/catalog customer-item overlap (FULL OUTER join)
# ---------------------------------------------------------------------------


def q97(dfs):
    dt = dfs["date_dim"].filter(col("d_year") == lit(2000)) \
        .select("d_date_sk")
    ssci = (dfs["store_sales"]
            .select("ss_sold_date_sk", "ss_customer_sk", "ss_item_sk")
            .join(dt, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .group_by("ss_customer_sk", "ss_item_sk").agg())
    csci = (dfs["catalog_sales"]
            .select("cs_sold_date_sk", "cs_bill_customer_sk",
                    "cs_item_sk")
            .join(dt, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .group_by("cs_bill_customer_sk", "cs_item_sk").agg())
    j = ssci.join(csci,
                  on=(col("ss_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("ss_item_sk") == col("cs_item_sk")),
                  how="full_outer")
    store_only = when(col("ss_customer_sk").is_not_null()
                      & col("cs_bill_customer_sk").is_null(), 1) \
        .otherwise(0)
    catalog_only = when(col("ss_customer_sk").is_null()
                        & col("cs_bill_customer_sk").is_not_null(), 1) \
        .otherwise(0)
    both = when(col("ss_customer_sk").is_not_null()
                & col("cs_bill_customer_sk").is_not_null(), 1) \
        .otherwise(0)
    return j.agg(("sum", store_only, "store_only"),
                 ("sum", catalog_only, "catalog_only"),
                 ("sum", both, "store_and_catalog"))


def q97_pandas(t):
    dt = t["date_dim"][t["date_dim"].d_year == 2000][["d_date_sk"]]
    ss = t["store_sales"].merge(dt, left_on="ss_sold_date_sk",
                                right_on="d_date_sk")
    ssci = ss[["ss_customer_sk", "ss_item_sk"]].drop_duplicates()
    cs = t["catalog_sales"].merge(dt, left_on="cs_sold_date_sk",
                                  right_on="d_date_sk")
    csci = cs[["cs_bill_customer_sk", "cs_item_sk"]].drop_duplicates()
    j = ssci.merge(csci, how="outer",
                   left_on=["ss_customer_sk", "ss_item_sk"],
                   right_on=["cs_bill_customer_sk", "cs_item_sk"])
    return pd.DataFrame({
        "store_only": [int((j.ss_customer_sk.notna()
                            & j.cs_bill_customer_sk.isna()).sum())],
        "catalog_only": [int((j.ss_customer_sk.isna()
                              & j.cs_bill_customer_sk.notna()).sum())],
        "store_and_catalog": [int((j.ss_customer_sk.notna()
                                   & j.cs_bill_customer_sk.notna()).sum())],
    })


QUERIES_EXT = {
    "q1": (q1, q1_pandas), "q6": (q6, q6_pandas),
    "q20": (q20, q20_pandas), "q27": (q27, q27_pandas),
    "q29": (q29, q29_pandas), "q32": (q32, q32_pandas),
    "q34": (q34, q34_pandas), "q36": (q36, q36_pandas),
    "q41": (q41, q41_pandas), "q46": (q46, q46_pandas),
    "q70": (q70, q70_pandas), "q73": (q73, q73_pandas),
    "q81": (q81, q81_pandas), "q93": (q93, q93_pandas),
    "q97": (q97, q97_pandas),
}
