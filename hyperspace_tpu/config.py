"""Config system.

The reference piggybacks on Spark `SQLConf` string keys declared in
`index/IndexConstants.scala:21-50` and read lazily at use sites
(`actions/CreateActionBase.scala:44-48`). Here `HyperspaceConf` is a small
string-keyed config owned by the session, with the same keys and defaults.
Both the `spark.hyperspace.*` spelling and a `hyperspace.*` short form are
accepted.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from hyperspace_tpu import constants


def _canonical(key: str) -> str:
    if key.startswith("hyperspace."):
        return "spark." + key
    return key


class HyperspaceConf:
    """String-keyed configuration with lazy reads at use sites."""

    def __init__(self, conf: Optional[Dict[str, str]] = None):
        self._conf: Dict[str, str] = {}
        for k, v in (conf or {}).items():
            self.set(k, v)

    def set(self, key: str, value) -> "HyperspaceConf":
        self._conf[_canonical(key)] = str(value)
        return self

    def unset(self, key: str) -> "HyperspaceConf":
        self._conf.pop(_canonical(key), None)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(_canonical(key), default)

    def get_int(self, key: str, default: int) -> int:
        value = self.get(key)
        return int(value) if value is not None else default

    def contains(self, key: str) -> bool:
        return _canonical(key) in self._conf

    # Derived settings, mirroring reference defaulting rules.

    @property
    def warehouse_dir(self) -> str:
        return self.get(constants.WAREHOUSE_PATH,
                        os.path.join(os.getcwd(), constants.WAREHOUSE_PATH_DEFAULT))

    @property
    def system_path(self) -> str:
        """Index system root; default `<warehouse>/indexes`.

        Parity: reference `index/PathResolver.scala:65-69`.
        """
        configured = self.get(constants.INDEX_SYSTEM_PATH)
        if configured:
            return configured
        return os.path.join(self.warehouse_dir, constants.INDEXES_DIR)

    @property
    def num_buckets(self) -> int:
        return self.get_int(constants.INDEX_NUM_BUCKETS,
                            constants.INDEX_NUM_BUCKETS_DEFAULT)

    @property
    def distribution(self) -> str:
        """"auto" | "true" | "false" — see `parallel/context.py`."""
        return (self.get(constants.DISTRIBUTION_ENABLED,
                         constants.DISTRIBUTION_ENABLED_DEFAULT) or
                "auto").lower()

    @property
    def trace_dir(self):
        """Directory for XLA profiler traces of executed queries (None =
        tracing off)."""
        return self.get(constants.TRACE_DIR)

    @property
    def fusion_enabled(self) -> bool:
        """Whole-stage fusion (engine/fusion.py): operator chains compile
        into one jitted executable per chain instead of eager
        per-operator dispatch."""
        return (self.get(constants.FUSION_ENABLED,
                         constants.FUSION_ENABLED_DEFAULT)
                or "true").lower() == "true"

    @property
    def min_device_rows(self) -> int:
        """Batches below this row count run on the host lane."""
        return self.get_int(constants.MIN_DEVICE_ROWS,
                            constants.MIN_DEVICE_ROWS_DEFAULT)

    @property
    def distribution_min_rows(self) -> int:
        return self.get_int(constants.DISTRIBUTION_MIN_ROWS,
                            constants.DISTRIBUTION_MIN_ROWS_DEFAULT)

    @property
    def distribution_spmd(self) -> bool:
        """Born-sharded SPMD execution lane (`parallel/spmd.py`) on/off;
        off = the legacy per-query-placement mesh path."""
        return (self.get(constants.DISTRIBUTION_SPMD,
                         constants.DISTRIBUTION_SPMD_DEFAULT)
                or "true").lower() == "true"

    @property
    def distribution_slices(self) -> int:
        """Number of slices (DCN rows) in the mesh topology.
        `distribution.slices` is canonical; the original
        `distribution.dcn.size` spelling is the legacy fallback."""
        value = self.get(constants.DISTRIBUTION_SLICES)
        if value is not None:
            try:
                return int(value)
            except ValueError:
                return constants.DISTRIBUTION_DCN_SIZE_DEFAULT
        return self.get_int(constants.DISTRIBUTION_DCN_SIZE,
                            constants.DISTRIBUTION_DCN_SIZE_DEFAULT)

    @property
    def distribution_replication(self) -> bool:
        """Read replication across slices (`parallel/replica.py`): each
        slice serves as a full replica and the scheduler routes queries
        to the least-loaded one."""
        return (self.get(constants.DISTRIBUTION_REPLICATION,
                         constants.DISTRIBUTION_REPLICATION_DEFAULT)
                or "true").lower() == "true"

    @property
    def distribution_replication_min_slices(self) -> int:
        return self.get_int(
            constants.DISTRIBUTION_REPLICATION_MIN_SLICES,
            constants.DISTRIBUTION_REPLICATION_MIN_SLICES_DEFAULT)

    @property
    def distribution_replication_hot_fraction(self) -> float:
        value = self.get(constants.DISTRIBUTION_REPLICATION_HOT_FRACTION)
        return (float(value) if value is not None else
                constants.DISTRIBUTION_REPLICATION_HOT_FRACTION_DEFAULT)

    @property
    def distribution_capacity_factor(self) -> float:
        value = self.get(constants.DISTRIBUTION_CAPACITY_FACTOR)
        return (float(value) if value is not None
                else constants.DISTRIBUTION_CAPACITY_FACTOR_DEFAULT)

    @property
    def distribution_dict_max_entries(self) -> int:
        """Per-range string-dictionary entry cap for the recorded
        born-sharded layout (`_shard_layout.json`); <= 0 disables
        recording (readers derive dictionaries from the files)."""
        return self.get_int(constants.DISTRIBUTION_DICT_MAX_ENTRIES,
                            constants.DISTRIBUTION_DICT_MAX_ENTRIES_DEFAULT)

    @property
    def broadcast_threshold(self) -> int:
        """Join sides estimated under this many bytes broadcast as a
        direct-address table instead of riding Exchange+Sort; <= 0
        disables (Spark `autoBroadcastJoinThreshold` analog)."""
        return self.get_int(constants.BROADCAST_THRESHOLD,
                            constants.BROADCAST_THRESHOLD_DEFAULT)

    @property
    def read_cache_bytes(self):
        """Host decoded-batch cache budget; None = env/process default.
        The cache itself is PROCESS-wide — a session that sets this
        governs the shared cache while its queries run, so sessions
        sharing a process should agree on it."""
        value = self.get(constants.READ_CACHE_BYTES_KEY)
        return int(value) if value is not None else None

    @property
    def device_cache_bytes(self):
        """Legacy spelling of the HBM segment-cache budget (the old
        device-batch LRU); kept as the fallback key for
        `segment_cache_bytes`."""
        value = self.get(constants.DEVICE_CACHE_BYTES_KEY)
        return int(value) if value is not None else None

    @property
    def segment_cache_bytes(self):
        """HBM segment-cache budget (`io/segcache.py`); None = the
        legacy `cache.device.bytes` key, then the env/process default.
        Competes with join/sort working sets for device memory — lower
        it (or 0) when large queries OOM; 0 releases already-resident
        segments. Process-wide cache, same caveat as
        read_cache_bytes."""
        value = self.get(constants.SEGMENT_CACHE_BYTES_KEY)
        if value is not None:
            return int(value)
        return self.device_cache_bytes

    @property
    def segment_cache_host_bytes(self) -> int:
        """Host-RAM tier budget of the tiered segment cache
        (`io/segcache.py`): device-tier evictions demote into host
        memory up to this many bytes instead of dropping, and a later
        read re-promotes through the TransferEngine fill lane (H2D
        paid, parquet decode skipped). 0 (default) disables the tier."""
        return self.get_int(constants.SEGMENT_CACHE_HOST_BYTES_KEY,
                            constants.SEGMENT_CACHE_HOST_BYTES_DEFAULT)

    @property
    def segment_cache_pin_indexes(self) -> str:
        """Comma-separated index names whose cached segments are never
        evicted by byte pressure (invalidation still drops them)."""
        return self.get(constants.SEGMENT_CACHE_PIN_INDEXES, "") or ""

    @property
    def fusion_promote_cache_bytes(self) -> int:
        """Byte budget for the fusion device-promotion cache (host
        source columns held device-resident between executions); evicts
        dead-source entries first, then oldest-inserted."""
        return self.get_int(constants.FUSION_PROMOTE_CACHE_BYTES,
                            constants.FUSION_PROMOTE_CACHE_BYTES_DEFAULT)

    @property
    def fusion_bcast_cache_bytes(self) -> int:
        """Byte budget for the broadcast direct-address table cache."""
        return self.get_int(constants.FUSION_BCAST_CACHE_BYTES,
                            constants.FUSION_BCAST_CACHE_BYTES_DEFAULT)

    @property
    def io_retry_attempts(self) -> int:
        """Total tries (first call included) for transient storage-IO
        failures; see `utils/retry.py`."""
        return self.get_int(constants.IO_RETRY_ATTEMPTS,
                            constants.IO_RETRY_ATTEMPTS_DEFAULT)

    @property
    def io_retry_base_ms(self) -> float:
        """First backoff delay; doubles per retry (jittered)."""
        return float(self.get(constants.IO_RETRY_BASE_MS,
                              str(constants.IO_RETRY_BASE_MS_DEFAULT)))

    @property
    def io_retry_max_ms(self) -> float:
        """Backoff ceiling per retry."""
        return float(self.get(constants.IO_RETRY_MAX_MS,
                              str(constants.IO_RETRY_MAX_MS_DEFAULT)))

    @property
    def io_transfer_chunk_bytes(self) -> int:
        """Chunk granularity of pipelined H2D stagings
        (`io/transfer.py`); large arrays ship as row chunks of at most
        this many bytes."""
        return self.get_int(constants.IO_TRANSFER_CHUNK_BYTES,
                            constants.IO_TRANSFER_CHUNK_BYTES_DEFAULT)

    @property
    def io_transfer_inflight_bytes(self) -> int:
        """Bound on bytes in flight over the device link across all
        outstanding puts (the transfer engine blocks the oldest put
        before admitting more)."""
        return self.get_int(constants.IO_TRANSFER_INFLIGHT_BYTES,
                            constants.IO_TRANSFER_INFLIGHT_BYTES_DEFAULT)

    @property
    def io_transfer_threads(self) -> int:
        """Staging-thread pool width: how many column decodes / chunk
        conversions can run ahead of the link."""
        return self.get_int(constants.IO_TRANSFER_THREADS,
                            constants.IO_TRANSFER_THREADS_DEFAULT)

    @property
    def io_transfer_acquire_timeout_ms(self) -> float:
        """Bound on waiting for in-flight-window headroom before a put
        raises a typed transient `TransferAcquireTimeoutError` instead
        of hanging on bytes a dead transfer never released; <= 0
        disables the bound."""
        return float(self.get(
            constants.IO_TRANSFER_ACQUIRE_TIMEOUT_MS,
            str(constants.IO_TRANSFER_ACQUIRE_TIMEOUT_MS_DEFAULT)))

    @property
    def serve_hbm_budget_bytes(self) -> int:
        """Serving-plane admission budget: the sum of concurrently
        admitted queries' projected HBM footprints stays under this; 0
        (the default) disables budgeting. Process-wide scheduler —
        co-resident sessions should agree (same caveat as the transfer
        knobs)."""
        return self.get_int(constants.SERVE_HBM_BUDGET_BYTES,
                            constants.SERVE_HBM_BUDGET_BYTES_DEFAULT)

    @property
    def serve_queue_depth(self) -> int:
        """How many over-budget queries may WAIT for admission; a query
        arriving at a full queue gets a typed QueryRejectedError
        immediately (backpressure to the caller)."""
        return self.get_int(constants.SERVE_QUEUE_DEPTH,
                            constants.SERVE_QUEUE_DEPTH_DEFAULT)

    @property
    def serve_deadline_seconds(self) -> float:
        """Default per-query deadline (queued time included); 0 = none.
        `collect(timeout=...)` overrides per call."""
        return float(self.get(constants.SERVE_DEADLINE_SECONDS,
                              str(constants.SERVE_DEADLINE_SECONDS_DEFAULT)))

    @property
    def serve_batch_enabled(self) -> bool:
        """Inter-query batched execution (`engine/batcher.py`):
        concurrent same-signature point/filter queries coalesce into
        one jitted predicate program over the shared scan. "false"
        restores strictly per-query execution."""
        return (self.get(constants.SERVE_BATCH_ENABLED,
                         constants.SERVE_BATCH_ENABLED_DEFAULT)
                or "true").lower() == "true"

    @property
    def serve_batch_window_ms(self) -> float:
        """Gather window: how long the first query of a signature waits
        for cohort joiners before executing. Skipped when nothing else
        is in flight (serial latency untouched)."""
        return float(self.get(
            constants.SERVE_BATCH_WINDOW_MS,
            str(constants.SERVE_BATCH_WINDOW_MS_DEFAULT)))

    @property
    def serve_batch_max(self) -> int:
        """Cohort-size cap per batched invocation; also the top padded
        constant-lane bucket (cohorts pad to the next power of two up
        to this, so K is a compile bucket, not a retrace)."""
        return self.get_int(constants.SERVE_BATCH_MAX,
                            constants.SERVE_BATCH_MAX_DEFAULT)

    @property
    def serve_batch_aot_warmup(self) -> bool:
        """Pre-compile the canonical cohort-size buckets of a batch
        signature the first time it is seen (and for the explicit
        `engine.batcher.warmup(df)` replica API)."""
        return (self.get(constants.SERVE_BATCH_AOT_WARMUP,
                         constants.SERVE_BATCH_AOT_WARMUP_DEFAULT)
                or "true").lower() == "true"

    @property
    def serve_breaker_failures(self) -> int:
        """Degraded-fallback count within the window that OPENS a
        per-index circuit breaker (known-bad index skips straight to
        the source plan)."""
        return self.get_int(constants.SERVE_BREAKER_FAILURES,
                            constants.SERVE_BREAKER_FAILURES_DEFAULT)

    @property
    def serve_breaker_window_seconds(self) -> float:
        return float(self.get(
            constants.SERVE_BREAKER_WINDOW_SECONDS,
            str(constants.SERVE_BREAKER_WINDOW_SECONDS_DEFAULT)))

    @property
    def serve_breaker_cooldown_seconds(self) -> float:
        """Open-state dwell before one half-open probe is allowed."""
        return float(self.get(
            constants.SERVE_BREAKER_COOLDOWN_SECONDS,
            str(constants.SERVE_BREAKER_COOLDOWN_SECONDS_DEFAULT)))

    @property
    def serve_slo_p99_seconds(self) -> float:
        """Sliding-window SLO target: 99% of queries must finish under
        this many seconds. 0 (default) disables SLO tracking."""
        return float(self.get(constants.SERVE_SLO_P99_SECONDS,
                              str(constants.SERVE_SLO_P99_SECONDS_DEFAULT)))

    @property
    def serve_slo_window_seconds(self) -> float:
        """Span of the sliding window the burn rate is computed over
        (also the default trailing window of the timeseries sampler's
        `window.*` quantile gauges)."""
        return float(self.get(
            constants.SERVE_SLO_WINDOW_SECONDS,
            str(constants.SERVE_SLO_WINDOW_SECONDS_DEFAULT)))

    @property
    def serve_slo_shed_enabled(self) -> bool:
        """Opt-in load shedding: while the SLO burn rate exceeds 1.0
        the admission wait queue is tightened to half its configured
        depth (`serve.slo.shed` counts queries the tightening
        rejected). Off by default — tracking alone never sheds."""
        return (self.get(constants.SERVE_SLO_SHED_ENABLED,
                         constants.SERVE_SLO_SHED_ENABLED_DEFAULT)
                or "false").lower() == "true"

    # -- multi-tenant serving (tenant id embedded in the conf key) -----

    def serve_tenant_weight(self, tenant: str) -> float:
        """Deficit-round-robin dequeue weight for `tenant` (default
        1.0). Relative: a weight-2 tenant drains its wait queue twice
        as fast as a weight-1 tenant under contention."""
        v = self.get(f"{constants.SERVE_TENANT_PREFIX}{tenant}.weight")
        try:
            w = float(v) if v is not None else \
                constants.SERVE_TENANT_WEIGHT_DEFAULT
        except ValueError:
            w = constants.SERVE_TENANT_WEIGHT_DEFAULT
        return w if w > 0 else constants.SERVE_TENANT_WEIGHT_DEFAULT

    def serve_tenant_hbm_fraction(self, tenant: str) -> float:
        """Fraction of `serve.hbm.budget.bytes` the tenant may hold
        admitted concurrently (0, the default, = unlimited)."""
        v = self.get(
            f"{constants.SERVE_TENANT_PREFIX}{tenant}.hbm.fraction")
        try:
            f = float(v) if v is not None else \
                constants.SERVE_TENANT_HBM_FRACTION_DEFAULT
        except ValueError:
            f = constants.SERVE_TENANT_HBM_FRACTION_DEFAULT
        return min(max(f, 0.0), 1.0)

    def serve_tenant_queue_depth(self, tenant: str) -> int:
        """Per-tenant cap on WAITING queries (0, the default, = only
        the global `serve.queue.depth` applies)."""
        return self.get_int(
            f"{constants.SERVE_TENANT_PREFIX}{tenant}.queue.depth",
            constants.SERVE_TENANT_QUEUE_DEPTH_DEFAULT)

    def advisor_tenant_budget_bytes(self, tenant: str) -> int:
        """Per-tenant cap on summed estimated index bytes the advisor
        may auto-build for candidates mined from that tenant's queries
        (0, the default, = only the global advisor budget applies)."""
        return self.get_int(
            f"{constants.ADVISOR_TENANT_PREFIX}{tenant}.budget.bytes",
            constants.ADVISOR_TENANT_BUDGET_BYTES_DEFAULT)

    @property
    def telemetry_ops_port(self) -> Optional[int]:
        """Operations-plane HTTP port (`telemetry/ops_server.py`):
        unset (default) = no server; 0 = bind an ephemeral port; any
        other value = bind that port. Setting it also starts the
        background timeseries sampler."""
        value = self.get(constants.TELEMETRY_OPS_PORT)
        if value is None or value == "":
            return None
        return int(value)

    @property
    def telemetry_ops_host(self) -> str:
        """Bind address of the ops server — localhost by default (the
        endpoints are unauthenticated; exposing them wider is an
        explicit decision)."""
        return self.get(constants.TELEMETRY_OPS_HOST,
                        constants.TELEMETRY_OPS_HOST_DEFAULT) \
            or constants.TELEMETRY_OPS_HOST_DEFAULT

    @property
    def timeseries_interval_seconds(self) -> float:
        """Fixed sampling interval of the background timeseries
        sampler (`telemetry/timeseries.py`)."""
        return float(self.get(
            constants.TELEMETRY_TIMESERIES_INTERVAL_SECONDS,
            str(constants.TELEMETRY_TIMESERIES_INTERVAL_SECONDS_DEFAULT)))

    @property
    def timeseries_capacity(self) -> int:
        """Bound on the sampler's ring (samples retained; older samples
        rotate out)."""
        return self.get_int(
            constants.TELEMETRY_TIMESERIES_CAPACITY,
            constants.TELEMETRY_TIMESERIES_CAPACITY_DEFAULT)

    @property
    def slowlog_seconds(self) -> float:
        """Slow-query dump threshold for the flight recorder
        (`telemetry/flight.py`): any query whose wall exceeds this many
        seconds persists its full metric tree, a registry snapshot,
        and a trace slice to `slowlog_dir`. 0 (the default) disables
        dumping; the in-memory ring of recent queries is always on."""
        return float(self.get(constants.TELEMETRY_SLOWLOG_SECONDS,
                              str(constants.TELEMETRY_SLOWLOG_SECONDS_DEFAULT)))

    @property
    def slowlog_dir(self) -> str:
        """Slow-query dump directory; default `<warehouse>/slowlog`."""
        configured = self.get(constants.TELEMETRY_SLOWLOG_DIR)
        if configured:
            return configured
        return os.path.join(self.warehouse_dir, "slowlog")

    @property
    def slowlog_keep(self) -> int:
        """How many slow-query dump files to retain (oldest pruned)."""
        return self.get_int(constants.TELEMETRY_SLOWLOG_KEEP,
                            constants.TELEMETRY_SLOWLOG_KEEP_DEFAULT)

    @property
    def critpath_enabled(self) -> bool:
        """Per-query critical-path stamping
        (`telemetry/critical_path.py`): "false" skips the decomposition
        at query finish (the per-segment source counters still
        record)."""
        return (self.get(constants.TELEMETRY_CRITPATH_ENABLED,
                         constants.TELEMETRY_CRITPATH_ENABLED_DEFAULT)
                or "true").lower() == "true"

    @property
    def profiler_enabled(self) -> bool:
        """Host sampling profiler (`telemetry/profiler.py`): "true"
        starts the stack-sampling daemon at session init."""
        return (self.get(constants.TELEMETRY_PROFILER_ENABLED,
                         constants.TELEMETRY_PROFILER_ENABLED_DEFAULT)
                or "false").lower() == "true"

    @property
    def profiler_hz(self) -> float:
        """Stack-sampling rate of the host profiler (samples/second;
        the default sits off the 10/100 Hz grid to avoid aliasing
        periodic work)."""
        return float(self.get(
            constants.TELEMETRY_PROFILER_HZ,
            str(constants.TELEMETRY_PROFILER_HZ_DEFAULT)))

    @property
    def profiler_capture_seconds(self) -> float:
        """Length of a TRIGGERED device-trace capture (SLO burn or a
        slowlog dump fires one). 0 (the default) disarms triggered
        capture."""
        return float(self.get(
            constants.TELEMETRY_PROFILER_CAPTURE_SECONDS,
            str(constants.TELEMETRY_PROFILER_CAPTURE_SECONDS_DEFAULT)))

    @property
    def profiler_capture_keep(self) -> int:
        """How many triggered `profile-*` capture directories to
        retain next to the slow-query dumps (oldest pruned)."""
        return self.get_int(
            constants.TELEMETRY_PROFILER_CAPTURE_KEEP,
            constants.TELEMETRY_PROFILER_CAPTURE_KEEP_DEFAULT)

    @property
    def profiler_capture_min_interval_s(self) -> float:
        """Rate limit between triggered captures — a sustained SLO
        burn produces a trickle of profiles, not a flood."""
        return float(self.get(
            constants.TELEMETRY_PROFILER_CAPTURE_MIN_INTERVAL_SECONDS,
            str(constants
                .TELEMETRY_PROFILER_CAPTURE_MIN_INTERVAL_SECONDS_DEFAULT)))

    @property
    def telemetry_history_enabled(self) -> bool:
        """Durable on-lake telemetry history (`telemetry/history.py`):
        "true" makes the sampler's tick hook flush periodic history
        segments under `telemetry_history_dir`. Off by default — the
        history store writes to the warehouse, which is an explicit
        operator decision."""
        return (self.get(constants.TELEMETRY_HISTORY_ENABLED,
                         constants.TELEMETRY_HISTORY_ENABLED_DEFAULT)
                or "false").lower() == "true"

    @property
    def telemetry_history_dir(self) -> str:
        """History segment directory; defaults to
        `constants.TELEMETRY_HISTORY_DIRNAME` under the warehouse
        (telemetry history is metadata, and metadata lives on the
        lake)."""
        configured = self.get(constants.TELEMETRY_HISTORY_DIR)
        if configured:
            return configured
        return os.path.join(self.warehouse_dir,
                            constants.TELEMETRY_HISTORY_DIRNAME)

    @property
    def telemetry_history_interval_seconds(self) -> float:
        """Minimum seconds between periodic history flushes (incident
        flushes are immediate and ignore this)."""
        return float(self.get(
            constants.TELEMETRY_HISTORY_INTERVAL_SECONDS,
            str(constants.TELEMETRY_HISTORY_INTERVAL_SECONDS_DEFAULT)))

    @property
    def telemetry_history_keep_seconds(self) -> float:
        """Age past which history segments are pruned (0 = keep by
        byte budget only)."""
        return float(self.get(
            constants.TELEMETRY_HISTORY_KEEP_SECONDS,
            str(constants.TELEMETRY_HISTORY_KEEP_SECONDS_DEFAULT)))

    @property
    def telemetry_history_keep_bytes(self) -> int:
        """Total byte budget of the history directory; oldest segments
        pruned beyond it (0 = no byte bound)."""
        return self.get_int(constants.TELEMETRY_HISTORY_KEEP_BYTES,
                            constants.TELEMETRY_HISTORY_KEEP_BYTES_DEFAULT)

    @property
    def alerts_enabled(self) -> bool:
        """Rule-driven alerting (`telemetry/alerts.py`): "false" skips
        rule evaluation on sampler ticks entirely."""
        return (self.get(constants.TELEMETRY_ALERTS_ENABLED,
                         constants.TELEMETRY_ALERTS_ENABLED_DEFAULT)
                or "true").lower() == "true"

    def alert_rule_override(self, rule: str, knob: str) -> Optional[str]:
        """Per-rule alert override (`telemetry.alerts.rule.<rule>.
        <knob>`), or None when unset. Knobs: `enabled`, `threshold`,
        `clear`, `sustain.seconds`, `window.seconds`."""
        return self.get(
            f"{constants.TELEMETRY_ALERTS_RULE_PREFIX}{rule}.{knob}")

    @property
    def skipping_enabled(self) -> bool:
        """Query-side gate on data-skipping pruning (`plan/rules/
        skipping.py`): "false" stops FilterIndexRule consulting sketch
        blobs (unpruned scans — correct, just unaccelerated). Build
        verbs ignore it."""
        return (self.get(constants.SKIPPING_ENABLED,
                         constants.SKIPPING_ENABLED_DEFAULT)
                or "true").lower() == "true"

    @property
    def skipping_bloom_fpp(self) -> float:
        """Target false-positive rate of the per-file blocked bloom
        filters; sizes the filter from the file's row count."""
        return float(self.get(constants.SKIPPING_BLOOM_FPP,
                              str(constants.SKIPPING_BLOOM_FPP_DEFAULT)))

    @property
    def skipping_bloom_max_bytes(self) -> int:
        """Per-file, per-column cap on bloom filter bytes — a huge file
        gets a degraded (higher-FPP) filter, never an unbounded blob."""
        return self.get_int(constants.SKIPPING_BLOOM_MAX_BYTES,
                            constants.SKIPPING_BLOOM_MAX_BYTES_DEFAULT)

    @property
    def skipping_zorder_files(self) -> int:
        """Output file count of the optional Z-order clustering rewrite
        at data-skipping build time (more files = tighter zones)."""
        return self.get_int(constants.SKIPPING_ZORDER_FILES,
                            constants.SKIPPING_ZORDER_FILES_DEFAULT)

    @property
    def compile_cache_dir(self):
        """Directory for JAX's persistent compilation cache (warm-start
        compilation: a fresh replica's first canonical-shape query
        loads persisted executables instead of tracing). None = off.
        Wired at session init via
        `telemetry.compilation.configure_persistent_cache`."""
        return self.get(constants.COMPILE_CACHE_DIR)

    @property
    def advisor_enabled(self) -> bool:
        """Self-driving index advisor (`hyperspace_tpu/advisor/`) on/off
        — "false" makes `IndexAdvisor.run_once` a mine-only no-op (no
        recommendations acted on, no builds)."""
        return (self.get(constants.ADVISOR_ENABLED,
                         constants.ADVISOR_ENABLED_DEFAULT)
                or "true").lower() == "true"

    @property
    def advisor_build_budget_bytes(self) -> int:
        """Per-run cap on summed ESTIMATED index bytes the advisor may
        build (its per-warehouse build budget)."""
        return self.get_int(constants.ADVISOR_BUILD_BUDGET_BYTES,
                            constants.ADVISOR_BUILD_BUDGET_BYTES_DEFAULT)

    @property
    def advisor_max_builds(self) -> int:
        """How many builds one advisor run may start."""
        return self.get_int(constants.ADVISOR_MAX_BUILDS,
                            constants.ADVISOR_MAX_BUILDS_DEFAULT)

    @property
    def advisor_serve_headroom(self) -> float:
        """Fraction of `serve.hbm.budget.bytes` that may be admitted
        before the advisor defers its builds (never starve admission)."""
        return float(self.get(
            constants.ADVISOR_SERVE_HEADROOM,
            str(constants.ADVISOR_SERVE_HEADROOM_DEFAULT)))

    @property
    def advisor_min_benefit_bytes(self) -> int:
        """Minimum amortized bytes-avoided estimate before a candidate
        is recommended."""
        return self.get_int(constants.ADVISOR_MIN_BENEFIT_BYTES,
                            constants.ADVISOR_MIN_BENEFIT_BYTES_DEFAULT)

    @property
    def advisor_skipping_prune_fraction(self) -> float:
        """Assumed prune effectiveness of a hypothetical data-skipping
        index in the what-if math (sketches don't exist yet, so this is
        a conservative constant, not a measurement)."""
        return float(self.get(
            constants.ADVISOR_SKIPPING_PRUNE_FRACTION,
            str(constants.ADVISOR_SKIPPING_PRUNE_FRACTION_DEFAULT)))

    @property
    def advisor_min_repeats(self) -> int:
        """Observed repeat count below which a workload signature is
        not considered recurring."""
        return self.get_int(constants.ADVISOR_MIN_REPEATS,
                            constants.ADVISOR_MIN_REPEATS_DEFAULT)

    @property
    def ingest_interval_seconds(self) -> float:
        """Cadence between ingest-coordinator micro-batch ticks; the
        caller's loop sleeps this long between `run_once` calls (the
        coordinator never owns a thread)."""
        return float(self.get(constants.INGEST_INTERVAL_SECONDS,
                              str(constants.INGEST_INTERVAL_SECONDS_DEFAULT)))

    @property
    def ingest_serve_headroom(self) -> float:
        """Fraction of `serve.hbm.budget.bytes` that may be admitted
        before the ingest coordinator defers index refresh (appends
        still land; refresh never starves admission)."""
        return float(self.get(constants.INGEST_SERVE_HEADROOM,
                              str(constants.INGEST_SERVE_HEADROOM_DEFAULT)))

    @property
    def ingest_conflict_attempts(self) -> int:
        """Total refresh tries per tick when the coordinator loses the
        op-log race to a manual refresher, before it concedes."""
        return self.get_int(constants.INGEST_CONFLICT_ATTEMPTS,
                            constants.INGEST_CONFLICT_ATTEMPTS_DEFAULT)

    @property
    def maintenance_lease_seconds(self) -> int:
        """Age past which a transient op-log entry is treated as a crashed
        writer and auto-recovered (Cancel FSM) by the next maintenance
        action; `Hyperspace.recover_index` forces it immediately."""
        return self.get_int(constants.MAINTENANCE_LEASE_SECONDS,
                            constants.MAINTENANCE_LEASE_SECONDS_DEFAULT)

    @property
    def cache_expiry_seconds(self) -> int:
        return self.get_int(
            constants.INDEX_CACHE_EXPIRY_DURATION_SECONDS,
            constants.INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT)

    def copy(self) -> "HyperspaceConf":
        return HyperspaceConf(dict(self._conf))
