"""Window functions over partitions: rank / dense_rank / row_number and
aggregates (sum/avg/min/max/count), appended as columns with the input
row order preserved.

The reference delegates windows to Spark SQL; here they compile to the
same sorted-segment machinery aggregation uses: ONE stable sort keyed
(partition lanes, order lanes), segment ids from partition-lane change
flags, rank family via cumulative max/sum over tie-run flags, partition
aggregates as segment reductions broadcast back through the segment ids,
and an inverse permutation restoring input order. Host batches run the
numpy mirror; device batches stay XLA end to end.

Frames follow SQL/Spark defaults: an aggregate WITHOUT order_by is
whole-partition; WITH order_by it is the running frame
`RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW` — cumulative over
the partition, peers (order-key ties) included. Running sum/avg/count
ride a segment-rebased cumsum; running min/max a segmented prefix scan
(`associative_scan` on device, log-step numpy on host); the peer-run
last index maps the row frame onto the RANGE frame.

SQL semantics: NULL is its own partition/peer value (validity rides the
sort lanes); aggregates skip NULL inputs; a frame with zero non-null
inputs yields NULL for sum/avg/min/max and 0 for count.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.plan.schema import Schema

RANK_FUNCS = ("rank", "dense_rank", "row_number")
AGG_FUNCS = ("sum", "avg", "min", "max", "count")


def window_compute(batch: ColumnBatch, partition_by: Sequence[str],
                   order_by: Sequence[str], specs,
                   out_schema: Schema) -> ColumnBatch:
    """`specs` are AggSpec-shaped (func, column, alias). Returns `batch`
    with one appended column per spec, rows in the INPUT order."""
    from hyperspace_tpu.ops.sort import sort_permutation

    n = batch.num_rows
    host = batch.is_host
    if host:
        xp = np
        cummax = np.maximum.accumulate
        from hyperspace_tpu.ops.keys import (
            host_column_sort_lanes as lanes_of)
    else:
        import jax
        import jax.numpy as jnp
        xp = jnp
        cummax = jax.lax.cummax
        from hyperspace_tpu.ops.keys import column_sort_lanes as lanes_of

    from hyperspace_tpu.io.columnar import HOST_NP_DTYPES

    if n == 0:
        columns = dict(batch.columns)
        for spec in specs:
            f = out_schema.field(spec.alias)
            dt = HOST_NP_DTYPES.get(f.dtype, np.int64)
            columns[f.name] = DeviceColumn(
                np.zeros(0, dtype=dt) if host
                else xp.zeros(0, dtype=dt), f.dtype)
        return ColumnBatch(out_schema, columns)

    by = list(partition_by) + list(order_by)
    perm = sort_permutation(batch, by) if by else xp.arange(n, dtype=np.int32)
    sorted_batch = batch.take(perm)

    def change_flags(names):
        """True where any of `names`'s sort lanes differ from the previous
        sorted row (column names may carry a '-' descending prefix —
        direction doesn't matter for equality)."""
        from hyperspace_tpu.plan.nodes import sort_direction
        changed = xp.zeros(n - 1, dtype=bool) if n > 1 else xp.zeros(
            0, dtype=bool)
        for spec_name in names:
            name, _ = sort_direction(spec_name)
            for lane in lanes_of(sorted_batch.column(name)):
                lane = xp.asarray(lane)
                changed = changed | (lane[1:] != lane[:-1])
        return changed

    first = xp.ones(1, dtype=bool)
    seg_flag = xp.concatenate([first, change_flags(partition_by)])
    seg_ids = (xp.cumsum(seg_flag.astype(np.int32)) - 1).astype(np.int32)
    num_segs_arr = seg_ids[-1] + 1
    iota = xp.arange(n, dtype=np.int64)
    # First row index of each row's segment, broadcast per row.
    seg_first = cummax(xp.where(seg_flag, iota, xp.zeros_like(iota)))

    agg_needed = [s for s in specs if s.func in AGG_FUNCS]
    # SQL default frames: aggregates with order_by are RUNNING (RANGE
    # UNBOUNDED PRECEDING..CURRENT ROW, peers included); without order_by
    # they are whole-partition.
    running = bool(order_by) and bool(agg_needed)
    rank_needed = any(s.func in RANK_FUNCS and s.func != "row_number"
                      for s in specs)
    if rank_needed or running:
        peer_flag = xp.concatenate([first, change_flags(by)])
        run_first = cummax(xp.where(peer_flag, iota, xp.zeros_like(iota)))
    if rank_needed:
        dense = xp.cumsum(peer_flag.astype(np.int64))
    if running:
        # Last sorted index of each row's peer run: the next peer-run
        # start (suffix-min over start positions, shifted) minus one.
        # RANGE-frame values are the row-frame running values read there.
        starts = xp.where(peer_flag, iota, n)
        if host:
            suffmin = np.minimum.accumulate(starts[::-1])[::-1]
        else:
            import jax
            suffmin = jax.lax.cummin(starts, reverse=True)
        run_last = xp.concatenate(
            [suffmin[1:], xp.full(1, n, dtype=starts.dtype)]) - 1

    if agg_needed and not running:
        num_segs = int(num_segs_arr)  # one host sync, shared by all specs

    out_sorted = {}
    for spec in specs:
        if spec.func == "row_number":
            out_sorted[spec.alias] = DeviceColumn(
                (iota - seg_first + 1).astype(np.int64), "int64")
            continue
        if spec.func == "rank":
            out_sorted[spec.alias] = DeviceColumn(
                (run_first - seg_first + 1).astype(np.int64), "int64")
            continue
        if spec.func == "dense_rank":
            # Peer-run ordinal within the segment: dense index at the row
            # minus the dense index at the segment's first row, + 1.
            seg_dense = (dense[seg_first] if host
                         else xp.take(dense, seg_first))
            out_sorted[spec.alias] = DeviceColumn(
                (dense - seg_dense + 1).astype(np.int64), "int64")
            continue
        # Aggregate: running (order_by given) or whole-partition.
        f = out_schema.field(spec.alias)
        src = sorted_batch.column(spec.column) if spec.column != "*" else None
        if src is not None and src.is_string and spec.func != "count":
            raise HyperspaceException(
                f"Window {spec.func} over string column {spec.column} "
                "is not supported.")
        if running:
            if spec.func == "count" and spec.column == "*":
                out_sorted[spec.alias] = DeviceColumn(
                    (run_last - seg_first + 1).astype(np.int64), "int64")
                continue
            valid = (xp.asarray(src.validity) if src.validity is not None
                     else xp.ones(n, dtype=bool))
            rcounts = _take(_running_sum(valid.astype(np.int64), seg_first,
                                         host, xp), run_last, host, xp)
            if spec.func == "count":
                out_sorted[spec.alias] = DeviceColumn(rcounts, "int64")
                continue
            values = xp.asarray(src.data)
            if spec.func in ("sum", "avg"):
                acc = np.float64 if (f.dtype == "float64"
                                     or spec.func == "avg") else np.int64
                masked = xp.where(valid, values, 0).astype(acc)
                # Integer sums: exact global-cumsum rebase. Float sums:
                # segmented scan — rebasing subtracts the WHOLE preceding
                # prefix, which catastrophically cancels when an earlier
                # partition's magnitude dwarfs this one's values.
                if acc is np.int64:
                    row_sum = _running_sum(masked, seg_first, host, xp)
                else:
                    row_sum = _running_scan(masked, seg_flag, seg_ids,
                                            "add", host)
                rtotal = _take(row_sum, run_last, host, xp)
                r = (rtotal if spec.func == "sum"
                     else rtotal.astype(np.float64)
                     / xp.maximum(rcounts, 1))
            else:
                if spec.func == "min":
                    fill = (np.inf if values.dtype.kind == "f"
                            else np.iinfo(values.dtype).max)
                else:
                    fill = (-np.inf if values.dtype.kind == "f"
                            else np.iinfo(values.dtype).min)
                r = _take(
                    _running_scan(xp.where(valid, values, fill), seg_flag,
                                  seg_ids, spec.func, host), run_last,
                    host, xp)
            out_sorted[spec.alias] = DeviceColumn(
                r.astype(HOST_NP_DTYPES.get(f.dtype, np.int64)), f.dtype,
                validity=rcounts > 0)
            continue
        # Whole-partition: segment-reduce, broadcast back.
        if spec.func == "count" and spec.column == "*":
            ones = xp.ones(n, dtype=np.int64)
            per_seg = _seg_sum(ones, seg_ids, num_segs, host)
            out_sorted[spec.alias] = DeviceColumn(
                _bcast(per_seg, seg_ids, host, xp), "int64")
            continue
        valid = (xp.asarray(src.validity) if src.validity is not None
                 else xp.ones(n, dtype=bool))
        counts = _seg_sum(valid.astype(np.int64), seg_ids, num_segs, host)
        if spec.func == "count":
            out_sorted[spec.alias] = DeviceColumn(
                _bcast(counts, seg_ids, host, xp), "int64")
            continue
        values = xp.asarray(src.data)
        if spec.func in ("sum", "avg"):
            acc = np.float64 if f.dtype == "float64" else np.int64
            total = _seg_sum(xp.where(valid, values, 0).astype(acc),
                             seg_ids, num_segs, host)
            per_seg = (total if spec.func == "sum"
                       else total.astype(np.float64)
                       / xp.maximum(counts, 1))
        elif spec.func == "min":
            big = (np.inf if values.dtype.kind == "f"
                   else np.iinfo(values.dtype).max)
            per_seg = _seg_min(xp.where(valid, values, big), seg_ids,
                               num_segs, host)
        else:  # max
            small = (-np.inf if values.dtype.kind == "f"
                     else np.iinfo(values.dtype).min)
            per_seg = _seg_max(xp.where(valid, values, small), seg_ids,
                               num_segs, host)
        data = _bcast(per_seg, seg_ids, host, xp)
        validity = _bcast(counts > 0, seg_ids, host, xp)
        out_sorted[spec.alias] = DeviceColumn(
            data.astype(HOST_NP_DTYPES.get(f.dtype, np.int64)), f.dtype,
            validity=validity)

    # Inverse permutation: out[perm[i]] = sorted_val[i].
    if host:
        inv = np.empty(n, dtype=np.int32)
        inv[np.asarray(perm)] = np.arange(n, dtype=np.int32)
    else:
        import jax.numpy as jnp
        inv = jnp.zeros(n, dtype=jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32))
    columns = dict(batch.columns)
    for spec in specs:
        col = out_sorted[spec.alias]
        f = out_schema.field(spec.alias)
        columns[f.name] = DeviceColumn(
            col.data[inv] if host else xp.take(col.data, inv),
            col.dtype,
            validity=(None if col.validity is None else
                      (col.validity[inv] if host
                       else xp.take(col.validity, inv))))
    return ColumnBatch(out_schema, columns)


def _seg_sum(x, seg_ids, num_segs, host):
    if host:
        # seg_ids are sorted-contiguous here, so reduceat applies — and
        # keeps int64 sums exact (bincount's float64 weights would not).
        starts = np.searchsorted(seg_ids, np.arange(num_segs), "left")
        return np.add.reduceat(x, starts)
    import jax
    return jax.ops.segment_sum(x, seg_ids, num_segments=num_segs)


def _seg_min(x, seg_ids, num_segs, host):
    if host:
        return np.minimum.reduceat(
            x, np.searchsorted(seg_ids, np.arange(num_segs), "left"))
    import jax
    return jax.ops.segment_min(x, seg_ids, num_segments=num_segs)


def _seg_max(x, seg_ids, num_segs, host):
    if host:
        return np.maximum.reduceat(
            x, np.searchsorted(seg_ids, np.arange(num_segs), "left"))
    import jax
    return jax.ops.segment_max(x, seg_ids, num_segments=num_segs)


def _bcast(per_seg, seg_ids, host, xp):
    return per_seg[seg_ids] if host else xp.take(per_seg, seg_ids)


def _take(arr, idx, host, xp):
    return arr[idx] if host else xp.take(arr, idx)


def _running_sum(x, seg_first, host, xp):
    """Segment-rebased INCLUSIVE cumsum: at sorted row i, the sum of x
    over [segment start, i]. Exact for integer accumulators (one global
    cumsum minus the value just before each segment's start)."""
    g = xp.cumsum(x)
    head = _take(x, seg_first, host, xp)
    base = _take(g, seg_first, host, xp) - head
    return g - base


def _running_scan(x, seg_flag, seg_ids, func, host):
    """Segmented inclusive prefix min/max/sum. Device: one fused
    `associative_scan` with a start-flag reset combiner. Host: log-step
    Hillis-Steele passes masked to same-segment positions."""
    n = x.shape[0]
    if not host:
        import jax
        import jax.numpy as jnp
        op = {"min": jnp.minimum, "max": jnp.maximum, "add": jnp.add}[func]
        def combine(a, b):
            va, fa = a
            vb, fb = b
            return jnp.where(fb, vb, op(va, vb)), fa | fb
        v, _ = jax.lax.associative_scan(combine, (x, seg_flag))
        return v
    op = {"min": np.minimum, "max": np.maximum, "add": np.add}[func]
    out = np.asarray(x).copy()
    ids = np.asarray(seg_ids)
    k = 1
    while k < n:
        same = np.concatenate([np.zeros(k, dtype=bool), ids[k:] == ids[:-k]])
        prev = np.concatenate([out[:k], out[:-k]])
        out = np.where(same, op(out, prev), out)
        k *= 2
    return out
