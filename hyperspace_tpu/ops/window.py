"""Window functions over partitions: rank / dense_rank / row_number and
partition-wide aggregates (sum/avg/min/max/count), appended as columns
with the input row order preserved.

The reference delegates windows to Spark SQL; here they compile to the
same sorted-segment machinery aggregation uses: ONE stable sort keyed
(partition lanes, order lanes), segment ids from partition-lane change
flags, rank family via cumulative max/sum over tie-run flags, partition
aggregates as segment reductions broadcast back through the segment ids,
and an inverse permutation restoring input order. Host batches run the
numpy mirror; device batches stay XLA end to end.

SQL semantics: NULL is its own partition/peer value (validity rides the
sort lanes); aggregates skip NULL inputs; a partition with zero non-null
inputs yields NULL for sum/avg/min/max and 0 for count.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.plan.schema import Schema

RANK_FUNCS = ("rank", "dense_rank", "row_number")
AGG_FUNCS = ("sum", "avg", "min", "max", "count")


def window_compute(batch: ColumnBatch, partition_by: Sequence[str],
                   order_by: Sequence[str], specs,
                   out_schema: Schema) -> ColumnBatch:
    """`specs` are AggSpec-shaped (func, column, alias). Returns `batch`
    with one appended column per spec, rows in the INPUT order."""
    from hyperspace_tpu.ops.sort import sort_permutation

    n = batch.num_rows
    host = batch.is_host
    if host:
        xp = np
        cummax = np.maximum.accumulate
        from hyperspace_tpu.ops.keys import (
            host_column_sort_lanes as lanes_of)
    else:
        import jax
        import jax.numpy as jnp
        xp = jnp
        cummax = jax.lax.cummax
        from hyperspace_tpu.ops.keys import column_sort_lanes as lanes_of

    from hyperspace_tpu.io.columnar import HOST_NP_DTYPES

    if n == 0:
        columns = dict(batch.columns)
        for spec in specs:
            f = out_schema.field(spec.alias)
            dt = HOST_NP_DTYPES.get(f.dtype, np.int64)
            columns[f.name] = DeviceColumn(
                np.zeros(0, dtype=dt) if host
                else xp.zeros(0, dtype=dt), f.dtype)
        return ColumnBatch(out_schema, columns)

    by = list(partition_by) + list(order_by)
    perm = sort_permutation(batch, by) if by else xp.arange(n, dtype=np.int32)
    sorted_batch = batch.take(perm)

    def change_flags(names):
        """True where any of `names`'s sort lanes differ from the previous
        sorted row (column names may carry a '-' descending prefix —
        direction doesn't matter for equality)."""
        from hyperspace_tpu.plan.nodes import sort_direction
        changed = xp.zeros(n - 1, dtype=bool) if n > 1 else xp.zeros(
            0, dtype=bool)
        for spec_name in names:
            name, _ = sort_direction(spec_name)
            for lane in lanes_of(sorted_batch.column(name)):
                lane = xp.asarray(lane)
                changed = changed | (lane[1:] != lane[:-1])
        return changed

    first = xp.ones(1, dtype=bool)
    seg_flag = xp.concatenate([first, change_flags(partition_by)])
    seg_ids = (xp.cumsum(seg_flag.astype(np.int32)) - 1).astype(np.int32)
    num_segs_arr = seg_ids[-1] + 1
    iota = xp.arange(n, dtype=np.int64)
    # First row index of each row's segment, broadcast per row.
    seg_first = cummax(xp.where(seg_flag, iota, xp.zeros_like(iota)))

    rank_needed = any(s.func in RANK_FUNCS and s.func != "row_number"
                      for s in specs)
    if rank_needed:
        peer_flag = xp.concatenate([first, change_flags(by)])
        run_first = cummax(xp.where(peer_flag, iota, xp.zeros_like(iota)))
        dense = xp.cumsum(peer_flag.astype(np.int64))

    agg_needed = [s for s in specs if s.func in AGG_FUNCS]
    if agg_needed:
        num_segs = int(num_segs_arr)  # one host sync, shared by all specs

    out_sorted = {}
    for spec in specs:
        if spec.func == "row_number":
            out_sorted[spec.alias] = DeviceColumn(
                (iota - seg_first + 1).astype(np.int64), "int64")
            continue
        if spec.func == "rank":
            out_sorted[spec.alias] = DeviceColumn(
                (run_first - seg_first + 1).astype(np.int64), "int64")
            continue
        if spec.func == "dense_rank":
            # Peer-run ordinal within the segment: dense index at the row
            # minus the dense index at the segment's first row, + 1.
            seg_dense = (dense[seg_first] if host
                         else xp.take(dense, seg_first))
            out_sorted[spec.alias] = DeviceColumn(
                (dense - seg_dense + 1).astype(np.int64), "int64")
            continue
        # Partition-wide aggregate: segment-reduce, broadcast back.
        f = out_schema.field(spec.alias)
        src = sorted_batch.column(spec.column) if spec.column != "*" else None
        if spec.func == "count" and spec.column == "*":
            ones = xp.ones(n, dtype=np.int64)
            per_seg = _seg_sum(ones, seg_ids, num_segs, host)
            out_sorted[spec.alias] = DeviceColumn(
                _bcast(per_seg, seg_ids, host, xp), "int64")
            continue
        if src.is_string and spec.func != "count":
            raise HyperspaceException(
                f"Window {spec.func} over string column {spec.column} "
                "is not supported.")
        valid = (xp.asarray(src.validity) if src.validity is not None
                 else xp.ones(n, dtype=bool))
        counts = _seg_sum(valid.astype(np.int64), seg_ids, num_segs, host)
        if spec.func == "count":
            out_sorted[spec.alias] = DeviceColumn(
                _bcast(counts, seg_ids, host, xp), "int64")
            continue
        values = xp.asarray(src.data)
        if spec.func in ("sum", "avg"):
            acc = np.float64 if f.dtype == "float64" else np.int64
            total = _seg_sum(xp.where(valid, values, 0).astype(acc),
                             seg_ids, num_segs, host)
            per_seg = (total if spec.func == "sum"
                       else total.astype(np.float64)
                       / xp.maximum(counts, 1))
        elif spec.func == "min":
            big = (np.inf if values.dtype.kind == "f"
                   else np.iinfo(values.dtype).max)
            per_seg = _seg_min(xp.where(valid, values, big), seg_ids,
                               num_segs, host)
        else:  # max
            small = (-np.inf if values.dtype.kind == "f"
                     else np.iinfo(values.dtype).min)
            per_seg = _seg_max(xp.where(valid, values, small), seg_ids,
                               num_segs, host)
        data = _bcast(per_seg, seg_ids, host, xp)
        validity = _bcast(counts > 0, seg_ids, host, xp)
        out_sorted[spec.alias] = DeviceColumn(
            data.astype(HOST_NP_DTYPES.get(f.dtype, np.int64)), f.dtype,
            validity=validity)

    # Inverse permutation: out[perm[i]] = sorted_val[i].
    if host:
        inv = np.empty(n, dtype=np.int32)
        inv[np.asarray(perm)] = np.arange(n, dtype=np.int32)
    else:
        import jax.numpy as jnp
        inv = jnp.zeros(n, dtype=jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32))
    columns = dict(batch.columns)
    for spec in specs:
        col = out_sorted[spec.alias]
        f = out_schema.field(spec.alias)
        columns[f.name] = DeviceColumn(
            col.data[inv] if host else xp.take(col.data, inv),
            col.dtype,
            validity=(None if col.validity is None else
                      (col.validity[inv] if host
                       else xp.take(col.validity, inv))))
    return ColumnBatch(out_schema, columns)


def _seg_sum(x, seg_ids, num_segs, host):
    if host:
        # seg_ids are sorted-contiguous here, so reduceat applies — and
        # keeps int64 sums exact (bincount's float64 weights would not).
        starts = np.searchsorted(seg_ids, np.arange(num_segs), "left")
        return np.add.reduceat(x, starts)
    import jax
    return jax.ops.segment_sum(x, seg_ids, num_segments=num_segs)


def _seg_min(x, seg_ids, num_segs, host):
    if host:
        return np.minimum.reduceat(
            x, np.searchsorted(seg_ids, np.arange(num_segs), "left"))
    import jax
    return jax.ops.segment_min(x, seg_ids, num_segments=num_segs)


def _seg_max(x, seg_ids, num_segs, host):
    if host:
        return np.maximum.reduceat(
            x, np.searchsorted(seg_ids, np.arange(num_segs), "left"))
    import jax
    return jax.ops.segment_max(x, seg_ids, num_segments=num_segs)


def _bcast(per_seg, seg_ids, host, xp):
    return per_seg[seg_ids] if host else xp.take(per_seg, seg_ids)
