"""Device merge-join kernels over sorted columnar batches.

The reference's query-time win is Spark's SortMergeJoin with Exchange+Sort
elided thanks to bucketed relations (`index/rules/JoinIndexRule.scala:41-43`).
The device equivalent joins two *sorted* key columns entirely with
vectorized XLA primitives — no scalar merge loop (which would defeat the
TPU's vector units):

1. multi-column keys are first reduced to order-preserving dense group ids
   by a joint sort over both sides (`encode_join_keys`) — this also makes
   string keys from different dictionaries comparable;
2. per left row, the matching right range is found with two
   `searchsorted` calls (lo/hi);
3. the ragged match expansion is linearized by an exclusive cumsum and one
   `searchsorted` over output slots — static shapes everywhere except one
   host sync for the total match count, which happens at result
   materialization anyway.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch


def encode_join_keys(left: ColumnBatch, right: ColumnBatch,
                     left_keys: Sequence[str], right_keys: Sequence[str]):
    """Map key tuples of both sides onto shared order-preserving dense int32
    group ids (equal tuples <-> equal ids, and ids sort in key order).

    SQL join-null semantics: rows with a NULL in any key column must match
    nothing. They are assigned the sentinels -1 (left) / -2 (right), which
    never compare equal across sides; because sorts place nulls first
    (validity is the leading sub-key, `ops/sort.py`), the sentinels land at
    the front of an already key-sorted batch and preserve the sortedness
    invariant `merge_join_indices` relies on.

    There is exactly ONE device key-identity implementation — the 32-bit
    lane encoder in `ops/bucketed_join.encode_group_ids` (normalized float
    order bits: -0.0 == 0.0, NaN == NaN) — so the global and bucketed
    join paths can never diverge on which tuples compare equal.
    """
    from hyperspace_tpu.ops.bucketed_join import encode_group_ids
    return encode_group_ids(left, right, left_keys, right_keys)


def _join_lane_operands(left: ColumnBatch, right: ColumnBatch,
                        left_keys: Sequence[str],
                        right_keys: Sequence[str]):
    """Per-side 32-bit lane tuples for the ONE-SORT counting join: a
    null-marker lane (0 = valid keys; 1 = left-null; 2 = right-null — so
    null keys form single-side runs and match nothing, the shared join
    null semantics) followed by the order-preserving value lanes
    (`ops/keys.py`). Strings unify onto one merged dictionary first."""
    import jax.numpy as jnp

    from hyperspace_tpu.io.columnar import unify_string_columns
    from hyperspace_tpu.ops import keys as keymod

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    l_valid = jnp.ones(n, dtype=bool)
    r_valid = jnp.ones(m, dtype=bool)
    l_lanes: List = []
    r_lanes: List = []
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        if lcol.validity is not None:
            l_valid = l_valid & lcol.validity
        if rcol.validity is not None:
            r_valid = r_valid & rcol.validity
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        l_lanes.extend(keymod.key_lanes(ldata))
        r_lanes.extend(keymod.key_lanes(rdata))
    marker_l = jnp.where(l_valid, jnp.int32(0), jnp.int32(1))
    marker_r = jnp.where(r_valid, jnp.int32(0), jnp.int32(2))
    return (marker_l, *l_lanes), (marker_r, *r_lanes)


def _runs_to_counts(differs, side_s, left_outer: bool):
    """Shared tail of the counting match: per-run right-counts and
    bracket starts from the (T-1) adjacent-key-difference vector over
    the sorted (key, side, orig) sequence."""
    import jax
    import jax.numpy as jnp

    T = side_s.shape[0]
    pos = jnp.arange(T, dtype=jnp.int32)
    run_start = jnp.concatenate([jnp.ones(1, bool), differs])
    run_first = jax.lax.cummax(jnp.where(run_start, pos, 0))
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(run_start, pos, jnp.int32(T)))))
    run_last = jnp.concatenate([nxt[1:], jnp.full(1, T, jnp.int32)]) - 1
    R = jnp.cumsum(side_s)  # inclusive right-element count
    rights = (jnp.take(R, run_last) - jnp.take(R, run_first)
              + jnp.take(side_s, run_first))
    rstart = run_last - rights + 1
    counts = jnp.where(side_s == 0, rights, 0).astype(jnp.int32)
    if left_outer:
        counts = jnp.where(side_s == 0, jnp.maximum(counts, 1), 0)
    starts = jnp.cumsum(counts) - counts
    return counts, starts, rights, rstart


@__import__("functools").partial(__import__("jax").jit,
                                 static_argnames=("left_outer",))
def _counting_match_lanes(lanes_l, lanes_r, left_outer: bool):
    """The counting match directly over raw key LANES — ONE staged sort
    of (marker, *value lanes, side, orig) replaces the earlier two-sort
    pipeline (dense-id encode sort + id/side match sort): runs come from
    adjacent lane differences in the single sorted sequence. Orig
    indices ride as trailing sort keys (unique, so equivalent to the
    stable carried-value formulation)."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import _staged_sort

    n, m = lanes_l[0].shape[0], lanes_r[0].shape[0]
    lanes = [jnp.concatenate([a, b]) for a, b in zip(lanes_l, lanes_r)]
    side = jnp.concatenate([jnp.zeros(n, jnp.int32),
                            jnp.ones(m, jnp.int32)])
    orig = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                            jnp.arange(m, dtype=jnp.int32)])
    _, sorted_ops = _staged_sort([*lanes, side, orig])
    side_s = sorted_ops[-2]
    orig_s = sorted_ops[-1]
    keys_sorted = sorted_ops[:-2]
    T = n + m
    differs = jnp.zeros(T - 1, dtype=bool)
    for k in keys_sorted:
        differs = differs | (k[1:] != k[:-1])
    counts, starts, rights, rstart = _runs_to_counts(differs, side_s,
                                                     left_outer)
    return counts, starts, rights, rstart, orig_s


# Wide join keys route through ONE u64-hash-lane sort instead of the
# chunked multi-lane sort (same trick, same collision fallback as
# `ops/aggregate._group_phase_a_hashed`). Below this lane count (incl.
# the null-marker lane) the narrow sort is already a single pass.
HASH_MATCH_MIN_LANES = 4


@__import__("functools").partial(__import__("jax").jit,
                                 static_argnames=("left_outer",))
def _counting_match_lanes_hashed(lanes_l, lanes_r, left_outer: bool):
    """Hashed counting match: sort (u64 key-hash, side, orig) — one
    3-operand sort regardless of key width — then derive runs from the
    FULL lane differences (gathered through the permutation). Equal keys
    share a hash so runs stay contiguous unless two different keys
    collide; `collision` (any full-key boundary inside an equal-hash
    run, exactly the split/interleave case) tells the caller to re-run
    the exact path. Run order within a key run is (side, orig), same as
    the exact sort's trailing operands."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hash_partition import dual_hash64

    n, m = lanes_l[0].shape[0], lanes_r[0].shape[0]
    T = n + m
    lanes = [jnp.concatenate([a, b]) for a, b in zip(lanes_l, lanes_r)]
    h = dual_hash64(lanes)

    side = jnp.concatenate([jnp.zeros(n, jnp.int32),
                            jnp.ones(m, jnp.int32)])
    orig = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                            jnp.arange(m, dtype=jnp.int32)])
    h_s, side_s, orig_s = jax.lax.sort([h, side, orig], num_keys=3,
                                       is_stable=False)
    gidx = orig_s + side_s * jnp.int32(n)
    differs = jnp.zeros(T - 1, dtype=bool)
    for k in lanes:
        ks = jnp.take(k, gidx)
        differs = differs | (ks[1:] != ks[:-1])
    h_differs = h_s[1:] != h_s[:-1]
    collision = jnp.any(differs & ~h_differs)
    counts, starts, rights, rstart = _runs_to_counts(differs, side_s,
                                                     left_outer)
    return counts, starts, rights, rstart, orig_s, collision


def _match_lanes(lanes_l, lanes_r, left_outer: bool):
    """(counts, starts, rights, rstart, orig_s, collision|None): the
    hashed match for wide keys, the exact narrow sort otherwise. A None
    collision needs no verification; a device-scalar collision must be
    folded into the caller's sizing sync, and a truthy value means
    re-running via `_counting_match_lanes`."""
    if len(lanes_l) >= HASH_MATCH_MIN_LANES:
        return _counting_match_lanes_hashed(lanes_l, lanes_r, left_outer)
    return (*_counting_match_lanes(lanes_l, lanes_r, left_outer), None)


def _packed_sync(value_dev, collision):
    """ONE device fetch carrying (sizing value, collision flag): returns
    (int value, collided). `value_dev` must be an int64 device scalar."""
    import jax.numpy as jnp

    packed = int(value_dev * jnp.int64(2) + collision.astype(jnp.int64))
    return packed >> 1, bool(packed & 1)


def counting_join_batch_indices(left: ColumnBatch, right: ColumnBatch,
                                left_keys: Sequence[str],
                                right_keys: Sequence[str],
                                how: str = "inner") -> Tuple:
    """Device join row-index pairs straight from the key COLUMNS: one
    fused sort+count executable and one host sync. Same null semantics
    as the id-based `counting_join_indices` (which remains for id-space
    callers); pair ORDER is deterministic per path but unspecified —
    wide keys (>= HASH_MATCH_MIN_LANES lanes) come back in hash-run
    order, narrow keys in key-sorted order."""
    import jax.numpy as jnp

    left_outer = how == "left_outer"
    n, m = left.num_rows, right.num_rows
    empty = jnp.zeros(0, dtype=jnp.int32)
    if n == 0 or (m == 0 and not left_outer):
        return empty, empty
    if m == 0:
        return (jnp.arange(n, dtype=jnp.int32),
                jnp.full(n, -1, dtype=jnp.int32))
    lanes_l, lanes_r = _join_lane_operands(left, right, left_keys,
                                           right_keys)
    counts, starts, rights, rstart, orig_s, collision = _match_lanes(
        lanes_l, lanes_r, left_outer)
    if collision is None:
        total = int(jnp.sum(counts, dtype=jnp.int64))  # the one host sync
    else:
        # One sync carries (total, collision); a collision re-runs exact.
        total, collided = _packed_sync(jnp.sum(counts, dtype=jnp.int64),
                                       collision)
        if collided:
            counts, starts, rights, rstart, orig_s = _counting_match_lanes(
                lanes_l, lanes_r, left_outer)
            total = int(jnp.sum(counts, dtype=jnp.int64))
    if total == 0:
        return empty, empty
    return _counting_expand(counts, starts, rights, rstart, orig_s,
                            total, left_outer)


def counting_join_indices(l_ids, r_ids, how: str = "inner") -> Tuple:
    """Join row-index pairs over UNSORTED id arrays (original row space),
    via ONE joint sort + cumulative counting — no `searchsorted`.

    On TPU, `searchsorted` over tens of millions of rows lowers to
    log(n) serialized gather sweeps and dominated the join at TPC-DS
    scale (measured ~17-20s of a 22s 39M-row join); a flat 1-D
    `lax.sort` of the same rows runs in ~1s. So: sort (id, side,
    original index) once, derive per-id-run right-row counts and bracket
    starts from cumulative sums over the SORTED sequence, and expand
    matches with `jnp.repeat`. 4-5x faster end-to-end at 39M rows, and
    callers no longer pre-sort their payload batches — indices come back
    in original row space.

    Supports how='inner' and 'left_outer' (unmatched left rows appear
    once with right index -1); callers express right/full outer by
    swapping / appending as usual. Null sentinels (-1 left, -2 right)
    form single-side runs, so they match nothing.
    """
    import jax
    import jax.numpy as jnp

    left_outer = how == "left_outer"
    n, m = int(l_ids.shape[0]), int(r_ids.shape[0])
    empty = jnp.zeros(0, dtype=jnp.int32)
    if n == 0 or (m == 0 and not left_outer):
        return empty, empty
    if m == 0:
        return (jnp.arange(n, dtype=jnp.int32),
                jnp.full(n, -1, dtype=jnp.int32))
    counts, starts, rights, rstart, orig_s = _counting_match(
        l_ids, r_ids, left_outer)
    total = int(jnp.sum(counts))  # the one host sync
    if total == 0:
        return empty, empty
    return _counting_expand(counts, starts, rights, rstart, orig_s,
                            total, left_outer)


@__import__("functools").partial(__import__("jax").jit,
                                 static_argnames=("left_outer",))
def _counting_match(l_ids, r_ids, left_outer: bool):
    import jax
    import jax.numpy as jnp

    n, m = l_ids.shape[0], r_ids.shape[0]
    T = n + m
    ids2 = jnp.concatenate([l_ids, r_ids])
    side = jnp.concatenate([jnp.zeros(n, jnp.int32),
                            jnp.ones(m, jnp.int32)])
    orig = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                            jnp.arange(m, dtype=jnp.int32)])
    ids_s, side_s, orig_s = jax.lax.sort([ids2, side, orig], num_keys=2,
                                         is_stable=True)
    pos = jnp.arange(T, dtype=jnp.int32)
    run_start = jnp.concatenate([jnp.ones(1, bool),
                                 ids_s[1:] != ids_s[:-1]])
    run_first = jax.lax.cummax(jnp.where(run_start, pos, 0))
    # Exclusive run end: position of the NEXT run start (reverse cummin).
    nxt = jnp.flip(jax.lax.cummin(jnp.flip(
        jnp.where(run_start, pos, jnp.int32(T)))))
    run_last = jnp.concatenate([nxt[1:], jnp.full(1, T, jnp.int32)]) - 1
    R = jnp.cumsum(side_s)  # inclusive right-element count
    rights = (jnp.take(R, run_last) - jnp.take(R, run_first)
              + jnp.take(side_s, run_first))
    rstart = run_last - rights + 1  # first right element of the run
    counts = jnp.where(side_s == 0, rights, 0).astype(jnp.int32)
    if left_outer:
        counts = jnp.where(side_s == 0, jnp.maximum(counts, 1), 0)
    starts = jnp.cumsum(counts) - counts
    return counts, starts, rights, rstart, orig_s


@__import__("functools").partial(
    __import__("jax").jit, static_argnames=("total", "left_outer"))
def _counting_expand(counts, starts, rights, rstart, orig_s, total: int,
                     left_outer: bool):
    import jax.numpy as jnp

    rows = jnp.repeat(jnp.arange(counts.shape[0], dtype=jnp.int32),
                      counts, total_repeat_length=total)
    slots = jnp.arange(total, dtype=starts.dtype)
    offset = (slots - jnp.take(starts, rows)).astype(jnp.int32)
    li = jnp.take(orig_s, rows)
    r_sorted_pos = jnp.clip(jnp.take(rstart, rows) + offset, 0,
                            orig_s.shape[0] - 1)
    ri = jnp.take(orig_s, r_sorted_pos)
    if left_outer:
        ri = jnp.where(jnp.take(rights, rows) > 0, ri, jnp.int32(-1))
    return li, ri


def merge_join_indices(left_ids, right_ids, how: str = "inner") -> Tuple:
    """Join row index pairs of two *sorted* id arrays.

    Returns (left_idx, right_idx) device arrays of equal length; for
    how='left_outer' every unmatched left row appears once with right index
    -1. One host sync (the total count) sizes the output.
    """
    import jax.numpy as jnp

    lo = jnp.searchsorted(right_ids, left_ids, side="left")
    hi = jnp.searchsorted(right_ids, left_ids, side="right")
    counts = hi - lo
    if how == "left_outer":
        counts = jnp.maximum(counts, 1)
    starts = jnp.cumsum(counts) - counts  # exclusive cumsum
    total = int(jnp.sum(counts))  # host sync — sizes the result
    if total == 0:
        empty = jnp.zeros(0, dtype=jnp.int32)
        return empty, empty
    slots = jnp.arange(total, dtype=counts.dtype)
    left_idx = jnp.searchsorted(starts, slots, side="right") - 1
    matched = jnp.take(hi, left_idx) > jnp.take(lo, left_idx)
    right_idx = jnp.take(lo, left_idx) + (slots - jnp.take(starts, left_idx))
    right_idx = jnp.where(matched, right_idx, -1)
    return left_idx.astype(jnp.int32), right_idx.astype(jnp.int32)


def unmatched_right_from_indices(ri, num_right: int):
    """Right-row indices absent from a join's right index vector `ri` —
    the rows a FULL OUTER join appends after its left_outer expansion.
    Derived by scatter from the ALREADY-COMPUTED match indices, so the
    keys are never re-encoded. Works on host (numpy) and device arrays;
    the device path costs one host sync to size the output."""
    import numpy as np_

    if isinstance(ri, np_.ndarray):
        matched = np_.zeros(num_right, dtype=bool)
        hit = ri[ri >= 0]
        matched[hit] = True
        return np_.nonzero(~matched)[0].astype(np_.int32)
    import jax.numpy as jnp

    hit = ri >= 0
    matched = jnp.zeros(num_right, dtype=bool).at[
        jnp.where(hit, ri, 0)].max(hit)
    count = int(jnp.sum(~matched))  # host sync
    if count == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    (idx,) = jnp.nonzero(~matched, size=count, fill_value=0)
    return idx.astype(jnp.int32)


def semi_anti_indices(left: ColumnBatch, right: ColumnBatch,
                      left_keys: Sequence[str], right_keys: Sequence[str],
                      anti: bool = False):
    """Left-row indices for LEFT SEMI (has >= 1 match) or LEFT ANTI
    (NOT EXISTS: no match; null-key left rows are emitted) joins. Host
    batches compute in numpy; device batches in one XLA program + one
    host sync."""
    import numpy as np_

    if left.num_rows == 0:
        return np_.zeros(0, dtype=np_.int32)
    if left.is_host and right.is_host:
        if right.num_rows == 0:
            matched = np_.zeros(left.num_rows, dtype=bool)
        else:
            packed = _packed_keys(left, right, left_keys, right_keys)
            if packed is not None:
                lv, rv = packed
                rs = np_.sort(rv)
                matched = (np_.searchsorted(rs, lv, side="left")
                           < np_.searchsorted(rs, lv, side="right"))
            else:
                l_ids, r_ids = _host_encode_join_keys(
                    left, right, left_keys, right_keys)
                rs = np_.sort(r_ids)
                matched = (np_.searchsorted(rs, l_ids, side="left")
                           < np_.searchsorted(rs, l_ids, side="right"))
        mask = ~matched if anti else matched
        return np_.nonzero(mask)[0].astype(np_.int32)
    import jax.numpy as jnp

    if right.num_rows == 0:
        if anti:
            return jnp.arange(left.num_rows, dtype=jnp.int32)
        return jnp.zeros(0, dtype=jnp.int32)
    # Membership via the one-sort counting match over raw key lanes:
    # with left_outer counting, counts > 0 marks exactly the LEFT
    # elements in sorted space, and `rights` holds each element's run
    # match count. Scatter-max back to original row order (right
    # elements carry False so they never touch a left slot).
    lanes_l, lanes_r = _join_lane_operands(left, right, left_keys,
                                           right_keys)

    def membership_mask(counts, rights, orig_s):
        is_left = counts > 0
        hit = is_left & ((rights == 0) if anti else (rights > 0))
        # Right-side orig values (0..m-1) can exceed left.num_rows; they
        # carry hit=False, but drop them explicitly rather than relying
        # on JAX's default out-of-bounds scatter behavior.
        return jnp.zeros(left.num_rows, dtype=bool).at[orig_s].max(
            hit, mode="drop")

    counts, _starts, rights, _rstart, orig_s, collision = _match_lanes(
        lanes_l, lanes_r, True)
    mask = membership_mask(counts, rights, orig_s)
    if collision is None:
        count = int(jnp.sum(mask))  # host sync
    else:
        count, collided = _packed_sync(jnp.sum(mask, dtype=jnp.int64),
                                       collision)
        if collided:  # hash collision: exact re-run
            counts, _starts, rights, _rstart, orig_s = \
                _counting_match_lanes(lanes_l, lanes_r, True)
            mask = membership_mask(counts, rights, orig_s)
            count = int(jnp.sum(mask))
    if count == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    (idx,) = jnp.nonzero(mask, size=count, fill_value=0)
    return idx.astype(jnp.int32)


def sort_merge_join(left: ColumnBatch, right: ColumnBatch,
                    left_keys: Sequence[str], right_keys: Sequence[str],
                    how: str = "inner", columns=None):
    """Join of two batches on equi-keys (inner / left_outer / right_outer
    / full_outer). Neither side needs to be pre-sorted: the device lane
    matches unsorted group ids in original row space
    (`counting_join_indices`), the host lane sorts ids internally.

    full_outer = the left_outer expansion plus one appended row per
    unmatched right row (the index-pair machinery both outer sides share).

    Output column names are left's then right's; duplicate names get a
    `_r` suffix on the right.
    """
    import jax.numpy as jnp

    from hyperspace_tpu.ops.bucketed_join import assemble_join_output

    if left.is_host and right.is_host:
        # Adaptive host lane: both sides host-resident (small reads) —
        # the whole join runs in numpy, no device round-trips.
        import numpy as np_
        if how == "right_outer":
            ri, li = host_join_indices(right, left, right_keys, left_keys,
                                       how="left_outer")
        else:
            li, ri = host_join_indices(
                left, right, left_keys, right_keys,
                how="left_outer" if how == "full_outer" else how)
            if how == "full_outer":
                extra = unmatched_right_from_indices(ri, right.num_rows)
                li = np_.concatenate(
                    [li, np_.full(len(extra), -1, dtype=np_.int32)])
                ri = np_.concatenate([ri, extra])
        return assemble_join_output(left, right, li, ri, how=how,
                                    columns=columns)

    # Device lane: the counting join works in ORIGINAL row space over
    # raw key lanes — ONE fused sort+count executable, no dense-id
    # pre-encode, no argsort, no searchsorted.
    if how == "right_outer":
        ri, li = counting_join_batch_indices(right, left, right_keys,
                                             left_keys, how="left_outer")
    else:
        li, ri = counting_join_batch_indices(
            left, right, left_keys, right_keys,
            how="left_outer" if how == "full_outer" else how)
        if how == "full_outer":
            extra = unmatched_right_from_indices(ri, right.num_rows)
            li = jnp.concatenate(
                [li, jnp.full(extra.shape[0], -1, dtype=jnp.int32)])
            ri = jnp.concatenate([ri, extra])
    return assemble_join_output(left, right, li, ri, how=how,
                                columns=columns)


# ---------------------------------------------------------------------------
# Host lane (numpy): same join semantics, zero device round-trips.
# ---------------------------------------------------------------------------


def _host_encode_join_keys(left: ColumnBatch, right: ColumnBatch,
                           left_keys: Sequence[str],
                           right_keys: Sequence[str]):
    """Host mirror of `encode_join_keys` over numpy-backed batches:
    order-preserving dense group ids with null sentinels -1/-2."""
    import numpy as np

    from hyperspace_tpu.io.columnar import _merged_dictionary
    from hyperspace_tpu.ops.keys import host_key_lanes

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    operands: List = []
    l_valid = np.ones(n, dtype=bool)
    r_valid = np.ones(m, dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.validity is not None:
            l_valid = l_valid & np.asarray(lcol.validity)
        if rcol.validity is not None:
            r_valid = r_valid & np.asarray(rcol.validity)
        if lcol.is_string:
            _, (remap_l, remap_r), _ = _merged_dictionary(
                [lcol.dictionary, rcol.dictionary], device=False)
            operands.append(np.concatenate([remap_l[lcol.data],
                                            remap_r[rcol.data]]))
            continue
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = np.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        for ll, rl in zip(host_key_lanes(ldata), host_key_lanes(rdata)):
            operands.append(np.concatenate([ll, rl]))
    from hyperspace_tpu.ops.keys import host_dense_group_ids

    validity_key = np.concatenate([l_valid, r_valid])
    perm, group_sorted = host_dense_group_ids([validity_key, *operands])
    groups = np.empty(n + m, dtype=np.int32)
    groups[perm] = group_sorted
    l_ids = np.where(l_valid, groups[:n], np.int32(-1))
    r_ids = np.where(r_valid, groups[n:], np.int32(-2))
    return l_ids, r_ids


def _host_merge_join_indices(left_ids, right_ids, how: str = "inner"):
    """Numpy mirror of `merge_join_indices` over sorted id arrays."""
    import numpy as np

    lo = np.searchsorted(right_ids, left_ids, side="left")
    hi = np.searchsorted(right_ids, left_ids, side="right")
    counts = hi - lo
    if how == "left_outer":
        counts = np.maximum(counts, 1)
    total = int(counts.sum())
    if total == 0:
        empty = np.zeros(0, dtype=np.int32)
        return empty, empty
    left_idx = np.repeat(np.arange(len(left_ids)), counts)
    starts = np.cumsum(counts) - counts
    offsets = np.arange(total) - starts[left_idx]
    matched = hi[left_idx] > lo[left_idx]
    right_idx = np.where(matched, lo[left_idx] + offsets, -1)
    return left_idx.astype(np.int32), right_idx.astype(np.int32)


def _packed_keys(left: ColumnBatch, right: ColumnBatch,
                 left_keys: Sequence[str], right_keys: Sequence[str]):
    """(left_vals, right_vals) int64/float arrays whose scalar order equals
    the key-tuple lexicographic order, or None when the keys are not
    packable (strings, nulls, ranges too wide). Single numeric key returns
    the values as-is; multi-key packs integer tuples into one int64 via
    per-column offsets and range products (order-preserving because every
    column contributes a non-negative bounded digit)."""
    import numpy as np

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    lvals, rvals = [], []
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if (lcol.is_string or rcol.is_string or lcol.validity is not None
                or rcol.validity is not None):
            return None
        ld, rd = np.asarray(lcol.data), np.asarray(rcol.data)
        if ld.dtype != rd.dtype:
            common = np.promote_types(ld.dtype, rd.dtype)
            ld, rd = ld.astype(common), rd.astype(common)
        lvals.append(ld)
        rvals.append(rd)
    if len(lvals) == 1:
        return lvals[0], rvals[0]
    if any(v.dtype.kind == "f" for v in lvals):
        return None  # float digits don't pack
    mins, ranges = [], []
    for ld, rd in zip(lvals, rvals):
        if len(ld) == 0 and len(rd) == 0:
            mins.append(0)
            ranges.append(1)
            continue
        mn = min(int(ld.min()) if len(ld) else int(rd.min()),
                 int(rd.min()) if len(rd) else int(ld.min()))
        mx = max(int(ld.max()) if len(ld) else int(rd.max()),
                 int(rd.max()) if len(rd) else int(ld.max()))
        mins.append(mn)
        ranges.append(mx - mn + 1)
    capacity = 1
    for r in ranges:
        capacity *= r
        if capacity > 1 << 62:
            return None
    lp = np.zeros(len(lvals[0]), dtype=np.int64)
    rp = np.zeros(len(rvals[0]), dtype=np.int64)
    for ld, rd, mn, r in zip(lvals, rvals, mins, ranges):
        lp = lp * r + (ld.astype(np.int64) - mn)
        rp = rp * r + (rd.astype(np.int64) - mn)
    return lp, rp


def _host_probe_join_indices(lv, rv, how: str) -> Tuple:
    """Probe join over packed scalar keys: sort ONLY the right side, then
    per-left-row match ranges via searchsorted — no sort of the (usually
    much larger) probe side."""
    import numpy as np

    r_order = np.argsort(rv, kind="stable")
    rs = rv[r_order]
    lo = np.searchsorted(rs, lv, side="left")
    hi = np.searchsorted(rs, lv, side="right")
    counts = hi - lo
    if how == "left_outer":
        counts = np.maximum(counts, 1)
    total = int(counts.sum())
    if total == 0:
        empty = np.zeros(0, dtype=np.int32)
        return empty, empty
    left_idx = np.repeat(np.arange(len(lv)), counts)
    starts = np.cumsum(counts) - counts
    offsets = np.arange(total) - starts[left_idx]
    if how == "inner":
        right_idx = r_order[lo[left_idx] + offsets]
    else:
        matched = hi[left_idx] > lo[left_idx]
        right_idx = np.where(
            matched, r_order[np.clip(lo[left_idx] + offsets, 0,
                                     max(len(rv) - 1, 0))], -1)
    return left_idx.astype(np.int32), right_idx.astype(np.int32)


def host_join_indices(left: ColumnBatch, right: ColumnBatch,
                      left_keys: Sequence[str], right_keys: Sequence[str],
                      how: str = "inner") -> Tuple:
    """Join row-index pairs computed entirely on the host (numpy) for
    host-lane batches. `how` is inner or left_outer (callers swap sides
    for right_outer). Null-free numeric keys take the probe path (only
    the build side is sorted); everything else goes through the general
    dense-group-id encode."""
    import numpy as np

    empty = np.zeros(0, dtype=np.int32)
    if left.num_rows == 0:
        return empty, empty
    if right.num_rows == 0:
        if how == "left_outer":
            return (np.arange(left.num_rows, dtype=np.int32),
                    np.full(left.num_rows, -1, dtype=np.int32))
        return empty, empty

    packed = _packed_keys(left, right, left_keys, right_keys)
    if packed is not None:
        return _host_probe_join_indices(packed[0], packed[1], how)

    l_ids, r_ids = _host_encode_join_keys(left, right, left_keys, right_keys)
    l_perm = np.argsort(l_ids, kind="stable")
    r_perm = np.argsort(r_ids, kind="stable")
    li_s, ri_s = _host_merge_join_indices(l_ids[l_perm], r_ids[r_perm],
                                          how=how)
    if len(li_s) == 0:
        return li_s, ri_s
    li = l_perm[li_s].astype(np.int32)
    ri = np.where(ri_s >= 0, r_perm[np.clip(ri_s, 0, None)],
                  -1).astype(np.int32)
    return li, ri


def host_bucketed_join_indices(left: ColumnBatch, right: ColumnBatch,
                               l_lengths, r_lengths,
                               left_keys: Sequence[str],
                               right_keys: Sequence[str],
                               how: str = "inner") -> Tuple:
    """Host join over concat-in-bucket-order sides that EXPLOITS the index
    layout: keys within each bucket arrive sorted from the bucketed write,
    so matching is per-bucket `searchsorted` — no sort, no hash table; the
    structural win the reference buys from Spark's bucketed SMJ
    (`JoinIndexRule.scala:41-43`). Fast path: single numeric null-free
    key; anything else falls back to the general host sort join."""
    import numpy as np

    packed = (None if how not in ("inner", "left_outer")
              else _packed_keys(left, right, left_keys, right_keys))
    if packed is None:
        return host_join_indices(left, right, left_keys, right_keys,
                                 how="left_outer" if how == "left_outer"
                                 else "inner")
    # Packing is monotone in key-tuple order, so within-bucket sortedness
    # of the key tuples carries over to the packed scalars.
    lkey, rkey = packed
    B = len(l_lengths)
    lb = np.concatenate([[0], np.cumsum(l_lengths)]).astype(np.int64)
    rb = np.concatenate([[0], np.cumsum(r_lengths)]).astype(np.int64)

    def _unsorted_within(key, bounds):
        if len(key) <= 1:
            return False
        in_bucket = np.ones(len(key) - 1, dtype=bool)
        boundary = bounds[1:-1]
        boundary = boundary[(boundary > 0) & (boundary < len(key))]
        in_bucket[boundary - 1] = False
        return not (key[1:][in_bucket] >= key[:-1][in_bucket]).all()

    # Sides must be sorted within each bucket (multi-run buckets from
    # incremental refresh are concatenated unsorted): one vectorized check
    # per side; repair with a per-bucket stable sort.
    r_perm = None
    if _unsorted_within(rkey, rb):
        bucket_of = np.searchsorted(rb[1:], np.arange(len(rkey)),
                                    side="right")
        r_perm = np.lexsort((rkey, bucket_of)).astype(np.int64)
        rkey = rkey[r_perm]

    # Native lane: multithreaded C++ per-bucket merge join emits the
    # (li, ri) pairs directly — no searchsorted pass, no numpy expansion
    # (the host lane's two dominant costs at millions of rows). Requires
    # the LEFT side sorted within buckets too (the index layout's
    # guarantee; repaired above only for the right), so check-and-fall-
    # through when it is not.
    if (lkey.dtype == np.int64 and rkey.dtype == np.int64
            and not _unsorted_within(lkey, lb)):
        from hyperspace_tpu import native
        pairs = native.bucketed_merge_join_i64(
            lkey, rkey, lb, rb, left_outer=(how == "left_outer"))
        if pairs is not None:
            li, ri = pairs
            if r_perm is not None and len(ri):
                ri = np.where(ri >= 0,
                              r_perm[np.clip(ri, 0, None)], -1
                              ).astype(np.int32)
            return li, ri

    lo = np.empty(len(lkey), dtype=np.int64)
    hi = np.empty(len(lkey), dtype=np.int64)
    for b in range(B):
        ls, le = lb[b], lb[b + 1]
        rs, re = rb[b], rb[b + 1]
        if le == ls:
            continue
        lo[ls:le] = rs + np.searchsorted(rkey[rs:re], lkey[ls:le], "left")
        hi[ls:le] = rs + np.searchsorted(rkey[rs:re], lkey[ls:le], "right")
    counts = hi - lo
    if how == "left_outer":
        counts = np.maximum(counts, 1)
    total = int(counts.sum())
    if total == 0:
        empty = np.zeros(0, dtype=np.int32)
        return empty, empty
    left_idx = np.repeat(np.arange(len(lkey)), counts)
    starts = np.cumsum(counts) - counts
    offsets = np.arange(total) - starts[left_idx]
    if how == "inner":
        # Zero-count rows emit nothing, so every emitted row is a match.
        right_idx = lo[left_idx] + offsets
    else:
        matched = hi[left_idx] > lo[left_idx]
        right_idx = np.where(matched, lo[left_idx] + offsets, -1)
    if r_perm is not None:
        right_idx = np.where(right_idx >= 0,
                             r_perm[np.clip(right_idx, 0, None)], -1)
    return left_idx.astype(np.int32), right_idx.astype(np.int32)
