"""Device sort kernels: stable multi-key (lexicographic) sort.

The reference delegates per-bucket sorting to Spark's bucketed write
(`index/DataFrameWriterExtensions.scala:49-66`); here sorting is a single
XLA `lax.sort` over all key columns at once (`num_keys` gives lexicographic
order; `is_stable` preserves input order for ties), with an iota operand to
extract the permutation that is then gathered across every payload column.
XLA lowers this to its bitonic/radix sorter tiled for the TPU VPU.

Order semantics: ascending, nulls first (validity participates as the
leading sub-key for nullable columns; False < True places nulls ahead).
String columns sort by dictionary code, which is order-preserving because
dictionaries are sorted at encode time (`io/columnar.py`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from hyperspace_tpu.io.columnar import ColumnBatch


def _key_operands(batch: ColumnBatch, by: Sequence[str]) -> List:
    from hyperspace_tpu.ops.keys import column_sort_lanes
    operands = []
    for name in by:
        # 32-bit order-preserving lanes (validity first: nulls-first order).
        operands.extend(column_sort_lanes(batch.column(name)))
    return operands


def sort_permutation(batch: ColumnBatch, by: Sequence[str],
                     leading_keys: Optional[Sequence] = None):
    """Stable lexicographic sort permutation by `by` columns; optional
    `leading_keys` (e.g. bucket ids) sort before them. Host-lane batches
    sort with np.lexsort (stable) — no device round-trip."""
    if batch.is_host and not leading_keys:
        import numpy as np

        from hyperspace_tpu.ops.keys import host_column_sort_lanes
        operands = []
        for name in by:
            operands.extend(host_column_sort_lanes(batch.column(name)))
        # np.lexsort's primary key is the LAST operand.
        return np.lexsort(tuple(reversed(operands))).astype(np.int32)
    import jax
    import jax.numpy as jnp

    operands = list(leading_keys or []) + _key_operands(batch, by)
    iota = jnp.arange(batch.num_rows, dtype=jnp.int32)
    results = jax.lax.sort([*operands, iota], num_keys=len(operands),
                           is_stable=True)
    return results[-1]


def sort_batch(batch: ColumnBatch, by: Sequence[str],
               leading_keys: Optional[Sequence] = None) -> ColumnBatch:
    return batch.take(sort_permutation(batch, by, leading_keys))


def bucket_boundaries(sorted_bucket_ids, num_buckets: int) -> Tuple:
    """(starts, ends) of each bucket's contiguous row range in a batch sorted
    by bucket id. starts[b] == ends[b] for empty buckets."""
    import jax.numpy as jnp

    buckets = jnp.arange(num_buckets, dtype=sorted_bucket_ids.dtype)
    starts = jnp.searchsorted(sorted_bucket_ids, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket_ids, buckets, side="right")
    return starts, ends
