"""Device sort kernels: stable multi-key (lexicographic) sort.

The reference delegates per-bucket sorting to Spark's bucketed write
(`index/DataFrameWriterExtensions.scala:49-66`); here sorting is a single
XLA `lax.sort` over all key columns at once (`num_keys` gives lexicographic
order; `is_stable` preserves input order for ties), with an iota operand to
extract the permutation that is then gathered across every payload column.
XLA lowers this to its bitonic/radix sorter tiled for the TPU VPU.

Order semantics: ascending, nulls first (validity participates as the
leading sub-key for nullable columns; False < True places nulls ahead).
String columns sort by dictionary code, which is order-preserving because
dictionaries are sorted at encode time (`io/columnar.py`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from hyperspace_tpu.io.columnar import ColumnBatch


def _descend(lane, xp):
    """Map a sort lane to its DESCENDING-order equivalent: convert to the
    unsigned order-preserving form, then bitwise-invert. Applied to the
    validity lane too, which flips null placement to nulls-last —
    Spark's default for descending keys."""
    import numpy as _np

    dt = lane.dtype
    # (No float lanes exist: float keys always decompose to uint32
    # bit-transform lanes, on every backend.)
    if dt == bool:
        u = lane.astype(xp.uint32)
    elif xp.issubdtype(dt, xp.signedinteger):
        # Reinterpret (not convert): signed->unsigned value conversion of
        # negatives is backend-defined on TPU, the bit pattern is not.
        if xp is _np:
            u = lane.view(_np.uint32) ^ _np.uint32(0x80000000)
        else:
            import jax
            u = jax.lax.bitcast_convert_type(
                lane.astype(xp.int32), xp.uint32) ^ xp.uint32(0x80000000)
    else:
        u = lane.astype(xp.uint32)
    return ~u


def _key_operands(batch: ColumnBatch, by: Sequence[str]) -> List:
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import column_sort_lanes
    from hyperspace_tpu.plan.nodes import sort_direction
    operands = []
    for spec in by:
        name, desc = sort_direction(spec)
        # 32-bit order-preserving lanes (validity first: nulls-first order).
        lanes = column_sort_lanes(batch.column(name))
        if desc:
            lanes = [_descend(lane, jnp) for lane in lanes]
        operands.extend(lanes)
    return operands


def sort_permutation(batch: ColumnBatch, by: Sequence[str],
                     leading_keys: Optional[Sequence] = None):
    """Stable lexicographic sort permutation by `by` columns; optional
    `leading_keys` (e.g. bucket ids) sort before them. Host-lane batches
    sort with np.lexsort (stable) — no device round-trip."""
    if batch.is_host and not leading_keys:
        import numpy as np

        from hyperspace_tpu import native
        from hyperspace_tpu.ops.keys import host_column_sort_lanes
        from hyperspace_tpu.plan.nodes import sort_direction
        operands = []
        for spec in by:
            name, desc = sort_direction(spec)
            lanes = host_column_sort_lanes(batch.column(name))
            if desc:
                lanes = [_descend(lane, np) for lane in lanes]
            operands.extend(lanes)
        # Native radix lane first (4-7x np.lexsort on wide TPC-DS sorts);
        # the C++ kernel is stable over packed u64 words like lexsort.
        nat = native.key_sort_perm(batch.num_rows, operands)
        if nat is not None:
            return nat
        # np.lexsort's primary key is the LAST operand.
        return np.lexsort(tuple(reversed(operands))).astype(np.int32)
    from hyperspace_tpu.ops.keys import staged_sort_permutation

    operands = list(leading_keys or []) + _key_operands(batch, by)
    return staged_sort_permutation(operands)


def sort_batch(batch: ColumnBatch, by: Sequence[str],
               leading_keys: Optional[Sequence] = None) -> ColumnBatch:
    return batch.take(sort_permutation(batch, by, leading_keys))


def bucket_boundaries(sorted_bucket_ids, num_buckets: int) -> Tuple:
    """(starts, ends) of each bucket's contiguous row range in a batch sorted
    by bucket id. starts[b] == ends[b] for empty buckets."""
    import jax.numpy as jnp

    buckets = jnp.arange(num_buckets, dtype=sorted_bucket_ids.dtype)
    starts = jnp.searchsorted(sorted_bucket_ids, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket_ids, buckets, side="right")
    return starts, ends
