"""Device sort kernels: stable multi-key (lexicographic) sort.

The reference delegates per-bucket sorting to Spark's bucketed write
(`index/DataFrameWriterExtensions.scala:49-66`); here sorting is a single
XLA `lax.sort` over all key columns at once (`num_keys` gives lexicographic
order; `is_stable` preserves input order for ties), with an iota operand to
extract the permutation that is then gathered across every payload column.
XLA lowers this to its bitonic/radix sorter tiled for the TPU VPU.

Order semantics: ascending, nulls first (validity participates as the
leading sub-key for nullable columns; False < True places nulls ahead).
String columns sort by dictionary code, which is order-preserving because
dictionaries are sorted at encode time (`io/columnar.py`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from hyperspace_tpu.io.columnar import ColumnBatch


def _as_u32(lane, xp):
    """Order-preserving uint32 form of a sort lane (host or device).
    Signed lanes REINTERPRET (not convert) then bias: signed->unsigned
    value conversion of negatives is backend-defined on TPU, the bit
    pattern is not. (No float lanes exist: float keys always decompose
    to uint32 bit-transform lanes, on every backend.)"""
    import numpy as _np

    dt = lane.dtype
    if dt == bool:
        return lane.astype(xp.uint32)
    if xp.issubdtype(dt, xp.signedinteger):
        if xp is _np:
            return lane.astype(_np.int32).view(_np.uint32) \
                ^ _np.uint32(0x80000000)
        import jax
        return jax.lax.bitcast_convert_type(
            lane.astype(xp.int32), xp.uint32) ^ xp.uint32(0x80000000)
    return lane.astype(xp.uint32)


def _descend(lane, xp):
    """Map a sort lane to its DESCENDING-order equivalent: convert to the
    unsigned order-preserving form, then bitwise-invert. Applied to the
    validity lane too, which flips null placement to nulls-last —
    Spark's default for descending keys."""
    return ~_as_u32(lane, xp)


def _key_operands(batch: ColumnBatch, by: Sequence[str]) -> List:
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import column_sort_lanes
    from hyperspace_tpu.plan.nodes import sort_direction
    operands = []
    for spec in by:
        name, desc = sort_direction(spec)
        # 32-bit order-preserving lanes (validity first: nulls-first order).
        lanes = column_sort_lanes(batch.column(name))
        if desc:
            lanes = [_descend(lane, jnp) for lane in lanes]
        operands.extend(lanes)
    return operands


def sort_permutation(batch: ColumnBatch, by: Sequence[str],
                     leading_keys: Optional[Sequence] = None):
    """Stable lexicographic sort permutation by `by` columns; optional
    `leading_keys` (e.g. bucket ids) sort before them. Host-lane batches
    sort with np.lexsort (stable) — no device round-trip."""
    if batch.is_host and not leading_keys:
        import numpy as np

        from hyperspace_tpu import native
        from hyperspace_tpu.ops.keys import host_column_sort_lanes
        from hyperspace_tpu.plan.nodes import sort_direction
        operands = []
        for spec in by:
            name, desc = sort_direction(spec)
            lanes = host_column_sort_lanes(batch.column(name))
            if desc:
                lanes = [_descend(lane, np) for lane in lanes]
            operands.extend(lanes)
        # Native radix lane first (4-7x np.lexsort on wide TPC-DS sorts);
        # the C++ kernel is stable over packed u64 words like lexsort.
        nat = native.key_sort_perm(batch.num_rows, operands)
        if nat is not None:
            return nat
        # np.lexsort's primary key is the LAST operand.
        return np.lexsort(tuple(reversed(operands))).astype(np.int32)
    from hyperspace_tpu.ops.keys import staged_sort_permutation

    operands = list(leading_keys or []) + _key_operands(batch, by)
    return staged_sort_permutation(operands)


def sort_batch(batch: ColumnBatch, by: Sequence[str],
               leading_keys: Optional[Sequence] = None) -> ColumnBatch:
    return batch.take(sort_permutation(batch, by, leading_keys))


# ---------------------------------------------------------------------------
# Top-k (ORDER BY + LIMIT collapsed): the full wide sort is wasted work
# when only k rows survive — and on a tunneled TPU its chunked-LSD
# executable costs minutes of one-time compile at novel shapes. The
# device path sorts ONE packed prefix lane to find the k-th prefix value,
# keeps the candidate rows (every true top-k row has prefix <= that
# threshold, since > means at least k rows order strictly before it),
# and finishes with an exact full-key host sort of the small candidate
# set. Ties only ever grow the candidate set, never drop a winner.
# ---------------------------------------------------------------------------

# Candidate sets beyond this fall back to the full sort (low-cardinality
# leading keys: the threshold no longer prunes).
TOPK_CANDIDATE_CAP = 1 << 21

_topk_threshold_jit = None


def _jnp_empty_i32():
    import jax.numpy as jnp
    return jnp.empty(0, dtype=jnp.int32)


def _topk_threshold(prefix, k: int):
    """(mask, count) for rows whose packed prefix is <= the k-th smallest
    prefix value — ONE module-level jitted program (cached across calls;
    a per-call wrapper would recompile every execution)."""
    global _topk_threshold_jit
    if _topk_threshold_jit is None:
        import jax
        import jax.numpy as jnp
        from functools import partial

        from hyperspace_tpu.telemetry import instrumented_jit

        @partial(instrumented_jit, "sort.topk_threshold",
                 static_argnames=("k",))
        def run(prefix, k):
            (sorted_prefix,) = jax.lax.sort([prefix], num_keys=1)
            thresh = sorted_prefix[k - 1]
            mask = prefix <= thresh
            return mask, jnp.sum(mask.astype(jnp.int64))

        _topk_threshold_jit = run
    return _topk_threshold_jit(prefix, k)


def topk_batch(batch: ColumnBatch, by: Sequence[str], n: int) -> ColumnBatch:
    """First `n` rows of `batch` ordered by `by` (stable, identical to
    sort_batch(...)[:n]).

    Residency contract (downstream lane selection keys on `is_host`):
    - host input -> HOST output (pure numpy path);
    - device input, threshold path -> HOST output: the candidate set is
      pulled to the host for the exact full-key finish, and at <= n +
      ties rows re-uploading it would only pay the link again;
    - device input, candidate-cap fallback (low-cardinality prefix; see
      TOPK_CANDIDATE_CAP) -> DEVICE output from the full device sort.
    So a device caller gets a host batch on the common path and a device
    batch on the fallback — by design, not drift: each path leaves the
    rows where its last computation put them, and TopK is a root-adjacent
    operator (ORDER BY + LIMIT) whose small output promotes or transfers
    cheaply either way. The fallback is recorded as a telemetry event
    (`topk.candidate-cap-fallback`) so lane surprises stay diagnosable."""
    import numpy as np

    if n == 0:
        return batch.take(np.empty(0, dtype=np.int32)
                          if batch.is_host else _jnp_empty_i32())
    if batch.num_rows <= n:
        return sort_batch(batch, by)
    if batch.is_host:
        perm = sort_permutation(batch, by)
        return batch.take(np.asarray(perm)[:n].astype(np.int32))

    import os
    import time as _time

    import jax.numpy as jnp

    dbg = os.environ.get("HYPERSPACE_TOPK_DEBUG")
    t0 = _time.perf_counter()
    # Only the first two prefix lanes are consumed; building all ~34
    # lanes of a wide ORDER BY would waste dozens of device dispatches.
    operands = _key_operands(batch, list(by)[:2])
    prefix = _as_u32(operands[0], jnp).astype(jnp.uint64) << jnp.uint64(32)
    if len(operands) > 1:
        prefix = prefix | _as_u32(operands[1], jnp).astype(jnp.uint64)
    mask, count_dev = _topk_threshold(prefix, n)
    count = int(count_dev)  # the one sizing sync
    t1 = _time.perf_counter()
    if count > max(TOPK_CANDIDATE_CAP, 4 * n):
        from hyperspace_tpu import telemetry
        telemetry.event("topk", "candidate-cap-fallback",
                        candidates=count, n=n, rows=batch.num_rows,
                        residency="device")
        full = sort_batch(batch, by)
        return full.take(jnp.arange(n, dtype=jnp.int32))
    # Pad the gather size to powers of two so distinct candidate counts
    # reuse a handful of compiled executables; nonzero places real hits
    # first, so the host slice [:count] drops the padding exactly.
    size = 1 << max(count - 1, 1).bit_length()
    (idx,) = jnp.nonzero(mask, size=size, fill_value=0)
    cand = batch.take(idx.astype(jnp.int32))
    t2 = _time.perf_counter()
    # Issue every candidate array's D2H before the first blocking read:
    # per-column np.asarray would pay ~40 sequential link round-trips.
    for col in cand.columns.values():
        for arr in (col.data, col.validity, *(col.dict_hashes or ())):
            if arr is not None and hasattr(arr, "copy_to_host_async"):
                try:
                    arr.copy_to_host_async()
                except Exception:
                    pass  # best-effort prefetch only
    host_cols = {}
    from hyperspace_tpu.io.columnar import DeviceColumn
    for name, col in cand.columns.items():
        host_cols[name] = DeviceColumn(
            data=np.asarray(col.data)[:count],
            dtype=col.dtype,
            validity=(np.asarray(col.validity)[:count]
                      if col.validity is not None else None),
            dictionary=col.dictionary,
            dict_hashes=(tuple(np.asarray(h) for h in col.dict_hashes)
                         if col.dict_hashes is not None else None))
    host_cand = ColumnBatch(cand.schema, host_cols)
    perm = sort_permutation(host_cand, by)
    out = host_cand.take(np.asarray(perm)[:n].astype(np.int32))
    if dbg:
        print(f"[topk] n={batch.num_rows} count={count} "
              f"threshold+sync={t1 - t0:.2f}s gather={t2 - t1:.2f}s "
              f"pull+sort={_time.perf_counter() - t2:.2f}s", flush=True)
    return out


def bucket_boundaries(sorted_bucket_ids, num_buckets: int) -> Tuple:
    """(starts, ends) of each bucket's contiguous row range in a batch sorted
    by bucket id. starts[b] == ends[b] for empty buckets."""
    import jax.numpy as jnp

    buckets = jnp.arange(num_buckets, dtype=sorted_bucket_ids.dtype)
    starts = jnp.searchsorted(sorted_bucket_ids, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket_ids, buckets, side="right")
    return starts, ends
