"""Sort-key transforms: everything becomes 32-bit lanes, order preserved.

TPU VPU lanes are 32-bit; int64/float64 arithmetic is emulated. Sorting and
hashing therefore decompose every key column into one or two 32-bit arrays
whose lexicographic order equals the source order:

- int64  -> (hi: int32 arithmetic-shift — sign order preserved,
             lo: uint32 — unsigned order of the low word)
- float64 -> order-preserving bit transform (negatives: all bits flipped;
             positives: sign bit set) -> uint64 -> (hi, lo) uint32
- float32 -> same transform -> one uint32
- int32/int16/int8/bool/date32 -> one int32
- string -> dictionary code (int32; order-preserving by construction)

`ops/hash_partition.py` mixes the same lanes, so hashing and sorting share
one decomposition.
"""

from __future__ import annotations

from typing import List

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.io.columnar import DeviceColumn


def _float_order_bits(data, int_dtype, uint_dtype, sign_bit):
    """IEEE total-order transform: monotone map float -> unsigned int
    (negatives flip all bits; positives set the sign bit).

    Floats are normalized first — -0.0 -> +0.0 and every NaN bit pattern
    -> one canonical quiet NaN — so sort order, bucket hash, and join/group
    key identity agree with numeric equality on every lane (Spark's
    NormalizeFloatingNumbers; NaNs group together and sort last)."""
    import jax
    import jax.numpy as jnp
    zero = jnp.zeros((), data.dtype)
    data = jnp.where(data == zero, zero, data)
    data = jnp.where(jnp.isnan(data), jnp.full((), jnp.nan, data.dtype),
                     data)
    bits = jax.lax.bitcast_convert_type(data, int_dtype).astype(uint_dtype)
    sign = (bits >> (sign_bit - 1)) & uint_dtype(1)
    mask = jnp.where(sign == 1, ~uint_dtype(0), uint_dtype(1) << (sign_bit - 1))
    return bits ^ mask


def _can_bitcast64() -> bool:
    """TPU backends emulate 64-bit types by splitting into 32-bit pairs,
    and that x64 rewrite has no lowering for 64-bit bitcast-convert — so
    the IEEE bit transform for float64 only compiles on cpu/gpu."""
    import jax
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:
        return True


def key_lanes(data) -> List:
    """Decompose one key array into order-preserving 32-bit lanes. On
    backends that cannot bitcast 64-bit types (TPU x64 emulation),
    float64 lanes come from HOST bit decomposition for concrete arrays
    and raise for tracers — see the float64 branch."""
    import jax
    import jax.numpy as jnp

    dtype = data.dtype
    if dtype == jnp.int64:
        hi = (data >> 32).astype(jnp.int32)
        lo = (data & 0xFFFFFFFF).astype(jnp.uint32)
        return [hi, lo]
    if dtype == jnp.float64:
        if not _can_bitcast64():
            # TPU x64 emulation has no 64-bit bitcast AND demotes raw f64
            # comparisons, so exact order lanes must come from HOST bits.
            # Concrete arrays pay one device->host read of the key column;
            # inside a compiled program there is no correct lowering —
            # fail loudly rather than mis-sort.
            import numpy as np

            from jax.core import Tracer
            if isinstance(data, Tracer):
                from hyperspace_tpu.exceptions import HyperspaceException
                raise HyperspaceException(
                    "float64 sort/bucket keys are not supported inside "
                    "compiled programs on TPU backends (no exact 64-bit "
                    "decomposition); use an integer or string key, or run "
                    "on the host lane.")
            return [jnp.asarray(lane)
                    for lane in host_key_lanes(np.asarray(data))]
        bits = _float_order_bits(data, jnp.int64, jnp.uint64, 64)
        return [(bits >> 32).astype(jnp.uint32),
                (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)]
    if dtype == jnp.float32:
        return [_float_order_bits(data, jnp.int32, jnp.uint32, 32)]
    if dtype == jnp.bool_:
        return [data.astype(jnp.int32)]
    if dtype in (jnp.int8, jnp.int16, jnp.int32):
        return [data.astype(jnp.int32)]
    if dtype == jnp.uint32:
        return [data]
    return [data]


def column_sort_lanes(col: DeviceColumn) -> List:
    """32-bit sort lanes for a column; validity (nulls-first) leads."""
    lanes: List = []
    if col.validity is not None:
        lanes.append(col.validity)
    lanes.extend(key_lanes(col.data))
    return lanes


def host_key_lanes(data) -> List:
    """Host (numpy) mirror of `key_lanes`: same order-preserving
    decomposition with zero device traffic, for the adaptive host lane."""
    import numpy as np

    dtype = data.dtype
    if dtype == np.int64:
        return [(data >> 32).astype(np.int32),
                (data & 0xFFFFFFFF).astype(np.uint32)]
    if dtype == np.float64:
        from hyperspace_tpu.ops.host_hash import _float_order_bits
        bits = _float_order_bits(data, np.uint64, 64)
        return [(bits >> np.uint64(32)).astype(np.uint32),
                (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    if dtype == np.float32:
        from hyperspace_tpu.ops.host_hash import _float_order_bits
        return [_float_order_bits(data, np.uint32, 32)]
    if dtype == np.bool_:
        return [data.astype(np.int32)]
    if dtype in (np.int8, np.int16, np.int32):
        return [data.astype(np.int32)]
    return [data]


def host_column_sort_lanes(col: DeviceColumn) -> List:
    lanes: List = []
    if col.validity is not None:
        lanes.append(col.validity)
    lanes.extend(host_key_lanes(col.data))
    return lanes


def host_dense_group_ids(keys):
    """Stable dense group encoding on the host: a stable sort over the key
    arrays (primary key first), then adjacent-difference ids in sorted
    order. Returns (perm, sorted_group_ids); original-order ids are
    `out[perm] = sorted_group_ids`. Shared by the host join encode and the
    host aggregation so the grouping invariants live in one place. The
    sort permutation comes from the native C++ radix lane when the keys
    decompose to packable lanes (4-7x np.lexsort on wide key sets);
    np.lexsort otherwise. Both are stable, and for int/bool/string keys
    they produce the SAME permutation; float keys only agree up to NaN
    placement — the native lane orders by the normalized IEEE
    total-order bit transform while the np.lexsort fallback sorts the
    RAW floats (numpy puts every NaN last, ignoring payload/sign bits) —
    so the two lanes may interleave NaN rows differently. Group CONTENT
    is unaffected either way (equal keys stay contiguous and NaNs group
    together under the normalized lane identity); only the permutation,
    which no grouping consumer depends on, can differ."""
    import numpy as np

    keys = [np.asarray(k) for k in keys]
    perm = None
    n = len(keys[0]) if keys else 0
    if keys and n:
        from hyperspace_tpu import native
        lanes = []
        for k in keys:
            if k.dtype == np.object_ or k.dtype.kind == "U":
                lanes = None
                break
            lanes.extend(host_key_lanes(k))
        if lanes is not None:
            perm = native.key_sort_perm(n, lanes)
    if perm is None:
        perm = np.lexsort(tuple(reversed(keys)))
    n = len(perm)
    differs = np.zeros(n, dtype=np.int32)
    for k in keys:
        ks = k[perm]
        differs[1:] |= (ks[1:] != ks[:-1]).astype(np.int32)
    return perm, np.cumsum(differs, dtype=np.int32)


# XLA's variadic sort builds an O(k^2)-sized comparator; past ~15 key
# operands (TPC-DS q64 groups by 15 columns = ~25 lanes) compile time on
# TPU explodes from seconds to tens of minutes. Above this width the
# lexicographic sort runs as stable LSD passes of narrow sorts instead —
# compile cost stays bounded and every pass reuses one cached
# narrow-comparator executable.
MAX_SORT_OPERANDS = 8


def _staged_sort(operands):
    """Traceable body: (permutation, sorted operands), stable
    lexicographic, via chunked LSD passes (or one narrow sort that
    yields the sorted operands for free). Call under jit so ALL passes
    fuse into ONE executable — on a tunneled backend every separate
    executable costs a ~25s compile round-trip regardless of size."""
    import jax
    import jax.numpy as jnp

    n = operands[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    if len(operands) <= MAX_SORT_OPERANDS:
        results = jax.lax.sort([*operands, iota],
                               num_keys=len(operands), is_stable=True)
        return results[-1], tuple(results[:-1])
    chunks = [operands[i:i + MAX_SORT_OPERANDS]
              for i in range(0, len(operands), MAX_SORT_OPERANDS)]
    perm = iota
    for chunk in reversed(chunks):
        gathered = [jnp.take(lane, perm) for lane in chunk]
        results = jax.lax.sort([*gathered, perm], num_keys=len(chunk),
                               is_stable=True)
        perm = results[-1]
    return perm, tuple(jnp.take(op, perm) for op in operands)


def _staged_perm(operands):
    return _staged_sort(operands)[0]


@__import__("jax").jit
def _staged_perm_jit(operands):
    return _staged_perm(list(operands))


def staged_sort_permutation(operands):
    """Stable lexicographic sort permutation over `operands` (primary key
    first). Narrow key sets sort in ONE `lax.sort`; wide ones run
    least-significant-chunk-first stable passes (LSD radix over chunks),
    whose composition equals the single wide sort — XLA's wide variadic
    comparator explodes TPU compile time (TPC-DS q64's 15-column
    grouping). One jitted executable either way."""
    import jax.numpy as jnp

    return _staged_perm_jit(tuple(jnp.asarray(o) for o in operands))
