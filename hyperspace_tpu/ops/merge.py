"""Device k-way merge-compaction: every bucket's runs, ONE compiled program.

OptimizeAction compacts the base + incremental delta runs living side by
side in one `v__=N` dir into a single fully-sorted file per bucket
(reference roadmap `/root/reference/ROADMAP.md:66-75` — the surveyed
reference has only full rebuild). The naive implementation loops buckets in
Python and re-sorts each on the device — one fresh XLA compile per novel
bucket shape (tens of seconds on a remote-compile TPU toolchain) and a
blocking sync per bucket.

Here compaction is ONE batched program over a padded [B, L] layout, the
same trick the bucketed join uses (`ops/bucketed_join.py`):

1. key columns decompose into order-preserving 32-bit lanes
   (`ops/keys.py`) — already staged on device;
2. each bucket's rows (its runs concatenated in file order) are gathered
   into a [B, L] matrix, L = next power of two of the largest bucket so
   repeated compactions reuse the compile; padding slots carry a trailing
   pad flag that sorts last;
3. one batched stable `lax.sort` along the row axis orders every bucket at
   once;
4. the per-bucket orderings are flattened back into a single global row
   permutation, split into link-overlap chunks for the D2H fetch.

Why a batched SORT rather than a literal k-way merge loop: on TPU,
`lax.sort` IS the merge primitive — a data-dependent heap/merge loop
serializes on the scalar unit and defeats the VPU, while the bitonic-family
batched sort runs fully vectorized across all buckets simultaneously. The
asymptotic O(L log^2 L) vs O(L log k) trade buys one compile, zero scalar
control flow, and bucket-parallel execution; the runs' pre-sortedness
still helps (a stable sort over nearly-sorted lanes does minimal data
movement in the final permutation application, which is where the real
cost — the payload gather — lives, and that runs on the host in Arrow).

The payload never touches the device (the `_perm_core` lesson,
`ops/build.py`): only key lanes go over the link, and the host applies the
permutation chunk-by-chunk while later chunks are still in flight.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.ops.build import LINK_CHUNK_ROWS, LINK_CHUNKS


def next_pow2(n: int) -> int:
    return 1 << max(2, (int(n) - 1).bit_length())


@partial(__import__("jax").jit, static_argnames=("n_chunks",))
def _bucket_sort_core(lanes, l_idx, l_valid, flat_pick, n_chunks: int):
    """Batched within-bucket sort permutation.

    lanes: tuple of [N] 32-bit key lanes (validity leading when present);
    l_idx/l_valid: [B, L] padded gather matrix + mask into the
    concat-in-bucket-order row space; flat_pick: [N] int32 positions of the
    valid cells in the row-major [B*L] flattening, in bucket order.
    Returns the [N] row permutation split into n_chunks contiguous slices.
    """
    import jax
    import jax.numpy as jnp

    B, L = l_idx.shape
    pad = (~l_valid).astype(jnp.int32)  # 0 = real row, 1 = padding
    operands = [pad]
    for lane in lanes:
        gathered = jnp.take(lane, l_idx)
        # Padding rows ride the pad flag (leading key); their lane values
        # are the safe-gather duplicates and never affect real ordering.
        operands.append(gathered)
    pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))
    results = jax.lax.sort([*operands, pos], num_keys=len(operands),
                           is_stable=True, dimension=1)
    pos_sorted = results[-1]
    # original row index occupying sorted slot (b, j)
    orig = jnp.take_along_axis(l_idx, pos_sorted, axis=1).reshape(-1)
    perm = jnp.take(orig, flat_pick)
    n = perm.shape[0]
    base = n // n_chunks
    chunks = tuple(
        jax.lax.slice(perm, (i * base,),
                      ((i + 1) * base if i < n_chunks - 1 else n,))
        for i in range(n_chunks))
    return chunks


def _padded_layout(lengths: np.ndarray, width: int):
    """[B, width] gather matrix + validity into a concat-in-bucket-order
    row space (the `ops/bucketed_join.py` layout; padding slots point at a
    real row for safe gathers)."""
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    j = np.arange(width)[None, :]
    valid = j < lengths[:, None]
    idx = np.where(valid, starts[:, None] + np.minimum(
        j, np.maximum(lengths[:, None] - 1, 0)), 0)
    return idx.astype(np.int32), valid


def bucket_sort_permutation(key_batch, sort_columns: Sequence[str],
                            lengths: np.ndarray) -> Tuple[List, np.ndarray,
                                                          np.ndarray]:
    """Permutation that sorts every bucket of a concat-in-bucket-order
    batch by `sort_columns`, computed in ONE compiled program across all
    buckets. `key_batch` needs only the key columns resident on device.

    Returns (device perm chunks, starts, ends) shaped exactly like
    `ops/build.build_permutation`, so `io/builder._write_sorted_runs`
    consumes the result unchanged.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    n = int(lengths.sum())
    B = len(lengths)
    L = next_pow2(max(1, int(lengths.max(initial=0))))

    lanes: List = []
    for name in sort_columns:
        lanes.extend(keymod.column_sort_lanes(key_batch.column(name)))

    l_idx, l_valid = _padded_layout(lengths, L)
    # Valid-cell positions in the row-major [B*L] flattening, bucket order:
    # after the in-row sort, the first lengths[b] slots of row b hold its
    # sorted rows (padding sorts last).
    row_base = np.repeat(np.arange(B, dtype=np.int64) * L, lengths)
    within = np.concatenate([np.arange(c, dtype=np.int64)
                             for c in lengths]) if n else np.zeros(
                                 0, dtype=np.int64)
    flat_pick = (row_base + within).astype(np.int32)

    import jax.numpy as jnp
    n_chunks = LINK_CHUNKS if n >= LINK_CHUNK_ROWS else 1
    n_chunks = max(1, min(n_chunks, max(n, 1)))
    chunks = _bucket_sort_core(tuple(lanes), jnp.asarray(l_idx),
                               jnp.asarray(l_valid),
                               jnp.asarray(flat_pick), n_chunks)
    ends = np.cumsum(lengths)
    starts = ends - lengths
    return list(chunks), starts, ends


def host_bucket_sort_permutation(key_batch, sort_columns: Sequence[str],
                                 lengths: np.ndarray):
    """Host (numpy) twin: stable lexsort keyed (bucket, *sort lanes) —
    below the device-amortization row count a fresh XLA compile can never
    pay for itself (`io/builder.BUILD_MIN_DEVICE_ROWS`)."""
    lengths = np.asarray(lengths, dtype=np.int64)
    bucket_of_row = np.repeat(np.arange(len(lengths), dtype=np.int64),
                              lengths)
    sort_keys: List = [bucket_of_row]
    for name in sort_columns:
        sort_keys.extend(keymod.host_column_sort_lanes(
            key_batch.column(name)))
    perm = np.lexsort(tuple(reversed(sort_keys))).astype(np.int64)
    ends = np.cumsum(lengths)
    starts = ends - lengths
    return [perm], starts, ends
