"""Device k-way merge-compaction: every bucket's runs, ONE compiled program.

OptimizeAction compacts the base + incremental delta runs living side by
side in one `v__=N` dir into a single fully-sorted file per bucket
(reference roadmap `/root/reference/ROADMAP.md:66-75` — the surveyed
reference has only full rebuild). The naive implementation loops buckets in
Python and re-sorts each on the device — one fresh XLA compile per novel
bucket shape (tens of seconds on a remote-compile TPU toolchain) and a
blocking sync per bucket.

Here compaction is ONE batched program over a padded [B, L] layout, the
same trick the bucketed join uses (`ops/bucketed_join.py`):

1. key columns decompose into order-preserving 32-bit lanes
   (`ops/keys.py`) — already staged on device;
2. each bucket's rows (its runs concatenated in file order) are gathered
   into a [B, L] matrix, L = next power of two of the largest bucket so
   repeated compactions reuse the compile; padding slots carry a trailing
   pad flag that sorts last;
3. one batched stable `lax.sort` along the row axis orders every bucket at
   once;
4. the per-bucket orderings are flattened back into a single global row
   permutation, split into link-overlap chunks for the D2H fetch.

Why a batched SORT rather than a literal k-way merge loop: on TPU,
`lax.sort` IS the merge primitive — a data-dependent heap/merge loop
serializes on the scalar unit and defeats the VPU, while the bitonic-family
batched sort runs fully vectorized across all buckets simultaneously. The
asymptotic O(L log^2 L) vs O(L log k) trade buys one compile, zero scalar
control flow, and bucket-parallel execution; the runs' pre-sortedness
still helps (a stable sort over nearly-sorted lanes does minimal data
movement in the final permutation application, which is where the real
cost — the payload gather — lives, and that runs on the host in Arrow).

The payload never touches the device (the `_perm_core` lesson,
`ops/build.py`): only key lanes go over the link, and the host applies the
permutation chunk-by-chunk while later chunks are still in flight.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.ops.build import LINK_CHUNK_ROWS, LINK_CHUNKS
# ONE padded-layout builder and pow2 rounding for every [B, L] consumer
# (join, distributed join, compaction) — they must stay in lockstep.
from hyperspace_tpu.ops.bucketed_join import _padded_layout, next_pow2


@partial(__import__("jax").jit, static_argnames=("n_chunks",))
def _bucket_sort_core(lanes, l_idx, l_valid, flat_pick, n_chunks: int):
    """Batched within-bucket sort permutation.

    lanes: tuple of [N] 32-bit key lanes (validity leading when present);
    l_idx/l_valid: [B, L] padded gather matrix + mask into the
    concat-in-bucket-order row space; flat_pick: [N] int32 positions of the
    valid cells in the row-major [B*L] flattening, in bucket order.
    Returns the [N] row permutation split into n_chunks contiguous slices.
    """
    import jax
    import jax.numpy as jnp

    B, L = l_idx.shape
    pad = (~l_valid).astype(jnp.int32)  # 0 = real row, 1 = padding
    operands = [pad]
    for lane in lanes:
        gathered = jnp.take(lane, l_idx)
        # Padding rows ride the pad flag (leading key); their lane values
        # are the safe-gather duplicates and never affect real ordering.
        operands.append(gathered)
    pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))
    results = jax.lax.sort([*operands, pos], num_keys=len(operands),
                           is_stable=True, dimension=1)
    pos_sorted = results[-1]
    # original row index occupying sorted slot (b, j)
    orig = jnp.take_along_axis(l_idx, pos_sorted, axis=1).reshape(-1)
    perm = jnp.take(orig, flat_pick)
    n = perm.shape[0]
    base = n // n_chunks
    chunks = tuple(
        jax.lax.slice(perm, (i * base,),
                      ((i + 1) * base if i < n_chunks - 1 else n,))
        for i in range(n_chunks))
    return chunks


def bucket_sort_permutation(key_batch, sort_columns: Sequence[str],
                            lengths: np.ndarray) -> Tuple[List, np.ndarray,
                                                          np.ndarray]:
    """Permutation that sorts every bucket of a concat-in-bucket-order
    batch by `sort_columns`, computed in ONE compiled program across all
    buckets. `key_batch` needs only the key columns resident on device.

    Returns (device perm chunks, starts, ends) shaped exactly like
    `ops/build.build_permutation`, so `io/builder._write_sorted_runs`
    consumes the result unchanged.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    n = int(lengths.sum())
    B = len(lengths)
    L = next_pow2(max(1, int(lengths.max(initial=0))))

    lanes: List = []
    for name in sort_columns:
        lanes.extend(keymod.column_sort_lanes(key_batch.column(name)))

    l_idx, l_valid = _padded_layout(lengths, L)
    # Valid-cell positions in the row-major [B*L] flattening, bucket order:
    # after the in-row sort, the first lengths[b] slots of row b hold its
    # sorted rows (padding sorts last).
    row_base = np.repeat(np.arange(B, dtype=np.int64) * L, lengths)
    within = np.concatenate([np.arange(c, dtype=np.int64)
                             for c in lengths]) if n else np.zeros(
                                 0, dtype=np.int64)
    flat_pick = (row_base + within).astype(np.int32)

    import jax.numpy as jnp
    n_chunks = LINK_CHUNKS if n >= LINK_CHUNK_ROWS else 1
    n_chunks = max(1, min(n_chunks, max(n, 1)))
    chunks = _bucket_sort_core(tuple(lanes), jnp.asarray(l_idx),
                               jnp.asarray(l_valid),
                               jnp.asarray(flat_pick), n_chunks)
    ends = np.cumsum(lengths)
    starts = ends - lengths
    return list(chunks), starts, ends


def host_merge_runs_permutation(key: np.ndarray, run_bounds):
    """True k-way MERGE permutation for the common compaction shape: per
    bucket, one large sorted base run plus small sorted-ish delta runs,
    over a single null-free integer key column.

    Per bucket the deltas are stable-sorted together (tiny), their insert
    positions into the base run found with ONE searchsorted (side='right'
    — appended rows follow equal-key base rows, the same tie order a
    stable sort of base-then-deltas produces), and the output permutation
    assembled by prefix counting. O(n + k log k + k log n) per bucket with
    NO re-sort of the base run — the asymptotic win a re-sorting
    compaction gives up. Falls back to a bucket-local stable sort when a
    base run is not actually sorted.

    `run_bounds`: per bucket, list of (start, end) global row ranges of
    its runs in version order (base first). Returns ([perm], starts, ends)
    in the writer's shape.
    """
    lengths = np.array([sum(e - s for s, e in runs)
                        for runs in run_bounds], dtype=np.int64)
    total = int(lengths.sum())
    perm = np.empty(total, dtype=np.int64)
    out = 0
    for runs in run_bounds:
        n_bucket = sum(e - s for s, e in runs)
        if n_bucket == 0:
            continue
        (b0, b1) = runs[0]
        base = key[b0:b1]
        if len(runs) == 1:
            perm[out:out + n_bucket] = np.arange(b0, b1)
            out += n_bucket
            continue
        d_idx = np.concatenate([np.arange(s, e) for s, e in runs[1:]])
        if len(base) and not (base[1:] >= base[:-1]).all():
            # Base run unexpectedly unsorted: bucket-local stable sort.
            all_idx = np.concatenate([np.arange(b0, b1), d_idx])
            perm[out:out + n_bucket] = all_idx[
                np.argsort(key[all_idx], kind="stable")]
            out += n_bucket
            continue
        d_sorted = d_idx[np.argsort(key[d_idx], kind="stable")]
        pos = np.searchsorted(base, key[d_sorted], side="right")
        nb, kd = len(base), len(d_sorted)
        # base row i lands at i + #{deltas inserted at or before i}
        shift = np.cumsum(np.bincount(pos, minlength=nb + 1))[:nb]
        local = np.empty(n_bucket, dtype=np.int64)
        local[np.arange(nb) + shift] = np.arange(b0, b1)
        local[pos + np.arange(kd)] = d_sorted
        perm[out:out + n_bucket] = local
        out += n_bucket
    ends = np.cumsum(lengths)
    starts = ends - lengths
    return [perm], starts, ends


def host_bucket_sort_permutation(key_batch, sort_columns: Sequence[str],
                                 lengths: np.ndarray):
    """Host twin: stable sort keyed (bucket, *sort lanes) — the native C++
    radix lane when available (`native.bucket_key_sort_perm`), np.lexsort
    otherwise. Below the device-amortization row count a fresh XLA
    compile can never pay for itself (`io/builder.BUILD_MIN_DEVICE_ROWS`);
    with the native lane the host path also wins at size by skipping the
    link round-trip entirely."""
    from hyperspace_tpu import native

    lengths = np.asarray(lengths, dtype=np.int64)
    bucket_of_row = np.repeat(np.arange(len(lengths), dtype=np.int32),
                              lengths)
    sort_lanes: List = []
    for name in sort_columns:
        sort_lanes.extend(keymod.host_column_sort_lanes(
            key_batch.column(name)))
    ends = np.cumsum(lengths)
    starts = ends - lengths
    nat = native.bucket_key_sort_perm(bucket_of_row, len(lengths),
                                      sort_lanes)
    if nat is not None:
        # Only the permutation is consumed: the native starts/ends are
        # redundant here — bounds computed from `lengths` above agree
        # with the sort's by construction (rows were labeled with the
        # bucket ids those same lengths induce).
        return [nat[0]], starts, ends
    perm = np.lexsort(tuple(reversed([bucket_of_row] + sort_lanes)))
    return [perm.astype(np.int64)], starts, ends
