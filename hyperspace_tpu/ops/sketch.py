"""Sketch kernels for data-skipping indexes: zone maps, blocked bloom
filters, and the Z-order clustering permutation.

Build-side math for `index/sketch.py` (blob IO) and
`actions/skipping.py` (the FSM action). Two lanes, one identity:

- DEVICE lane (batches staged through the `TransferEngine` by
  `columnar.from_arrow(device=True)`): per-column min/max/null/NaN
  reductions and the bloom bit-set run as jitted XLA programs
  (`instrumented_jit` — compile telemetry like every other entry
  point). The bloom scatter-OR is expressed as a bincount over FLAT BIT
  POSITIONS (`counts.at[flat_bits].add(1)` then a pack) because XLA has
  no scatter-or primitive.
- HOST lane (numpy mirror, used below the device-amortization row
  count): identical results bit-for-bit — the bloom words and zone
  values a query probes against must not depend on which lane built
  them (`tests/test_skipping.py` pins host == device).

Hash identity: blooms hash COLUMN VALUES through the same lanes the
bucket hash uses (`ops/hash_partition.column_hash_lanes` /
`ops/host_hash.host_column_hash_lanes` — strings contribute their
per-dictionary FNV-1a value hashes, numerics their order-preserving
32-bit key lanes, null rows all-zero lanes), mixed into a (h1, h2)
uint32 pair by a dual murmur-style mix. A plan-time literal probes with
`probe_hash_pair(value, dtype)` over the same lanes, so build and probe
can never disagree. The filter layout is a parquet-style SPLIT-BLOCK
bloom: 256-bit blocks of 8 uint32 words, block chosen by h1, one bit
per word from h2 x per-word salt.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException

# Per-word salts of the split-block bloom (parquet's constants).
_SALT = (0x47B6137B, 0x44974D91, 0x8824AD5B, 0xA2B7289D,
         0x705495C7, 0x2DF1424B, 0x9EFC4947, 0x5C6BFB31)
_SEED2 = 0x6A09E667  # second-hash derivation seed (mirrors dual_hash64)

BLOCK_BITS = 256
WORDS_PER_BLOCK = 8


def bloom_num_bits(rows: int, fpp: float, max_bytes: int) -> int:
    """Filter size in bits for `rows` distinct-ish values at target
    false-positive rate `fpp`: the standard -n*ln(p)/ln(2)^2 estimate,
    rounded UP to whole 256-bit blocks and capped at `max_bytes` (a
    huge file degrades to a higher-FPP filter, never an unbounded
    blob)."""
    rows = max(1, int(rows))
    fpp = min(max(float(fpp), 1e-6), 0.5)
    bits = int(math.ceil(-rows * math.log(fpp) / (math.log(2.0) ** 2)))
    blocks = max(1, (bits + BLOCK_BITS - 1) // BLOCK_BITS)
    max_blocks = max(1, (int(max_bytes) * 8) // BLOCK_BITS)
    return min(blocks, max_blocks) * BLOCK_BITS


# ---------------------------------------------------------------------------
# The dual hash (build and probe share it)
# ---------------------------------------------------------------------------


def _dual_mix_host(lanes: Sequence[np.ndarray]):
    """(h1, h2) uint32 pair per row from hash-input lanes (numpy)."""
    from hyperspace_tpu.ops.host_hash import _combine, _fmix32
    u0 = lanes[0].astype(np.uint32)
    h1 = _fmix32(u0)
    h2 = _fmix32(u0 ^ np.uint32(_SEED2))
    for lane in lanes[1:]:
        u = lane.astype(np.uint32)
        h1 = _combine(h1, _fmix32(u))
        h2 = _combine(h2, _fmix32(u ^ np.uint32(_SEED2)))
    return h1, h2


def _dual_mix_device(lanes):
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hash_partition import _combine, _fmix32
    u0 = lanes[0].astype(jnp.uint32)
    h1 = _fmix32(u0)
    h2 = _fmix32(u0 ^ jnp.uint32(_SEED2))
    for lane in lanes[1:]:
        u = lane.astype(jnp.uint32)
        h1 = _combine(h1, _fmix32(u))
        h2 = _combine(h2, _fmix32(u ^ jnp.uint32(_SEED2)))
    return h1, h2


def probe_hash_pair(value, dtype: str) -> Tuple[int, int]:
    """(h1, h2) of ONE literal value under the bloom hash identity —
    what the plan-time rule probes membership with. Raises
    HyperspaceException when the value is not representable in the
    column's dtype (callers treat that as un-refutable)."""
    from hyperspace_tpu.ops.host_hash import _hash_lanes
    try:
        lanes = _hash_lanes([value], dtype)
    except (ValueError, TypeError, OverflowError) as exc:
        raise HyperspaceException(
            f"Unprobeable literal {value!r} for dtype {dtype}") from exc
    h1, h2 = _dual_mix_host(lanes)
    return int(h1[0]), int(h2[0])


# ---------------------------------------------------------------------------
# Bloom build (host + device) and probe
# ---------------------------------------------------------------------------


def _host_bloom_words(h1: np.ndarray, h2: np.ndarray,
                      nbits: int) -> np.ndarray:
    nblocks = nbits // BLOCK_BITS
    words = np.zeros(nblocks * WORDS_PER_BLOCK, dtype=np.uint32)
    block = (h1 % np.uint32(nblocks)).astype(np.int64)
    for j, salt in enumerate(_SALT):
        bit = (h2 * np.uint32(salt)) >> np.uint32(27)
        np.bitwise_or.at(words, block * WORDS_PER_BLOCK + j,
                         np.uint32(1) << bit)
    return words


_bloom_kernel_jit = None


def _bloom_kernel(lanes, counts_init):
    """Traceable bloom body: lanes -> (h1, h2) -> per-row flat bit
    positions -> bincount -> packed uint32 words. `counts_init` is a
    zeros array whose SHAPE carries nbits (no static args needed)."""
    import jax.numpy as jnp

    h1, h2 = _dual_mix_device(list(lanes))
    nbits = counts_init.shape[0]
    nblocks = nbits // BLOCK_BITS
    block = (h1 % jnp.uint32(nblocks)).astype(jnp.int32)
    flats = []
    for j, salt in enumerate(_SALT):
        bit = ((h2 * jnp.uint32(salt)) >> jnp.uint32(27)).astype(jnp.int32)
        flats.append(block * BLOCK_BITS + j * 32 + bit)
    flat = jnp.stack(flats, axis=1).reshape(-1)
    counts = counts_init.at[flat].add(1)
    bits = (counts > 0).reshape(nbits // 32, 32).astype(jnp.uint32)
    return (bits << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
        axis=1, dtype=jnp.uint32)


def _bloom_jit():
    global _bloom_kernel_jit
    if _bloom_kernel_jit is None:
        from hyperspace_tpu.telemetry import instrumented_jit
        _bloom_kernel_jit = instrumented_jit("sketch.bloom")(_bloom_kernel)
    return _bloom_kernel_jit


def bloom_build(col, nbits: int) -> np.ndarray:
    """Bloom words (uint32, host) over every row of one column
    (DeviceColumn, host- or device-lane). Null rows insert their
    all-zero lanes — a harmless extra member, never a false negative."""
    if col.is_host:
        from hyperspace_tpu.ops.host_hash import host_column_hash_lanes
        h1, h2 = _dual_mix_host(host_column_hash_lanes(col))
        return _host_bloom_words(h1, h2, nbits)
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hash_partition import column_hash_lanes
    lanes = tuple(column_hash_lanes(col))
    words = _bloom_jit()(lanes, jnp.zeros(nbits, dtype=jnp.int32))
    return np.asarray(words)


def bloom_maybe_contains(words: np.ndarray, h1: int, h2: int) -> bool:
    """Membership probe: True = value MAY be present (bloom semantics);
    False = definitely absent."""
    nblocks = len(words) // WORDS_PER_BLOCK
    if nblocks <= 0:
        return True
    block = (int(h1) & 0xFFFFFFFF) % nblocks
    for j, salt in enumerate(_SALT):
        bit = (((int(h2) & 0xFFFFFFFF) * salt) & 0xFFFFFFFF) >> 27
        if not (int(words[block * WORDS_PER_BLOCK + j]) >> bit) & 1:
            return False
    return True


# ---------------------------------------------------------------------------
# Zone maps (host + device)
# ---------------------------------------------------------------------------

_FLOAT_DTYPES = ("float32", "float64")

_zones_jit = None


def _zones_kernel(data, validity, nan_mask):
    """Traceable zone body: (valid_count, ok_count, min, max, has_nan)
    where ok = valid AND not-NaN. Identity fill values keep the min/max
    sound when nothing qualifies (callers gate on ok_count)."""
    import jax.numpy as jnp

    valid = validity
    ok = valid & ~nan_mask
    big = (jnp.finfo(data.dtype).max
           if jnp.issubdtype(data.dtype, jnp.floating)
           else jnp.iinfo(data.dtype).max)
    small = (jnp.finfo(data.dtype).min
             if jnp.issubdtype(data.dtype, jnp.floating)
             else jnp.iinfo(data.dtype).min)
    vmin = jnp.min(jnp.where(ok, data, big))
    vmax = jnp.max(jnp.where(ok, data, small))
    return (valid.sum(dtype=jnp.int64), ok.sum(dtype=jnp.int64),
            vmin, vmax, (valid & nan_mask).any())


def zones(col) -> dict:
    """Zone-map facts of one column (DeviceColumn, host- or
    device-lane): {"nulls", "ok" (non-null, non-NaN count), "min",
    "max" (python scalars in code space for strings; None when no row
    qualifies), "has_nan"}. String columns reduce over their
    order-preserving dictionary codes; the caller maps the code bounds
    back through the dictionary."""
    n = len(col)
    is_float = col.dtype in _FLOAT_DTYPES and not col.is_string
    is_bool = col.dtype == "bool" and not col.is_string
    if col.is_host:
        data = col.data
        if is_bool:  # min/max over ints (no iinfo for bool)
            data = data.astype(np.int32)
        valid = (col.validity if col.validity is not None
                 else np.ones(n, dtype=bool))
        nan = np.isnan(data) if is_float else np.zeros(n, dtype=bool)
        ok = valid & ~nan
        cnt_valid = int(valid.sum())
        cnt_ok = int(ok.sum())
        vmin = data[ok].min() if cnt_ok else None
        vmax = data[ok].max() if cnt_ok else None
        has_nan = bool((valid & nan).any())
    else:
        import jax.numpy as jnp

        global _zones_jit
        if _zones_jit is None:
            from hyperspace_tpu.telemetry import instrumented_jit
            _zones_jit = instrumented_jit("sketch.zones")(_zones_kernel)
        data = col.data
        if is_bool:
            data = data.astype(jnp.int32)
        valid = (col.validity if col.validity is not None
                 else jnp.ones(n, dtype=bool))
        nan = (jnp.isnan(data) if is_float
               else jnp.zeros(n, dtype=bool))
        cv, co, vmin, vmax, hn = _zones_jit(data, valid, nan)
        cnt_valid, cnt_ok = int(cv), int(co)
        has_nan = bool(hn)
        vmin = np.asarray(vmin)[()] if cnt_ok else None
        vmax = np.asarray(vmax)[()] if cnt_ok else None
    return {"nulls": n - cnt_valid, "ok": cnt_ok,
            "min": None if vmin is None else vmin.item(),
            "max": None if vmax is None else vmax.item(),
            "has_nan": has_nan}


# ---------------------------------------------------------------------------
# Z-order clustering permutation
# ---------------------------------------------------------------------------

# Quantile resolution per column: 16 bits (65536 quantiles) is plenty
# for file-level clustering and keeps up to 4 interleaved columns in
# one uint64 z-value.
_Z_BITS_MAX = 16


def zorder_permutation(batch, columns: Sequence[str]) -> np.ndarray:
    """Stable row permutation clustering `batch` by the Z-order
    (Morton) interleave of `columns`. Each column is RANK-normalized
    first (dense quantiles via its order-preserving sort lanes, nulls
    first) so low-entropy or skewed value ranges still interleave
    meaningfully, then the quantile bits are woven MSB-first. One
    column degenerates to a plain sort. Host-side: the build's row
    gather and parquet encode are host work already, and the rank pass
    is one lexsort per column."""
    from hyperspace_tpu.ops.keys import host_column_sort_lanes

    n = batch.num_rows
    if n == 0:
        return np.arange(0, dtype=np.int64)
    k = max(1, len(columns))
    bits = min(_Z_BITS_MAX, 64 // k)
    quantized: List[np.ndarray] = []
    for name in columns:
        lanes = host_column_sort_lanes(batch.column(name))
        order = np.lexsort(tuple(reversed([np.asarray(l) for l in lanes])))
        rank = np.empty(n, dtype=np.uint64)
        rank[order] = np.arange(n, dtype=np.uint64)
        quantized.append((rank * np.uint64(1 << bits))
                         // np.uint64(n))
    z = np.zeros(n, dtype=np.uint64)
    for i in range(bits):
        shift = np.uint64(bits - 1 - i)
        for q in quantized:
            z = (z << np.uint64(1)) | ((q >> shift) & np.uint64(1))
    return np.argsort(z, kind="stable").astype(np.int64)
