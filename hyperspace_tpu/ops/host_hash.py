"""Host (numpy) mirror of THE bucket hash identity.

`ops/hash_partition.flat_hash32` defines the on-disk bucket layout; this
module reproduces it bit-for-bit on the host so control-plane decisions
that need a handful of bucket ids — bucket pruning of point filters, small
host-lane batches — never pay a device round-trip (~100 ms on a tunneled
link). `tests/test_ops.py::test_host_bucket_ids_match_device` pins host ==
device for every key dtype; any change to either side must keep them equal.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException


def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _combine(h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
    return h1 ^ (h2 + np.uint32(0x9E3779B9) + (h1 << np.uint32(6))
                 + (h1 >> np.uint32(2)))


def _float_order_bits(data: np.ndarray, uint_dtype, sign_bit: int):
    # Normalize first (-0.0 -> +0.0, NaNs -> one canonical NaN) so lane
    # identity equals numeric equality on every path; see the device
    # twin's docstring (`ops/keys.py::_float_order_bits`).
    data = np.where(data == 0, np.zeros((), data.dtype), data)
    data = np.where(np.isnan(data), np.full((), np.nan, data.dtype), data)
    bits = data.view(np.int64 if sign_bit == 64 else np.int32).astype(uint_dtype)
    sign = (bits >> uint_dtype(sign_bit - 1)) & uint_dtype(1)
    mask = np.where(sign == 1, ~uint_dtype(0), uint_dtype(1) << uint_dtype(sign_bit - 1))
    return bits ^ mask


def _hash_lanes(values: np.ndarray, dtype: str) -> List[np.ndarray]:
    """Per-value hash-input lanes, mirroring `column_hash_lanes` /
    `key_lanes` for host arrays (null-free inputs)."""
    if dtype == "string":
        from hyperspace_tpu.io.columnar import _string_hash64
        h = _string_hash64(np.asarray(values, dtype=str))
        return [(h >> np.uint64(32)).astype(np.uint32),
                (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    if dtype in ("int64", "timestamp"):
        data = np.asarray(values, dtype=np.int64)
        return [(data >> 32).astype(np.int32).astype(np.uint32),
                (data & 0xFFFFFFFF).astype(np.uint32)]
    if dtype == "float64":
        bits = _float_order_bits(np.asarray(values, dtype=np.float64),
                                 np.uint64, 64)
        return [(bits >> np.uint64(32)).astype(np.uint32),
                (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    if dtype == "float32":
        return [_float_order_bits(np.asarray(values, dtype=np.float32),
                                  np.uint32, 32)]
    if dtype in ("bool", "int8", "int16", "int32", "date32"):
        return [np.asarray(values).astype(np.int32).astype(np.uint32)]
    raise HyperspaceException(f"Unhashable key dtype: {dtype}")


def host_flat_hash32(lanes: Sequence[np.ndarray]) -> np.ndarray:
    h = _fmix32(lanes[0].astype(np.uint32))
    for lane in lanes[1:]:
        h = _combine(h, _fmix32(lane.astype(np.uint32)))
    return h


def host_bucket_ids(columns: Sequence[np.ndarray], dtypes: Sequence[str],
                    num_buckets: int) -> np.ndarray:
    """Bucket ids for rows given as per-column value arrays (no nulls)."""
    lanes: List[np.ndarray] = []
    for values, dtype in zip(columns, dtypes):
        lanes.extend(_hash_lanes(values, dtype))
    return (host_flat_hash32(lanes) % np.uint32(num_buckets)).astype(np.int32)


def host_column_hash_lanes(col) -> List[np.ndarray]:
    """Hash-input lanes for a host-lane DeviceColumn, mirroring the device
    `column_hash_lanes`: strings contribute gathered per-dictionary value
    hashes, numerics their 32-bit key lanes; null rows contribute all-zero
    lanes."""
    if col.is_string:
        hi, lo = col.dict_hashes
        lanes = [np.asarray(hi)[col.data], np.asarray(lo)[col.data]]
    else:
        from hyperspace_tpu.ops.keys import host_key_lanes
        lanes = [lane.astype(np.uint32) for lane in host_key_lanes(col.data)]
    if col.validity is not None:
        lanes = [np.where(col.validity, lane, np.uint32(0))
                 for lane in lanes]
    return lanes
