"""Bucketed sort-merge join, batched across all buckets.

The naive per-bucket Python loop dispatches a separately-compiled join
per bucket — on a TPU each unique bucket shape is a fresh XLA compile.
Here the whole join is TWO compiled programs and one host sync:

1. key tuples of both sides are globally group-encoded to order-preserving
   int32 ids (one joint `lax.sort` over 32-bit key lanes, `ops/keys.py`);
2. the GLOBAL counting join (`ops/join.counting_join_indices`) matches
   the id arrays — legal precisely because both sides hash-bucket by the
   same keys, so equal tuples always co-bucket and the global match set
   equals the per-bucket one. One more flat sort + cumulative counting;
   no `searchsorted` (log-n serialized gather sweeps dominate on TPU at
   TPC-DS scale), no padded [B, L] layout, skew-immune by construction.

SQL null semantics ride shared sentinels: left-null id -1, right-null id
-2 — never equal across sides.

The host lane keeps the per-bucket merge over the already-sorted index
layout (`ops/join.host_bucketed_join_indices` / the native C++ kernel);
the padded-layout helpers below (`next_pow2`, `_padded_layout`) serve
merge compaction (`ops/merge.py`) — the mesh-sharded distributed join
(`parallel/join.py`) builds its own [S, C] shard layout since round 4.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import (ColumnBatch, DeviceColumn,
                                        unify_string_columns)
from hyperspace_tpu.ops import keys as keymod

_I32_MAX = np.int32(np.iinfo(np.int32).max)

def next_pow2(n: int) -> int:
    return 1 << max(4, (int(n) - 1).bit_length())


def encode_group_ids(left: ColumnBatch, right: ColumnBatch,
                     left_keys: Sequence[str], right_keys: Sequence[str]):
    """Global order-preserving group ids over both sides' key tuples, with
    null sentinels (-1 left / -2 right). Key columns are decomposed into
    32-bit lanes so int64/float64 keys sort TPU-natively."""
    import jax
    import jax.numpy as jnp

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    lane_operands: List = []
    l_valid = jnp.ones(n, dtype=bool)
    r_valid = jnp.ones(m, dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        if lcol.validity is not None:
            l_valid = l_valid & lcol.validity
        if rcol.validity is not None:
            r_valid = r_valid & rcol.validity
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        llanes = keymod.key_lanes(ldata)
        rlanes = keymod.key_lanes(rdata)
        for ll, rl in zip(llanes, rlanes):
            lane_operands.append(jnp.concatenate([ll, rl]))
    return _encode_core(tuple(lane_operands), l_valid, r_valid, n)


@partial(__import__("jax").jit, static_argnames=("n",))
def _encode_core(lane_operands, l_valid, r_valid, n: int):
    import jax
    import jax.numpy as jnp

    total = lane_operands[0].shape[0]
    validity_key = jnp.concatenate([l_valid, r_valid])
    iota = jnp.arange(total, dtype=jnp.int32)
    sorted_ops = jax.lax.sort([validity_key, *lane_operands, iota],
                              num_keys=1 + len(lane_operands), is_stable=True)
    perm = sorted_ops[-1]
    keys_sorted = sorted_ops[:-1]
    differs = jnp.zeros(total, dtype=jnp.int32)
    for k in keys_sorted:
        differs = differs | jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             (k[1:] != k[:-1]).astype(jnp.int32)])
    group_sorted = jnp.cumsum(differs, dtype=jnp.int32)
    groups = jnp.zeros(total, dtype=jnp.int32).at[perm].set(group_sorted)
    l_ids = jnp.where(l_valid, groups[:n], jnp.int32(-1))
    r_ids = jnp.where(r_valid, groups[n:], jnp.int32(-2))
    return l_ids, r_ids


def _padded_layout(lengths: np.ndarray, width: int):
    """Host-side [B, width] gather matrix into a concat-in-bucket-order
    array, plus validity. Padding slots point at row 0 (safe gather)."""
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    j = np.arange(width)[None, :]
    valid = j < lengths[:, None]
    idx = np.where(valid, starts[:, None] + np.minimum(j, np.maximum(
        lengths[:, None] - 1, 0)), 0)
    return idx.astype(np.int32), valid


def bucketed_join_indices(left: ColumnBatch, right: ColumnBatch,
                          l_lengths: np.ndarray, r_lengths: np.ndarray,
                          left_keys: Sequence[str],
                          right_keys: Sequence[str],
                          how: str = "inner") -> Tuple:
    """Join row-index pairs for two sides stored concat-in-bucket-order with
    the given per-bucket lengths. One host sync total. For how='left_outer'
    unmatched left rows appear once with right index -1.

    Device lane: the global counting join over the shared group encode
    (`ops/join.counting_join_indices`) — both sides hash-bucket by the
    same keys, so equal tuples always co-bucket and the GLOBAL match set
    IS the per-bucket match set. The earlier padded [B, L] per-bucket
    formulation is gone: its batched dim-1 sorts and vmapped
    `searchsorted` were 4-7x slower than one flat sort + cumulative
    counting at every device-lane size (3.4s vs ~0.5s at 4M rows, 22s vs
    ~5s at 39M on a v5e), and the counting join is skew-immune — memory
    is bounded by true row count, so no skew fallback either."""
    import jax.numpy as jnp

    left_outer = how == "left_outer"
    empty = jnp.zeros(0, dtype=jnp.int32)
    if left.num_rows == 0:
        return empty, empty
    if right.num_rows == 0 and not left_outer:
        return empty, empty
    if right.num_rows == 0:
        li = jnp.arange(left.num_rows, dtype=jnp.int32)
        return li, jnp.full(left.num_rows, -1, dtype=jnp.int32)
    if left.is_host and right.is_host:
        # Host lane: per-bucket searchsorted over the ALREADY-SORTED index
        # layout (no sort, no hash — the bucketed-SMJ structural win); the
        # general host sort join covers multi-key/string/nullable keys.
        from hyperspace_tpu.ops.join import host_bucketed_join_indices
        return host_bucketed_join_indices(
            left, right, np.asarray(l_lengths), np.asarray(r_lengths),
            left_keys, right_keys, how="left_outer" if left_outer else how)
    from hyperspace_tpu.ops.join import counting_join_batch_indices
    return counting_join_batch_indices(
        left, right, left_keys, right_keys,
        how="left_outer" if left_outer else how)


def _gather_side(batch: ColumnBatch, idx, names, may_unmatch: bool = True):
    """Gather `names` columns of rows by index; index -1 (unmatched outer
    row) yields null. Host-lane batches with host indices gather in numpy.

    `may_unmatch=False` (inner-join sides) skips the unmatched handling —
    on device arrays a data-dependent `any()` would cost a blocking
    host sync (~100 ms tunneled), so the decision must be static."""
    if isinstance(idx, np.ndarray) and batch.is_host:
        xp = np
    else:
        import jax.numpy as xp

    narrowed = batch.select(names)
    if not may_unmatch or idx.shape[0] == 0:
        return narrowed.take(idx)
    unmatched = idx < 0
    out = narrowed.take(xp.clip(idx, 0, None))
    columns = {}
    for name, col in out.columns.items():
        validity = (col.validity & ~unmatched
                    if col.validity is not None else ~unmatched)
        columns[name] = DeviceColumn(col.data, col.dtype, validity,
                                     col.dictionary, col.dict_hashes)
    return ColumnBatch(out.schema, columns)


def join_output_plan(left_schema, right_schema, columns):
    """THE join output-naming contract, shared by the eager assembly and
    the fused masked lane (`engine/fusion.py`): [(out_name, side, src,
    dtype)] where side is "l"/"r". Left names are kept; right-side
    collisions get a `_r` suffix; `columns` (lowered OUTPUT names)
    late-projects. A consumer needing no columns at all (count(*) over
    the join) still needs the row count, which a ColumnBatch carries
    only through its columns — one is kept."""
    left_names = {f.name.lower() for f in left_schema.fields}
    plan = []
    for f in left_schema.fields:
        if columns is None or f.name.lower() in columns:
            plan.append((f.name, "l", f.name, f.dtype))
    for f in right_schema.fields:
        out = f.name if f.name.lower() not in left_names else f.name + "_r"
        if columns is None or out.lower() in columns:
            plan.append((out, "r", f.name, f.dtype))
    if not plan:
        f = left_schema.fields[0]
        plan.append((f.name, "l", f.name, f.dtype))
    return plan


def assemble_join_output(left: ColumnBatch, right: ColumnBatch,
                         li, ri, how: str = "left_outer",
                         columns=None) -> ColumnBatch:
    """Gather both sides by index pairs into the joined batch; -1 on either
    side (unmatched outer row) yields null columns for that side. Duplicate
    output names get a `_r` suffix on the right. `how` statically bounds
    which sides can hold -1 (inner: neither; left_outer: right only;
    right_outer: left only) so no data-dependent device sync is needed.

    `columns` (lowered OUTPUT names) enables late projection: only the
    listed output columns are gathered — a join used under a projection
    never materializes the join keys or other dropped payload."""
    from hyperspace_tpu.plan.schema import Field, Schema

    plan = join_output_plan(left.schema, right.schema, columns)
    lwanted = [src for _, side, src, _ in plan if side == "l"]
    rwanted = [src for _, side, src, _ in plan if side == "r"]
    left_out = _gather_side(left, li, lwanted,
                            may_unmatch=how in ("right_outer", "full_outer"))
    right_out = _gather_side(right, ri, rwanted,
                             may_unmatch=how in ("left_outer", "full_outer"))
    fields = []
    out_columns = {}
    for out, side, src, dtype in plan:
        if side == "l":
            fields.append(Field(out, dtype,
                                left.schema.field(src).nullable
                                or how in ("right_outer", "full_outer")))
            out_columns[out] = left_out.columns[src]
        else:
            fields.append(Field(out, dtype, True))
            out_columns[out] = right_out.columns[src]
    return ColumnBatch(Schema(fields), out_columns)


def bucketed_sort_merge_join(left: ColumnBatch, right: ColumnBatch,
                             l_lengths: np.ndarray, r_lengths: np.ndarray,
                             left_keys: Sequence[str],
                             right_keys: Sequence[str],
                             how: str = "inner",
                             columns=None) -> ColumnBatch:
    """Full bucketed join over concat-in-bucket-order sides. full_outer =
    the left_outer expansion plus one appended row per unmatched right
    row (both sides share one hash layout, so membership is global)."""
    from hyperspace_tpu import telemetry
    telemetry.annotate(join_buckets=len(np.asarray(l_lengths)),
                       left_rows=left.num_rows, right_rows=right.num_rows)
    if how == "right_outer":
        ri, li = bucketed_join_indices(right, left, np.asarray(r_lengths),
                                       np.asarray(l_lengths), right_keys,
                                       left_keys, how="left_outer")
    else:
        li, ri = bucketed_join_indices(
            left, right, np.asarray(l_lengths), np.asarray(r_lengths),
            left_keys, right_keys,
            how="left_outer" if how == "full_outer" else how)
        if how == "full_outer":
            # Unmatched right rows come straight from the match indices —
            # no key re-encode (a matched right row always appears in ri).
            from hyperspace_tpu.ops.join import unmatched_right_from_indices
            extra = unmatched_right_from_indices(ri, right.num_rows)
            if isinstance(ri, np.ndarray):
                li = np.concatenate(
                    [li, np.full(len(extra), -1, dtype=np.int32)])
                ri = np.concatenate([ri, extra])
            else:
                import jax.numpy as jnp
                li = jnp.concatenate(
                    [li, jnp.full(extra.shape[0], -1, dtype=jnp.int32)])
                ri = jnp.concatenate([ri, extra])
    return assemble_join_output(left, right, li, ri, how=how,
                                columns=columns)
