"""Bucketed sort-merge join as ONE batched XLA program.

The naive per-bucket Python loop dispatches a separately-compiled join per
bucket — on a TPU each unique bucket shape is a fresh XLA compile. Here all
buckets are joined in a single compiled program:

1. key tuples of both sides are globally group-encoded to order-preserving
   int32 ids (one joint `lax.sort` over 32-bit key lanes, `ops/keys.py`);
2. each side is laid out as a padded [B, L] matrix (L = next power of two of
   the largest bucket, so repeated queries reuse compiles), padding slots
   carry id INT32_MAX;
3. one batched `lax.sort` per side orders every bucket's ids (robust to
   multi-run buckets from incremental refresh — no reliance on file order);
4. a vmapped double `searchsorted` finds per-row match ranges; counts are
   clamped to each bucket's valid length;
5. after ONE host sync for the total match count, a second jitted program
   expands (bucket, row, offset) -> original row index pairs.

SQL null semantics ride the same sentinels as `ops/join.py`: left-null id
-1, right-null id -2, padding +INT32_MAX — none ever equal.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import (ColumnBatch, DeviceColumn,
                                        unify_string_columns)
from hyperspace_tpu.ops import keys as keymod

_I32_MAX = np.int32(np.iinfo(np.int32).max)

# Skew guard: the padded [B, L] layout costs B * next_pow2(max bucket len)
# cells per side, so ONE hot key inflates every bucket's row to L and the
# batched join degrades to O(B*L) memory/compute. Past this blowup the
# layout loses to a global id-sort + merge join, whose cost is
# O((n+m) log(n+m)) regardless of how keys distribute — the analog of
# Spark's ragged partitions, where no bucket pays for a neighbour's skew.
SKEW_BLOWUP_FACTOR = 8
SKEW_MIN_CELLS = 1 << 22


def next_pow2(n: int) -> int:
    return 1 << max(4, (int(n) - 1).bit_length())


def padded_skew(l_lengths, r_lengths, n_rows: int, m_rows: int) -> bool:
    """True when the padded bucket layout would materially out-size the
    actual row count (hot-key skew) and the global join should be used."""
    B = max(len(l_lengths), 1)
    Ll = next_pow2(max(1, int(np.asarray(l_lengths).max(initial=0))))
    Lr = next_pow2(max(1, int(np.asarray(r_lengths).max(initial=0))))
    cells = B * (Ll + Lr)
    return (cells > SKEW_MIN_CELLS
            and cells > SKEW_BLOWUP_FACTOR * max(n_rows + m_rows, 1))


def _global_join_indices(left: ColumnBatch, right: ColumnBatch,
                         left_keys: Sequence[str],
                         right_keys: Sequence[str], how: str) -> Tuple:
    """Skew fallback. Both sides are bucketed by the same hash of the same
    keys, so equal key tuples always share a bucket: a global id-sort +
    merge join over all rows returns exactly the per-bucket match set
    (row order differs; join output order is unspecified), with memory
    bounded by the true row count."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.join import merge_join_indices

    l_ids, r_ids = encode_group_ids(left, right, left_keys, right_keys)
    l_perm = jnp.argsort(l_ids, stable=True)
    r_perm = jnp.argsort(r_ids, stable=True)
    li_s, ri_s = merge_join_indices(jnp.take(l_ids, l_perm),
                                    jnp.take(r_ids, r_perm), how=how)
    if li_s.shape[0] == 0:
        return li_s, ri_s
    li = jnp.take(l_perm, li_s).astype(jnp.int32)
    ri = jnp.where(ri_s >= 0,
                   jnp.take(r_perm, jnp.clip(ri_s, 0, None)),
                   jnp.int32(-1)).astype(jnp.int32)
    return li, ri


def encode_group_ids(left: ColumnBatch, right: ColumnBatch,
                     left_keys: Sequence[str], right_keys: Sequence[str]):
    """Global order-preserving group ids over both sides' key tuples, with
    null sentinels (-1 left / -2 right). Key columns are decomposed into
    32-bit lanes so int64/float64 keys sort TPU-natively."""
    import jax
    import jax.numpy as jnp

    if len(left_keys) != len(right_keys) or not left_keys:
        raise HyperspaceException("Join requires matching key column lists.")
    n, m = left.num_rows, right.num_rows
    lane_operands: List = []
    l_valid = jnp.ones(n, dtype=bool)
    r_valid = jnp.ones(m, dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        lcol, rcol = left.column(lk), right.column(rk)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(f"Join key type mismatch: {lk} vs {rk}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        if lcol.validity is not None:
            l_valid = l_valid & lcol.validity
        if rcol.validity is not None:
            r_valid = r_valid & rcol.validity
        ldata, rdata = lcol.data, rcol.data
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata = ldata.astype(common)
            rdata = rdata.astype(common)
        llanes = keymod.key_lanes(ldata)
        rlanes = keymod.key_lanes(rdata)
        for ll, rl in zip(llanes, rlanes):
            lane_operands.append(jnp.concatenate([ll, rl]))
    return _encode_core(tuple(lane_operands), l_valid, r_valid, n)


@partial(__import__("jax").jit, static_argnames=("n",))
def _encode_core(lane_operands, l_valid, r_valid, n: int):
    import jax
    import jax.numpy as jnp

    total = lane_operands[0].shape[0]
    validity_key = jnp.concatenate([l_valid, r_valid])
    iota = jnp.arange(total, dtype=jnp.int32)
    sorted_ops = jax.lax.sort([validity_key, *lane_operands, iota],
                              num_keys=1 + len(lane_operands), is_stable=True)
    perm = sorted_ops[-1]
    keys_sorted = sorted_ops[:-1]
    differs = jnp.zeros(total, dtype=jnp.int32)
    for k in keys_sorted:
        differs = differs | jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             (k[1:] != k[:-1]).astype(jnp.int32)])
    group_sorted = jnp.cumsum(differs, dtype=jnp.int32)
    groups = jnp.zeros(total, dtype=jnp.int32).at[perm].set(group_sorted)
    l_ids = jnp.where(l_valid, groups[:n], jnp.int32(-1))
    r_ids = jnp.where(r_valid, groups[n:], jnp.int32(-2))
    return l_ids, r_ids


def _padded_layout(lengths: np.ndarray, width: int):
    """Host-side [B, width] gather matrix into a concat-in-bucket-order
    array, plus validity. Padding slots point at row 0 (safe gather)."""
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    j = np.arange(width)[None, :]
    valid = j < lengths[:, None]
    idx = np.where(valid, starts[:, None] + np.minimum(j, np.maximum(
        lengths[:, None] - 1, 0)), 0)
    return idx.astype(np.int32), valid


@partial(__import__("jax").jit, static_argnames=())
def _match_core(l_ids, r_ids, l_idx, l_valid, r_idx, r_valid):
    """Batched per-bucket match-range computation.

    l_idx/l_valid: [B, Ll] gather matrix + mask; likewise right. Returns
    (counts [B*Ll], starts [B*Ll], lo [B, Ll], l_pos [B, Ll], r_pos [B, Lr])
    where l_pos/r_pos give, per bucket, the original padded-slot position of
    each id-sorted element.
    """
    import jax
    import jax.numpy as jnp

    B, Ll = l_idx.shape
    Lr = r_idx.shape[1]
    lid = jnp.where(l_valid, jnp.take(l_ids, l_idx), _I32_MAX)
    rid = jnp.where(r_valid, jnp.take(r_ids, r_idx), _I32_MAX)

    pos_l = jnp.broadcast_to(jnp.arange(Ll, dtype=jnp.int32), (B, Ll))
    pos_r = jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.int32), (B, Lr))
    lid_s, l_pos = jax.lax.sort([lid, pos_l], num_keys=1, is_stable=True,
                                dimension=1)
    rid_s, r_pos = jax.lax.sort([rid, pos_r], num_keys=1, is_stable=True,
                                dimension=1)

    lo = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="left"))(rid_s, lid_s)
    hi = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="right"))(rid_s, lid_s)
    r_len = jnp.sum(r_valid, axis=1).astype(lo.dtype)  # valid (incl. null-id) rows sort before pads
    lo_c = jnp.minimum(lo, r_len[:, None])
    hi_c = jnp.minimum(hi, r_len[:, None])
    counts = jnp.maximum(hi_c - lo_c, 0)
    real = (lid_s != _I32_MAX).reshape(-1)  # non-padding left slots
    counts = jnp.where(lid_s == _I32_MAX, 0, counts)  # padding left rows
    flat = counts.reshape(-1)
    starts = jnp.cumsum(flat) - flat
    return flat, starts, lo_c, l_pos, r_pos, real


@partial(__import__("jax").jit, static_argnames=("total", "Ll"))
def _expand_core(starts, match_counts, lo_c, l_pos, r_pos, l_idx, r_idx,
                 total: int, Ll: int):
    """Expand (bucket,row,offset) -> original row index pairs.

    `starts` is the cumulative layout of EFFECTIVE counts (outer joins
    reserve one output slot for unmatched real left rows); `match_counts`
    is the TRUE per-slot match count from `_match_core`, pre-outer-fill —
    a slot whose true count is zero emits right index -1. Deriving
    `matched` from the effective counts would make every reserved outer
    slot look matched and gather an arbitrary right row."""
    import jax.numpy as jnp

    slots = jnp.arange(total, dtype=starts.dtype)
    row = jnp.searchsorted(starts, slots, side="right") - 1
    b = (row // Ll).astype(jnp.int32)
    i = (row % Ll).astype(jnp.int32)
    offset = (slots - jnp.take(starts, row)).astype(jnp.int32)
    l_slot = l_pos[b, i]
    matched = jnp.take(match_counts, row) > 0
    Lr = r_pos.shape[1]
    r_lookup = jnp.clip(lo_c[b, i] + offset, 0, Lr - 1)
    r_slot = r_pos[b, r_lookup]
    ri = jnp.where(matched, r_idx[b, r_slot], jnp.int32(-1))
    return l_idx[b, l_slot], ri


def bucketed_join_indices(left: ColumnBatch, right: ColumnBatch,
                          l_lengths: np.ndarray, r_lengths: np.ndarray,
                          left_keys: Sequence[str],
                          right_keys: Sequence[str],
                          how: str = "inner") -> Tuple:
    """Join row-index pairs for two sides stored concat-in-bucket-order with
    the given per-bucket lengths. One host sync total. For how='left_outer'
    unmatched left rows appear once with right index -1."""
    import jax.numpy as jnp

    left_outer = how == "left_outer"
    empty = jnp.zeros(0, dtype=jnp.int32)
    if left.num_rows == 0:
        return empty, empty
    if right.num_rows == 0 and not left_outer:
        return empty, empty
    if right.num_rows == 0:
        li = jnp.arange(left.num_rows, dtype=jnp.int32)
        return li, jnp.full(left.num_rows, -1, dtype=jnp.int32)
    if left.is_host and right.is_host:
        # Host lane: per-bucket searchsorted over the ALREADY-SORTED index
        # layout (no sort, no hash — the bucketed-SMJ structural win); the
        # general host sort join covers multi-key/string/nullable keys.
        from hyperspace_tpu.ops.join import host_bucketed_join_indices
        return host_bucketed_join_indices(
            left, right, np.asarray(l_lengths), np.asarray(r_lengths),
            left_keys, right_keys, how="left_outer" if left_outer else how)
    if padded_skew(l_lengths, r_lengths, left.num_rows, right.num_rows):
        return _global_join_indices(left, right, left_keys, right_keys,
                                    "left_outer" if left_outer else how)
    l_ids, r_ids = encode_group_ids(left, right, left_keys, right_keys)
    Ll = next_pow2(max(1, int(l_lengths.max(initial=0))))
    Lr = next_pow2(max(1, int(r_lengths.max(initial=0))))
    l_idx, l_valid = _padded_layout(np.asarray(l_lengths), Ll)
    r_idx, r_valid = _padded_layout(np.asarray(r_lengths), Lr)
    l_idx, l_valid = jnp.asarray(l_idx), jnp.asarray(l_valid)
    r_idx, r_valid = jnp.asarray(r_idx), jnp.asarray(r_valid)

    match_counts, starts, lo_c, l_pos, r_pos, real = _match_core(
        l_ids, r_ids, l_idx, l_valid, r_idx, r_valid)
    counts = match_counts
    if left_outer:
        # One output row per unmatched REAL left row (incl. null keys).
        counts = jnp.maximum(match_counts, real.astype(match_counts.dtype))
        starts = jnp.cumsum(counts) - counts
    total = int(jnp.sum(counts))  # the one host sync
    if total == 0:
        return empty, empty
    return _expand_core(starts, match_counts, lo_c, l_pos, r_pos, l_idx,
                        r_idx, total, int(l_pos.shape[1]))


def _gather_side(batch: ColumnBatch, idx, names, may_unmatch: bool = True):
    """Gather `names` columns of rows by index; index -1 (unmatched outer
    row) yields null. Host-lane batches with host indices gather in numpy.

    `may_unmatch=False` (inner-join sides) skips the unmatched handling —
    on device arrays a data-dependent `any()` would cost a blocking
    host sync (~100 ms tunneled), so the decision must be static."""
    if isinstance(idx, np.ndarray) and batch.is_host:
        xp = np
    else:
        import jax.numpy as xp

    narrowed = batch.select(names)
    if not may_unmatch or idx.shape[0] == 0:
        return narrowed.take(idx)
    unmatched = idx < 0
    out = narrowed.take(xp.clip(idx, 0, None))
    columns = {}
    for name, col in out.columns.items():
        validity = (col.validity & ~unmatched
                    if col.validity is not None else ~unmatched)
        columns[name] = DeviceColumn(col.data, col.dtype, validity,
                                     col.dictionary, col.dict_hashes)
    return ColumnBatch(out.schema, columns)


def assemble_join_output(left: ColumnBatch, right: ColumnBatch,
                         li, ri, how: str = "left_outer",
                         columns=None) -> ColumnBatch:
    """Gather both sides by index pairs into the joined batch; -1 on either
    side (unmatched outer row) yields null columns for that side. Duplicate
    output names get a `_r` suffix on the right. `how` statically bounds
    which sides can hold -1 (inner: neither; left_outer: right only;
    right_outer: left only) so no data-dependent device sync is needed.

    `columns` (lowered OUTPUT names) enables late projection: only the
    listed output columns are gathered — a join used under a projection
    never materializes the join keys or other dropped payload."""
    from hyperspace_tpu.plan.schema import Field, Schema

    left_names = {f.name.lower() for f in left.schema.fields}
    plan = []  # (out_name, side, source_name, dtype)
    for f in left.schema.fields:
        if columns is None or f.name.lower() in columns:
            plan.append((f.name, "l", f.name, f.dtype))
    for f in right.schema.fields:
        out = f.name if f.name.lower() not in left_names else f.name + "_r"
        if columns is None or out.lower() in columns:
            plan.append((out, "r", f.name, f.dtype))

    if not plan:
        # A consumer needing no columns at all (count(*) over the join)
        # still needs the row count, which a ColumnBatch carries only
        # through its columns — keep one.
        f = left.schema.fields[0]
        plan.append((f.name, "l", f.name, f.dtype))
    lwanted = [src for _, side, src, _ in plan if side == "l"]
    rwanted = [src for _, side, src, _ in plan if side == "r"]
    left_out = _gather_side(left, li, lwanted,
                            may_unmatch=how in ("right_outer", "full_outer"))
    right_out = _gather_side(right, ri, rwanted,
                             may_unmatch=how in ("left_outer", "full_outer"))
    fields = []
    out_columns = {}
    for out, side, src, dtype in plan:
        if side == "l":
            fields.append(Field(out, dtype,
                                left.schema.field(src).nullable
                                or how in ("right_outer", "full_outer")))
            out_columns[out] = left_out.columns[src]
        else:
            fields.append(Field(out, dtype, True))
            out_columns[out] = right_out.columns[src]
    return ColumnBatch(Schema(fields), out_columns)


def bucketed_sort_merge_join(left: ColumnBatch, right: ColumnBatch,
                             l_lengths: np.ndarray, r_lengths: np.ndarray,
                             left_keys: Sequence[str],
                             right_keys: Sequence[str],
                             how: str = "inner",
                             columns=None) -> ColumnBatch:
    """Full bucketed join over concat-in-bucket-order sides. full_outer =
    the left_outer expansion plus one appended row per unmatched right
    row (both sides share one hash layout, so membership is global)."""
    if how == "right_outer":
        ri, li = bucketed_join_indices(right, left, np.asarray(r_lengths),
                                       np.asarray(l_lengths), right_keys,
                                       left_keys, how="left_outer")
    else:
        li, ri = bucketed_join_indices(
            left, right, np.asarray(l_lengths), np.asarray(r_lengths),
            left_keys, right_keys,
            how="left_outer" if how == "full_outer" else how)
        if how == "full_outer":
            # Unmatched right rows come straight from the match indices —
            # no key re-encode (a matched right row always appears in ri).
            from hyperspace_tpu.ops.join import unmatched_right_from_indices
            extra = unmatched_right_from_indices(ri, right.num_rows)
            if isinstance(ri, np.ndarray):
                li = np.concatenate(
                    [li, np.full(len(extra), -1, dtype=np.int32)])
                ri = np.concatenate([ri, extra])
            else:
                import jax.numpy as jnp
                li = jnp.concatenate(
                    [li, jnp.full(extra.shape[0], -1, dtype=jnp.int32)])
                ri = jnp.concatenate([ri, extra])
    return assemble_join_output(left, right, li, ri, how=how,
                                columns=columns)
