"""Device group-by aggregation: sort-based segment reductions.

The reference leaves aggregation to Spark SQL's hash/sort aggregates; here
groups are formed by ONE stable multi-key sort (32-bit lanes) and reduced
with XLA segment ops — TPU-friendly: no scatter contention, fully
vectorized, one host sync (the group count) to size the output.

SQL null semantics: sum/min/max/avg ignore null inputs; count(col) counts
non-null; count(*) counts rows; a group whose inputs are all null yields
null (validity False) for sum/min/max/avg and 0 for count.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.plan.nodes import AggSpec
from hyperspace_tpu.plan.schema import Schema


@__import__("jax").jit
def _group_phase_a(operands):
    """(sort permutation, sorted-space segment ids) of the group-key
    lanes, fused into one executable (staged sort + adjacent-difference
    segmenting; the narrow path's sort yields the sorted lanes for
    free — no re-gather)."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import _staged_sort

    ops = list(operands)
    n = ops[0].shape[0]
    perm, sorted_ops = _staged_sort(ops)
    differs = jnp.zeros(n, dtype=jnp.int32)
    for k in sorted_ops:
        differs = differs | jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             (k[1:] != k[:-1]).astype(jnp.int32)])
    segment_ids = jnp.cumsum(differs, dtype=jnp.int32)
    return perm, segment_ids


# Wide groupings (q64's 15 columns -> ~25 lanes) pay the chunked-LSD
# sort's data movement AND its minutes-long one-time XLA compile at each
# novel shape over a tunneled link. Above this lane count the HASHED
# phase sorts ONE u64 hash lane instead and verifies no collision split
# a group (fallback: the full sort). 64-bit hash over ~10^7 rows makes
# the fallback astronomically rare; correctness never depends on it.
HASH_GROUP_MIN_LANES = 5


@__import__("jax").jit
def _group_phase_a_hashed(operands):
    """(perm, segment ids, collision flag) via ONE u64-hash-lane sort.
    Equal keys share a hash, so a stable hash sort puts every group in
    one contiguous run unless two DIFFERENT keys collide; `collision` is
    true iff any adjacent-row group boundary (full-lane difference)
    occurs INSIDE an equal-hash run — exactly the split-group case. The
    caller re-runs the exact full-lane sort when it fires. The last
    output packs (num_segments, collision) into one int64 scalar so the
    caller's sizing sync is a single fetch."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hash_partition import dual_hash64

    ops = list(operands)
    n = ops[0].shape[0]
    h = dual_hash64(ops)
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_h, perm = jax.lax.sort([h, iota], num_keys=1, is_stable=True)
    zero = jnp.zeros(1, dtype=jnp.int32)
    differs = zero
    for k in ops:
        ks = jnp.take(k, perm)
        differs = differs | jnp.concatenate(
            [zero, (ks[1:] != ks[:-1]).astype(jnp.int32)])
    h_differs = jnp.concatenate(
        [zero, (sorted_h[1:] != sorted_h[:-1]).astype(jnp.int32)])
    collision = jnp.any((differs == 1) & (h_differs == 0))
    segment_ids = jnp.cumsum(differs, dtype=jnp.int32)
    packed = (segment_ids[-1].astype(jnp.int64) * jnp.int64(2)
              + collision.astype(jnp.int64))
    return perm, segment_ids, packed


def group_aggregate(batch: ColumnBatch, group_columns: Sequence[str],
                    aggregates: Sequence[AggSpec],
                    out_schema: Schema) -> ColumnBatch:
    if batch.is_host and batch.num_rows > 0:
        return _host_group_aggregate(batch, group_columns, aggregates,
                                     out_schema)
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import column_sort_lanes

    n = batch.num_rows
    _NP_OF = {"int64": jnp.int64, "float64": jnp.float64, "int32": jnp.int32,
              "float32": jnp.float32, "int8": jnp.int8, "int16": jnp.int16,
              "bool": jnp.bool_, "date32": jnp.int32, "timestamp": jnp.int64,
              "string": jnp.int32}

    if n == 0:
        if not group_columns:
            # SQL: a GLOBAL aggregate over zero rows is ONE row —
            # count/count_distinct 0, everything else NULL. (The
            # cross-join scalar-assembly queries rely on this: an empty
            # bucket must not collapse the whole product to zero rows.)
            columns = {}
            for spec in aggregates:
                f = out_schema.field(spec.alias)
                if (spec.column != "*"
                        and batch.column(spec.column).is_string
                        and spec.func not in ("count", "count_distinct")):
                    # Same contract as the non-empty path: surface the
                    # unsupported case here, not as a downstream crash on a
                    # dictionary-less string column.
                    raise HyperspaceException(
                        f"Aggregate {spec.func} over string column "
                        f"{spec.column} is not supported.")
                if spec.func in ("count", "count_distinct"):
                    columns[f.name] = DeviceColumn(
                        jnp.zeros(1, dtype=jnp.int64), "int64")
                else:
                    columns[f.name] = DeviceColumn(
                        jnp.zeros(1, dtype=_NP_OF[f.dtype]), f.dtype,
                        validity=jnp.zeros(1, dtype=bool))
            return ColumnBatch(out_schema, columns)
        columns = {}
        for f in out_schema.fields:
            src = (batch.column(f.name)
                   if f.name in [batch.schema.field(c).name
                                 for c in group_columns] else None)
            columns[f.name] = DeviceColumn(
                data=jnp.zeros(0, dtype=_NP_OF[f.dtype]), dtype=f.dtype,
                dictionary=src.dictionary if src is not None else None,
                dict_hashes=src.dict_hashes if src is not None else None)
        return ColumnBatch(out_schema, columns)

    if group_columns:
        operands: List = []
        for name in group_columns:
            operands.extend(column_sort_lanes(batch.column(name)))
        # ONE fused executable: hash-lane sort for wide groupings (full
        # staged sort re-run on the astronomically-rare collision),
        # staged narrow-pass sort otherwise + segment-id derivation.
        # Separate eager ops would each pay a compile round-trip over
        # the tunneled backend.
        ops = tuple(jnp.asarray(op) for op in operands)
        if len(ops) >= HASH_GROUP_MIN_LANES:
            perm, segment_ids, packed = _group_phase_a_hashed(ops)
            packed = int(packed)  # the one host sync
            if packed & 1:  # hash collision split a group: exact re-run
                perm, segment_ids = _group_phase_a(ops)
                num_groups = int(segment_ids[-1]) + 1
            else:
                num_groups = (packed >> 1) + 1
        else:
            perm, segment_ids = _group_phase_a(ops)
            num_groups = int(segment_ids[-1]) + 1  # the one host sync
        sorted_batch = batch.take(perm)
        # Representative row (first of each segment) carries the group keys.
        firsts = jnp.searchsorted(segment_ids,
                                  jnp.arange(num_groups, dtype=jnp.int32),
                                  side="left")
    else:
        segment_ids = jnp.zeros(n, dtype=jnp.int32)
        num_groups = 1
        sorted_batch = batch
        firsts = jnp.zeros(1, dtype=jnp.int32)

    columns = {}
    for name in group_columns:
        src = sorted_batch.column(name)
        f = batch.schema.field(name)
        columns[f.name] = DeviceColumn(
            data=jnp.take(src.data, firsts),
            dtype=src.dtype,
            validity=(jnp.take(src.validity, firsts)
                      if src.validity is not None else None),
            dictionary=src.dictionary, dict_hashes=src.dict_hashes)

    for spec in aggregates:
        out_field = out_schema.field(spec.alias)
        if spec.func == "count" and spec.column == "*":
            data = jax.ops.segment_sum(jnp.ones(n, dtype=jnp.int64),
                                       segment_ids, num_segments=num_groups)
            columns[out_field.name] = DeviceColumn(data, "int64")
            continue
        src = sorted_batch.column(spec.column)
        if src.is_string and spec.func not in ("count", "count_distinct"):
            raise HyperspaceException(
                f"Aggregate {spec.func} over string column {spec.column} "
                "is not supported.")
        valid = (src.validity if src.validity is not None
                 else jnp.ones(n, dtype=bool))
        counts = jax.ops.segment_sum(valid.astype(jnp.int64), segment_ids,
                                     num_segments=num_groups)
        if spec.func == "count":
            columns[out_field.name] = DeviceColumn(counts, "int64")
            continue
        if spec.func == "count_distinct":
            # Distinct non-null values per group: ONE more device sort
            # keyed (segment, invalid-last, *value lanes), then count run
            # starts at valid rows. Strings count by dictionary code
            # (dictionaries are sorted+unique, so code identity is value
            # identity); nulls sort after the valid block so a shared
            # masked value can never swallow a valid run start.
            lanes = column_sort_lanes(src)
            invalid = (~valid).astype(jnp.int32)
            # Bounded width (one column: <= 5 operands) — the single
            # fused sort also returns the sorted lanes.
            res = jax.lax.sort([segment_ids, invalid, *lanes],
                               num_keys=2 + len(lanes))
            seg_s, inv_s, lanes_s = res[0], res[1], res[2:]
            differs = seg_s[1:] != seg_s[:-1]
            for lane in lanes_s:
                differs = differs | (lane[1:] != lane[:-1])
            run_start = jnp.concatenate(
                [jnp.ones(1, dtype=bool), differs])
            data = jax.ops.segment_sum(
                (run_start & (inv_s == 0)).astype(jnp.int64), seg_s,
                num_segments=num_groups)
            columns[out_field.name] = DeviceColumn(data, "int64")
            continue
        values = src.data
        validity_out = counts > 0
        if spec.func in ("sum", "avg"):
            acc_dtype = (jnp.float64 if out_field.dtype == "float64"
                         else jnp.int64)
            total = jax.ops.segment_sum(
                jnp.where(valid, values, 0).astype(acc_dtype), segment_ids,
                num_segments=num_groups)
            if spec.func == "sum":
                data = total
            else:
                data = total.astype(jnp.float64) / jnp.maximum(counts, 1)
        elif spec.func == "stddev":
            # Sample stddev (SQL stddev_samp) via TWO passes: per-group
            # mean, then squared deviations — the one-pass sum-of-squares
            # identity catastrophically cancels in float64 when
            # mean^2 >> variance (ids, timestamps). Null when fewer than
            # 2 non-null inputs.
            x = jnp.where(valid, values, 0).astype(jnp.float64)
            cnt = counts.astype(jnp.float64)
            mu = jax.ops.segment_sum(
                x, segment_ids, num_segments=num_groups) / jnp.maximum(cnt, 1)
            dev = jnp.where(valid, x - jnp.take(mu, segment_ids), 0.0)
            var = jax.ops.segment_sum(
                dev * dev, segment_ids,
                num_segments=num_groups) / jnp.maximum(cnt - 1, 1)
            data = jnp.sqrt(jnp.maximum(var, 0.0))
            validity_out = counts > 1
        elif spec.func == "min":
            big = _dtype_max(values.dtype)
            data = jax.ops.segment_min(jnp.where(valid, values, big),
                                       segment_ids, num_segments=num_groups)
        else:  # max
            small = _dtype_min(values.dtype)
            data = jax.ops.segment_max(jnp.where(valid, values, small),
                                       segment_ids, num_segments=num_groups)
        # Validity is attached unconditionally: deciding with
        # `bool(any(~validity_out))` would cost one blocking device sync
        # per aggregate; an all-True mask is semantically identical.
        columns[out_field.name] = DeviceColumn(
            data.astype(_NP_OF[out_field.dtype]), out_field.dtype,
            validity=validity_out)
    return ColumnBatch(out_schema, columns)


def _dtype_max(dtype):
    import jax.numpy as jnp
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    return jnp.iinfo(dtype).max


def _dtype_min(dtype):
    import jax.numpy as jnp
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


def _host_group_aggregate(batch: ColumnBatch,
                          group_columns: Sequence[str],
                          aggregates: Sequence[AggSpec],
                          out_schema: Schema) -> ColumnBatch:
    """Host-lane (numpy) mirror of the device aggregation: same grouping
    (stable lexicographic sort, nulls first) and the same SQL null
    semantics, with contiguous-segment `ufunc.reduceat` reductions."""
    from hyperspace_tpu.ops.keys import host_column_sort_lanes

    _HOST_NP = {"int64": np.int64, "float64": np.float64, "int32": np.int32,
                "float32": np.float32, "int8": np.int8, "int16": np.int16,
                "bool": np.bool_, "date32": np.int32, "timestamp": np.int64,
                "string": np.int32}
    from hyperspace_tpu.ops.keys import host_dense_group_ids

    n = batch.num_rows
    if group_columns:
        operands = []
        for name in group_columns:
            operands.extend(host_column_sort_lanes(batch.column(name)))
        perm, segment_ids = host_dense_group_ids(operands)
        perm = perm.astype(np.int32)
        num_groups = int(segment_ids[-1]) + 1
        sorted_batch = batch.take(perm)
        starts = np.searchsorted(segment_ids, np.arange(num_groups),
                                 side="left")
    else:
        segment_ids = np.zeros(n, dtype=np.int32)
        num_groups = 1
        sorted_batch = batch
        starts = np.zeros(1, dtype=np.int64)

    columns = {}
    for name in group_columns:
        src = sorted_batch.column(name)
        f = batch.schema.field(name)
        columns[f.name] = DeviceColumn(
            data=np.asarray(src.data)[starts], dtype=src.dtype,
            validity=(np.asarray(src.validity)[starts]
                      if src.validity is not None else None),
            dictionary=src.dictionary, dict_hashes=src.dict_hashes)

    for spec in aggregates:
        out_field = out_schema.field(spec.alias)
        if spec.func == "count" and spec.column == "*":
            data = np.bincount(segment_ids,
                               minlength=num_groups).astype(np.int64)
            columns[out_field.name] = DeviceColumn(data, "int64")
            continue
        src = sorted_batch.column(spec.column)
        if src.is_string and spec.func not in ("count", "count_distinct"):
            raise HyperspaceException(
                f"Aggregate {spec.func} over string column {spec.column} "
                "is not supported.")
        valid = (np.asarray(src.validity) if src.validity is not None
                 else np.ones(n, dtype=bool))
        counts = np.bincount(segment_ids, weights=valid,
                             minlength=num_groups).astype(np.int64)
        if spec.func == "count":
            columns[out_field.name] = DeviceColumn(counts, "int64")
            continue
        if spec.func == "count_distinct":
            # Mirror of the device lane: lexsort (segment, invalid-last,
            # *value lanes), count run starts at valid rows.
            lanes = [np.asarray(lane)
                     for lane in host_column_sort_lanes(src)]
            inv = (~valid).astype(np.int8)
            order = np.lexsort(tuple(reversed(
                [segment_ids, inv] + lanes)))
            seg_s = segment_ids[order]
            differs = seg_s[1:] != seg_s[:-1]
            for lane in lanes:
                lane_s = lane[order]
                differs = differs | (lane_s[1:] != lane_s[:-1])
            run_start = np.concatenate([[True], differs])
            data = np.bincount(
                seg_s, weights=(run_start & valid[order]),
                minlength=num_groups).astype(np.int64)
            columns[out_field.name] = DeviceColumn(data, "int64")
            continue
        values = np.asarray(src.data)
        validity_out = counts > 0
        if spec.func in ("sum", "avg"):
            acc = (np.float64 if out_field.dtype == "float64" else np.int64)
            total = np.add.reduceat(
                np.where(valid, values, 0).astype(acc), starts)
            data = (total if spec.func == "sum"
                    else total.astype(np.float64) / np.maximum(counts, 1))
        elif spec.func == "stddev":
            # Two-pass shifted variance; see the device lane for why the
            # one-pass identity is numerically unsafe.
            x = np.where(valid, values, 0).astype(np.float64)
            cnt = counts.astype(np.float64)
            mu = np.add.reduceat(x, starts) / np.maximum(cnt, 1)
            dev = np.where(valid, x - mu[segment_ids], 0.0)
            var = np.add.reduceat(dev * dev, starts) / np.maximum(
                cnt - 1, 1)
            data = np.sqrt(np.maximum(var, 0.0))
            validity_out = counts > 1
        elif spec.func == "min":
            big = (np.inf if np.issubdtype(values.dtype, np.floating)
                   else np.iinfo(values.dtype).max)
            data = np.minimum.reduceat(np.where(valid, values, big), starts)
        else:  # max
            small = (-np.inf if np.issubdtype(values.dtype, np.floating)
                     else np.iinfo(values.dtype).min)
            data = np.maximum.reduceat(np.where(valid, values, small), starts)
        columns[out_field.name] = DeviceColumn(
            data.astype(_HOST_NP[out_field.dtype]), out_field.dtype,
            validity=validity_out)
    return ColumnBatch(out_schema, columns)
