"""Broadcast-style dimension join: replicate a SMALL unique-keyed build
side and match probe rows by direct-address lookup — no Exchange, no
sort of the probe side.

The reference gets BroadcastHashJoin from Spark for free for dimension
joins: its E2E suite has to DISABLE broadcast to even exercise the
bucketed SMJ path (`E2EHyperspaceRulesTests.scala:42`), and production
Spark routes every small-side join here via
`spark.sql.autoBroadcastJoinThreshold`. This engine's general join is
the counting join (`ops/join.py`) whose cost is a joint sort of
probe+build rows — for a fact x dimension join that sort of tens of
millions of fact rows is pure overhead.

The TPU-friendly equivalent of a hash table is a dense lookup TABLE
over the build-side key range: dimension surrogate keys (TPC-DS
`d_date_sk`, `i_item_sk`, `s_store_sk`, ...) are dense integers, so
table size ~ build rows. Build: pack each build key tuple into one
int64 digit space and scatter build row ids into the table (m rows,
computed in numpy — the build side is small and usually host-resident).
Probe: ONE vectorized gather per probe row + range/validity masks —
O(n + m + range) with no sort anywhere. The table transfers to the
device once (int32, ~4B x range).

Eligibility is decided at RUN time from the build side (the planner
only sizes it): integer-family keys on both sides, key-tuple digit
space <= `_MAX_TABLE` slots, and unique non-null build key tuples.
Anything else returns None and the caller falls back to the counting
join — same results, just without the shortcut. Duplicate build keys
would need the ragged expansion machinery; real dimension keys are
unique, so the fallback (not extra complexity here) covers that case.

SQL join-null semantics match `encode_join_keys`: a NULL in any key
column on either side matches nothing.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import ColumnBatch

# Integer-family dtypes whose values join by exact integer identity
# (date32/timestamp are day/us counts; bool is 0/1). Floats are excluded:
# the engine's float key identity normalizes -0.0/NaN through order lanes
# (`ops/keys.py`), which a raw int cast would diverge from.
_INT_DTYPES = ("int8", "int16", "int32", "int64", "date32", "timestamp",
               "bool")

# Table slot cap: 16M int32 slots = 64 MB — far above any dimension key
# range worth broadcasting, far below working-set sizes that matter.
_MAX_TABLE = 1 << 24


def _int_key_arrays(batch: ColumnBatch, keys: Sequence[str], to_numpy: bool):
    """Per-key int64 arrays + combined validity, or None when any key is
    outside the integer family. `to_numpy` pulls device columns to host
    (build side only — small)."""
    arrays = []
    valid = None
    for k in keys:
        col = batch.column(k)
        if col.is_string or col.dtype not in _INT_DTYPES:
            return None
        data = np.asarray(col.data) if to_numpy else col.data
        arrays.append(data)
        if col.validity is not None:
            v = np.asarray(col.validity) if to_numpy else col.validity
            valid = v if valid is None else (valid & v)
    return arrays, valid


def build_broadcast_table(build: ColumnBatch, build_keys: Sequence[str]):
    """(table, mins, ranges) for the build side, or None when ineligible.
    `table[packed_key] = build row id`, -1 elsewhere; `mins`/`ranges`
    define the per-column digit packing probe rows must mirror."""
    m = build.num_rows
    if m == 0:
        return None
    prep = _int_key_arrays(build, build_keys, to_numpy=True)
    if prep is None:
        return None
    arrays, valid = prep
    arrays = [np.asarray(a, dtype=np.int64) for a in arrays]
    if valid is not None:
        if not valid.any():
            # All build keys NULL: nothing can match — a 1-slot empty
            # table keeps the probe path uniform.
            return (np.full(1, -1, dtype=np.int32), [0] * len(arrays),
                    [1] * len(arrays))
        arrays_v = [a[valid] for a in arrays]
    else:
        arrays_v = arrays
    mins = [int(a.min()) for a in arrays_v]
    ranges = []
    capacity = 1
    for a, mn in zip(arrays_v, mins):
        r = int(a.max()) - mn + 1
        ranges.append(r)
        capacity *= r
        if capacity > _MAX_TABLE:
            return None
    packed = np.zeros(len(arrays_v[0]), dtype=np.int64)
    for a, mn, r in zip(arrays_v, mins, ranges):
        packed = packed * r + (a - mn)
    table = np.full(capacity, -1, dtype=np.int32)
    rows = (np.nonzero(valid)[0] if valid is not None
            else np.arange(m)).astype(np.int32)
    table[packed] = rows
    # Uniqueness: every valid build row must own its slot (duplicates
    # overwrote each other above — detect by occupancy count).
    if int((table >= 0).sum()) != len(rows):
        return None
    return table, mins, ranges


def _probe_lookup(probe: ColumnBatch, probe_keys: Sequence[str], table,
                  mins, ranges):
    """(build_row_or_minus1, matched) per probe row, on the probe's lane.
    None when a probe key is outside the integer family."""
    prep = _int_key_arrays(probe, probe_keys, to_numpy=probe.is_host)
    if prep is None:
        return None
    arrays, valid = prep
    if probe.is_host:
        xp = np
        table_x = table
    else:
        import jax.numpy as jnp
        xp = jnp
        table_x = jnp.asarray(table)
    n = probe.num_rows
    ok = xp.ones(n, dtype=bool) if valid is None else xp.asarray(valid)
    idx = xp.zeros(n, dtype=np.int64)
    for a, mn, r in zip(arrays, mins, ranges):
        av = xp.asarray(a).astype(np.int64)
        # Range-check on the ORIGINAL values (comparisons cannot wrap);
        # `av - mn` can wrap in int64 for adversarial probe keys near
        # INT64_MIN against builds near INT64_MAX, and a wrapped digit
        # must never slip into [0, r) as a false match. mn + (r - 1) is
        # the build max, exact in Python ints.
        ok = ok & (av >= mn) & (av <= mn + (r - 1))
        d = av - mn
        idx = idx * r + xp.clip(d, 0, r - 1)
    hit = xp.where(ok, xp.take(table_x, xp.where(ok, idx, 0)),
                   np.int32(-1)).astype(np.int32)
    return hit, hit >= 0


def broadcast_join_indices(probe: ColumnBatch, build: ColumnBatch,
                           probe_keys: Sequence[str],
                           build_keys: Sequence[str],
                           how: str) -> Optional[Tuple]:
    """(probe_idx, build_idx) row-index pairs in original row space for
    `how` in inner/left_outer (probe plays left), or None when the
    direct-address path is ineligible. With unique build keys every probe
    row matches at most once, so no ragged expansion exists: left_outer
    is the identity on probe rows and inner one mask-compress."""
    prep = build_broadcast_table(build, build_keys)
    if prep is None:
        return None
    looked = _probe_lookup(probe, probe_keys, *prep)
    if looked is None:
        return None
    hit, matched = looked
    n = probe.num_rows
    if probe.is_host:
        if how == "left_outer":
            return np.arange(n, dtype=np.int32), hit
        li = np.nonzero(matched)[0].astype(np.int32)
        return li, hit[li]
    import jax.numpy as jnp
    if how == "left_outer":
        return jnp.arange(n, dtype=jnp.int32), hit
    count = int(jnp.sum(matched))  # host sync — sizes the result
    if count == 0:
        empty = jnp.zeros(0, dtype=jnp.int32)
        return empty, empty
    (li,) = jnp.nonzero(matched, size=count, fill_value=0)
    li = li.astype(jnp.int32)
    return li, jnp.take(hit, li)


def build_membership_table(build: ColumnBatch, build_keys: Sequence[str]):
    """(table, mins, ranges) occupancy table over the build side's valid
    key tuples (duplicates allowed — existence is all membership needs),
    or None when ineligible. All-NULL build keys yield a 1-slot empty
    table so the probe path stays uniform. Shared by the eager membership
    probe below and the fused masked lane (`engine/fusion.py`)."""
    prep = _int_key_arrays(build, build_keys, to_numpy=True)
    if prep is None:
        return None
    arrays, valid = prep
    arrays = [np.asarray(a, dtype=np.int64) for a in arrays]
    if valid is not None:
        arrays = [a[valid] for a in arrays]
        if len(arrays[0]) == 0:
            table = np.full(1, -1, dtype=np.int32)
            return table, [0] * len(build_keys), [1] * len(build_keys)
    return _membership_table(arrays)


def broadcast_membership(probe: ColumnBatch, build: ColumnBatch,
                         probe_keys: Sequence[str],
                         build_keys: Sequence[str], anti: bool):
    """Probe-row indices for LEFT SEMI (matched) / LEFT ANTI (unmatched —
    NULL-key probe rows are emitted, NOT EXISTS semantics), or None when
    ineligible. Membership tolerates DUPLICATE build keys (the table
    keeps some row per key; existence is all that matters), so only the
    table build itself can decline."""
    m = build.num_rows
    if m == 0:
        return None  # callers' empty-side fast paths are already exact
    prep2 = build_membership_table(build, build_keys)
    if prep2 is None:
        return None
    looked = _probe_lookup(probe, probe_keys, *prep2)
    if looked is None:
        return None
    _hit, matched = looked
    want = ~matched if anti else matched
    if probe.is_host:
        return np.nonzero(want)[0].astype(np.int32)
    import jax.numpy as jnp
    count = int(jnp.sum(want))  # host sync
    if count == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    (idx,) = jnp.nonzero(want, size=count, fill_value=0)
    return idx.astype(jnp.int32)


def _membership_table(arrays):
    """Occupancy table over valid build keys (duplicates allowed)."""
    mins = [int(a.min()) for a in arrays]
    ranges = []
    capacity = 1
    for a, mn in zip(arrays, mins):
        r = int(a.max()) - mn + 1
        ranges.append(r)
        capacity *= r
        if capacity > _MAX_TABLE:
            return None
    packed = np.zeros(len(arrays[0]), dtype=np.int64)
    for a, mn, r in zip(arrays, mins, ranges):
        packed = packed * r + (a - mn)
    table = np.full(capacity, -1, dtype=np.int32)
    table[packed] = 1
    return table, mins, ranges
