"""Device-side hash partitioning: column values -> bucket ids.

This is the TPU-native replacement for the reference's build-time shuffle
`df.repartition(numBuckets, indexedCols)` (reference
`actions/CreateActionBase.scala:110-111`): instead of a JVM hash exchange,
bucket ids are computed on device with 32-bit murmur-style mixing (uint32
arithmetic — native on the TPU VPU; no 64-bit emulation on the hot path) and
rows are then grouped by one stable device sort (`ops/sort.py`).

Hash identity rules:
- Numeric columns hash their *bit pattern* (int64 is mixed as two 32-bit
  halves; floats are bitcast) — stable across batches and files.
- String columns hash their *value* via the per-dictionary-entry hashes
  computed at encode time (`io/columnar.py`), gathered by code — stable
  across batches with different dictionaries.
- Nulls hash to 0.
"""

from __future__ import annotations

from typing import List, Sequence

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn


def _fmix32(h):
    """murmur3 finalizer on uint32 (wrapping arithmetic)."""
    import jax.numpy as jnp
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _combine(h1, h2):
    """boost-style hash_combine on uint32."""
    import jax.numpy as jnp
    return h1 ^ (h2 + jnp.uint32(0x9E3779B9) + (h1 << 6) + (h1 >> 2))


def dual_hash64(lanes):
    """u64 hash per row from two independent 32-bit mixes over the
    BITCAST (order-preserving-uint32) forms of the given sort lanes —
    THE hash identity of the hashed group/match fast paths
    (`ops/aggregate._group_phase_a_hashed`,
    `ops/join._counting_match_lanes_hashed`). Distinct from the bucket
    identity `flat_hash32`: this one may change freely (no on-disk
    layout depends on it), but both fast paths MUST share it."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.sort import _as_u32

    u0 = _as_u32(lanes[0], jnp)
    h1 = _fmix32(u0)
    h2 = _fmix32(u0 ^ jnp.uint32(0x6A09E667))
    for lane in lanes[1:]:
        u = _as_u32(lane, jnp)
        h1 = _combine(h1, _fmix32(u))
        h2 = _combine(h2, _fmix32(u ^ jnp.uint32(0x6A09E667)))
    return (h1.astype(jnp.uint64) << jnp.uint64(32)) | h2.astype(jnp.uint64)


def column_hash_lanes(col: DeviceColumn) -> List:
    """The column's hash-input lanes: uint32 arrays, one value hash input
    per lane. Strings contribute their gathered per-dictionary-entry value
    hashes (hi, lo); numerics their order-preserving 32-bit key lanes.
    Null rows contribute all-zero lanes."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import key_lanes

    if col.is_string:
        hi, lo = col.dict_hashes
        lanes = [jnp.take(hi, col.data), jnp.take(lo, col.data)]
    else:
        lanes = [lane.astype(jnp.uint32) for lane in key_lanes(col.data)]
    if col.validity is not None:
        lanes = [jnp.where(col.validity, lane, jnp.uint32(0))
                 for lane in lanes]
    return lanes


def flat_hash32(lanes: Sequence):
    """THE hash identity: fmix32 of the first lane, then hash-combine of
    each further lane's fmix32, over the FLAT concatenation of all key
    columns' lanes in key order. Every path that assigns buckets (this
    eager kernel, the jitted build core `ops/build.py`, the Pallas kernel
    `ops/pallas/hash_kernel.py`, the mesh build `parallel/build.py`) MUST
    share it — on-disk bucket layout depends on it."""
    import jax.numpy as jnp

    h = _fmix32(lanes[0].astype(jnp.uint32))
    for lane in lanes[1:]:
        h = _combine(h, _fmix32(lane.astype(jnp.uint32)))
    return h


def column_hash32(col: DeviceColumn):
    """Per-row uint32 value hash of one column (flat identity)."""
    return flat_hash32(column_hash_lanes(col))


def batch_hash32(batch: ColumnBatch, key_columns: Sequence[str]):
    """Combined per-row uint32 hash over the key columns, in order."""
    if not key_columns:
        raise HyperspaceException("Hash partitioning requires key columns.")
    lanes: List = []
    for name in key_columns:
        lanes.extend(column_hash_lanes(batch.column(name)))
    return flat_hash32(lanes)


def bucket_ids(batch: ColumnBatch, key_columns: Sequence[str],
               num_buckets: int):
    """Per-row bucket assignment in [0, num_buckets) as int32."""
    import jax.numpy as jnp
    h = batch_hash32(batch, key_columns)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)
