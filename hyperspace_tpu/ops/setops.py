"""Set-operation kernels: INTERSECT / EXCEPT with SQL DISTINCT semantics.

Output = DISTINCT rows of the left side present in (intersect) / absent
from (except) the right side. Row identity treats NULL as equal to NULL
(SQL set-op semantics — joins do the opposite), so validity participates
as a leading key lane and null slots' payloads are zeroed to one
canonical value before lane decomposition.

Device path: ONE fused executable — joint staged sort of both sides'
lanes -> dense group ids -> right-presence scatter + first-left-occurrence
scatter -> selection mask — plus the single host sync that sizes the
output. Host path is the numpy mirror over `host_dense_group_ids`.

The reference serializes Catalyst Intersect/Except for exactly these
queries (`index/serde/package.scala:64-167`); execution there is Spark's.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, unify_string_columns


def _zeroed(xp, data, valid):
    """Null slots -> one canonical payload so all NULLs compare equal."""
    if valid is None:
        return data
    return xp.where(valid, data, xp.zeros((), data.dtype))


def _device_lanes(left: ColumnBatch, right: ColumnBatch,
                  names: Sequence[str]) -> List:
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import key_lanes

    lanes: List = []
    for name in names:
        lcol, rcol = left.column(name), right.column(name)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(
                f"Set-op column type mismatch: {name}")
        if lcol.is_string:
            lcol, rcol = unify_string_columns(lcol, rcol)
        lv = (jnp.ones(left.num_rows, bool) if lcol.validity is None
              else jnp.asarray(lcol.validity))
        rv = (jnp.ones(right.num_rows, bool) if rcol.validity is None
              else jnp.asarray(rcol.validity))
        lanes.append(jnp.concatenate([lv, rv]).astype(jnp.int32))
        ldata, rdata = jnp.asarray(lcol.data), jnp.asarray(rcol.data)
        if ldata.dtype != rdata.dtype:
            common = jnp.promote_types(ldata.dtype, rdata.dtype)
            ldata, rdata = ldata.astype(common), rdata.astype(common)
        ldata = _zeroed(jnp, ldata, None if lcol.validity is None
                        else jnp.asarray(lcol.validity))
        rdata = _zeroed(jnp, rdata, None if rcol.validity is None
                        else jnp.asarray(rcol.validity))
        for ll, rl in zip(key_lanes(ldata), key_lanes(rdata)):
            lanes.append(jnp.concatenate([ll, rl]))
    return lanes


@partial(__import__("jax").jit, static_argnames=("n", "anti"))
def _setop_core(lanes, n: int, anti: bool):
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import _staged_sort

    total = lanes[0].shape[0]
    perm, sorted_ops = _staged_sort(list(lanes))
    differs = jnp.zeros(total, dtype=jnp.int32)
    for k in sorted_ops:
        differs = differs | jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             (k[1:] != k[:-1]).astype(jnp.int32)])
    gid_sorted = jnp.cumsum(differs, dtype=jnp.int32)
    groups = jnp.zeros(total, dtype=jnp.int32).at[perm].set(gid_sorted)
    l_ids, r_ids = groups[:n], groups[n:]
    present_r = jnp.zeros(total, dtype=bool).at[r_ids].set(True)
    member = jnp.take(present_r, l_ids)
    first = jnp.full(total, n, dtype=jnp.int32).at[l_ids].min(
        jnp.arange(n, dtype=jnp.int32))
    keep = jnp.arange(n, dtype=jnp.int32) == jnp.take(first, l_ids)
    mask = keep & (~member if anti else member)
    return mask, jnp.sum(mask.astype(jnp.int64))


def _host_indices(left: ColumnBatch, right: ColumnBatch,
                  names: Sequence[str], anti: bool) -> np.ndarray:
    from hyperspace_tpu.io.columnar import _merged_dictionary
    from hyperspace_tpu.ops.keys import host_dense_group_ids, host_key_lanes

    n, m = left.num_rows, right.num_rows
    lanes: List = []
    for name in names:
        lcol, rcol = left.column(name), right.column(name)
        if lcol.is_string != rcol.is_string:
            raise HyperspaceException(
                f"Set-op column type mismatch: {name}")
        if lcol.is_string:
            _, (rl, rr), _ = _merged_dictionary(
                [lcol.dictionary, rcol.dictionary], device=False)
            ldata = rl[np.asarray(lcol.data)]
            rdata = rr[np.asarray(rcol.data)]
        else:
            ldata, rdata = np.asarray(lcol.data), np.asarray(rcol.data)
            if ldata.dtype != rdata.dtype:
                common = np.promote_types(ldata.dtype, rdata.dtype)
                ldata, rdata = ldata.astype(common), rdata.astype(common)
        lv = (np.ones(n, bool) if lcol.validity is None
              else np.asarray(lcol.validity))
        rv = (np.ones(m, bool) if rcol.validity is None
              else np.asarray(rcol.validity))
        lanes.append(np.concatenate([lv, rv]).astype(np.int32))
        ldata = _zeroed(np, ldata, lv if lcol.validity is not None else None)
        rdata = _zeroed(np, rdata, rv if rcol.validity is not None else None)
        for ll, rl_ in zip(host_key_lanes(ldata), host_key_lanes(rdata)):
            lanes.append(np.concatenate([ll, rl_]))
    perm, gid_sorted = host_dense_group_ids(lanes)
    groups = np.empty(n + m, dtype=np.int32)
    groups[perm] = gid_sorted
    l_ids, r_ids = groups[:n], groups[n:]
    present_r = np.zeros(n + m, dtype=bool)
    present_r[r_ids] = True
    member = present_r[l_ids]
    first = np.full(n + m, n, dtype=np.int64)
    np.minimum.at(first, l_ids, np.arange(n))
    keep = np.arange(n) == first[l_ids]
    mask = keep & (~member if anti else member)
    return np.nonzero(mask)[0].astype(np.int32)


def set_op_indices(left: ColumnBatch, right: ColumnBatch,
                   names: Sequence[str], anti: bool):
    """Left-row indices of the set-op result, in first-occurrence order.
    `anti=False` -> INTERSECT, `anti=True` -> EXCEPT."""
    import jax.numpy as jnp

    if left.num_rows == 0:
        return np.zeros(0, dtype=np.int32)
    if right.num_rows == 0 and not anti:
        return np.zeros(0, dtype=np.int32)
    if left.is_host and right.is_host:
        return _host_indices(left, right, names, anti)
    lanes = _device_lanes(left, right, names)
    mask, cnt = _setop_core(tuple(lanes), left.num_rows, anti)
    count = int(cnt)  # the one host sync
    if count == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    (idx,) = jnp.nonzero(mask, size=count, fill_value=0)
    return idx.astype(jnp.int32)
