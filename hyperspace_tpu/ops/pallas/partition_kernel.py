"""Pallas TPU kernel: fused hash -> bucket id -> per-tile histogram.

The repartition primitive (`ExchangeExec.partition`, the mesh build's
capacity sizing) needs BOTH the per-row bucket id and the per-bucket
lengths. The jnp path makes two HBM passes (hash+modulo, then
segment_sum); this kernel produces both in ONE pass: each [256, 128] VMEM
tile mixes its key lanes (the same fmix32/hash-combine chain as
`ops/hash_partition.py` — bit-for-bit, asserted in interpret mode by
`tests/test_pallas.py`), writes the bucket ids, and accumulates a one-hot
histogram entirely in registers/VMEM before a single [B] store.

Like `hash_kernel.py`, chunking uses `lax.map` over fixed tiles rather
than a Pallas grid (grids fail to legalize on the remote-compile
toolchain targeted here); the kernel compiles once and loops.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.ops.pallas.hash_kernel import pallas_available  # noqa: F401

_BLOCK_ROWS = 256
_LANES = 128
# Rows per histogram accumulation sub-block: bounds the one-hot
# intermediate at _HIST_SUB * _LANES * hist_cols int32s (1 MB at 256
# bucket columns).
_HIST_SUB = 8
# Above this bucket count even the sub-blocked accumulator churns VMEM;
# callers should take the two-pass jnp path instead (`kernel_supported`).
MAX_KERNEL_BUCKETS = 1024


def kernel_supported(num_buckets: int) -> bool:
    """True when the fused kernel path is appropriate for this bucket
    count (and Pallas is available on the backend)."""
    return pallas_available() and num_buckets <= MAX_KERNEL_BUCKETS


def _kernel(num_buckets: int, n_lanes: int, *refs):
    import jax.numpy as jnp

    in_refs = refs[:n_lanes]
    valid_ref = refs[n_lanes]
    ids_ref = refs[n_lanes + 1]
    hist_ref = refs[n_lanes + 2]

    def fmix32(h):
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    h = fmix32(in_refs[0][:])
    for ref in in_refs[1:]:
        h2 = fmix32(ref[:])
        h = h ^ (h2 + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    bucket = (h % jnp.uint32(num_buckets)).astype(jnp.int32)
    ids_ref[:] = bucket
    valid = valid_ref[:] != 0
    # One-hot histogram accumulated over row sub-blocks: a full-tile
    # one-hot would materialize [256, 128, hist_cols] (32 MB of int32 at
    # 200+ buckets if the reduction is not fused — over a core's ~16 MB
    # VMEM); per-sub-block the intermediate is bounded at
    # _HIST_SUB*128*hist_cols. Padding rows count toward no bucket.
    masked = jnp.where(valid, bucket, jnp.int32(num_buckets))
    b_range = jnp.arange(hist_ref.shape[1], dtype=jnp.int32)

    # STATIC slices in an unrolled loop: `lax.dynamic_slice` on a value
    # has no Mosaic TC lowering (found the hard way on real hardware —
    # interpret-mode tests pass either way), and the trip count is a
    # compile-time constant anyway.
    hist = jnp.zeros(hist_ref.shape[1], dtype=jnp.int32)
    for i in range(_BLOCK_ROWS // _HIST_SUB):
        rows = masked[i * _HIST_SUB:(i + 1) * _HIST_SUB]
        onehot = (rows[:, :, None] == b_range[None, None, :])
        hist = hist + jnp.sum(onehot, axis=(0, 1), dtype=jnp.int32)
    hist_ref[:] = hist[None, :]


def partition_ids_and_histogram(lanes: Sequence, num_buckets: int,
                                interpret: bool = False) -> Tuple:
    """(bucket ids int32 [n], lengths int64 [num_buckets]) in one fused
    pass over uint32 key lanes (first lane seeds, further lanes combine —
    THE hash identity)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = lanes[0].shape[0]
    per_block = _BLOCK_ROWS * _LANES
    padded = -(-n // per_block) * per_block
    n_chunks = padded // per_block
    hist_cols = -(-num_buckets // _LANES) * _LANES

    def prep(x, fill=0):
        x = x.astype(jnp.uint32)
        x = jnp.pad(x, (0, padded - n), constant_values=fill)
        return x.reshape(n_chunks, _BLOCK_ROWS, _LANES)

    tiles = [prep(x) for x in lanes]
    valid = prep(jnp.ones(n, dtype=jnp.uint32))
    kernel = functools.partial(_kernel, num_buckets, len(tiles))
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((_BLOCK_ROWS, _LANES), jnp.int32),
                   jax.ShapeDtypeStruct((1, hist_cols), jnp.int32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * (len(tiles) + 1),
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=interpret,
    )

    if n_chunks == 1:
        ids, hist = call(*(t[0] for t in tiles), valid[0])
        return (ids.reshape(-1)[:n],
                hist.reshape(-1)[:num_buckets].astype(jnp.int64))
    ids, hists = jax.lax.map(lambda chunk: call(*chunk),
                             (*tiles, valid))
    lengths = jnp.sum(hists.reshape(n_chunks, -1), axis=0)
    return (ids.reshape(-1)[:n],
            lengths[:num_buckets].astype(jnp.int64))


def batch_partition(batch, key_columns: List[str], num_buckets: int,
                    interpret: bool = False) -> Tuple:
    """ColumnBatch -> (bucket ids, lengths) via the fused kernel, using
    the shared hash-lane decomposition (`column_hash_lanes`)."""
    from hyperspace_tpu.ops.hash_partition import column_hash_lanes

    lanes: List = []
    for name in key_columns:
        lanes.extend(column_hash_lanes(batch.column(name)))
    return partition_ids_and_histogram(lanes, num_buckets,
                                       interpret=interpret)
