from hyperspace_tpu.ops.pallas.hash_kernel import (hash_lanes_to_buckets,
                                                   pallas_available)

__all__ = ["hash_lanes_to_buckets", "pallas_available"]
