"""Pallas TPU kernel: fused multi-lane murmur mix -> bucket id.

One VMEM pass computes, for every row, the fmix32/hash-combine chain over
all key lanes and the bucket modulo — the device half of the build
pipeline's hash partitioning (`ops/hash_partition.py` documents the hash
identity; this kernel MUST match it bit-for-bit, asserted by
`tests/test_pallas.py` in interpret mode).

Layout: uint32 lanes are padded to a multiple of (8, 128) and viewed as
[rows, 128] tiles (the VPU's native 8x128 lanes); the grid walks row
blocks. The same mixing is what XLA emits for the jnp path, so the win is
not arithmetic but fusion control: one HBM read per lane, one write, no
intermediate materialization — and a scaffold for the heavier Pallas
kernels (merge-path joins, radix histograms) to come.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import hyperspace_tpu._jax_config  # noqa: F401

_BLOCK_ROWS = 256
_LANES = 128


def pallas_available() -> bool:
    import jax
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _kernel(num_buckets: int, n_lanes: int, *refs):
    import jax.numpy as jnp

    in_refs = refs[:n_lanes]
    out_ref = refs[n_lanes]

    def fmix32(h):
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    h = fmix32(in_refs[0][:])
    for ref in in_refs[1:]:
        h2 = fmix32(ref[:])
        h = h ^ (h2 + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    out_ref[:] = (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def hash_lanes_to_buckets(lanes: Sequence, num_buckets: int,
                          interpret: bool = False):
    """lanes: uint32 [n] arrays (first lane's fmix is the seed, further
    lanes hash-combine, matching `hash_partition.batch_hash32` for
    single-lane-per-column keys). Returns int32 [n] bucket ids.

    Chunking is done with `lax.map` over fixed [BLOCK_ROWS, 128] tiles
    rather than a Pallas grid (grids fail to legalize on the remote-compile
    toolchain targeted here); the kernel compiles once and loops.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = lanes[0].shape[0]
    per_block = _BLOCK_ROWS * _LANES
    padded = -(-n // per_block) * per_block
    n_chunks = padded // per_block

    def prep(x):
        x = x.astype(jnp.uint32)
        return jnp.pad(x, (0, padded - n)).reshape(n_chunks, _BLOCK_ROWS,
                                                   _LANES)

    tiles = [prep(x) for x in lanes]
    kernel = functools.partial(_kernel, num_buckets, len(tiles))
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((_BLOCK_ROWS, _LANES), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(tiles),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )

    if n_chunks == 1:
        out = call(*(t[0] for t in tiles))
        return out.reshape(-1)[:n]
    out = jax.lax.map(lambda chunk: call(*chunk), tuple(tiles))
    return out.reshape(-1)[:n]
