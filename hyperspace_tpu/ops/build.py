"""Jitted index-build core: hash + bucket + sort + gather in ONE XLA program.

The eager pipeline dispatches ~a dozen separately-compiled ops; on a TPU
with remote compilation each unique (op, shape) costs a compile round-trip.
Fusing the whole build into one `jax.jit` program makes the build one
compile per (schema structure, row count) — and lets XLA fuse the hash mix,
key-lane decomposition, sort, and payload gathers.

Sort keys ride 32-bit lanes (`ops/keys.py`): int64/float64 keys become two
native 32-bit operands instead of emulated 64-bit compares on the VPU.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.io.columnar import (ColumnBatch, batch_to_tree,
                                        tree_to_batch)
from hyperspace_tpu.ops import keys as keymod


def _tree_hash32(entry):
    """uint32 value hash of one column tree entry (mirrors
    `ops/hash_partition.column_hash32` on raw arrays)."""
    import jax.numpy as jnp
    from hyperspace_tpu.ops.hash_partition import _combine, _fmix32

    data = entry["data"]
    if "hash_hi" in entry:  # string: gather per-dictionary-entry hashes
        h = _combine(_fmix32(jnp.take(entry["hash_hi"], data)),
                     _fmix32(jnp.take(entry["hash_lo"], data)))
    else:
        lanes = keymod.key_lanes(data)
        h = _fmix32(lanes[0].astype(jnp.uint32))
        for lane in lanes[1:]:
            h = _combine(h, _fmix32(lane.astype(jnp.uint32)))
    if "validity" in entry:
        h = jnp.where(entry["validity"], h, jnp.uint32(0))
    return h


def _entry_sort_lanes(entry):
    lanes = []
    if "validity" in entry:
        lanes.append(entry["validity"])
    lanes.extend(keymod.key_lanes(entry["data"]))
    return lanes


@partial(__import__("jax").jit,
         static_argnames=("key_names", "num_buckets"))
def _build_core(tree, key_names: Tuple[str, ...], num_buckets: int):
    import jax
    import jax.numpy as jnp

    h = _tree_hash32(tree[key_names[0]])
    for name in key_names[1:]:
        from hyperspace_tpu.ops.hash_partition import _combine
        h = _combine(h, _tree_hash32(tree[name]))
    bucket = (h % jnp.uint32(num_buckets)).astype(jnp.int32)

    n = bucket.shape[0]
    operands = [bucket]
    for name in key_names:
        operands.extend(_entry_sort_lanes(tree[name]))
    iota = jnp.arange(n, dtype=jnp.int32)
    results = jax.lax.sort([*operands, iota], num_keys=len(operands),
                           is_stable=True)
    perm = results[-1]
    sorted_bucket = results[0]

    sorted_tree = {}
    for name, entry in tree.items():
        out = dict(entry)  # hash tables are dictionary-indexed: pass through
        out["data"] = jnp.take(entry["data"], perm, axis=0)
        if "validity" in entry:
            out["validity"] = jnp.take(entry["validity"], perm, axis=0)
        sorted_tree[name] = out

    buckets = jnp.arange(num_buckets, dtype=jnp.int32)
    starts = jnp.searchsorted(sorted_bucket, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket, buckets, side="right")
    return sorted_tree, sorted_bucket, starts, ends


def build_sorted(batch: ColumnBatch, key_columns: Sequence[str],
                 num_buckets: int):
    """Bucket + lexicographically sort a batch by (bucket, *keys) in one
    compiled program. Returns (sorted batch, starts, ends) with starts/ends
    the per-bucket row ranges."""
    key_names = tuple(batch.schema.field(c).name for c in key_columns)
    tree, aux = batch_to_tree(batch)
    sorted_tree, _sorted_bucket, starts, ends = _build_core(
        tree, key_names, num_buckets)
    return tree_to_batch(sorted_tree, batch.schema, aux), starts, ends
