"""Jitted index-build core: hash + bucket + sort + gather in ONE XLA program.

The eager pipeline dispatches ~a dozen separately-compiled ops; on a TPU
with remote compilation each unique (op, shape) costs a compile round-trip.
Fusing the whole build into one `jax.jit` program makes the build one
compile per (schema structure, row count) — and lets XLA fuse the hash mix,
key-lane decomposition, sort, and payload gathers.

Sort keys ride 32-bit lanes (`ops/keys.py`): int64/float64 keys become two
native 32-bit operands instead of emulated 64-bit compares on the VPU.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.io.columnar import (ColumnBatch, batch_to_tree,
                                        tree_to_batch)
from hyperspace_tpu.ops import keys as keymod


def _tree_hash_lanes(entry):
    """Hash-input lanes of one column tree entry (mirrors
    `ops/hash_partition.column_hash_lanes` on raw arrays): strings gather
    their dictionary value hashes; numerics decompose into 32-bit key
    lanes; null rows contribute all-zero lanes. A `lo32` entry is the
    narrow transport of an int64 column whose hi lane is provably zero
    (host-checked range): the hash still mixes the canonical [hi, lo]
    lane chain — hi synthesized as zeros — so bucket ids are bit-identical
    to the wide path."""
    import jax.numpy as jnp

    entry = _entry_assemble(entry)
    if "lo32" in entry:
        lo = entry["lo32"]
        return [jnp.zeros_like(lo), lo]
    data = entry["data"]
    if "hash_hi" in entry:
        lanes = [jnp.take(entry["hash_hi"], data),
                 jnp.take(entry["hash_lo"], data)]
    else:
        lanes = [lane.astype(jnp.uint32)
                 for lane in keymod.key_lanes(data)]
    if "validity" in entry:
        lanes = [jnp.where(entry["validity"], lane, jnp.uint32(0))
                 for lane in lanes]
    return lanes


def _entry_sort_lanes(entry):
    entry = _entry_assemble(entry)
    if "lo32" in entry:
        # hi lane is constant zero -> order is fully determined by lo.
        return [entry["lo32"]]
    lanes = []
    if "validity" in entry:
        lanes.append(entry["validity"])
    lanes.extend(keymod.key_lanes(entry["data"]))
    return lanes


def _tree_bucket_ids(tree, key_names: Tuple[str, ...], num_buckets: int,
                     use_pallas: bool):
    """Per-row bucket ids over the FLAT lane chain (the one shared hash
    identity, `ops/hash_partition.flat_hash32`) — the Pallas kernel and the
    jnp fold are bit-identical by construction."""
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hash_partition import flat_hash32
    from hyperspace_tpu.ops.pallas.hash_kernel import hash_lanes_to_buckets

    lanes = []
    for name in key_names:
        lanes.extend(_tree_hash_lanes(tree[name]))
    if use_pallas:
        return hash_lanes_to_buckets(lanes, num_buckets)
    h = flat_hash32(lanes)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def _pallas_enabled() -> bool:
    import os

    from hyperspace_tpu.ops.pallas.hash_kernel import pallas_available
    return (os.environ.get("HYPERSPACE_PALLAS", "1") == "1"
            and pallas_available())


@partial(__import__("jax").jit,
         static_argnames=("key_names", "num_buckets", "use_pallas"))
def _build_core(tree, key_names: Tuple[str, ...], num_buckets: int,
                use_pallas: bool = False):
    import jax
    import jax.numpy as jnp

    bucket = _tree_bucket_ids(tree, key_names, num_buckets, use_pallas)

    n = bucket.shape[0]
    operands = [bucket]
    for name in key_names:
        operands.extend(_entry_sort_lanes(tree[name]))
    iota = jnp.arange(n, dtype=jnp.int32)
    results = jax.lax.sort([*operands, iota], num_keys=len(operands),
                           is_stable=True)
    perm = results[-1]
    sorted_bucket = results[0]

    sorted_tree = {}
    for name, entry in tree.items():
        out = dict(entry)  # hash tables are dictionary-indexed: pass through
        out["data"] = jnp.take(entry["data"], perm, axis=0)
        if "validity" in entry:
            out["validity"] = jnp.take(entry["validity"], perm, axis=0)
        sorted_tree[name] = out

    buckets = jnp.arange(num_buckets, dtype=jnp.int32)
    starts = jnp.searchsorted(sorted_bucket, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket, buckets, side="right")
    return sorted_tree, sorted_bucket, starts, ends


# Legacy transfer policy for the tunneled host<->device link: split
# transfers of >= LINK_CHUNK_ROWS rows into LINK_CHUNKS concurrent
# streams (measured ~1.7x faster than one stream; below the threshold
# the ~0.1s per-sync latency dominates). H2D staging and the build's
# D2H permutation fetch now size their chunks from the transfer
# engine's byte budget (`io/transfer.py`); these remain for the
# compaction merge path (`ops/merge.py`).
LINK_CHUNK_ROWS = 1 << 19
LINK_CHUNKS = 4


def _entry_assemble(entry):
    """Reassemble a chunk-staged entry (lo32 shipped as LINK_CHUNKS
    concurrent H2D streams) into its single-array form inside the compiled
    program. Called by every entry reader so ALL consumers of a staged
    tree handle the chunked form."""
    import jax.numpy as jnp

    if "lo32_chunks" in entry:
        return {"lo32": jnp.concatenate(entry["lo32_chunks"])}
    return entry


@partial(__import__("jax").jit,
         static_argnames=("key_names", "num_buckets", "n_chunks",
                          "use_pallas"))
def _perm_core(key_tree, key_names: Tuple[str, ...], num_buckets: int,
               n_chunks: int, use_pallas: bool = False):
    """Permutation-only build core: hash + ONE stable (bucket, *keys) sort
    over the KEY columns, returning the int32 row permutation (split into
    n_chunks contiguous slices for overlapped D2H) + per-bucket ranges.

    The payload never touches the device: profiling on the tunneled v5e
    showed the D2H of gathered payload columns dominating the whole build
    (~1.3s of a 2.2s/2M-row build), while the permutation is one int32
    lane. The host applies the permutation with Arrow `take` (C++) and
    streams bucket files while later chunks are still in flight.
    """
    import jax
    import jax.numpy as jnp

    bucket = _tree_bucket_ids(key_tree, key_names, num_buckets, use_pallas)
    n = bucket.shape[0]
    operands = [bucket]
    for name in key_names:
        operands.extend(_entry_sort_lanes(key_tree[name]))
    iota = jnp.arange(n, dtype=jnp.int32)
    results = jax.lax.sort([*operands, iota], num_keys=len(operands),
                           is_stable=True)
    perm = results[-1]
    sorted_bucket = results[0]
    buckets = jnp.arange(num_buckets, dtype=jnp.int32)
    starts = jnp.searchsorted(sorted_bucket, buckets, side="left")
    ends = jnp.searchsorted(sorted_bucket, buckets, side="right")
    base = n // n_chunks
    chunks = tuple(
        jax.lax.slice(perm, (i * base,),
                      ((i + 1) * base if i < n_chunks - 1 else n,))
        for i in range(n_chunks))
    return chunks, starts, ends


def permutation_from_tree(key_tree, key_names: Sequence[str], n: int,
                          num_buckets: int, n_chunks: int = 0):
    """As `build_permutation` over an already-staged device key tree."""
    if n_chunks <= 0:
        # Chunked D2H only pays off once the transfer dwarfs the ~0.1s
        # per-sync latency of the tunneled device link; the chunk count
        # follows the transfer engine's byte budget (int32 permutation),
        # so H2D and D2H pipeline at the same granularity.
        from hyperspace_tpu.io import transfer
        n_chunks = transfer.get_engine().d2h_chunk_count(n * 4)
    n_chunks = max(1, min(n_chunks, n))
    return _perm_core(key_tree, tuple(key_names), num_buckets, n_chunks,
                      use_pallas=_pallas_enabled())


def build_permutation(batch: ColumnBatch, key_columns: Sequence[str],
                      num_buckets: int, n_chunks: int = 0):
    """Device-computed sort permutation for a bucketed build. `batch` only
    needs the key columns resident. Returns (perm chunk arrays, starts,
    ends); concatenated chunks give the full row permutation in
    (bucket, *keys) order."""
    key_names = tuple(batch.schema.field(c).name for c in key_columns)
    tree, _aux = batch_to_tree(batch.select(key_names))
    return permutation_from_tree(tree, key_names, batch.num_rows,
                                 num_buckets, n_chunks)


def build_sorted(batch: ColumnBatch, key_columns: Sequence[str],
                 num_buckets: int):
    """Bucket + lexicographically sort a batch by (bucket, *keys) in one
    compiled program. Returns (sorted batch, starts, ends) with starts/ends
    the per-bucket row ranges."""
    key_names = tuple(batch.schema.field(c).name for c in key_columns)
    tree, aux = batch_to_tree(batch)
    # The flag is a STATIC jit arg: toggling HYPERSPACE_PALLAS between
    # calls selects a different cached executable instead of being baked in.
    sorted_tree, _sorted_bucket, starts, ends = _build_core(
        tree, key_names, num_buckets, use_pallas=_pallas_enabled())
    return tree_to_batch(sorted_tree, batch.schema, aux), starts, ends
