"""Inter-query batched execution: coalesce concurrent same-shape
point/filter queries into ONE jitted predicate invocation.

PR 8 dedupes the cache FILL (single-flight segment fills: K concurrent
queries over one cold bucket trigger one decode+H2D); this module
dedupes the EXECUTION. The `QueryScheduler` sees every in-flight plan,
so when K concurrent queries share an *execution signature* — same scan
identity (root paths + pinned index version + explicit-file restriction),
same scanned columns, same predicate SHAPE with only the literals free,
same projection — they collapse into one shared scan read plus one
`instrumented_jit("serve.batch")` program (`parallel/
spmd.batched_predicate_masks`, the lint-enforced batching seam) that
evaluates all K predicates as stacked constant lanes and returns a
[K, N] mask matrix. Each member's rows are then sliced out and settled
individually: per-query deadlines, per-query `QueryMetrics` (a
`serve: batched` event with the cohort size), and the degradation /
breaker path are all preserved — a batch-lane failure falls back to
per-query execution (`serve.batch.fallbacks`), never fails the cohort,
and a cancelled member drops only its own slice.

Mechanics:

- **gather window**: the first query of a signature becomes the
  cohort LEADER and waits `spark.hyperspace.serve.batch.window.ms` for
  joiners (up to `serve.batch.max`). The window is skipped entirely
  when nothing else is in flight — serial latency is untouched — and a
  leader that gathers nobody falls back to the normal path
  (`serve.batch.solo`), so the lane only ever runs with a real cohort.
- **compile-bucketed cohorts**: predicate constants ride [K_b, T]
  lanes with K_b the next power of two (padding replicates the first
  member's constants), so cohort size is a compile bucket, not a
  retrace per K. The shared scan deliberately skips per-member bucket
  pruning: a signature's read shape (full scan N) stays stable across
  cohorts, which is what makes the AOT warm-start (below) and the
  segment cache's version-keyed residency line up.
- **snapshot-pin safety**: the signature includes the scan's pinned
  index version and explicit file list, so two plans over different
  committed versions can NEVER share a cohort (a concurrent refresher
  splits the groups; each cohort reads exactly its pinned bytes).
- **warm-start AOT executables**: the first time a signature is seen
  (and via the explicit `warmup(df)` replica API), the canonical
  cohort-size buckets are primed through `telemetry.compilation.
  aot_warmup` — keyed like the segment cache by (index root, version,
  shape, rows, bucket) — riding the PR-11 persistent compile cache so
  a fresh replica's first batched query loads executables instead of
  tracing (`compile.traces == 0` on the warmed shapes, gated by
  `bench_regress.py --serve`).

Series: `serve.batch.{invocations,members,window_wait_s,fallbacks,
solo}`, plus `compile.aot.*` and the segment cache's
`cache.segments.shared.*` (one read serving K members).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu import telemetry
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import Filter, Project, Scan

__all__ = ["QueryBatcher", "BatchSignature", "plan_signature",
           "get_batcher", "set_batcher", "reset_batcher", "warmup"]

# Member wait quantum: short enough that a cancelled member notices its
# deadline promptly, long enough not to spin (the scheduler's queue-wait
# discipline).
_WAIT_QUANTUM_S = 0.02

# Adaptive gather backoff (see QueryBatcher._solo_streak): empty
# gathers before a signature's window is skipped, and how often a
# skipped signature re-probes.
_SOLO_STREAK = 2
_SOLO_PROBE = 8

_CMP_OPS = {E.EqualTo: "eq", E.NotEqualTo: "ne", E.LessThan: "lt",
            E.LessThanOrEqual: "le", E.GreaterThan: "gt",
            E.GreaterThanOrEqual: "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
         "eq": "eq", "ne": "ne"}
_INT_DTYPES = ("int8", "int16", "int32", "int64", "date32", "timestamp")
_FLOAT_DTYPES = ("float32", "float64")


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class BatchSignature:
    """One query's parsed batchable form. `key` is the grouping
    identity (queries batch iff their keys are equal); the constant
    vectors are this MEMBER's literals in shape order. String literals
    cannot ride the lanes directly — their code-space translation is
    per-dictionary state — so `strs` records (int-lane slot, column,
    op, value) resolutions the leader performs against the SHARED
    scan's dictionary at gather time; the resolved codes then ride the
    int lanes like any other constant."""

    __slots__ = ("key", "scan", "shape", "columns", "projection",
                 "needed", "ints", "floats", "strs")

    def __init__(self, key, scan, shape, columns, projection, needed,
                 ints, floats, strs=()):
        self.key = key
        self.scan = scan
        self.shape = shape            # static term tuple (spmd contract)
        self.columns = columns        # referenced column names, shape order
        self.projection = projection  # output column names, output order
        self.needed = needed          # columns the shared scan must read
        self.ints = ints              # this member's int-lane constants
        self.floats = floats          # this member's float-lane constants
        self.strs = strs              # deferred string resolutions


def _parse_terms(condition, schema):
    """Conjunction -> (shape, cols, ints, floats, strs) or None when any
    term falls outside the batched lane's exactly-mirrored subset (see
    `parallel/spmd.batched_predicate_masks`). String comparisons and
    string IN lists qualify: their constants ride the INT lanes as
    dictionary codes, resolved per member at gather time (`strs` —
    the dictionary is shared-scan state, so the translation mirrors the
    solo compiler's code-space tests exactly)."""
    cols: List[str] = []
    index: Dict[str, int] = {}

    def col_idx(name: str) -> int:
        f = schema.field(name)
        i = index.get(f.name)
        if i is None:
            i = index[f.name] = len(cols)
            cols.append(f.name)
        return i

    shape: List[tuple] = []
    ints: List[int] = []
    floats: List[float] = []
    strs: List[tuple] = []
    for term in E.split_conjunctive(condition):
        if type(term) in _CMP_OPS:
            op = _CMP_OPS[type(term)]
            left, right = term.left, term.right
            if isinstance(left, E.Literal) and isinstance(right, E.Column):
                left, right = right, left
                op = _FLIP[op]
            if not (isinstance(left, E.Column)
                    and isinstance(right, E.Literal)):
                return None
            if not schema.contains(left.name):
                return None
            dtype = schema.field(left.name).dtype
            v = right.value
            if type(v) is int and abs(v) < 2 ** 63 \
                    and dtype in _INT_DTYPES + _FLOAT_DTYPES:
                shape.append(("cmp", op, col_idx(left.name), "i"))
                ints.append(int(v))
            elif type(v) is float and dtype in _INT_DTYPES + _FLOAT_DTYPES:
                shape.append(("cmp", op, col_idx(left.name), "f"))
                floats.append(float(v))
            elif type(v) is str and dtype == "string":
                # Code-space translation deferred to gather time: the
                # resolved code occupies this int-lane slot.
                ci = col_idx(left.name)
                shape.append(("cmp", op, ci, "i"))
                strs.append(("cmp", len(ints), ci, op, v))
                ints.append(0)
            else:
                return None
        elif isinstance(term, E.In):
            # Mirror the solo engine's fast paths exactly: integer
            # column with an all-int literal list (one vectorized isin),
            # or string column with an all-string list (OR-fold of
            # code-space equalities — identical definite-truth mask).
            if not isinstance(term.child, E.Column) or not term.values:
                return None
            if not schema.contains(term.child.name):
                return None
            dtype = schema.field(term.child.name).dtype
            if dtype in _INT_DTYPES:
                vals = [v.value for v in term.values
                        if isinstance(v, E.Literal)
                        and type(v.value) is int]
                if len(vals) != len(term.values):
                    return None
                padded = _pow2(len(vals))
                shape.append(("in", col_idx(term.child.name), padded))
                # Padding repeats the last value — harmless for
                # membership.
                ints.extend(vals + [vals[-1]] * (padded - len(vals)))
            elif dtype == "string":
                svals = [v.value for v in term.values
                         if isinstance(v, E.Literal)
                         and type(v.value) is str]
                if len(svals) != len(term.values):
                    return None
                ci = col_idx(term.child.name)
                padded = _pow2(len(svals))
                shape.append(("in", ci, padded))
                strs.append(("in", len(ints), ci, padded, tuple(svals)))
                ints.extend([0] * padded)
            else:
                return None
        elif isinstance(term, (E.IsNull, E.IsNotNull)):
            if not isinstance(term.child, E.Column) \
                    or not schema.contains(term.child.name):
                return None
            kind = "isnull" if isinstance(term, E.IsNull) else "notnull"
            shape.append((kind, col_idx(term.child.name)))
        else:
            return None
    if not shape:
        return None
    return tuple(shape), tuple(cols), ints, floats, tuple(strs)


def plan_signature(plan, session_key) -> Optional[BatchSignature]:
    """The plan's batch signature, or None when its shape does not
    qualify: exactly `[Project(simple)] <- Filter <- Scan`, with every
    predicate term in the mirrored subset — numeric comparisons,
    int/string IN lists, null-ness, and string comparisons (constants
    resolved to dictionary codes per member at gather time)."""
    node = plan
    projection: Optional[Tuple[str, ...]] = None
    if isinstance(node, Project):
        if not node.is_simple():
            return None
        projection = tuple(node.columns)
        node = node.child
    if not isinstance(node, Filter):
        return None
    condition = node.condition
    node = node.child
    if not isinstance(node, Scan):
        return None
    scan = node
    parsed = _parse_terms(condition, scan.schema)
    if parsed is None:
        return None
    shape, cols, ints, floats, strs = parsed
    if projection is None:
        projection = tuple(scan.schema.names)
    else:
        projection = tuple(scan.schema.field(c).name for c in projection)
    wanted = set(projection) | set(cols)
    needed = tuple(n for n in scan.schema.names if n in wanted)
    files_tag = (tuple(scan.files()) if scan._explicit_files else None)
    key = (session_key, tuple(scan.root_paths), scan.pinned_version,
           scan.index_name, files_tag, shape, cols, projection, needed)
    return BatchSignature(key, scan, shape, cols, projection, needed,
                          ints, floats, strs)


# ---------------------------------------------------------------------------
# Cohorts
# ---------------------------------------------------------------------------

_WAITING, _DONE, _FAILED, _ABANDONED = range(4)


class _Member:
    __slots__ = ("sig", "deadline", "state", "result", "cohort_size",
                 "cohort_id", "tenant", "cohort_tenants")

    def __init__(self, sig: BatchSignature, deadline):
        self.sig = sig
        self.deadline = deadline
        self.state = _WAITING
        self.result = None
        self.cohort_size = 0
        self.cohort_id: Optional[str] = None
        # The member's serving tenant, captured on its OWN thread at
        # join time. Chargeback is leader-pays: `_execute` runs on the
        # leader's thread under the leader's tenant scope, so the whole
        # cohort's device dispatch bills the leader's tenant — the
        # exactness contract (per-tenant sums == global counters) holds
        # because every charge lands on exactly one tenant. The cohort
        # report records every member tenant so the subsidy is visible.
        self.tenant: str = telemetry.current_tenant()
        self.cohort_tenants: tuple = ()


class _Cohort:
    __slots__ = ("key", "members", "gathering", "ready")

    def __init__(self, key):
        self.key = key
        self.members: List[_Member] = []
        self.gathering = True
        # Early close: set by a joiner that observed every in-flight
        # query already inside this cohort — nobody else CAN join, so
        # the leader stops burning the rest of its gather window (a
        # closed loop would otherwise sleep whole windows with all its
        # clients parked in the cohort).
        self.ready = False


class QueryBatcher:
    """Process-wide batching lane (module docstring). Owns NO threads:
    the leader executes on its own caller thread, members wait on
    theirs — same discipline as the scheduler."""

    def __init__(self):
        self._cv = threading.Condition()
        self._cohorts: Dict[tuple, _Cohort] = {}
        # Convoy pipeline: the cohort currently EXECUTING per signature.
        # While one runs, the next cohort of the same signature gathers
        # — the predecessor's execution is the natural gather window, so
        # sustained same-shape traffic batches continuously without
        # sleeping out timers (the fixed window only pays off the FIRST
        # cohort of a burst).
        self._running: Dict[tuple, _Cohort] = {}
        # Adaptive gather: consecutive EMPTY gathers per signature.
        # After _SOLO_STREAK of them the lane stops paying the window
        # for that signature (a parked closed-loop client is lost
        # throughput), re-probing every _SOLO_PROBE-th candidate so a
        # traffic shift re-enables batching within a few queries.
        self._solo_streak: Dict[tuple, int] = {}
        self._warmed: set = set()
        # Cohort ids: one per batched invocation, stamped on every
        # member's QueryMetrics (`metrics.cohort`) so the flight ring
        # can group a cohort's members post-hoc.
        self._cohort_ids = itertools.count(1)

    # -- entry point (called by QueryScheduler.collect) -------------------

    def try_collect(self, df, plan, metrics, conf, deadline, scheduler):
        """Execute `plan` through the batched lane, or return None when
        the caller should run the normal per-query path (ineligible
        shape, nothing to coalesce with, or batch-lane failure — the
        fallback contract). Typed serving errors (this query's own
        deadline/cancel) propagate."""
        session = df.session
        sig = plan_signature(plan, id(session) if session is not None
                             else 0)
        if sig is None:
            return None
        if sig.scan.index_name:
            # A not-closed breaker means the per-query resilient path
            # (short-circuit / probe bookkeeping) must see this query.
            root = sig.scan.root_paths[0] if sig.scan.root_paths else ""
            if scheduler.breakers.state(
                    f"{sig.scan.index_name}@{root}") != "closed":
                return None
        me = _Member(sig, deadline)
        max_members = max(2, conf.serve_batch_max)
        with self._cv:
            cohort = self._cohorts.get(sig.key)
            if cohort is not None and cohort.gathering \
                    and len(cohort.members) < max_members:
                cohort.members.append(me)
                if len(cohort.members) >= max_members or \
                        scheduler.pressure()["inflight"] \
                        <= len(cohort.members):
                    # Full, or every in-flight query is already HERE:
                    # wake the leader instead of letting the whole
                    # system sleep out the window.
                    cohort.ready = True
                    self._cv.notify_all()
                leader = False
            else:
                if scheduler.pressure()["inflight"] <= 1:
                    return None  # nothing to coalesce with: skip the lane
                streak = self._solo_streak.get(sig.key, 0)
                if streak >= _SOLO_STREAK and self._running.get(
                        sig.key) is None:
                    # This signature keeps gathering nobody: don't park
                    # another client in an empty window; probe again
                    # every _SOLO_PROBE-th candidate.
                    self._solo_streak[sig.key] = streak + 1
                    if (streak - _SOLO_STREAK) % _SOLO_PROBE:
                        return None
                cohort = _Cohort(sig.key)
                cohort.members.append(me)
                self._cohorts[sig.key] = cohort
                leader = True
        if leader:
            return self._lead(cohort, me, conf, max_members)
        return self._follow(me)

    # -- leader ------------------------------------------------------------

    def _lead(self, cohort: _Cohort, me: _Member, conf,
              max_members: int):
        reg = telemetry.get_registry()
        window_s = max(0.0, conf.serve_batch_window_ms) / 1000.0
        t0 = time.perf_counter()
        sig_key = cohort.key
        members: List[_Member] = [me]
        try:
            with self._cv:
                end = time.monotonic() + window_s
                # Convoy bound: while a predecessor cohort of this
                # signature is executing, keep gathering past the
                # window (its completion wakes us) — bounded so one
                # slow batch can never park its successors forever.
                hard_end = time.monotonic() + max(0.1, window_s * 25)
                while cohort.gathering and not cohort.ready \
                        and len(cohort.members) < max_members:
                    me.deadline.check("batch")
                    now = time.monotonic()
                    soft = (hard_end
                            if self._running.get(cohort.key) is not None
                            else end)
                    left = soft - now
                    if left <= 0:
                        break
                    self._cv.wait(timeout=min(left, _WAIT_QUANTUM_S))
                cohort.gathering = False
                if self._cohorts.get(cohort.key) is cohort:
                    del self._cohorts[cohort.key]
                members = list(cohort.members)
                self._running[cohort.key] = cohort
            gather_s = time.perf_counter() - t0
            reg.histogram("serve.batch.window_wait_s").observe(gather_s)
            # Critical-path source: the leader's gather window is wall
            # this query spent collecting its cohort
            # (`telemetry/critical_path.py` classifies it
            # `batch_window`).
            telemetry.add_seconds("serve.batch.window_s", gather_s)
            live = [m for m in members
                    if m.state == _WAITING and m is not me]
            if not live:
                reg.counter("serve.batch.solo").inc()
                with self._cv:
                    self._solo_streak[sig_key] = \
                        self._solo_streak.get(sig_key, 0) + 1
                return None  # no cohort formed: the normal path wins
            with self._cv:
                self._solo_streak.pop(sig_key, None)
            me.deadline.check("batch")
            results = self._execute(me.sig, [me] + live, conf)
        except BaseException as exc:
            self._fail(cohort, me)
            if isinstance(exc, Exception) \
                    and not _is_serving_error(exc):
                # Ordinary batch-lane failure: the LEADER falls back to
                # per-query execution too (never fails the cohort).
                reg.counter("serve.batch.fallbacks").inc()
                telemetry.event("serve", "batch_fallback",
                                reason=repr(exc))
                return None
            raise  # the leader's own typed cancel, or an injected crash
        finally:
            with self._cv:
                cohort.gathering = False
                if self._cohorts.get(cohort.key) is cohort:
                    del self._cohorts[cohort.key]
                if self._running.get(cohort.key) is cohort:
                    del self._running[cohort.key]
                self._cv.notify_all()  # wake the successor's leader
        cohort_id = f"c-{next(self._cohort_ids)}"
        cohort_tenants = tuple(sorted({m.tenant for m in results}))
        with self._cv:
            for m, out in results.items():
                if m.state == _WAITING:
                    m.result = out
                    m.cohort_size = len(results)
                    m.cohort_id = cohort_id
                    m.cohort_tenants = cohort_tenants
                    m.state = _DONE
            # Anyone not sliced (joined too late to matter): fall back.
            for m in members:
                if m.state == _WAITING and m not in results:
                    m.state = _FAILED
            self._cv.notify_all()
        telemetry.event("serve", "batched", cohort=len(results),
                        cohort_id=cohort_id, leader=True)
        telemetry.add_count("serve.batch.member")
        rec = telemetry.current()
        if rec is not None:
            rec.cohort = {"id": cohort_id, "size": len(results),
                          "leader": True,
                          "tenants": list(cohort_tenants),
                          "tenant_pays": me.tenant}
        return results[me]

    def _fail(self, cohort: _Cohort, me: _Member) -> None:
        # Read the member list UNDER the lock — the leader may be
        # failing out of the gather loop itself (its own deadline),
        # where any local snapshot predates late joiners; missing one
        # would leave it waiting forever.
        with self._cv:
            cohort.gathering = False
            for m in cohort.members:
                if m is not me and m.state == _WAITING:
                    m.state = _FAILED
            self._cv.notify_all()

    # -- member ------------------------------------------------------------

    def _follow(self, me: _Member):
        reg = telemetry.get_registry()
        # The member's side of the handoff is a REAL operator record:
        # its metric tree shows where the query's wall went (waiting on
        # the cohort) and how many rows its slice produced, so the
        # flight ring / differ treat batched queries like any other.
        rec = telemetry.current()
        op = rec.start_operator("BatchedQuery") if rec is not None \
            else None
        t_wait0 = time.perf_counter()
        try:
            with telemetry.span("serve.batch.member", "serve.batch"):
                with self._cv:
                    while me.state == _WAITING:
                        try:
                            me.deadline.check("batch")
                        except BaseException:
                            # A cancelled member drops its slice —
                            # never the batch: the leader skips
                            # non-waiting members when it settles.
                            me.state = _ABANDONED
                            self._cv.notify_all()
                            raise
                        self._cv.wait(timeout=_WAIT_QUANTUM_S)
        except BaseException as exc:
            telemetry.add_seconds("serve.batch.window_s",
                                  time.perf_counter() - t_wait0)
            if op is not None:
                rec.finish_operator(op, error=repr(exc))
            raise
        # Critical-path source: a member's whole blocked-on-cohort wait
        # — gather window AND the shared execution — is classified
        # `batch_window` (the member can't tell the phases apart, and
        # from its side the distinction doesn't matter: it was parked).
        telemetry.add_seconds("serve.batch.window_s",
                              time.perf_counter() - t_wait0)
        if me.state == _DONE:
            if op is not None:
                op.detail["cohort"] = me.cohort_size
                rec.finish_operator(op, rows_out=me.result.num_rows)
            telemetry.event("serve", "batched", cohort=me.cohort_size,
                            cohort_id=me.cohort_id, leader=False)
            telemetry.add_count("serve.batch.member")
            if rec is not None:
                rec.cohort = {"id": me.cohort_id,
                              "size": me.cohort_size, "leader": False,
                              "tenants": list(me.cohort_tenants)}
            return me.result
        # Batch lane failed for this cohort: per-query fallback.
        if op is not None:
            rec.finish_operator(op, error="batch-lane fallback")
        reg.counter("serve.batch.fallbacks").inc()
        telemetry.event("serve", "batch_fallback", reason="cohort")
        return None

    # -- the batched execution ---------------------------------------------

    def _execute(self, sig: BatchSignature, live: List[_Member], conf):
        """ONE shared scan + ONE stacked-predicate program + per-member
        slices. Runs on the leader's thread under the leader's recorder
        and deadline (its operator records and checkpoints fire here).
        Returns {member: ColumnBatch}."""
        from hyperspace_tpu.engine.physical import ScanExec
        from hyperspace_tpu.parallel import spmd
        from hyperspace_tpu.utils import faults

        faults.fire("batch.execute")
        reg = telemetry.get_registry()
        K = len(live)
        with telemetry.span("serve.batch", "serve.batch", members=K):
            scan_exec = ScanExec(sig.scan, list(sig.needed), conf=conf,
                                 shared_members=K)
            batch = scan_exec.execute()
            self._maybe_warm(sig, batch, conf)
            Kb = _pow2(K)
            iconst, fconst = _constant_lanes(
                [_resolve_string_constants(m.sig, batch)
                 for m in live],
                [m.sig.floats for m in live], Kb)
            datas = tuple(batch.column(c).data for c in sig.columns)
            valids = tuple(batch.column(c).validity
                           for c in sig.columns)
            masks = np.asarray(spmd.batched_predicate_masks(
                sig.shape, datas, valids, iconst, fconst))
            reg.counter("serve.batch.invocations").inc()
            reg.counter("serve.batch.members").inc(K)
            results: Dict[_Member, object] = {}
            host = batch.is_host
            for k, m in enumerate(live):
                if m.state != _WAITING:
                    continue  # cancelled while the batch ran: drop slice
                idx = np.nonzero(masks[k])[0].astype(np.int32)
                if not host:
                    import jax.numpy as jnp
                    idx = jnp.asarray(idx)
                results[m] = batch.take(idx).select(
                    list(m.sig.projection))
            return results

    # -- AOT warm-start -----------------------------------------------------

    def _buckets(self, conf) -> List[int]:
        top = _pow2(max(2, conf.serve_batch_max))
        out, b = [], 2
        while b <= top:
            out.append(b)
            b <<= 1
        return out

    def _warm_key(self, sig: BatchSignature, n_rows: int):
        return (tuple(sig.scan.root_paths), sig.scan.pinned_version,
                sig.shape, n_rows)

    def _maybe_warm(self, sig: BatchSignature, batch, conf) -> None:
        """Index-open priming: the first time this signature executes,
        pre-compile EVERY canonical cohort bucket for its shape (zero
        arrays of the real columns' dtypes/validity presence), so later
        cohorts of any size dispatch warm."""
        if not conf.serve_batch_aot_warmup:
            return
        key0 = self._warm_key(sig, batch.num_rows)
        with self._cv:
            if key0 in self._warmed:
                return
            self._warmed.add(key0)
        dtypes = [batch.column(c).data.dtype for c in sig.columns]
        flags = [batch.column(c).validity is not None
                 for c in sig.columns]
        self._warm(sig, batch.num_rows, dtypes, flags, conf)

    def _warm(self, sig: BatchSignature, n_rows: int, dtypes, flags,
              conf, buckets: Optional[List[int]] = None) -> int:
        from hyperspace_tpu.parallel import spmd
        from hyperspace_tpu.telemetry import compilation

        ti = sum(1 if t[0] == "cmp" and t[3] == "i" else
                 t[2] if t[0] == "in" else 0 for t in sig.shape)
        tf = sum(1 for t in sig.shape
                 if t[0] == "cmp" and t[3] == "f")
        ran = 0
        for kb in (buckets or self._buckets(conf)):
            def args(kb=kb):
                datas = tuple(np.zeros(n_rows, dtype=dt)
                              for dt in dtypes)
                valids = tuple(np.zeros(n_rows, dtype=bool) if f
                               else None for f in flags)
                return (sig.shape, datas, valids,
                        np.zeros((kb, ti), dtype=np.int64),
                        np.zeros((kb, tf), dtype=np.float64))

            key = self._warm_key(sig, n_rows) + (
                kb, tuple(str(d) for d in dtypes), tuple(flags))
            if compilation.aot_warmup(key, _warm_masks, args):
                ran += 1
        return ran


def _warm_masks(*args):
    """The warmup body: one real dispatch of the batched program (the
    batching-seam lint sanctions the call in this module only)."""
    from hyperspace_tpu.parallel import spmd

    out = spmd.batched_predicate_masks(*args)
    np.asarray(out)  # force dispatch completion (async backends)
    return out


def _string_code_constant(d, op: str, value: str) -> int:
    """One string literal -> one int-lane constant, mirroring the solo
    compiler's code-space tests (`_string_literal_compare`) as a plain
    integer comparison over codes: eq/ne use the value's code when
    present else -1 (no code equals -1, so eq is all-false and ne
    all-true — the absent-value semantics); lt/ge use the left
    insertion point, le/gt `right - 1` (`x <= right-1` == `x < right`
    on integer codes)."""
    left = int(np.searchsorted(d, value, side="left"))
    right = int(np.searchsorted(d, value, side="right"))
    if op in ("eq", "ne"):
        return left if left < right else -1
    if op in ("lt", "ge"):
        return left
    return right - 1  # le, gt


def _resolve_string_constants(sig: BatchSignature, batch):
    """This member's int-lane constants with every deferred string term
    translated against the SHARED scan's sorted dictionary (per-member,
    at gather time — counted as `spmd.strings.dict_lookups`)."""
    if not sig.strs:
        return sig.ints
    ints = list(sig.ints)
    lookups = 0
    for term in sig.strs:
        d = batch.column(sig.columns[term[2]]).dictionary
        if term[0] == "cmp":
            _kind, slot, _ci, op, value = term
            ints[slot] = _string_code_constant(d, op, value)
            lookups += 1
        else:  # ("in", start, ci, padded, values)
            _kind, start, _ci, padded, values = term
            codes = [_string_code_constant(d, "eq", v) for v in values]
            codes = codes + [codes[-1]] * (padded - len(codes))
            ints[start:start + padded] = codes
            lookups += len(values)
    telemetry.get_registry().counter(
        "spmd.strings.dict_lookups").inc(lookups)
    return ints


def _constant_lanes(ints: List[List[int]], floats: List[List[float]],
                    Kb: int):
    """[Kb, T] padded constant lanes; padding rows replicate member 0
    (any valid constants do — padded masks are never sliced)."""
    ti, tf = len(ints[0]), len(floats[0])
    iconst = np.zeros((Kb, ti), dtype=np.int64)
    fconst = np.zeros((Kb, tf), dtype=np.float64)
    for k in range(Kb):
        src = k if k < len(ints) else 0
        if ti:
            iconst[k] = ints[src]
        if tf:
            fconst[k] = floats[src]
    return iconst, fconst


def _is_serving_error(exc) -> bool:
    from hyperspace_tpu.exceptions import QueryServingError
    return isinstance(exc, QueryServingError)


# ---------------------------------------------------------------------------
# Replica warm-start API
# ---------------------------------------------------------------------------


def warmup(df, cohort_sizes: Optional[List[int]] = None) -> int:
    """Pre-compile the batched predicate executables for this
    DataFrame's plan signature across the canonical cohort-size buckets
    — the replica-start half of warm-start: point a fresh process at
    the shared persistent compile cache (`spark.hyperspace.compile.
    cache.dir`), call `warmup(df)` for each canonical serving shape at
    index-open time, and the first real cohort dispatches with
    `compile.traces == 0`. Returns how many programs were primed (0 =
    plan not batchable, empty scan, or already warm). Assumes null-free
    referenced columns (a nullable column's first cohort re-traces
    once, with validity lanes)."""
    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.io.columnar import HOST_NP_DTYPES

    session = df.session
    conf = session.conf if session is not None else None
    if conf is None or not conf.serve_batch_enabled:
        return 0
    plan = session.optimize(df.plan)
    sig = plan_signature(plan, id(session))
    if sig is None:
        return 0
    files = sig.scan.files()
    n_rows = int(sum(parquet.file_row_counts(files))) if files else 0
    if n_rows <= 0:
        return 0
    dtypes = [np.dtype(HOST_NP_DTYPES[sig.scan.schema.field(c).dtype])
              for c in sig.columns]
    flags = [False] * len(sig.columns)
    return get_batcher()._warm(sig, n_rows, dtypes, flags, conf,
                               buckets=cohort_sizes)


# ---------------------------------------------------------------------------
# Process-wide batcher
# ---------------------------------------------------------------------------

_batcher: Optional[QueryBatcher] = None
_batcher_lock = threading.Lock()


def get_batcher() -> QueryBatcher:
    global _batcher
    if _batcher is None:
        with _batcher_lock:
            if _batcher is None:
                _batcher = QueryBatcher()
    return _batcher


def set_batcher(batcher: QueryBatcher) -> QueryBatcher:
    """Install a specific batcher (tests: fresh cohorts/warm memo)."""
    global _batcher
    _batcher = batcher
    return batcher


def reset_batcher() -> None:
    global _batcher
    _batcher = None
